"""Build glue: compile the native C++ runtime into the wheel.

The reference installs via CMake (root CMakeLists.txt -> libmultiverso.so
+ headers); the TPU build's wheel carries the equivalent
``libmultiverso_tpu.so`` as package data under ``multiverso_tpu/native/``
(the ctypes loader checks there first in installed trees, falling back to
the repo's ``native/`` dir in source checkouts, and degrading to pure
python when no library exists — multiverso_tpu/native/__init__.py).

The library is built with the same flags as native/Makefile. A missing
C++ toolchain degrades gracefully: the wheel ships pure-python and the
fast readers / native CPU store are unavailable (the module contract).
"""

import shutil
import subprocess
import sys
from pathlib import Path

from setuptools import setup
from setuptools.command.build_py import build_py

ROOT = Path(__file__).resolve().parent
NATIVE = ROOT / "native"


def _build_native(out_path: Path) -> bool:
    """Build via the Makefile — the single source of truth for the native
    source list and flags (a parallel list here would silently drop new
    .cc files from wheels)."""
    if shutil.which("make") is None or not (NATIVE / "Makefile").exists():
        print("multiverso-tpu: no make/Makefile; wheel ships pure-python",
              file=sys.stderr)
        return False
    result = subprocess.run(["make", "-C", str(NATIVE), "-j4",
                             "libmultiverso_tpu.so"],
                            capture_output=True, text=True)
    if result.returncode != 0:
        print(f"multiverso-tpu: native build failed (pure-python wheel):\n"
              f"{result.stderr[-2000:]}", file=sys.stderr)
        return False
    shutil.copy2(NATIVE / "libmultiverso_tpu.so", out_path)
    return True


class BuildPyWithNative(build_py):
    def run(self):
        super().run()
        dest = Path(self.build_lib) / "multiverso_tpu" / "native"
        dest.mkdir(parents=True, exist_ok=True)
        _build_native(dest / "libmultiverso_tpu.so")


setup(cmdclass={"build_py": BuildPyWithNative})
