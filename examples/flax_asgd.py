#!/usr/bin/env python
"""Multi-worker ASGD training of a flax model through the parameter server.

The reference shipped Theano/Lasagne/Keras adapters for exactly this
pattern (reference theano_ext/lasagne_ext/param_manager.py,
keras_ext/callbacks.py:8-39, benchmark: binding/python/docs/BENCHMARK.md
ResNet-32 ASGD rows). The modern JAX-native stack is flax.linen + optax;
the adapter is the same ``JaxParamManager`` delta-sync (pytrees flatten
into ONE ArrayTable vector) plus ``SyncCallback`` — the Keras-callback
equivalent that syncs every ``freq`` batches.

Each worker owns a private model replica and a disjoint data shard; every
sync it pushes (current - last_synced) and pulls the merged parameters —
the reference's delta trick (param_manager.py:67-82). The replicas
converge to one shared model that fits the whole dataset.

Run:  python flax_asgd.py
"""

import threading

import numpy as np

import jax

if jax.default_backend() != "tpu":
    jax.config.update("jax_platforms", "cpu")

import flax.linen as nn
import jax.numpy as jnp
import optax

import multiverso_tpu as mv
from multiverso_tpu.binding import ArrayTableHandler
from multiverso_tpu.binding.param_manager import (JaxParamManager,
                                                  SyncCallback, _flatten)

WORKERS, EPOCHS, BATCH, SYNC_FREQ = 2, 8, 64, 4
FEATURES, CLASSES, N = 20, 3, 3000


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Dense(64)(x))
        return nn.Dense(CLASSES)(x)


def init_params():
    # identical init on every worker (the master's push wins; others
    # contribute zeros — the binding's master-initializes convention)
    return MLP().init(jax.random.PRNGKey(7), jnp.zeros((1, FEATURES)))


def main():
    rng = np.random.default_rng(0)
    centers = rng.standard_normal((CLASSES, FEATURES)).astype(np.float32) * 2
    y = rng.integers(0, CLASSES, N)
    X = centers[y] + rng.standard_normal((N, FEATURES)).astype(np.float32)

    mv.MV_Init([f"-num_workers={WORKERS}"])

    @jax.jit
    def train_step(params, opt_state, xb, yb):
        def loss_fn(p):
            logits = MLP().apply(p, xb)
            one_hot = jax.nn.one_hot(yb, CLASSES)
            return optax.softmax_cross_entropy(logits, one_hot).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    @jax.jit
    def accuracy(params, xb, yb):
        return (MLP().apply(params, xb).argmax(-1) == yb).mean()

    tx = optax.sgd(0.05)

    # ONE shared table for all in-process workers, sized from the pytree
    template = init_params()
    init_vec = _flatten([np.asarray(leaf).ravel()
                         for leaf in jax.tree.leaves(template)])
    shared = ArrayTableHandler(init_vec.size, init_value=init_vec)

    final_acc = {}

    def worker(wid):
        with mv.MV_WorkerContext(wid):
            wrng = np.random.default_rng(wid)  # Generators aren't thread-safe
            mgr = JaxParamManager(init_params(), table=shared)
            params = mgr.params()
            opt_state = tx.init(params)
            cb = SyncCallback(mgr, freq=SYNC_FREQ)
            shard = slice(wid * N // WORKERS, (wid + 1) * N // WORKERS)
            Xs, ys = X[shard], y[shard]
            for _ in range(EPOCHS):
                perm = wrng.permutation(len(Xs))
                for start in range(0, len(Xs), BATCH):
                    idx = perm[start:start + BATCH]
                    params, opt_state, _ = train_step(
                        params, opt_state, Xs[idx], ys[idx])
                    mgr.update(params)          # hand progress to the mgr
                    cb.on_batch_end()           # delta-sync every SYNC_FREQ
                    params = mgr.params()       # continue from merged state
            cb.on_train_end()                   # final flush + pull
            params = mgr.params()
            final_acc[wid] = float(accuracy(params, X, y))

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(WORKERS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    mv.MV_ShutDown()

    accs = [final_acc[w] for w in range(WORKERS)]
    print(f"per-worker accuracy on the FULL dataset: "
          f"{', '.join(f'{a:.3f}' for a in accs)}")
    assert all(a > 0.9 for a in accs), accs
    # workers ended on the same merged model
    assert abs(accs[0] - accs[1]) < 0.02, accs
    print("flax ASGD through the parameter server OK")


if __name__ == "__main__":
    main()
