#!/usr/bin/env python
"""Multi-worker ASGD training of a torch model through the parameter server.

The reference's flagship binding benchmark trains CIFAR-10 ResNet with N
processes doing ASGD through Multiverso's param-manager sync (reference
binding/python/docs/BENCHMARK.md:57-59 and the Theano/Lasagne
MVModelParamManager). Same pattern here, 2026-style: torch (CPU) model,
`TorchParamManager` delta-sync against an ArrayTable, in-process worker
threads standing in for the reference's processes.

Each worker owns a private model replica and a disjoint data shard; every
`sync_freq` batches it pushes (current - last_synced) and pulls the merged
parameters — the reference's delta trick (param_manager.py:67-82). The
workers' replicas converge to one shared model that fits the whole dataset.

Run:  python torch_asgd.py
"""

import threading

import numpy as np

import jax

if jax.default_backend() != "tpu":
    jax.config.update("jax_platforms", "cpu")

import torch
import torch.nn as nn

import multiverso_tpu as mv
from multiverso_tpu.binding.param_manager import TorchParamManager

WORKERS, EPOCHS, BATCH, SYNC_FREQ = 2, 30, 64, 4
FEATURES, CLASSES, N = 20, 3, 3000


def make_model():
    torch.manual_seed(7)  # identical init on every worker (master pushes)
    return nn.Sequential(nn.Linear(FEATURES, 64), nn.ReLU(),
                         nn.Linear(64, CLASSES))


def main():
    rng = np.random.default_rng(0)
    centers = rng.standard_normal((CLASSES, FEATURES)).astype(np.float32) * 2
    y = rng.integers(0, CLASSES, N)
    X = centers[y] + rng.standard_normal((N, FEATURES)).astype(np.float32)
    Xt = torch.from_numpy(X)
    yt = torch.from_numpy(y)

    mv.MV_Init([f"-num_workers={WORKERS}"])
    final_acc = {}

    # ONE shared table for all in-process workers (multi-process jobs
    # instead create one handler per process; table ids align like the
    # reference). Master-initializes from the seeded template model.
    from multiverso_tpu.binding import ArrayTableHandler
    from multiverso_tpu.binding.param_manager import _flatten
    template = make_model()
    init = _flatten([p.detach().numpy() for p in template.parameters()])
    shared = ArrayTableHandler(init.size, init_value=init)

    def worker(wid):
        with mv.MV_WorkerContext(wid):
            model = make_model()
            mgr = TorchParamManager(model, table=shared)
            opt = torch.optim.SGD(model.parameters(), lr=0.05)
            loss_fn = nn.CrossEntropyLoss()
            shard = slice(wid * N // WORKERS, (wid + 1) * N // WORKERS)
            Xs, ys = Xt[shard], yt[shard]
            step = 0
            for _ in range(EPOCHS):
                perm = torch.randperm(len(Xs))
                for start in range(0, len(Xs), BATCH):
                    idx = perm[start:start + BATCH]
                    opt.zero_grad()
                    loss_fn(model(Xs[idx]), ys[idx]).backward()
                    opt.step()
                    step += 1
                    if step % SYNC_FREQ == 0:
                        mgr.sync_all_param()
            mgr.sync_all_param()
            with torch.no_grad():
                acc = (model(Xt).argmax(1) == yt).float().mean().item()
            final_acc[wid] = acc

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(WORKERS)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    # (no MV_Barrier here: it is a NUM_WORKERS-party rendezvous for worker
    # threads; the main thread alone would wait forever)
    mv.MV_ShutDown()
    for wid, acc in sorted(final_acc.items()):
        print(f"worker {wid}: full-dataset accuracy {acc:.3f}")
    assert all(a > 0.9 for a in final_acc.values()), final_acc


if __name__ == "__main__":
    main()
