#!/usr/bin/env python
"""Generate synthetic MNIST-shaped data for the logreg example.

The reference example downloads MNIST and converts it to the dense text
format (reference Applications/LogisticRegression/example/convert.py);
this environment has no network, so we synthesize a linearly-separable
10-class problem of the same shape (784 features) instead. The config
file is the reference's mnist.config, parsed unchanged by
multiverso_tpu.models.logreg.configure.
"""
import numpy as np

FEATURES, CLASSES = 784, 10


def write(path, n, rng, centers):
    y = rng.integers(0, CLASSES, n)
    X = (centers[y] + rng.standard_normal((n, FEATURES)) * 0.35).astype(
        np.float32)
    with open(path, "w") as f:
        for label, row in zip(y, X):
            f.write(f"{label} " + " ".join(f"{v:.4f}" for v in row) + "\n")


def main():
    rng = np.random.default_rng(0)
    centers = rng.standard_normal((CLASSES, FEATURES)).astype(np.float32)
    write("train.data", 6000, rng, centers)
    write("test.data", 1000, rng, centers)
    print("wrote train.data (6000) and test.data (1000)")


if __name__ == "__main__":
    main()
