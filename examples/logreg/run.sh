#!/bin/sh
# Mirror of the reference example runner
# (Applications/LogisticRegression/example/run.sh): generate data, train,
# report accuracy. Run from this directory.
set -e
python gen_data.py
python -m multiverso_tpu.models.logreg.main mnist.config
# the same files through the PS + the r4 on-chip device plane
# (mnist_device_plane.config adds use_ps/device_plane/sync_frequency)
python -m multiverso_tpu.models.logreg.main mnist_device_plane.config
