#!/bin/sh
# Mirror of the reference example runner
# (Applications/LogisticRegression/example/run.sh): generate data, train,
# report accuracy. Run from this directory.
set -e
python gen_data.py
python -m multiverso_tpu.models.logreg.main mnist.config
