#!/usr/bin/env python
"""Device-plane example: a TPU-resident training loop fused with PS verbs.

The host plane (examples/logreg, examples/wordembedding) is the reference's
protocol surface — numpy in, numpy out, one host round-trip per verb. The
device plane is what the TPU build adds on top (docs/DESIGN.md §4): a
worker living on the same mesh as the store scans the table's traceable
``device_update_rows`` / ``device_gather_rows`` into its own training step,
so N parameter-server rounds compile into ONE XLA program and the weights
never leave HBM.

Here: factorize a low-rank matrix M ≈ U Vᵀ where V lives in a MatrixTable
(row-sharded over the mesh ``server`` axis) and each step gathers a row
batch, takes a gradient step, and scatters the update back — the classic
PS access pattern (cf. WordEmbedding's embedding rows), entirely on device.

Run (any backend; forces an 8-device CPU mesh when no TPU is present):
    python device_plane.py
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import numpy as np

import jax

if jax.default_backend() != "tpu":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
from jax import lax

import multiverso_tpu as mv
from multiverso_tpu.tables import MatrixTableOption
from multiverso_tpu.updaters import AddOption

ROWS, COLS, RANK, BATCH, STEPS, LR = 4096, 128, 8, 512, 300, 0.2


def main():
    mv.MV_Init(["-updater_type=sgd"])
    rng = np.random.default_rng(0)
    # ground truth M = A Bt; V (the PS table) must learn to reconstruct it
    A = rng.standard_normal((ROWS, RANK)).astype(np.float32)
    B = rng.standard_normal((COLS, RANK)).astype(np.float32)

    table = mv.MV_CreateTable(MatrixTableOption(
        num_rows=ROWS, num_cols=COLS, updater_type="sgd",
        initializer=lambda shape: rng.standard_normal(shape).astype(
            np.float32) * 0.01))
    server = table.server()
    opt = AddOption().as_jnp()

    # unique ids per batch: the device row ops require duplicate-free live
    # ids (the host verbs pre-combine duplicates; the traceable plane leaves
    # that to the caller — matrix_table.py module docstring)
    ids_all = np.stack([
        rng.permutation(ROWS)[:BATCH].astype(np.int32)
        for _ in range(STEPS)])
    Ad = jax.device_put(A)
    Bd = jax.device_put(B)
    ids_d = jax.device_put(ids_all)

    def step(state, ids):
        # Get: gather the batch's rows straight out of the sharded store
        rows = server.device_gather_rows(state["data"], state["aux"], ids)
        rows = rows[:, : COLS]
        target = Ad[ids] @ Bd.T                     # (BATCH, COLS) on MXU
        err = rows - target
        loss = jnp.mean(err * err)
        # Add: push the lr-scaled gradient back (sgd server: data -= delta)
        state = server.device_update_rows(state, ids, LR * err, opt)
        return state, loss

    @jax.jit
    def train(state, ids_all):
        return lax.scan(step, state, ids_all)

    state, losses = train(server.state, ids_d)
    server.state = state  # hand the trained store back to the table
    print(f"loss: {float(losses[0]):.4f} -> {float(losses[-1]):.4f} "
          f"over {STEPS} fused PS rounds on {jax.default_backend()} "
          f"({len(jax.devices())} device(s))")
    assert float(losses[-1]) < float(losses[0]) * 0.1

    # the host plane sees the device plane's work (same store)
    sample = table.GetRows(np.arange(4, dtype=np.int32))
    truth = A[:4] @ B.T
    err = np.abs(sample - truth).mean()
    print(f"host-plane readback mean abs err vs ground truth: {err:.4f}")
    mv.MV_ShutDown()


if __name__ == "__main__":
    main()
