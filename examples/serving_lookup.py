"""Serving-plane quickstart: publish consistent snapshots while training
and serve versioned lookups at high QPS off the engine's critical path.

Run (CPU is fine):

    JAX_PLATFORMS=cpu python examples/serving_lookup.py

What it shows:

* ``MV_PublishSnapshot()`` cuts an immutable, versioned,
  cross-table-consistent snapshot INSIDE the engine stream — every Add
  issued before the call is in, none after;
* ``MV_ServingLookup(table, ids, version=...)`` serves reads from the
  snapshot without touching the engine verb stream, micro-batching
  concurrent callers into one fused gather per table;
* ``MV_PinVersion`` holds a version past the ``-mv_serving_keep``
  retention window (read-your-version: a pinned cut never changes);
* overload and deadline failures are TYPED (``ServingOverloaded``,
  ``DeadlineExceeded``) — callers get backpressure, not hangs.
"""

import threading

import numpy as np

import multiverso_tpu as mv
from multiverso_tpu.failsafe.errors import (DeadlineExceeded,
                                            ServingOverloaded)
from multiverso_tpu.tables import MatrixTableOption
from multiverso_tpu.utils.log import Log


def main():
    mv.MV_Init([])
    rows, cols = 1024, 16
    table = mv.MV_CreateTable(MatrixTableOption(num_rows=rows,
                                                num_cols=cols))
    rng = np.random.default_rng(0)

    # --- train a little, then cut version 1 -----------------------------
    ids = np.arange(rows, dtype=np.int32)
    table.AddRows(ids, rng.standard_normal((rows, cols)).astype(np.float32))
    v1 = mv.MV_PublishSnapshot()
    mv.MV_PinVersion(v1)            # hold it for the serving tier
    baseline = mv.MV_ServingLookup(table, ids, version=v1)

    # --- keep training WHILE readers hammer the pinned version ----------
    stop = threading.Event()
    served = [0]

    def reader(seed):
        r = np.random.default_rng(seed)
        while not stop.is_set():
            sel = r.integers(0, rows, 64).astype(np.int32)
            try:
                got = mv.MV_ServingLookup(table, sel, version=v1,
                                          deadline=5.0)
            except (ServingOverloaded, DeadlineExceeded) as exc:
                Log.Info("backpressure: %r", exc)
                continue
            assert np.array_equal(got, baseline[sel]), \
                "a pinned version must never change"
            served[0] += 1

    readers = [threading.Thread(target=reader, args=(i,), daemon=True)
               for i in range(4)]
    for t in readers:
        t.start()
    for _ in range(50):             # the training burst
        sel = rng.integers(0, rows, 32).astype(np.int32)
        table.AddRows(np.unique(sel).astype(np.int32),
                      rng.standard_normal(
                          (len(np.unique(sel)), cols)).astype(np.float32))
    v2 = mv.MV_PublishSnapshot()    # new traffic can move to v2
    stop.set()
    for t in readers:
        t.join(10)

    fresh = mv.MV_ServingLookup(table, ids, version=v2)
    Log.Info("served %d pinned-version lookups during training; "
             "v1 vs v2 max delta = %.3f", served[0],
             float(np.abs(fresh - baseline).max()))
    mv.MV_UnpinVersion(v1)
    mv.MV_ShutDown()


if __name__ == "__main__":
    main()
