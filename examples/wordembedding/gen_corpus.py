#!/usr/bin/env python
"""Generate a small synthetic corpus with Zipf-distributed vocabulary and
local co-occurrence structure (words from the same topic cluster appear
together), so the example produces embeddings where cluster-mates are
nearest neighbours."""
import numpy as np

VOCAB, TOPICS, SENTS, SENT_LEN = 2000, 20, 20000, 12


def main():
    rng = np.random.default_rng(0)
    topic_of = rng.integers(0, TOPICS, VOCAB)
    by_topic = [np.where(topic_of == t)[0] for t in range(TOPICS)]
    zipf = 1.0 / np.arange(1, VOCAB + 1)
    with open("corpus.txt", "w") as f:
        for _ in range(SENTS):
            t = rng.integers(0, TOPICS)
            pool = by_topic[t]
            w = zipf[pool] / zipf[pool].sum()
            words = rng.choice(pool, SENT_LEN, p=w)
            f.write(" ".join(f"w{i}" for i in words) + "\n")
    print(f"wrote corpus.txt ({SENTS} sentences)")


if __name__ == "__main__":
    main()
