#!/bin/sh
# Mirror of the reference example runner
# (Applications/WordEmbedding/example/run.bat): build a corpus, train
# skip-gram + negative sampling, write word2vec-format vectors.
# Run from this directory. Flags are word2vec-style (reference util.h:20-44).
set -e
python gen_corpus.py
python -m multiverso_tpu.models.wordembedding.distributed \
    -train_file corpus.txt -output vectors.txt \
    -size 64 -epoch 3 -negative 5 -min_count 1 \
    -data_block_size 100000 -is_pipeline 1
# the TPU-native fused path: pairs derived ON DEVICE from the token
# stream (all four mode combos; the 6.8x head-to-head configuration)
python -m multiverso_tpu.models.wordembedding.distributed \
    -train_file corpus.txt -output vectors_dp.txt \
    -size 64 -epoch 3 -negative 5 -min_count 1 \
    -data_block_size 4000000 -is_pipeline 0 -device_pairs 1
