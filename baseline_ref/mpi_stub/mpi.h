// Minimal single-process MPI shim — JUST enough of the MPI-3 surface for
// the reference Multiverso's MPINetWrapper (mpi_net.h) to run a 1-process
// world (rank 0 = controller+server+worker; every send is a self-send).
// Used only to build and run the UNMODIFIED reference as a measured
// baseline (baseline_ref/README.md); this is not part of the framework.
#pragma once

#include <cstring>
#include <deque>
#include <mutex>
#include <vector>

typedef int MPI_Datatype;
typedef int MPI_Comm;
typedef int MPI_Op;
typedef int MPI_Request;

#define MPI_COMM_WORLD 0
#define MPI_SUCCESS 0
#define MPI_BYTE 1
#define MPI_CHAR 2
#define MPI_INT 3
#define MPI_FLOAT 4
#define MPI_DOUBLE 5
#define MPI_SUM 0
#define MPI_ANY_SOURCE (-1)
#define MPI_ANY_TAG (-1)
#define MPI_THREAD_SINGLE 0
#define MPI_THREAD_FUNNELED 1
#define MPI_THREAD_SERIALIZED 2
#define MPI_THREAD_MULTIPLE 3
#define MPI_IN_PLACE ((void*)1)
#define MPI_MAX_PROCESSOR_NAME 256

typedef struct MPI_Status {
  int MPI_SOURCE;
  int MPI_TAG;
  int MPI_ERROR;
  int count_;  // bytes
} MPI_Status;

namespace mpi_stub {
struct Msg {
  std::vector<char> bytes;
  int tag;
};
inline std::deque<Msg>& queue() {
  static std::deque<Msg> q;
  return q;
}
inline std::mutex& mu() {
  static std::mutex m;
  return m;
}
inline int& init_flag() {
  static int f = 0;
  return f;
}
inline int type_size(MPI_Datatype t) {
  switch (t) {
    case MPI_INT: return 4;
    case MPI_FLOAT: return 4;
    case MPI_DOUBLE: return 8;
    default: return 1;  // BYTE / CHAR
  }
}
}  // namespace mpi_stub

inline int MPI_Init(int*, char***) {
  mpi_stub::init_flag() = 1;
  return MPI_SUCCESS;
}
inline int MPI_Init_thread(int*, char***, int required, int* provided) {
  mpi_stub::init_flag() = 1;
  *provided = required;
  return MPI_SUCCESS;
}
inline int MPI_Initialized(int* flag) {
  *flag = mpi_stub::init_flag();
  return MPI_SUCCESS;
}
inline int MPI_Query_thread(int* provided) {
  *provided = MPI_THREAD_SERIALIZED;
  return MPI_SUCCESS;
}
inline int MPI_Finalize() {
  mpi_stub::init_flag() = 0;
  return MPI_SUCCESS;
}
inline int MPI_Comm_rank(MPI_Comm, int* rank) {
  *rank = 0;
  return MPI_SUCCESS;
}
inline int MPI_Comm_size(MPI_Comm, int* size) {
  *size = 1;
  return MPI_SUCCESS;
}
inline int MPI_Barrier(MPI_Comm) { return MPI_SUCCESS; }

inline int MPI_Isend(const void* buf, int count, MPI_Datatype type, int /*dst*/,
                     int tag, MPI_Comm, MPI_Request* req) {
  // 1-process world: every destination is self; copy eagerly, complete
  // immediately (the request is a dummy)
  std::lock_guard<std::mutex> lk(mpi_stub::mu());
  mpi_stub::Msg m;
  const char* p = static_cast<const char*>(buf);
  m.bytes.assign(p, p + static_cast<size_t>(count) * mpi_stub::type_size(type));
  m.tag = tag;
  mpi_stub::queue().push_back(std::move(m));
  *req = 1;
  return MPI_SUCCESS;
}

inline void mpi_stub_fill_status(MPI_Status* st, const mpi_stub::Msg& m) {
  if (st != nullptr) {
    st->MPI_SOURCE = 0;
    st->MPI_TAG = m.tag;
    st->MPI_ERROR = MPI_SUCCESS;
    st->count_ = static_cast<int>(m.bytes.size());
  }
}

inline int MPI_Iprobe(int /*src*/, int /*tag*/, MPI_Comm, int* flag,
                      MPI_Status* st) {
  std::lock_guard<std::mutex> lk(mpi_stub::mu());
  if (mpi_stub::queue().empty()) {
    *flag = 0;
  } else {
    *flag = 1;
    mpi_stub_fill_status(st, mpi_stub::queue().front());
  }
  return MPI_SUCCESS;
}

inline int MPI_Probe(int src, int tag, MPI_Comm comm, MPI_Status* st) {
  int flag = 0;
  while (flag == 0) MPI_Iprobe(src, tag, comm, &flag, st);
  return MPI_SUCCESS;
}

inline int MPI_Get_count(const MPI_Status* st, MPI_Datatype type, int* count) {
  *count = st->count_ / mpi_stub::type_size(type);
  return MPI_SUCCESS;
}

inline int MPI_Recv(void* buf, int count, MPI_Datatype type, int /*src*/,
                    int /*tag*/, MPI_Comm, MPI_Status* st) {
  for (;;) {
    std::lock_guard<std::mutex> lk(mpi_stub::mu());
    if (!mpi_stub::queue().empty()) {
      mpi_stub::Msg m = std::move(mpi_stub::queue().front());
      mpi_stub::queue().pop_front();
      size_t cap = static_cast<size_t>(count) * mpi_stub::type_size(type);
      std::memcpy(buf, m.bytes.data(),
                  m.bytes.size() < cap ? m.bytes.size() : cap);
      mpi_stub_fill_status(st, m);
      return MPI_SUCCESS;
    }
  }
}

inline int MPI_Wait(MPI_Request*, MPI_Status*) { return MPI_SUCCESS; }
inline int MPI_Waitall(int, MPI_Request*, MPI_Status*) { return MPI_SUCCESS; }
inline int MPI_Test(MPI_Request*, int* flag, MPI_Status*) {
  *flag = 1;
  return MPI_SUCCESS;
}
inline int MPI_Testall(int, MPI_Request*, int* flag, MPI_Status*) {
  *flag = 1;
  return MPI_SUCCESS;
}

inline int MPI_Allreduce(const void* send, void* recv, int count,
                         MPI_Datatype type, MPI_Op, MPI_Comm) {
  if (send != MPI_IN_PLACE && send != recv) {
    std::memcpy(recv, send,
                static_cast<size_t>(count) * mpi_stub::type_size(type));
  }
  return MPI_SUCCESS;  // size-1 sum = identity
}

inline int MPI_Abort(MPI_Comm, int code) {
  std::abort();
  return code;
}
