// Entry point for the reference's dense matrix perf harness
// (Test/test_matrix_perf.cpp TestDensePerf is not wired into the
// reference's Test/main.cpp dispatch; this main calls it directly).
namespace multiverso { namespace test {
void TestDensePerf(int argc, char* argv[]);
void TestSparsePerf(int argc, char* argv[]);
} }

#include <cstring>

int main(int argc, char* argv[]) {
  if (argc > 1 && std::strcmp(argv[1], "sparse") == 0)
    multiverso::test::TestSparsePerf(argc, argv);
  else
    multiverso::test::TestDensePerf(argc, argv);
  return 0;
}
