#!/usr/bin/env python3
"""Generate the synthetic head-to-head WordEmbedding corpus + vocab file.

5k-word vocabulary split into 50 topic clusters; each sentence draws one
topic and samples its words from that cluster (with a sprinkle of global
noise words), zipf-weighted inside the cluster. ~240k words (x3 epochs =
720k trained words), deterministic. Both the unmodified reference app and
this framework's app train on the identical files, and the cluster
structure gives `we_eval.py` a ground truth to score both embedding sets
against — the "equal loss" check of the head-to-head.
"""
import sys

import numpy as np

OUT = sys.argv[1] if len(sys.argv) > 1 else "."
VOCAB = 5000
TOPICS = 50
SENTS = 20_000
SENT_LEN = 12
NOISE = 0.1          # fraction of tokens drawn from the global distribution
rng = np.random.default_rng(7)
words = np.array([f"t{i // (VOCAB // TOPICS)}_w{i}" for i in range(VOCAB)])
per = VOCAB // TOPICS
# zipf-ish weights inside a cluster and globally
w_local = 1.0 / np.arange(1, per + 1) ** 0.9
w_local /= w_local.sum()
w_global = 1.0 / np.arange(1, VOCAB + 1) ** 1.05
w_global /= w_global.sum()
counts = np.zeros(VOCAB, np.int64)
with open(f"{OUT}/corpus.txt", "w") as f:
    for _ in range(SENTS):
        topic = rng.integers(TOPICS)
        local = topic * per + rng.choice(per, SENT_LEN, p=w_local)
        noise = rng.choice(VOCAB, SENT_LEN, p=w_global)
        use_noise = rng.random(SENT_LEN) < NOISE
        idx = np.where(use_noise, noise, local)
        counts += np.bincount(idx, minlength=VOCAB)
        f.write(" ".join(words[idx]) + "\n")
order = np.argsort(-counts, kind="stable")
with open(f"{OUT}/vocab.txt", "w") as f:
    for i in order:
        if counts[i] > 0:
            f.write(f"{words[i]} {counts[i]}\n")
print(f"wrote {OUT}/corpus.txt ({SENTS * SENT_LEN} words), "
      f"{OUT}/vocab.txt ({int((counts > 0).sum())} words)")
