#!/usr/bin/env python3
"""Score word2vec-format embeddings against we_corpus.py's topic clusters.

Metric: neighbor purity@10 — for each of the most frequent words, the
fraction of its top-10 cosine neighbors that belong to the same topic
cluster (cluster = the `tK_` prefix we_corpus.py bakes into each word).
Random embeddings score ~1/50; a model that learned the co-occurrence
structure scores far higher. Used to show the framework's app reaches the
same embedding quality as the unmodified reference at the measured
wall-clocks (the head-to-head's "equal loss" check).

usage: we_eval.py vec_a.txt [vec_b.txt ...]
"""
import sys

import numpy as np


def load(path):
    with open(path, encoding="utf-8") as f:
        n, dim = map(int, f.readline().split())
        words, rows = [], []
        for line in f:
            parts = line.rstrip("\n").split(" ")
            if len(parts) < dim + 1:
                continue
            words.append(parts[0])
            rows.append(np.asarray(parts[1: dim + 1], np.float32))
    return words, np.vstack(rows)


def purity(path, top_words=500, k=10):
    words, emb = load(path)
    words, emb = words[:top_words], emb[:top_words]
    topic = np.asarray([int(w.split("_")[0][1:]) for w in words])
    norm = emb / np.maximum(np.linalg.norm(emb, axis=1, keepdims=True), 1e-9)
    sim = norm @ norm.T
    np.fill_diagonal(sim, -np.inf)
    nbrs = np.argsort(-sim, axis=1)[:, :k]
    return float((topic[nbrs] == topic[:, None]).mean())


if __name__ == "__main__":
    for p in sys.argv[1:]:
        print(f"{p}: purity@10 = {purity(p):.3f}")
