#!/bin/sh
# Build the UNMODIFIED reference (at /root/reference) against the 1-process
# MPI shim, producing its own Test binary (multiverso.test) so the
# reference's perf harness (Test/test_matrix_perf.cpp) runs on this host
# as a measured baseline.
set -e
REF=${REF:-/root/reference}
HERE=$(cd "$(dirname "$0")" && pwd)
OUT=$HERE/build
mkdir -p "$OUT"
SRCS=$(ls "$REF"/src/*.cpp "$REF"/src/net/*.cpp "$REF"/src/table/*.cpp \
          "$REF"/src/updater/*.cpp "$REF"/src/util/*.cpp \
          "$REF"/src/io/io.cpp "$REF"/src/io/local_stream.cpp \
          "$REF"/src/io/hdfs_stream.cpp)
TESTS=$(ls "$REF"/Test/*.cpp)
g++ -O2 -std=c++11 -w -pthread -include cstddef -DMULTIVERSO_USE_MPI \
    -I"$HERE/mpi_stub" -I"$REF/include" -I"$REF" \
    $SRCS $TESTS -o "$OUT/multiverso.test"
PERF=$(ls "$REF"/Test/*.cpp | grep -v main.cpp)
g++ -O2 -std=c++11 -w -pthread -include cstddef -DMULTIVERSO_USE_MPI \
    -I"$HERE/mpi_stub" -I"$REF/include" -I"$REF" \
    $SRCS $PERF "$HERE/perf_main.cpp" -o "$OUT/multiverso.perf"
LR="$REF/Applications/LogisticRegression/src"
LRSRCS=$(find "$LR" -name "*.cpp")
g++ -O2 -std=c++11 -w -pthread -include cstddef -DMULTIVERSO_USE_MPI \
    -I"$HERE/mpi_stub" -I"$REF/include" -I"$LR" \
    $SRCS $LRSRCS -o "$OUT/logistic_regression"
WE="$REF/Applications/WordEmbedding/src"
WESRCS=$(find "$WE" -name "*.cpp")
g++ -O2 -std=c++11 -w -pthread -fopenmp -include cstddef -DMULTIVERSO_USE_MPI \
    -I"$HERE/mpi_stub" -I"$REF/include" -I"$WE" \
    $SRCS $WESRCS -o "$OUT/word_embedding"
echo "built $OUT/multiverso.test, $OUT/multiverso.perf, $OUT/logistic_regression, $OUT/word_embedding"
