"""Model-parameter synchronization managers.

Modern-stack equivalents of the reference's framework adapters:

* ``MVModelParamManager`` — the generic manager
  (reference binding/python/multiverso/theano_ext/param_manager.py:9-82):
  holds one ArrayTableHandler per model; ``sync_all_param`` pushes the
  *delta* (current − last-synced) and pulls the merged state, so every
  worker's local training between syncs lands on the server exactly once —
  the same trick as ``mv_sync`` on shared variables
  (reference theano_ext/sharedvar.py:37-49).

* ``JaxParamManager`` — flax/optax-style pytrees of jax arrays
  (replaces the Theano/Lasagne adapters).

* ``TorchParamManager`` — torch ``nn.Module`` parameters
  (replaces the Lua/Torch binding's ArrayTableHandler usage,
  reference binding/lua/ArrayTableHandler.lua:6-56).

Both concrete managers flatten parameters into ONE contiguous float32
vector in a single ArrayTable — one Get/Add per sync instead of one per
tensor, which keeps the device transfer large and batched (TPU-friendly)
and matches the reference's one-table-per-model layout.
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple

import numpy as np

import multiverso_tpu.binding as mv


class MVModelParamManager:
    """Generic delta-sync manager over a flat float32 parameter vector."""

    def __init__(self, get_params: Callable[[], np.ndarray],
                 set_params: Callable[[np.ndarray], None], table=None):
        """``get_params()`` returns the current flat parameter vector;
        ``set_params(vec)`` installs one. ``table`` shares an existing
        ArrayTableHandler — in-process worker threads must share ONE table
        (each process creates its own handler in multi-process jobs, where
        table ids align across processes like the reference)."""
        self._get = get_params
        self._set = set_params
        if table is None:
            # only the own-table path needs the initial flatten (shared
            # tables were initialized by their creator)
            init = np.asarray(self._get(), np.float32)
            self.tbh = mv.ArrayTableHandler(init.size, init_value=init)
        else:
            self.tbh = table
        mv.barrier()
        self.last_synced = self.tbh.get().copy()
        self._set(self.last_synced)

    def sync_all_param(self) -> None:
        """Push local progress as a delta, pull the merged model
        (reference param_manager.py:67-82)."""
        current = np.asarray(self._get(), np.float32)
        self.tbh.add(current - self.last_synced)
        merged = self.tbh.get()
        self.last_synced = merged.copy()
        self._set(merged)


def _flatten(arrays: Sequence[np.ndarray]) -> np.ndarray:
    return np.concatenate([np.asarray(a, np.float32).ravel() for a in arrays])


def _unflatten(vec: np.ndarray, shapes: List[Tuple[int, ...]]) -> List[np.ndarray]:
    out, off = [], 0
    for shape in shapes:
        n = int(np.prod(shape)) if shape else 1
        out.append(vec[off:off + n].reshape(shape))
        off += n
    return out


class JaxParamManager(MVModelParamManager):
    """Sync a jax pytree of parameters (flax ``params``, haiku params, …)."""

    def __init__(self, params, table=None):
        import jax
        self._treedef = jax.tree.structure(params)
        leaves = jax.tree.leaves(params)
        self._shapes = [tuple(np.shape(l)) for l in leaves]
        self._current = [np.asarray(l, np.float32) for l in leaves]
        super().__init__(self._get_flat, self._set_flat, table=table)

    def _get_flat(self) -> np.ndarray:
        return _flatten(self._current)

    def _set_flat(self, vec: np.ndarray) -> None:
        self._current = _unflatten(vec, self._shapes)

    def update(self, params) -> None:
        """Record locally-trained params (call before sync_all_param)."""
        import jax
        self._current = [np.asarray(l, np.float32)
                         for l in jax.tree.leaves(params)]

    def params(self):
        """Current merged params as the original pytree structure."""
        import jax
        return jax.tree.unflatten(self._treedef,
                                  [np.asarray(a) for a in self._current])

    def sync(self, params):
        """One-call convenience: update + sync + return merged pytree."""
        self.update(params)
        self.sync_all_param()
        return self.params()


class TorchParamManager(MVModelParamManager):
    """Sync a torch ``nn.Module``'s parameters (CPU tensors)."""

    def __init__(self, model, table=None):
        self._model = model
        self._params = list(model.parameters())
        self._shapes = [tuple(p.shape) for p in self._params]
        super().__init__(self._get_flat, self._set_flat, table=table)

    def _get_flat(self) -> np.ndarray:
        return _flatten([p.detach().cpu().numpy() for p in self._params])

    def _set_flat(self, vec: np.ndarray) -> None:
        import torch
        with torch.no_grad():
            for p, arr in zip(self._params, _unflatten(vec, self._shapes)):
                # explicit copy: the unflattened view may be read-only and
                # torch.from_numpy refuses non-writable arrays
                p.copy_(torch.from_numpy(np.array(arr, copy=True)))


class SyncCallback:
    """Train-loop hook syncing every ``freq`` batches
    (reference binding/python/multiverso/theano_ext/keras_ext/callbacks.py:8-39:
    ``MVCallback.on_batch_end`` calls ``param_manager.sync_all_param`` when
    the batch counter hits the frequency).

    Framework-agnostic: call ``on_batch_end()`` from any training loop (or
    wire it as a keras/flax callback); ``on_train_end()`` does a final sync.
    """

    def __init__(self, param_manager: MVModelParamManager, freq: int = 1):
        self.param_manager = param_manager
        self.freq = max(int(freq), 1)
        self._batch = 0

    def on_batch_end(self, *_args, **_kw) -> None:
        self._batch += 1
        if self._batch % self.freq == 0:
            self.param_manager.sync_all_param()

    def on_train_end(self, *_args, **_kw) -> None:
        self.param_manager.sync_all_param()
