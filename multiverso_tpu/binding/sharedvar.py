"""Per-variable PS-backed shared values — the ``mv_shared`` surface.

Behavioral counterpart of the reference's Theano extension
(binding/python/multiverso/theano_ext/sharedvar.py:12-99): a wrapper that
pairs one mutable array ("shared variable") with one ArrayTable and syncs
via the delta trick —

    add(current_value - last_synced_value); value = get()

so concurrent workers' updates merge additively on the server
(sharedvar.py:37-49). The model-level ``MVModelParamManager`` in
``param_manager.py`` applies the same algorithm to whole models; this
module is the fine-grained per-variable version, including the
master-initializes convention (only worker 0's init value lands,
sharedvar.py:20-27).

Theano is long gone; the 2026 equivalent of a "shared variable" is any
box with ``get_value()/set_value()``. ``SharedArray`` provides that box
for plain numpy/JAX values, and ``MVSharedVariable`` duck-types, so an
object exposing the Theano ``SharedVariable`` protocol works unchanged.
"""

from __future__ import annotations

import numpy as np


class SharedArray:
    """Minimal get_value/set_value box over a numpy array (the stand-in
    for ``theano.shared``)."""

    def __init__(self, value):
        self._value = np.array(value, np.float32)

    def get_value(self, borrow: bool = False) -> np.ndarray:
        return self._value if borrow else self._value.copy()

    def set_value(self, value, borrow: bool = False) -> None:
        arr = np.asarray(value, np.float32)
        self._value = arr if borrow else arr.copy()


class MVSharedVariable:
    """Pairs a shared-variable box with an ArrayTable (reference
    sharedvar.py:12-49). All other attribute access forwards to the
    wrapped object, as the reference's ``__getattr__`` forwarding did."""

    def __init__(self, svobj):
        from multiverso_tpu import binding as mv
        self._svobj = svobj
        init = np.asarray(svobj.get_value(), np.float32)
        self._shape = init.shape
        self._mv_array = mv.ArrayTableHandler(init.size,
                                              init_value=init.reshape(-1))
        # The reference barriers here so every process's init add lands
        # before the first get (sharedvar.py:29). In-process the sync Add
        # above already blocked until applied, and worker threads may not
        # even exist yet (vars are built during setup), so a thread
        # rendezvous would deadlock — only the cross-process leg is needed.
        from multiverso_tpu.parallel import multihost
        multihost.host_barrier("mv_sharedvar_init")
        synced = self._mv_array.get().reshape(self._shape)
        self._svobj.set_value(synced, borrow=False)
        self._last_mv_data = synced.copy()

    def mv_sync(self) -> None:
        """Push (current − last synced) and pull the merged value
        (reference sharedvar.py:37-49)."""
        current = np.asarray(self._svobj.get_value(), np.float32)
        self._mv_array.add((current - self._last_mv_data).reshape(-1))
        merged = self._mv_array.get().reshape(self._shape)
        self._svobj.set_value(merged, borrow=False)
        self._last_mv_data = merged.copy()

    def __getattr__(self, name):
        # everything not defined here behaves like the wrapped variable
        try:
            svobj = self.__dict__["_svobj"]
        except KeyError:
            raise AttributeError(name) from None
        return getattr(svobj, name)


def mv_shared(value, name=None, borrow=False, **kwargs):
    """``theano.shared``-shaped factory (reference sharedvar.py:76-87):
    builds the box, wraps it, and registers the wrapper for
    ``sync_all_mv_shared_vars``. ``name`` is kept on the box; ``borrow``
    is accepted for signature parity (SharedArray always copies on init —
    the PS round-trip rewrites the value anyway); other theano kwargs are
    rejected rather than silently dropped. Deviation: the reference
    returned the bare theano variable and kept the wrapper internal; we
    return the wrapper (it forwards every attribute, and callers need
    ``mv_sync``)."""
    if kwargs:
        raise TypeError(f"mv_shared: unsupported keyword arguments "
                        f"{sorted(kwargs)} (theano-era options have no "
                        f"equivalent here)")
    box = SharedArray(value)
    box.name = name
    var = MVSharedVariable(box)
    mv_shared.shared_vars.append(var)
    return var


mv_shared.shared_vars = []  # registry, reference sharedvar.py:87


def sync_all_mv_shared_vars() -> None:
    """Sync every variable created through ``mv_shared`` (reference
    sharedvar.py:90-99)."""
    for var in mv_shared.shared_vars:
        var.mv_sync()
