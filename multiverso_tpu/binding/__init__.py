"""Reference-compatible Python binding surface.

Drop-in equivalent of the reference's ``multiverso`` Python package
(reference binding/python/multiverso/api.py, tables.py): ``init(sync=)``,
``shutdown``, ``barrier``, ``workers_num``, ``worker_id``, ``server_id``,
``is_master_worker``, ``ArrayTableHandler`` and ``MatrixTableHandler`` with
the master-initializes convention (reference tables.py:49-58: every worker
calls a sync add at construction; only the master contributes the init
value, others contribute zeros — so in sync mode the clocks stay aligned).

The reference reaches these through ctypes over libmultiverso's C API;
here the same surface sits directly on the TPU runtime (the native C API
in native/ serves C/C++/Lua/C# callers instead).

Usage::

    import multiverso_tpu.binding as mv
    mv.init()
    t = mv.ArrayTableHandler(1000, init_value=w0)
    t.add(grad); w = t.get()
    mv.shutdown()
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import multiverso_tpu as _core
from multiverso_tpu.tables import ArrayTableOption, MatrixTableOption


def init(sync: bool = False, args: Optional[Sequence[str]] = None) -> None:
    """reference api.py:12-34 (builds argv with -sync=true when asked)."""
    argv = list(args or [])
    if sync:
        argv.append("-sync=true")
    _core.MV_Init(argv)


def shutdown() -> None:
    _core.MV_ShutDown()


def barrier() -> None:
    _core.MV_Barrier()


def workers_num() -> int:
    return _core.MV_NumWorkers()


def worker_id() -> int:
    return _core.MV_WorkerId()


def server_id() -> int:
    return _core.MV_ServerId()


def is_master_worker() -> bool:
    """Worker 0 owns one-time work: init values, validation, result output
    (reference api.py:68-75)."""
    return worker_id() == 0


class TableHandler:
    """reference tables.py:14-31."""

    def get(self):
        raise NotImplementedError

    def add(self, data, sync: bool = False):
        raise NotImplementedError


class ArrayTableHandler(TableHandler):
    """1-D float32 table (reference tables.py:38-84)."""

    def __init__(self, size: int, init_value=None):
        self._size = size
        self._table = _core.MV_CreateTable(ArrayTableOption(size=size))
        if init_value is not None:
            init_value = np.asarray(init_value, np.float32)
            # master-initializes convention (reference tables.py:49-58):
            # everyone adds (keeping sync clocks aligned); only the master's
            # contribution is the real init value.
            self.add(init_value if is_master_worker()
                     else np.zeros(init_value.shape, np.float32), sync=True)

    def get(self) -> np.ndarray:
        return self._table.Get()

    def add(self, data, sync: bool = False) -> None:
        data = np.asarray(data, np.float32)
        assert data.size == self._size
        if sync:
            self._table.Add(data)
        else:
            self._table.AddFireForget(data)


class MatrixTableHandler(TableHandler):
    """2-D float32 table with whole-table or row-set access
    (reference tables.py:87-165)."""

    def __init__(self, num_row: int, num_col: int, init_value=None):
        self._num_row = num_row
        self._num_col = num_col
        self._table = _core.MV_CreateTable(
            MatrixTableOption(num_rows=num_row, num_cols=num_col))
        if init_value is not None:
            init_value = np.asarray(init_value, np.float32).reshape(num_row,
                                                                    num_col)
            self.add(init_value if is_master_worker()
                     else np.zeros(init_value.shape, np.float32), sync=True)

    def get(self, row_ids=None) -> np.ndarray:
        if row_ids is None:
            return self._table.Get()
        return self._table.GetRows(np.asarray(row_ids, np.int32))

    def add(self, data, row_ids=None, sync: bool = False) -> None:
        data = np.asarray(data, np.float32)
        if row_ids is None:
            assert data.size == self._num_row * self._num_col
            if sync:
                self._table.Add(data)
            else:
                self._table.AddFireForget(data)
        else:
            row_ids = np.asarray(row_ids, np.int32)
            data = data.reshape(len(row_ids), self._num_col)
            if sync:
                self._table.AddRows(row_ids, data)
            else:
                self._table.AddFireForget(data, row_ids=row_ids)
