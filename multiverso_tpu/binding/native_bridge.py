"""Serve the native C ABI from the TPU runtime.

The reference's ``src/c_api.cpp:1-93`` wraps its *real* runtime, so every
foreign binding (Lua FFI ``binding/lua/init.lua:16-27``, C# P/Invoke, raw C)
reaches the actual parameter server. The TPU equivalent is this bridge: it
installs an ``MV_BackendVTable`` (native/include/mvt/c_api.h) into
``libmultiverso_tpu.so``, after which every ``MV_*`` table verb any native
caller in this process invokes routes to the SAME mesh-backed tables the
python surface uses — TPU/HBM storage, jit'd updaters, BSP sync included.
Without an installed bridge the library serves its self-contained native
CPU store (the fallback world for pure-native deployments).

Usage (embedding host process)::

    import multiverso_tpu as mv
    from multiverso_tpu.binding import native_bridge
    mv.MV_Init(["-num_workers=2"])
    bridge = native_bridge.install()     # native callers now reach the mesh
    ...  # load Lua/C#/C code in-process; it calls MV_* as usual
    bridge.uninstall()
    mv.MV_ShutDown()

The bridge may also be installed *before* any world exists; the first
native ``MV_Init`` then brings up the python world (flags forwarded) and
the matching native ``MV_ShutDown`` tears it down.

Threading: callbacks arrive on arbitrary native threads; ctypes enters the
GIL per call, and the table engine serializes state behind its actor
mailbox, so no extra locking is needed here. Each call runs under
``Zoo.worker_context(worker_id)`` with the caller thread's bound worker id
(MV_SetThreadWorkerId), preserving per-worker updater state (AdaGrad/
DCASGD) and BSP clock attribution across the ABI.
"""

from __future__ import annotations

import ctypes
import threading
import traceback
from typing import Dict, Optional

import numpy as np

from multiverso_tpu.utils.log import Log


# named callback types: the single source of truth for the vtable layout
# (field order below and callback construction in install() both use these)
INIT_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.POINTER(ctypes.c_int),
                           ctypes.POINTER(ctypes.c_char_p))
VOID_FN = ctypes.CFUNCTYPE(ctypes.c_int)
NEW_TABLE_FN = ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_int64,
                                ctypes.c_int64, ctypes.c_int32)
GET_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_int64,
                          ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
                          ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
                          ctypes.c_int32)
ADD_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_int64,
                          ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
                          ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
                          ctypes.c_int32, ctypes.c_int32,
                          ctypes.POINTER(ctypes.c_float))
URI_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_int64, ctypes.c_char_p)


class MV_BackendVTable(ctypes.Structure):
    """Mirror of the C struct (native/include/mvt/c_api.h)."""

    _fields_ = [
        ("init", INIT_FN),
        ("shutdown", VOID_FN),
        ("barrier", VOID_FN),
        ("num_workers", VOID_FN),
        ("new_table", NEW_TABLE_FN),
        ("get", GET_FN),
        ("add", ADD_FN),
        ("store", URI_FN),
        ("load", URI_FN),
    ]


class _Entry:
    __slots__ = ("worker", "server", "rows", "cols", "is_array")

    def __init__(self, worker, server, rows: int, cols: int, is_array: bool):
        self.worker = worker
        self.server = server
        self.rows = rows
        self.cols = cols
        self.is_array = is_array


class NativeBridge:
    """Holds the installed vtable (and the callback objects alive)."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        self._tables: Dict[int, _Entry] = {}
        self._tables_lock = threading.Lock()  # id allocation only
        self._owns_world = False
        self._vtable: Optional[MV_BackendVTable] = None

    # -- callback bodies (exceptions must not cross the FFI) ----------------

    def _guard(self, fn, *args, err=-1):
        try:
            return fn(*args)
        except Exception:  # noqa: BLE001 - FFI boundary
            Log.Error("native_bridge: %s", traceback.format_exc())
            return err

    def _init(self, argc, argv) -> int:
        from multiverso_tpu.zoo import Zoo
        import multiverso_tpu as core
        if Zoo.Get().started:
            return 0  # embedding host already owns the world
        args = []
        if argc and argv:
            args = [argv[i].decode() for i in range(1, argc[0])
                    if argv[i] is not None]
        core.MV_Init(args)
        self._owns_world = True
        return 0

    def _shutdown(self) -> int:
        import multiverso_tpu as core
        if self._owns_world:
            core.MV_ShutDown()
            self._owns_world = False
        self._tables.clear()
        return 0

    def _barrier(self) -> int:
        # the native ABI's MV_Barrier is a drain ping (c_api.cc: happens-
        # before for submitted ops, callable from any single thread) — NOT
        # the python surface's worker-thread-collective MV_Barrier, which
        # would deadlock a lone native caller in a multi-worker world
        from multiverso_tpu.zoo import Zoo
        Zoo.Get().DrainServer()
        return 0

    def _num_workers(self) -> int:
        import multiverso_tpu as core
        return core.MV_NumWorkers()

    def _new_table(self, rows: int, cols: int, is_array: int) -> int:
        import multiverso_tpu as core
        from multiverso_tpu.zoo import Zoo
        from multiverso_tpu.tables import ArrayTableOption, MatrixTableOption
        if is_array:  # MV_NewArrayTable; a 1xN MATRIX keeps row verbs
            worker = core.MV_CreateTable(ArrayTableOption(size=int(cols)))
        else:
            worker = core.MV_CreateTable(
                MatrixTableOption(num_rows=int(rows), num_cols=int(cols)))
        server = Zoo.Get().server_tables[worker.table_id]
        # MV_CreateTable releases the GIL internally (device placement),
        # so concurrent creations need the id allocation locked
        with self._tables_lock:
            bid = len(self._tables)
            self._tables[bid] = _Entry(worker, server, int(rows), int(cols),
                                       bool(is_array))
        return bid

    def _ids(self, row_ids, n_rows) -> Optional[np.ndarray]:
        if not row_ids or n_rows == 0:
            return None
        return np.ctypeslib.as_array(row_ids, shape=(n_rows,)).copy()

    def _get(self, table, row_ids, n_rows, out, n_floats, worker_id) -> int:
        from multiverso_tpu.zoo import Zoo
        entry = self._tables[table]
        ids = self._ids(row_ids, n_rows)
        with Zoo.Get().worker_context(worker_id):
            if ids is None:
                result = entry.worker.Get()
            else:
                result = entry.worker.GetRows(ids.astype(np.int32))
        flat = np.ascontiguousarray(result, np.float32).reshape(-1)
        if flat.size != n_floats:
            raise ValueError(f"get size mismatch: table has {flat.size} "
                             f"floats, caller buffer {n_floats}")
        ctypes.memmove(out, flat.ctypes.data, flat.size * 4)
        return 0

    def _add(self, table, row_ids, n_rows, data, n_floats, is_async,
             worker_id, add_opt) -> int:
        from multiverso_tpu.updaters.base import AddOption
        from multiverso_tpu.zoo import Zoo
        entry = self._tables[table]
        ids = self._ids(row_ids, n_rows)
        # copy: an async caller may reuse its buffer the moment we return
        values = np.ctypeslib.as_array(data, shape=(int(n_floats),)).copy()
        # {momentum, lr, rho, lambda} from MV_SetThreadAddOption; the
        # c_api contract says never NULL — surface a violation loudly
        if not add_opt:
            raise ValueError("add_opt must not be NULL (c_api.h contract)")
        opt = AddOption(worker_id=int(worker_id), momentum=add_opt[0],
                        learning_rate=add_opt[1], rho=add_opt[2],
                        lambda_=add_opt[3])
        with Zoo.Get().worker_context(worker_id):
            if ids is None:
                if values.size != entry.rows * entry.cols:
                    raise ValueError("add size mismatch")
                if not entry.is_array:
                    values = values.reshape(entry.rows, entry.cols)
                if is_async:
                    entry.worker.AddFireForget(values, option=opt)
                else:
                    entry.worker.Add(values, option=opt)
            else:
                values = values.reshape(len(ids), entry.cols)
                ids = ids.astype(np.int32)
                if is_async:
                    entry.worker.AddFireForget(values, row_ids=ids,
                                               option=opt)
                else:
                    entry.worker.AddRows(ids, values, option=opt)
        return 0

    def _store_load(self, table, uri: bytes, store: bool) -> int:
        import io as _io
        from multiverso_tpu.message import MsgType
        from multiverso_tpu.utils.io import Stream, StreamFactory
        from multiverso_tpu.zoo import Zoo
        entry = self._tables[table]
        name = uri.decode()

        # The snapshot/restore rides the engine mailbox through the one
        # shared cut helper (Zoo.CallOnEngine — native kStoreTable/
        # kLoadTable parity) so it is ordered against every applied Add;
        # a drain + caller-thread access could race Adds pushed after
        # the drain. But the URI IO itself (possibly slow remote
        # storage) stays on THIS thread: only the in-memory
        # serialize/deserialize occupies the engine.
        def submit(fn):
            Zoo.Get().CallOnEngine(
                MsgType.Request_StoreLoad, fn,
                f"native store/load of table {table}")

        if store:
            buf = _io.BytesIO()
            submit(lambda: entry.server.Store(Stream(buf, name)))
            with StreamFactory.GetStream(name, "wb") as s:
                s.Write(buf.getbuffer())  # zero-copy view of the snapshot
        else:
            with StreamFactory.GetStream(name, "rb") as s:
                raw = s.Read(-1)  # read-all
            submit(lambda: entry.server.Load(Stream(_io.BytesIO(raw), name)))
        return 0

    # -- install / uninstall ------------------------------------------------

    def install(self) -> "NativeBridge":
        g = self._guard
        self._vtable = MV_BackendVTable(
            init=INIT_FN(lambda argc, argv: g(self._init, argc, argv)),
            shutdown=VOID_FN(lambda: g(self._shutdown)),
            barrier=VOID_FN(lambda: g(self._barrier)),
            # error sentinel is NEGATIVE: err=1 would be indistinguishable
            # from a genuine 1-worker world (the C side MVT_CHECKs > 0)
            num_workers=VOID_FN(lambda: g(self._num_workers, err=-1)),
            new_table=NEW_TABLE_FN(
                lambda r, c, a: g(self._new_table, r, c, a)),
            get=GET_FN(lambda t, ids, n, out, nf, w:
                       g(self._get, t, ids, n, out, nf, w)),
            add=ADD_FN(lambda t, ids, n, d, nf, a, w, o:
                       g(self._add, t, ids, n, d, nf, a, w, o)),
            store=URI_FN(lambda t, uri: g(self._store_load, t, uri, True)),
            load=URI_FN(lambda t, uri: g(self._store_load, t, uri, False)),
        )
        self._lib.MV_RegisterBackend.restype = ctypes.c_int
        self._lib.MV_RegisterBackend.argtypes = [
            ctypes.POINTER(MV_BackendVTable)]
        rc = self._lib.MV_RegisterBackend(ctypes.byref(self._vtable))
        if rc != 0:
            raise RuntimeError("MV_RegisterBackend failed (world live?)")
        return self

    def uninstall(self) -> None:
        if self._vtable is None:
            return
        rc = self._lib.MV_RegisterBackend(None)
        if rc != 0:
            raise RuntimeError("cannot uninstall: native world still live")
        self._vtable = None
        self._tables.clear()


def install(lib: Optional[ctypes.CDLL] = None) -> NativeBridge:
    """Install the TPU backend into the native library (build/load it on
    demand). Returns the bridge; keep it alive while native code runs."""
    if lib is None:
        from multiverso_tpu import native as native_mod
        lib = native_mod.lib()
        if lib is None:
            raise RuntimeError("native library unavailable (no toolchain?)")
    return NativeBridge(lib).install()
