"""multiverso_tpu — a TPU-native parameter-server framework.

A ground-up re-design of the capabilities of Microsoft Multiverso
(C++11 MPI/ZMQ parameter server; see /root/reference) for TPU hardware:

* table shards live as JAX arrays in HBM, sharded over a ``jax.sharding.Mesh``
  "server" axis (replacing per-process C++ heap shards),
* server-side updaters (add / SGD / momentum / per-worker AdaGrad) run as
  jit'd XLA ops on the shards (replacing OpenMP loops,
  reference src/updater/updater.cpp:21-29),
* the Get/Add push-pull runs through sharded gather / scatter-add
  computations whose cross-chip movement is XLA ICI collectives
  (replacing MPI/ZMQ message transports, reference src/net*),
* ``MV_Aggregate`` model-average mode maps to ``psum`` over the mesh
  (replacing MPI_Allreduce and the Bruck/recursive-halving
  AllreduceEngine, reference src/net/allreduce_engine.cpp),
* the async / BSP(sync) / model-average consistency modes are preserved
  behaviorally, including the SyncServer vector-clock guarantee
  (reference src/server.cpp:60-67).

Public API mirrors the reference's ``MV_*`` surface
(reference include/multiverso/multiverso.h).

The ``MV_*`` surface is LAZY (PEP 562): importing the bare package does
not pull ``api`` → ``zoo`` → jax. That is what lets the replica plane's
jax-free reader processes (``multiverso_tpu/replica/replica.py``) import
their subpackage from this package without jax ever entering the import
graph — the first ``multiverso_tpu.MV_*`` attribute access triggers the
full training-plane import exactly as before.
"""

#: everything the eager ``from multiverso_tpu.api import ...`` used to
#: re-export — resolved on first attribute access
_API_NAMES = (
    "MV_Init",
    "MV_ShutDown",
    "MV_Barrier",
    "MV_Rank",
    "MV_Size",
    "MV_NumWorkers",
    "MV_NumServers",
    "MV_WorkerId",
    "MV_ServerId",
    "MV_WorkerIdToRank",
    "MV_ServerIdToRank",
    "MV_CreateTable",
    "MV_SetFlag",
    "MV_MultiAdd",
    "MV_MultiAddAsync",
    "MV_MultiGet",
    "MV_MultiGetAsync",
    "MV_Aggregate",
    "MV_NetBind",
    "MV_NetConnect",
    "MV_NetFinalize",
    "MV_SaveCheckpoint",
    "MV_LoadCheckpoint",
    "MV_PublishSnapshot",
    "MV_ServingLookup",
    "MV_PinVersion",
    "MV_UnpinVersion",
    "MV_StartProfiler",
    "MV_StopProfiler",
    "MV_MetricsSnapshot",
    "MV_DumpTrace",
    "MV_DumpFlightRecorder",
    "MV_DumpDiagnostics",
    "MV_ElasticSync",
    "MV_ElasticLeave",
    "MV_ElasticJoin",
    "MV_ElasticEpoch",
    "MV_ElasticMembers",
    "MV_PolicySync",
    "MV_PolicyReport",
    "MV_PolicyKill",
    "MV_WorkerContext",
)

__version__ = "0.1.0"

__all__ = list(_API_NAMES) + ["__version__"]


def __getattr__(name: str):
    if name in _API_NAMES:
        from multiverso_tpu import api
        value = getattr(api, name)
        globals()[name] = value     # cache: one import per process
        return value
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_API_NAMES))
