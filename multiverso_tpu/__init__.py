"""multiverso_tpu — a TPU-native parameter-server framework.

A ground-up re-design of the capabilities of Microsoft Multiverso
(C++11 MPI/ZMQ parameter server; see /root/reference) for TPU hardware:

* table shards live as JAX arrays in HBM, sharded over a ``jax.sharding.Mesh``
  "server" axis (replacing per-process C++ heap shards),
* server-side updaters (add / SGD / momentum / per-worker AdaGrad) run as
  jit'd XLA ops on the shards (replacing OpenMP loops,
  reference src/updater/updater.cpp:21-29),
* the Get/Add push-pull runs through sharded gather / scatter-add
  computations whose cross-chip movement is XLA ICI collectives
  (replacing MPI/ZMQ message transports, reference src/net*),
* ``MV_Aggregate`` model-average mode maps to ``psum`` over the mesh
  (replacing MPI_Allreduce and the Bruck/recursive-halving
  AllreduceEngine, reference src/net/allreduce_engine.cpp),
* the async / BSP(sync) / model-average consistency modes are preserved
  behaviorally, including the SyncServer vector-clock guarantee
  (reference src/server.cpp:60-67).

Public API mirrors the reference's ``MV_*`` surface
(reference include/multiverso/multiverso.h).
"""

from multiverso_tpu.api import (  # noqa: F401
    MV_Init,
    MV_ShutDown,
    MV_Barrier,
    MV_Rank,
    MV_Size,
    MV_NumWorkers,
    MV_NumServers,
    MV_WorkerId,
    MV_ServerId,
    MV_WorkerIdToRank,
    MV_ServerIdToRank,
    MV_CreateTable,
    MV_SetFlag,
    MV_Aggregate,
    MV_NetBind,
    MV_NetConnect,
    MV_NetFinalize,
    MV_SaveCheckpoint,
    MV_LoadCheckpoint,
    MV_PublishSnapshot,
    MV_ServingLookup,
    MV_PinVersion,
    MV_UnpinVersion,
    MV_StartProfiler,
    MV_StopProfiler,
    MV_MetricsSnapshot,
    MV_DumpTrace,
    MV_DumpFlightRecorder,
    MV_DumpDiagnostics,
    MV_ElasticSync,
    MV_ElasticLeave,
    MV_ElasticJoin,
    MV_ElasticEpoch,
    MV_ElasticMembers,
    MV_WorkerContext,
)

__version__ = "0.1.0"
