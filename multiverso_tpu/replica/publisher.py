"""Trainer-side replica fan-out: journals at the cut, ships off it.

Two halves, split exactly where the SPMD stream's soundness demands:

* :func:`note_publish` runs ON the engine thread INSIDE the publish
  cut (serving/snapshot._capture_all, every stream fenced): it drains
  each table's publish journal into the dirty-set record for the new
  version and kicks the fan-out thread. Local numpy only, zero
  collectives, a few microseconds — the cut pays nothing for fan-out.
* The fan-out THREAD does everything slow: polls the subscription
  roster (coordinator RPC), encodes base/delta blobs from the
  IMMUTABLE retained snapshots (never the live tables), and ships them
  — same-host subscribers over a dedicated per-replica shm ring
  (PR 9's transport, 2-proc point-to-point, its own session token so
  it can never collide with the engine wire's channels), cross-host
  subscribers over a dedicated round-24 tcp wire stream (the reader's
  join token carries its listener endpoint; the first ship dials it),
  and relay subscribers through the coordinator's mailbox.

Failure isolation: a replica that stalls or dies costs ONE bounded
ring wait (lease-derived ``timeout_s`` passed straight to
``ShmWire.exchange``) and is then evicted — the SPMD world never
blocks on the read tier. Eviction is driven by the same heartbeat
lease machinery SPMD members ride (coordinator ``replica_*`` ops).

Delta policy: a subscriber acked at version V gets
``delta(V -> latest)`` when every interval dirty set V+1..latest is
still retained (retention tracks ``-mv_serving_keep`` plus slack),
else a fresh base. The delta applies to any replica state in
``[V, latest]`` (delta.py's applicability rule), so ack lag can never
corrupt a mirror — at worst it ships a few already-applied rows.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional

from multiverso_tpu.failsafe.errors import (ActorDied, DeadlineExceeded,
                                            WireCorruption)
from multiverso_tpu.parallel import compress
from multiverso_tpu.replica import delta as rdelta
from multiverso_tpu.telemetry import fleet as tfleet
from multiverso_tpu.telemetry import flight as tflight
from multiverso_tpu.telemetry import metrics as tmetrics
from multiverso_tpu.utils.configure import (GetFlag, cached_bool_flag,
                                            cached_int_flag)
from multiverso_tpu.utils.log import CHECK, Log

_fanout_flag = cached_bool_flag("mv_replica_fanout", False)
_ring_flag = cached_int_flag("mv_replica_ring_bytes", 8 << 20)
_keep_flag = cached_int_flag("mv_serving_keep", 2)

#: fan-out thread idle poll (roster refresh between publishes — new
#: subscribers get their base without waiting for the next publish)
_POLL_S = 0.25

#: control-RPC bound for the fan-out thread's coordinator calls
_RPC_TIMEOUT_S = 10.0


def _lease_s() -> float:
    lease = float(GetFlag("mv_replica_lease_s"))
    if lease > 0:
        return lease
    from multiverso_tpu.failsafe import deadline as fdeadline
    dl = fdeadline.deadline_s()
    return max(2.0, 0.8 * dl) if dl > 0 else 5.0


class ReplicaPublisher:
    """Per-process fan-out state. Only the fan-out OWNER rank (boot
    rank 0 — the rank that already hosts every coordinator) journals
    and ships; other ranks keep the plane object as an inert flag
    holder so the hooks stay one attribute read."""

    def __init__(self, zoo, active: bool):
        self.zoo = zoo
        self.active = active
        self.client = None              #: coordinator RPC client
        self.endpoint: Optional[str] = None
        self._own_coordinator = None    #: hosted here when no elastic
        self.lease_s = _lease_s()
        self._lock = threading.Lock()
        #: version -> {tid: dirty descriptor} (interval prev->version)
        self._dirty: "collections.OrderedDict[int, Dict]" = \
            collections.OrderedDict()
        self.latest = -1
        self.fanout_bytes = 0
        self._subs: Dict[int, dict] = {}    #: rid -> local ship state
        self._roster: List[dict] = []       #: last roster (healthz)
        #: fleet identity for the rollup riding the roster poll —
        #: stamped by start_plane on the app thread (the fan-out thread
        #: must never touch multihost: device-work-domain law)
        self.member_label = "rank0"
        #: content-addressed encode cache (round 21): N same-lag
        #: subscribers share ONE encode+compress. Keyed by (kind,
        #: prev_version, version, codec config); entries for superseded
        #: versions are dropped at the first encode against a newer
        #: snapshot. Fan-out-thread-only state (never locked).
        self._enc_cache: Dict[tuple, bytes] = {}
        self._enc_version = -1
        self.max_lag = 0
        #: last seen client failover generation: a takeover voids the
        #: per-subscriber ship dedup (the successor's mailboxes are
        #: empty and needs_base is re-armed server-side — but our
        #: last_sent would skip the re-ship entirely)
        self._failover_gen = 0
        self._kick = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # EAGER registration (the PR 6 rule): the replica family
        # scrapes at zero from the first /metrics read
        self._t_bytes = tmetrics.counter("replica.fanout_bytes")
        self._t_blobs = tmetrics.counter("replica.fanout_blobs")
        self._t_enc_reuse = tmetrics.counter("replica.fanout_encode_reuse")
        self._t_evicted = tmetrics.counter("replica.evictions")
        self._t_subs = tmetrics.gauge("replica.subscribers")
        self._t_lag = tmetrics.gauge("replica.lag_versions")

    # -- engine-thread half (inside the cut) --------------------------------

    def record_cut(self, engine, snap) -> None:
        """Drain every table's journal at the fenced cut: the dirty
        descriptor for the interval (previous publish, ``snap``]."""
        descs: Dict[int, dict] = {}
        for tid, table in enumerate(engine.store_):
            if tid not in snap.tables:
                # family without a serving export: nothing to fan out,
                # but its journal still DRAINS (a kv write-set left
                # undrained would grow without bound across cuts)
                j = getattr(table, "_pub_journal", None)
                if j is not None:
                    j.drain()
                continue
            journal = getattr(table, "_pub_journal", None)
            if journal is None:
                # registered before the plane was up (or a family that
                # grew an export later): no coverage for THIS interval
                # — the merge turns that into a full payload, and the
                # fresh journal covers every later interval
                table._pub_journal = rdelta.journal_for_table(table)
                descs[tid] = {"kind": "all"}
            else:
                descs[tid] = journal.drain()
        keep = max(4, _keep_flag() + 2)
        with self._lock:
            self._dirty[snap.version] = descs
            while len(self._dirty) > keep:
                self._dirty.popitem(last=False)
            self.latest = snap.version
        self._kick.set()

    def _merged_descs(self, acked: int,
                      target_snap) -> Optional[Dict[int, dict]]:
        """Per-table dirty union over (acked, target]; None = a base is
        needed (some interval's record already pruned)."""
        with self._lock:
            need = range(acked + 1, target_snap.version + 1)
            if any(v not in self._dirty for v in need):
                return None
            per_version = [self._dirty[v] for v in need]
        out: Dict[int, dict] = {}
        for tid in target_snap.tables:
            # a tid absent from an interval's record did not exist at
            # that cut -> merge_descriptors(None) -> full payload
            out[tid] = rdelta.merge_descriptors(
                [d.get(tid) for d in per_version])
        return out

    # -- fan-out thread -----------------------------------------------------

    def start(self) -> None:
        if not self.active or self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run,
                                        name="mv-replica-fanout",
                                        daemon=True)
        self._thread.start()

    def _run(self) -> None:  # mv-lint: ok(never-collective): the only reachable "collectives" are ShmWire.exchange / TcpWire.exchange on a per-replica 2-proc fan-out channel with its own session token — a point-to-point stream to a non-SPMD reader, bounded by an explicit lease timeout; no SPMD rank ever participates, so it cannot interleave with the engine's window streams
        while not self._stop.is_set():
            self._kick.wait(_POLL_S)
            self._kick.clear()
            if self._stop.is_set():
                return
            try:
                self._tick()
            except Exception as exc:    # the fan-out must never die
                if self._stop.is_set():
                    return      # shutdown closed a wire under a stuck
                                # ship — the abandonment is already
                                # logged by stop()
                Log.Error("replica fan-out tick failed: %r", exc)

    def _tick(self) -> None:
        from multiverso_tpu.serving import peek_plane
        try:
            # round 22: this trainer rank's fleet rollup rides the
            # roster poll that already flows every tick — the one
            # guaranteed control message even outside elastic runs.
            # Telemetry must never cost the fan-out: failure -> empty.
            rollup = tfleet.encode_rollup(tfleet.build_rollup(
                self.member_label, "trainer"))
        except Exception:
            rollup = b""
        resp = self.client.call(
            "replica_roster", timeout=_RPC_TIMEOUT_S,
            latest=self.latest if self.latest >= 0 else None,
            rollup=rollup)
        roster = resp["replicas"]
        gen = getattr(self.client, "failover_gen", 0)
        if gen != self._failover_gen:
            # coordinator failover: the successor replayed the op log
            # (roster + acked versions survive) but relay mailboxes
            # died with the primary — drop the local ship dedup so
            # every live subscriber gets re-shipped against its
            # replayed ack state on this very tick
            self._failover_gen = gen
            for st in self._subs.values():
                st["last_sent"] = -1
            Log.Error("replica fan-out: coordinator failover detected "
                      "(gen %d) — re-shipping every subscription "
                      "against the successor's replayed state", gen)
        plane = peek_plane()
        store = plane.store if plane is not None else None
        live = 0
        max_lag = 0
        for rec in roster:
            rid = rec["rid"]
            st = self._subs.setdefault(
                rid, {"wire": None, "last_sent": -1, "state": "live"})
            if rec["status"] != "live":
                if st["state"] == "live":
                    self._evict(rid, st, rec["status"])
                continue
            live += 1
            if store is None or store.latest_version() is None:
                continue
            snap = store.get(None)
            if rec["acked"] >= 0:
                # a never-acked subscriber is SYNCING, not lagging —
                # counting it from version 0 would read as the
                # trainer's whole history and fire spurious
                # replica_lag alerts on every join (the lease owns the
                # never-arrives case)
                max_lag = max(max_lag, snap.version - rec["acked"])
            if st["last_sent"] >= snap.version:
                continue
            try:
                blob, kind = self._encode_for(rec, snap)
                sent = self._ship(rec, st, blob, snap.version)
            except (ActorDied, DeadlineExceeded, WireCorruption,
                    OSError, ConnectionError) as exc:
                Log.Error("replica %d ship failed (%r) — evicting its "
                          "subscription", rid, exc)
                try:
                    self.client.call("replica_evict", rid=rid,
                                     timeout=_RPC_TIMEOUT_S)
                except Exception:
                    pass
                self._evict(rid, st, "dead")
                continue
            if not sent:
                # relay mailbox overflow: the coordinator dropped the
                # queue and flagged needs_base — leave last_sent alone
                # so the NEXT tick ships that base (a laggard resyncs;
                # it is never evicted for being slow)
                continue
            st["last_sent"] = snap.version
            self.fanout_bytes += len(blob)
            self._t_bytes.inc(len(blob))
            self._t_blobs.inc()
            tflight.record("replica.fanout", detail=f"r{rid} {kind} "
                           f"v{snap.version} {len(blob)}B")
        self._roster = roster
        self.max_lag = max_lag
        self._t_subs.set(float(live))
        self._t_lag.set(float(max_lag))

    def _encode_for(self, rec: dict, snap):
        """(blob, kind) for one subscriber against the newest retained
        snapshot — delta when the interval is fully journal-covered
        and the subscriber doesn't need a resync, else base. Encodes
        are CONTENT-ADDRESSED by (kind, prev_version, version, codec
        config): every same-lag subscriber this tick (and across
        ticks, until the version advances) reuses one encode+compress
        instead of re-walking the snapshot per subscriber."""
        acked = int(rec["acked"])
        if rec["needs_base"] or acked < 0 or acked >= snap.version:
            acked = -1          # every base rider shares one cache key
            descs = None
        else:
            descs = self._merged_descs(acked, snap)
            if descs is None:
                acked = -1      # interval pruned: resync with a base
        kind = "base" if descs is None else "delta"
        key = (kind, acked, snap.version, compress.config_token())
        if self._enc_version != snap.version:
            # superseded interval blobs can never be asked for again
            # (ships only ever target the NEWEST retained snapshot)
            self._enc_cache.clear()
            self._enc_version = snap.version
        blob = self._enc_cache.get(key)
        if blob is None:
            blob = (rdelta.encode_base(snap) if kind == "base"
                    else rdelta.encode_delta(snap, acked, descs))
            self._enc_cache[key] = blob
        else:
            self._t_enc_reuse.inc()
        return blob, kind

    def _ship(self, rec: dict, st: dict, blob: bytes,
              version: int) -> bool:
        """Ship one blob; returns False on a relay mailbox overflow
        (the laggard-resync signal — NOT a failure; ship errors
        raise)."""
        if rec["mode"] == "shm":
            wire = st["wire"]
            if wire is None:
                from multiverso_tpu.parallel.shm_wire import ShmWire
                wire = ShmWire(rec["token"], rank=0, nprocs=2,
                               channels=1,
                               data_bytes=rec["ring_bytes"]
                               or _ring_flag(),
                               payload_crc=False)
                wire.attach_peers()     # replica created its segment
                st["wire"] = wire       # before it joined
            wire.exchange(blob, 0,
                          timeout_s=max(2.0 * self.lease_s, 5.0))
            return True
        if rec["mode"] == "tcp":
            wire = st["wire"]
            if wire is None:
                # the replica's join token carries its listener
                # endpoint verbatim: session@host:port (the reader
                # bound BEFORE joining, so this first dial lands)
                from multiverso_tpu.parallel.tcp_wire import TcpWire
                session, _, ep = str(rec["token"]).partition("@")
                host, _, port = ep.rpartition(":")
                wire = TcpWire(session, rank=0, nprocs=2, channels=1,
                               data_bytes=rec["ring_bytes"]
                               or _ring_flag(),
                               payload_crc=False)
                wire.connect({1: [(host, int(port))]},
                             timeout_s=max(2.0 * self.lease_s, 5.0))
                st["wire"] = wire
            wire.exchange(blob, 0,
                          timeout_s=max(2.0 * self.lease_s, 5.0))
            return True
        resp = self.client.call("replica_put", rid=rec["rid"],
                                version=version, blob=blob,
                                timeout=_RPC_TIMEOUT_S)
        return not resp.get("overflow")

    def _evict(self, rid: int, st: dict, state: str) -> None:
        wire, st["wire"] = st["wire"], None
        if wire is not None:
            wire.close()
        if st["state"] == "live":
            st["state"] = state
            self._t_evicted.inc()
            tflight.record("replica.evict", detail=f"r{rid} {state}")
            Log.Info("replica plane: subscription r%d evicted (%s)",
                     rid, state)

    def stop(self) -> None:
        self._stop.set()
        self._kick.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            if self._thread.is_alive():
                Log.Error("replica fan-out thread stuck at shutdown — "
                          "abandoning its daemon thread")
        for st in self._subs.values():
            wire, st["wire"] = st["wire"], None
            if wire is not None:
                wire.close()
        if self._own_coordinator is not None:
            self._own_coordinator.stop()
            self._own_coordinator = None


_publisher: Optional[ReplicaPublisher] = None
_pub_lock = threading.Lock()


def start_plane(zoo) -> bool:
    """Bring up the fan-out when ``-mv_replica_fanout`` is set
    (Zoo.Start, after the elastic plane so its coordinator can be
    reused). Rank 0 owns the fan-out; other ranks hold an inert plane
    object. Returns True when fan-out is active on this rank."""
    global _publisher
    if not _fanout_flag():
        return False
    CHECK(zoo.server_engine is not None,
          "-mv_replica_fanout needs the server engine (not -ma mode): "
          "the dirty journals drain at engine publish cuts")
    from multiverso_tpu import elastic
    from multiverso_tpu.elastic.coordinator import Coordinator, MemberClient
    from multiverso_tpu.parallel import multihost
    me = multihost.process_index()
    active = me == 0
    pub = ReplicaPublisher(zoo, active)
    pub.member_label = f"rank{me}"
    if active:
        addr = str(GetFlag("mv_replica_addr"))
        ep = elastic.coordinator_endpoint()
        endpoints = None
        if addr:
            host, _, port_s = addr.rpartition(":")
            CHECK(host and port_s.isdigit(),
                  f"-mv_replica_addr must be host:port, got {addr!r}")
            pub._own_coordinator = Coordinator(host, int(port_s),
                                               pub.lease_s)
            host, port = host, pub._own_coordinator.port
        elif ep is not None:
            host, port = ep     # ride the elastic coordinator —
            # and its ORDERED failover list: the relay must follow
            # the membership authority to its successor
            endpoints = elastic.coordinator_endpoints()
        else:
            CHECK(multihost.process_count() <= 1,
                  "-mv_replica_fanout in a multi-process world needs "
                  "-mv_elastic (to reuse its coordinator) or an "
                  "explicit -mv_replica_addr")
            pub._own_coordinator = Coordinator("127.0.0.1", 0,
                                               pub.lease_s)
            host, port = "127.0.0.1", pub._own_coordinator.port
        pub.client = MemberClient(host, port, me, pub.lease_s,
                                  endpoints=endpoints)
        pub.endpoint = f"{host}:{port}"
        pub.start()
        Log.Info("replica plane: fan-out up at %s (lease %.1fs)",
                 pub.endpoint, pub.lease_s)
    with _pub_lock:
        _publisher = pub
    return active


def shutdown_plane() -> None:
    global _publisher
    with _pub_lock:
        pub, _publisher = _publisher, None
    if pub is not None:
        pub.stop()


def note_publish(engine, snap) -> None:
    """The publish-cut hook — see :meth:`ReplicaPublisher.record_cut`.
    One attribute read when the plane is off or this rank is not the
    fan-out owner."""
    pub = _publisher
    if pub is None or not pub.active:
        return
    pub.record_cut(engine, snap)


def maybe_attach_journal(server_table) -> None:
    """RegisterTable hook: give the table its publish journal so the
    FIRST interval after a publish is covered from registration (a
    late-attached journal forces one full-payload fan-out)."""
    pub = _publisher
    if pub is None or not pub.active:
        return
    if getattr(server_table, "_pub_journal", None) is None:
        server_table._pub_journal = rdelta.journal_for_table(server_table)


def publisher_endpoint() -> Optional[str]:
    """host:port replicas should join (tests/bench); None when off."""
    pub = _publisher
    return pub.endpoint if pub is not None else None


def status_report() -> Optional[dict]:
    """Local fan-out view for /healthz: one line per known replica
    (departed ones included — operators see who left). Served from the
    fan-out thread's cached roster; never an RPC, never collective."""
    pub = _publisher
    if pub is None:
        return None
    subs = []
    for rec in pub._roster:
        # lag is meaningful only for live, at-least-once-acked
        # subscribers — a joiner mid-first-base reports None (syncing)
        lag = (pub.latest - rec["acked"]
               if pub.latest >= 0 and rec["acked"] >= 0
               and rec["status"] == "live" else None)
        # round 22 fix: a frozen telemetry feed used to render here as
        # healthy-looking stale numbers — now each line carries the
        # rollup age and an explicit stale verdict (vs -mv_fleet_stale_s)
        age = rec.get("rollup_age_s")
        subs.append({"rid": rec["rid"], "mode": rec["mode"],
                     "state": rec["status"], "acked": rec["acked"],
                     "lag_versions": lag, "rollup_age_s": age,
                     "rollup_stale": bool(
                         rec["status"] == "live" and age is not None
                         and age > tfleet.stale_s())})
    return {"active": pub.active, "endpoint": pub.endpoint,
            "latest": pub.latest if pub.latest >= 0 else None,
            "fanout_bytes": pub.fanout_bytes, "max_lag": pub.max_lag,
            "subscribers": subs}


def peek_sample() -> Optional[dict]:
    """Watchdog probe: plain local attrs, refreshed by the fan-out
    tick."""
    pub = _publisher
    if pub is None or not pub.active:
        return None
    live = sum(1 for r in pub._roster if r["status"] == "live")
    sample = {"replica_subscribers": live,
              "replica_lag_versions": pub.max_lag}
    # round 22: the replica_lag rule degrades to a stale-warn instead
    # of trusting frozen numbers — feed it the oldest live rollup age
    ages = [r["rollup_age_s"] for r in pub._roster
            if r["status"] == "live"
            and r.get("rollup_age_s") is not None]
    if ages:
        sample["replica_rollup_age_max_s"] = max(ages)
    return sample


def ledger_bytes() -> Optional[dict]:
    """Accounting probe: journal bitmaps/write-sets on the live tables
    plus the retained per-version dirty descriptors."""
    pub = _publisher
    if pub is None or not pub.active:
        return None
    journal = 0
    eng = pub.zoo.server_engine
    if eng is not None:
        for table in getattr(eng, "store_", []):
            j = getattr(table, "_pub_journal", None)
            if j is not None:
                journal += j.nbytes()
    with pub._lock:
        dirty = sum(rdelta.descriptor_nbytes(d)
                    for descs in pub._dirty.values()
                    for d in descs.values())
    return {"journal_bytes": journal, "dirty_set_bytes": dirty,
            "retained_versions": len(pub._dirty)}
