"""Replica reader process: a jax-free serving tier fed by fan-out.

Run as ``python -m multiverso_tpu.replica.replica --addr HOST:PORT``
against a trainer started with ``-mv_replica_fanout=true``. The
process:

1. **joins** the trainer's coordinator as a ``role=replica`` member —
   a heartbeat lease like an SPMD member's, but NO verb stream and no
   epoch membership; it never touches ``jax.distributed`` (this import
   path is numpy-only, asserted in :func:`main` and pinned by
   tests/test_packaging.py);
2. **receives** base+delta blobs — same-host over a dedicated shm ring
   (PR 9 transport, 2-proc point-to-point), cross-host over a
   dedicated round-24 tcp wire stream (this reader binds the listener
   BEFORE joining; the publisher dials it), or through the
   coordinator's relay mailbox — and applies them to local
   :class:`~multiverso_tpu.replica.delta.MirrorStore` mirrors;
3. **installs** each applied version into its own ``SnapshotStore``
   (the SAME class the trainer serves from, so the retention/pin
   contract — newest ``-mv_serving_keep`` live, pins nest — carries
   over verbatim) and **serves** lookups through a reused
   ``ServingFrontend``: admission bound, micro-batch coalescing into
   one fused union gather, typed ``ServingOverloaded`` shedding — all
   identical to in-process serving, host gather path only;
4. **answers** a tiny length-prefixed FLAT-framed TCP protocol
   (:class:`ReplicaClient`): ``lookup`` / ``status`` / ``pin`` /
   ``unpin`` — the QPS surface the bench drives. Round 19: the frames
   ride :mod:`multiverso_tpu.parallel.flat` (the window wire's
   header+raw-segments grammar, sealed with the versioned CRC32C
   trailer) instead of pickled dicts — id vectors ship as raw array
   segments and result rows decode ZERO-COPY (``np.frombuffer`` views
   into the received buffer), the ROADMAP's named "next 10x" for the
   read tier.

Lifecycle is lease-symmetric: the trainer evicts a replica whose lease
expires; the replica exits when its heartbeats report eviction or the
coordinator stays unreachable (trainer gone). Neither side ever blocks
the SPMD stream on the other.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import socketserver
import struct
import sys
import threading
import time
from typing import List, Optional

import numpy as np

from multiverso_tpu.elastic import dialer as _dialer
from multiverso_tpu.elastic.coordinator import MemberClient, _recv_exact
from multiverso_tpu.failsafe.errors import TransientError
from multiverso_tpu.parallel import compress, flat
from multiverso_tpu.replica import delta as rdelta
from multiverso_tpu.serving.frontend import ServingFrontend
from multiverso_tpu.serving.store import SnapshotStore
from multiverso_tpu.telemetry import fleet as tfleet
from multiverso_tpu.telemetry import metrics as tmetrics
from multiverso_tpu.telemetry import trace as ttrace
from multiverso_tpu.utils.configure import SetCMDFlag
from multiverso_tpu.utils.log import CHECK, Log

#: hold window for an UNREACHABLE coordinator: floor seconds and a
#: multiple of the lease, whichever is longer. A coordinator failover
#: (standby lease expiry + log replay + clients walking the endpoint
#: list) fits comfortably inside; a trainer that is actually gone still
#: ends the reader, just not on the first refused connect. Eviction is
#: a different verdict entirely: an "evicted" ANSWER exits immediately.
_HOLD_FLOOR_S = 20.0
_HOLD_LEASES = 6.0


def unreachable_verdict(silent_s: float, hold_s: float) -> str:
    """The hold-vs-evict boundary, as a pure function so the unit test
    pins it: an unreachable coordinator means **hold** (keep retrying —
    a failover window looks exactly like this) until the silence
    reaches ``hold_s``, and only then **die**. Exactly at the boundary
    is "die" (the window is a closed bound, like the lease)."""
    return "die" if silent_s >= hold_s else "hold"

#: how long the shm attach retries while the publisher discovers this
#: subscription and creates its ring segment
_ATTACH_TIMEOUT_S = 60.0

_FLEN = struct.Struct("<I")

#: cap on one lookup frame (guards the length prefix against reading
#: garbage as a gigabyte allocation — the coordinator frame posture)
_MAX_LOOKUP_FRAME = 1 << 31


def _send_flat(sock: socket.socket, obj) -> None:
    """One length-prefixed flat protocol frame (parallel/flat.py:
    header + raw array segments + the versioned seal). Replaced the
    pickled frames in round 19 — pickle walked and copied every result
    buffer twice per lookup; the flat frame writes array bytes once and
    the far side decodes them zero-copy."""
    blob = flat.encode_frame(obj)
    sock.sendall(_FLEN.pack(len(blob)) + blob)


def _recv_flat(sock: socket.socket):
    n = _FLEN.unpack(_recv_exact(sock, 4))[0]
    CHECK(0 < n < _MAX_LOOKUP_FRAME,
          f"replica lookup frame length insane: {n}")
    return flat.decode_frame(_recv_exact(sock, n))


class _LookupServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True
    replica: "Replica"


class _LookupHandler(socketserver.BaseRequestHandler):
    """Replica lookup serve loop — the read tier's QPS surface.
    Registered as a never-collective ROOT (analysis/collective.py):
    this process has no SPMD stream at all, and the handler must keep
    it that way — snapshot gathers through the reused frontend only.

    Connections are PERSISTENT (frame in, frame out, until the client
    closes): a connect per lookup caps the client at the TCP handshake
    rate, and the whole point of this tier is lookup QPS."""

    def handle(self):
        while True:
            try:
                req = _recv_flat(self.request)
            except (ConnectionError, OSError):
                return          # client closed — normal end of stream
            try:
                resp = self.server.replica._serve_op(req)
            except Exception as exc:
                resp = {"err": type(exc).__name__, "msg": str(exc)}
            try:
                _send_flat(self.request, resp)
            except OSError:
                return


class Replica:
    def __init__(self, host: str, port: int, *, mode: str = "shm",
                 serve_port: int = 0, ring_bytes: int = 8 << 20,
                 lease_s: float = 5.0, endpoints=None):
        CHECK(mode in ("shm", "tcp", "relay"),
              f"unknown replica mode {mode!r}")
        self.mode = mode
        self.ring_bytes = int(ring_bytes)
        self.lease_s = float(lease_s)
        self.hold_s = max(_HOLD_FLOOR_S, _HOLD_LEASES * self.lease_s)
        self.client = MemberClient(host, port, 0, self.lease_s,
                                   endpoints=endpoints)
        self.store = SnapshotStore()
        self.frontend = ServingFrontend(self.store)
        self.mirrors = rdelta.MirrorStore()
        self.rid: Optional[int] = None
        self.latest_known = -1
        #: guards latest_known/exit_code: the heartbeat thread and the
        #: main apply loop both advance latest_known with a
        #: read-max-write — unlocked, a stale read could regress it and
        #: fire a spurious lag gauge (found by mvlint
        #: cross-domain-state, regression-tested in test_replica)
        self._state_lock = threading.Lock()
        self.applies = 0
        self._wire = None
        self._serve_port = int(serve_port)
        self._server: Optional[socketserver.ThreadingTCPServer] = None
        self._stop = threading.Event()
        self.exit_code: Optional[int] = None
        # EAGER registration (the PR 6 rule)
        self._t_lag = tmetrics.gauge("replica.lag_versions")
        self._t_apply = tmetrics.histogram("replica.apply_s")
        self._t_applies = tmetrics.counter("replica.applies")
        self._t_recv = tmetrics.counter("replica.recv_bytes")
        self._t_mirror = tmetrics.gauge("mem.replica.mirror_bytes")
        self._d_serve = tmetrics.digest("digest.replica.serve_s")

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        token = ""
        if self.mode == "shm":
            from multiverso_tpu.parallel.shm_wire import ShmWire
            token = f"{os.getpid():x}{int(time.time() * 1e3) & 0xFFFF:x}"
            # our (rank 1) segment exists BEFORE the join lands, so the
            # publisher's first ship can attach immediately
            self._wire = ShmWire(token, rank=1, nprocs=2, channels=1,
                                 data_bytes=self.ring_bytes,
                                 payload_crc=False)
        elif self.mode == "tcp":
            from multiverso_tpu.parallel.tcp_wire import TcpWire
            session = f"{os.getpid():x}{int(time.time() * 1e3) & 0xFFFF:x}"
            # our (rank 1) listener is bound BEFORE the join lands, so
            # the publisher's first ship can dial immediately. The
            # listener endpoint rides the join's token field verbatim
            # (session@host:port) — the coordinator relays mode/token
            # untouched, so a REMOTE subscriber needs no coordinator
            # support beyond what shm already uses
            # assigned through a local: self._wire must keep ONE
            # statically inferred type (the wires share the exchange
            # contract; a conflicting ctor assignment would poison the
            # attribute and mv-lint's callgraph would fall back to
            # matching every .exchange in the package)
            wire = TcpWire(session, rank=1, nprocs=2, channels=1,
                           data_bytes=self.ring_bytes,
                           payload_crc=False)
            ep_host, ep_port = wire.listen_endpoints()[0]
            token = f"{session}@{ep_host}:{ep_port}"
            self._wire = wire
        resp = self.client.call_retry("replica_join", attempts=50,
                                      mode=self.mode, token=token,
                                      ring_bytes=self.ring_bytes,
                                      lease_s=self.lease_s)
        self.rid = int(resp["rid"])
        self.latest_known = int(resp.get("latest", -1))
        ttrace.set_process_label(f"multiverso replica r{self.rid}")
        self._start_serve_server()
        threading.Thread(target=self._hb_loop, name="mv-replica-hb",
                         daemon=True).start()
        Log.Info("replica r%d up: mode=%s, serving on 127.0.0.1:%d",
                 self.rid, self.mode, self.serve_port)

    @property
    def serve_port(self) -> int:
        return self._server.server_address[1] if self._server else -1

    def _die(self, code: int, why: str) -> None:
        Log.Error("replica r%s exiting (%d): %s", self.rid, code, why)
        with self._state_lock:
            self.exit_code = code
        self._stop.set()
        # the recv loop may be parked in an shm exchange with nothing
        # arriving — only a hard exit unblocks a standalone reader
        os._exit(code)

    def _hb_loop(self) -> None:
        first_fail: Optional[float] = None
        period = max(0.05, self.lease_s / 3.0)
        while not self._stop.wait(period):
            try:
                # round 22: the fleet rollup rides the lease beat that
                # already flows — zero new connections. Telemetry must
                # never cost the lease, so a rollup failure degrades to
                # an empty blob (the coordinator just sees no update).
                rollup = tfleet.encode_rollup(tfleet.build_rollup(
                    f"replica:{self.rid}", "replica"))
            except Exception:
                rollup = b""
            try:
                resp = self.client.call("replica_hb", rid=self.rid,
                                        rollup=rollup, timeout=5.0)
            except Exception:
                # UNREACHABLE is not EVICTED: a coordinator failover
                # looks exactly like this from here — hold (and keep
                # dialing the endpoint list, which is how we find the
                # successor) until the hold window says the trainer is
                # actually gone
                now = time.monotonic()
                if first_fail is None:
                    first_fail = now
                if unreachable_verdict(now - first_fail,
                                       self.hold_s) == "die":
                    self._die(3, "coordinator unreachable for "
                                 f"{now - first_fail:.1f}s — trainer "
                                 "gone")
                continue
            first_fail = None
            if resp.get("evicted"):
                self._die(4, "subscription evicted by the trainer")
            self._advance_latest(int(resp.get("latest", -1)))
            self._refresh_lag()

    def _advance_latest(self, version: int) -> None:
        """Monotonic max-merge of the newest version this replica has
        HEARD OF — written by the heartbeat thread (coordinator answer)
        and the apply loop (applied bundle), so the read-max-write must
        be atomic or a stale read regresses it."""
        with self._state_lock:
            self.latest_known = max(self.latest_known, version)

    def _refresh_lag(self) -> None:
        if self.latest_known >= 0:
            self._t_lag.set(float(max(
                0, self.latest_known - self.mirrors.version)))

    # -- the fan-in (apply) loop --------------------------------------------

    def _attach_ring(self) -> None:
        deadline = time.monotonic() + _ATTACH_TIMEOUT_S
        last: Exception = FileNotFoundError("never attempted")
        while time.monotonic() <= deadline:
            try:
                self._wire.attach_peers()
                return
            except Exception as exc:
                # the publisher creates its segment at first ship (one
                # roster poll, ~0.25s, after our join lands) — and the
                # engine's "attach after a world barrier" contract does
                # not exist here, so an attach can even land BETWEEN
                # the segment create and its magic store (a transient
                # foreign-layout CHECK). Both resolve by retrying.
                last = exc
                time.sleep(0.02)
        self._die(5, f"publisher never opened the fan-out ring "
                     f"(last attach error: {last!r})")

    def _await_publisher_dial(self) -> None:
        """tcp mode: rank 1 of 2 dials nobody — wait (bounded) for the
        publisher's inbound dial, which lands at its first ship (one
        roster tick, ~0.25s, after our join)."""
        try:
            self._wire.connect(None, timeout_s=_ATTACH_TIMEOUT_S)
        except Exception as exc:
            self._die(5, f"publisher never dialed the tcp fan-out "
                         f"stream ({exc!r})")

    def recv_loop(self) -> None:
        """Receive + apply until stopped. Runs on the main thread; the
        lookup server and heartbeats ride their own daemons."""
        if self.mode == "shm":
            self._attach_ring()
        elif self.mode == "tcp":
            self._await_publisher_dial()
        while not self._stop.is_set():
            if self.mode in ("shm", "tcp"):
                # parked between publishes; eviction/trainer death is
                # the heartbeat thread's exit path, not this wait's
                blob = self._wire.exchange(b"", 0)[0]
            else:
                try:
                    resp = self.client.call("replica_fetch",
                                            rid=self.rid, timeout=10.0)
                except (TransientError, ConnectionError, OSError):
                    continue        # quiet interval — keep parking
                if resp.get("evicted"):
                    self._die(4, "subscription evicted by the trainer")
                blob = resp["blob"]
            if blob:
                self._apply(blob)

    def _apply(self, blob: bytes) -> None:
        t0 = time.perf_counter()
        self._t_recv.inc(len(blob))
        bundle = rdelta.decode(blob)
        version = int(bundle["version"])
        if version <= self.mirrors.version:
            # idempotent re-delivery (publisher retry after an ack it
            # never saw): re-ack, never re-apply
            self._ack(self.mirrors.version)
            return
        snap = self.mirrors.apply(bundle)
        self.store.install(snap)
        self.applies += 1
        self._advance_latest(version)
        self._t_applies.inc()
        self._t_apply.observe(time.perf_counter() - t0)
        self._t_mirror.set(float(self.mirrors.mirror_bytes()))
        self._refresh_lag()
        self._ack(version)
        Log.Debug("replica r%s: applied %s v%d (%d tables)", self.rid,
                  bundle["kind"], version, len(snap.tables))

    def _ack(self, version: int) -> None:
        try:
            self.client.call_retry("replica_ack", rid=self.rid,
                                   version=version, timeout=5.0)
        except Exception as exc:    # the lease machinery owns liveness
            Log.Error("replica r%s: ack v%d failed: %r", self.rid,
                      version, exc)

    # -- the lookup serve surface -------------------------------------------

    def _start_serve_server(self) -> None:
        self._server = _LookupServer(("127.0.0.1", self._serve_port),
                                     _LookupHandler)
        self._server.replica = self
        threading.Thread(target=self._server.serve_forever,
                         kwargs={"poll_interval": 0.05},
                         name="mv-replica-serve", daemon=True).start()

    def _serve_op(self, req: dict) -> dict:
        # the optional trace context is popped BEFORE dispatch so op
        # handlers only ever see the verb's own keys; when present the
        # dispatch span parents under the caller's client span and the
        # merged timeline shows one tree across the process boundary
        tctx = req.pop(flat.TRACE_KEY, None)
        parent = (ttrace.SpanContext(int(tctx[0]), int(tctx[1]))
                  if tctx else None)
        op = req.get("op")
        t0 = time.perf_counter()
        with ttrace.span(f"replica.{op}", parent=parent, cat="server"):
            try:
                return self._dispatch_op(op, req)
            finally:
                self._d_serve.observe(time.perf_counter() - t0)

    def _dispatch_op(self, op, req: dict) -> dict:
        if op == "lookup":
            ids = req.get("ids")
            tid = int(req["table_id"])
            rows = self.frontend.lookup(
                tid,
                None if ids is None else np.asarray(ids),
                version=req.get("version"),
                deadline=req.get("deadline"))
            # -mv_compress + per-table lossy opt-in: f32 result rows
            # ride bf16 envelopes (flat 'q' tag); the client's eager
            # flat decode hands back a plain ndarray either way
            return {"rows": compress.pack_serve_rows(tid, rows)}
        if op == "status":
            return self.status()
        if op == "pin":
            return {"version": self.store.pin(int(req["version"]))}
        if op == "unpin":
            self.store.unpin(int(req["version"]))
            return {"ok": True}
        if op == "trace_dump":
            # this process's span buffer as Chrome trace JSON text —
            # the fleet merge CLI stitches several of these into one
            # wall-clock timeline. JSON (not flat values): the dump is
            # an offline artifact, not a hot-path payload.
            return {"trace_json": json.dumps(ttrace.to_chrome_trace())}
        CHECK(False, f"replica serve: unknown op {op!r}")

    def status(self) -> dict:
        return {
            "rid": self.rid, "mode": self.mode,
            "latest": self.store.latest_version(),
            "live_versions": self.store.live_versions(),
            "latest_known": self.latest_known,
            "lag_versions": (max(0, self.latest_known
                                 - self.mirrors.version)
                             if self.latest_known >= 0 else None),
            "applies": self.applies,
            "mirror_bytes": self.mirrors.mirror_bytes(),
            "jax_free": "jax" not in sys.modules,
        }


class ReplicaClient:
    """Client for the replica's lookup surface (tests/bench). Holds ONE
    persistent connection (a connect per lookup would cap throughput at
    the TCP handshake rate); reconnects once on a broken stream. A
    client instance serializes its calls under a lock — give each
    reader thread its own instance for concurrency (the server
    micro-batches across connections anyway).

    Round 19: requests/responses are flat frames — ``lookup`` ships its
    id vector as a raw array segment and the returned rows are a
    READ-ONLY zero-copy view into the receive buffer (copy before
    mutating, the window-wire contract)."""

    def __init__(self, host: str, port: int):
        self.host, self.port = host, int(port)
        # the shared dialer (single endpoint here): bounded connect
        # retries with jittered backoff instead of one-shot-fatal, and
        # the typed CoordinatorUnreachable on exhaustion — a reader
        # restarting its serve socket is not a client-fatal event
        self._dialer = _dialer.Dialer([(host, int(port))],
                                      what=f"replica-lookup:{port}")
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def close(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _call(self, timeout: float = 30.0, **req) -> dict:
        with ttrace.span(f"replica.{req.get('op')}", cat="client") as ctx:
            if ctx is not None:
                # trace context rides the frame as an OPTIONAL dict
                # entry — when tracing is off the key is absent and the
                # encoded frame stays byte-identical to pre-round-22
                req[flat.TRACE_KEY] = [ctx.trace_id, ctx.span_id]
            with self._lock:
                resp = None
                for attempt in (0, 1):
                    if self._sock is None:
                        self._sock = self._dialer.dial(
                            deadline_s=min(timeout,
                                           self._dialer.deadline_s))
                    try:
                        self._sock.settimeout(timeout)
                        _send_flat(self._sock, req)
                        resp = _recv_flat(self._sock)
                        break
                    except (ConnectionError, OSError):
                        # server restarted / idle stream dropped: one
                        # fresh-connection retry, then the error is real
                        self.close()
                        if attempt:
                            raise
        err = resp.get("err") if isinstance(resp, dict) else None
        if err is not None:
            raise RuntimeError(
                f"replica serve error {err}: {resp.get('msg')}")
        return resp

    def lookup(self, table_id: int, ids=None, *,
               version: Optional[int] = None,
               deadline: Optional[float] = None) -> np.ndarray:
        # ids ride the wire as a raw array segment (the flat codec's
        # 'a' tag) — the old pickled-list spelling re-boxed every id.
        # Dtype is NOT coerced here: the server's admission validation
        # owns id typing (a float id vector must fail THERE with the
        # typed message, not silently truncate in the client)
        ids_a = None if ids is None else np.ascontiguousarray(
            np.asarray(ids).ravel())
        return self._call(op="lookup", table_id=int(table_id),
                          ids=ids_a, version=version,
                          deadline=deadline)["rows"]

    def status(self) -> dict:
        return self._call(op="status")

    def pin(self, version: int) -> int:
        return self._call(op="pin", version=int(version))["version"]

    def unpin(self, version: int) -> None:
        self._call(op="unpin", version=int(version))

    def trace_dump(self) -> dict:
        """The server process's Chrome trace object (run the replica
        with ``--trace``; merge several with ``python -m
        multiverso_tpu.telemetry.fleet --trace``)."""
        return json.loads(self._call(op="trace_dump")["trace_json"])


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m multiverso_tpu.replica.replica",
        description="jax-free replica reader: join a trainer's "
                    "replica plane, mirror published versions, serve "
                    "lookups")
    p.add_argument("--addr", required=True,
                   help="trainer replica coordinator endpoint list "
                        "host:port[,host:port] — primary first, "
                        "standby successor endpoints after")
    p.add_argument("--mode", choices=("shm", "tcp", "relay"),
                   default="shm",
                   help="fan-out transport: shm (same host), tcp "
                        "(remote — bundles ride a direct framed "
                        "stream from the publisher), or the "
                        "coordinator socket relay (remote fallback)")
    p.add_argument("--serve-port", type=int, default=0,
                   help="lookup TCP port (0 = ephemeral)")
    p.add_argument("--ring-bytes", type=int, default=8 << 20)
    p.add_argument("--lease", type=float, default=5.0,
                   help="heartbeat lease seconds")
    p.add_argument("--keep", type=int, default=2,
                   help="version retention (the -mv_serving_keep "
                        "contract)")
    p.add_argument("--status-file", default="",
                   help="write {rid, serve_port, pid} JSON here once "
                        "up (test/bench discovery)")
    p.add_argument("--compress", action="store_true",
                   help="enable the tagged serve-frame codecs "
                        "(-mv_compress) in this reader; lookup rows "
                        "compress only for tables named in "
                        "--compress-lossy")
    p.add_argument("--trace", action="store_true",
                   help="arm -trace span recording in this reader; "
                        "fetch the buffer with the trace_dump serve op "
                        "and stitch dumps with python -m "
                        "multiverso_tpu.telemetry.fleet --trace")
    p.add_argument("--compress-lossy", default="",
                   help="comma-separated table ids (or 'all') whose "
                        "serve rows may ride the lossy bf16 codec "
                        "(-mv_compress_lossy)")
    p.add_argument("--chaos-spec", default="",
                   help="arm -chaos_spec fault injection in this "
                        "reader only (fleet drills: serving.delay:1@"
                        "0.05 makes THIS replica the deterministic "
                        "p99 outlier the /fleet attribution must name)")
    p.add_argument("--chaos-seed", type=int, default=0,
                   help="-chaos_seed for the reader's injector streams")
    args = p.parse_args(argv)
    # the whole point of this tier: a reader must never pay the jax
    # import (or its device bootstrap) — if this trips, some module on
    # the replica import path regressed to a top-level jax import
    CHECK("jax" not in sys.modules,
          "replica process import graph must stay numpy-only — "
          "something pulled jax at import time")
    endpoints = _dialer.parse_endpoints(args.addr)
    host, port_n = endpoints[0]
    SetCMDFlag("mv_serving_keep", args.keep)
    if args.trace:
        SetCMDFlag("trace", True)
    if args.compress:
        SetCMDFlag("mv_compress", True)
    if args.compress_lossy:
        SetCMDFlag("mv_compress_lossy", args.compress_lossy)
    if args.chaos_spec:
        SetCMDFlag("chaos_spec", args.chaos_spec)
        SetCMDFlag("chaos_seed", args.chaos_seed)
    rep = Replica(host, port_n, mode=args.mode,
                  serve_port=args.serve_port,
                  ring_bytes=args.ring_bytes, lease_s=args.lease,
                  endpoints=endpoints)
    rep.start()
    if args.status_file:
        tmp = args.status_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"rid": rep.rid, "serve_port": rep.serve_port,
                       "pid": os.getpid()}, f)
        os.replace(tmp, args.status_file)
    rep.recv_loop()
    return rep.exit_code or 0


if __name__ == "__main__":
    sys.exit(main())
