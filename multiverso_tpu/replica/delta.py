"""Versioned delta codec: publish journals, base/delta blobs, mirrors.

**The journal.** The SparseMatrixTable freshness machinery answers
"which rows changed since worker w's last Get" with a host-side boolean
bitmap transitioned by vectorized numpy ops at every Add
(tables/sparse_matrix_table.py ``up_to_date``). The publish journal is
the same idiom with ONE consumer — the fan-out publisher: matrix/sparse
tables keep a per-row dirty bitmap ORed at every applied Add (the
``_note_add_parts`` hook every Add path already fires), kv tables keep
a write-set journal of touched key arrays, array tables a whole-table
flag. ``drain()`` runs inside the publish cut (engine thread, every
stream fenced — the same lockstep position the capture itself runs at),
so the drained descriptor is EXACTLY "what changed between publish k-1
and publish k": every Add admitted before the cut marked the journal
before the drain, none after. That is the delta-soundness argument and
it is inherited from the cut, not invented here.

**The blobs.** A fan-out blob is one pickled bundle sealed with the
PR 3 CRC32 trailer (``parallel/seal.py`` — verified before any byte is
parsed):

* ``base``  — every exported table's full state at one version (first
  join, or a replica too far behind the retained dirty sets).
* ``delta`` — per-table rows/keys dirtied since ``prev_version``, with
  VALUES read from the already-captured immutable snapshot (the fan-out
  thread never touches live tables). Fan-out bytes therefore scale with
  churn, not table size.

Round 21 — under ``-mv_compress`` the payload arrays ride the tagged
codec envelopes of :mod:`multiverso_tpu.parallel.compress` before
pickling: dirty-id/key descriptors bitmap-RLE (lossless, always when it
wins), delta rows int8-per-row-scale and base value rows bf16 (LOSSY —
only for tables opted in via ``-mv_compress_lossy``). :func:`decode`
materializes every envelope back to plain arrays, so the mirror logic
below never sees a compressed value; with the flag off the bundle
bytes are identical to an uncompressed build.

**Delta applicability.** A delta ``prev → L`` applies to any replica
state at version W with ``prev <= W <= L``: rows inside the dirty union
take their version-L values, rows outside are bit-identical in every
version of that interval (that is what the journal proves). The mirror
store CHECKs that window and the publisher composes per-version
descriptors with :func:`merge_descriptors` for replicas more than one
publish behind.

**The mirrors.** :class:`MirrorStore` is the replica-side twin: plain
numpy logical state per table, copy-on-apply (the previous version's
installed snapshot keeps its own arrays — immutability is what makes
the frontend's lock-free reads sound), building the same
``serving.snapshot`` table-snapshot objects the training process
serves, so the reused ``ServingFrontend`` cannot tell it is running in
a replica.

Everything in this module is numpy-only — it imports no jax and runs
identically in the trainer and in the jax-free reader process.
"""

from __future__ import annotations

import pickle
import time
from typing import Dict, List, Optional

import numpy as np

from multiverso_tpu.parallel import compress, seal
from multiverso_tpu.serving.snapshot import (KVSnapshot, MatrixSnapshot,
                                             Snapshot, VectorSnapshot)
from multiverso_tpu.utils.log import CHECK

#: bundle format version (inside the sealed pickle)
FORMAT_VERSION = 1


# -- publish journals ------------------------------------------------------


class TableJournal:
    """Dirty-set accumulator for ONE server table between publish cuts.

    Kinds: ``rows`` (matrix/sparse — per-row bitmap, the up_to_date
    idiom), ``keys`` (kv — write-set of touched key arrays), ``all``
    (array — whole-table flag; its state is one vector, row granularity
    buys nothing). Mark calls run on the engine/apply thread that owns
    the table's applies (serial per table — the same single-writer
    argument as ``apply_busy_s``); ``drain()`` runs at the fenced cut,
    so no mark can race it."""

    __slots__ = ("kind", "_bits", "_keys", "_all")

    def __init__(self, kind: str, num_rows: int = 0):
        CHECK(kind in ("rows", "keys", "all"),
              f"unknown journal kind {kind!r}")
        self.kind = kind
        self._all = False
        self._bits = (np.zeros(int(num_rows), dtype=bool)
                      if kind == "rows" else None)
        self._keys: List[np.ndarray] = []

    def mark_rows(self, row_ids) -> None:
        """``row_ids`` touched (None = whole table)."""
        if row_ids is None:
            self._all = True
        elif not self._all:
            self._bits[np.asarray(row_ids, np.int64).ravel()] = True

    def mark_keys(self, keys) -> None:
        # copy: window-decode hands out zero-copy views into the
        # exchanged blob, which the engine recycles after the apply
        if not self._all:
            self._keys.append(
                np.array(np.asarray(keys, np.int64).ravel(), copy=True))

    def mark_all(self) -> None:
        self._all = True
        if self.kind == "keys":
            self._keys.clear()

    def drain(self) -> dict:
        """The interval's dirty descriptor; resets the journal."""
        if self._all:
            out = {"kind": "all"}
        elif self.kind == "rows":
            out = {"kind": "rows",
                   "ids": np.nonzero(self._bits)[0].astype(np.int64)}
        elif self.kind == "keys":
            out = {"kind": "keys",
                   "keys": (np.unique(np.concatenate(self._keys))
                            if self._keys
                            else np.empty(0, np.int64))}
        else:       # "all" journal with nothing marked
            out = {"kind": "none"}
        self._all = False
        if self._bits is not None:
            self._bits[:] = False
        self._keys = []
        return out

    def nbytes(self) -> int:
        """Ledger probe: journal footprint (bitmap + buffered keys)."""
        n = int(self._bits.nbytes) if self._bits is not None else 0
        return n + sum(int(k.nbytes) for k in self._keys)


def journal_for_table(table) -> TableJournal:
    """The right journal kind for a server table, by family contract:
    row-addressed tables journal rows, key-addressed tables keys,
    whole-vector tables a flag (``tables/base.py publish_journal_kind``
    contract)."""
    kind = getattr(table, "publish_journal_kind", "all")
    return TableJournal(kind, num_rows=getattr(table, "num_rows", 0))


def merge_descriptors(descs: List[Optional[dict]]) -> Optional[dict]:
    """Union of consecutive intervals' dirty descriptors (oldest
    first). ``None`` anywhere (an interval without journal coverage)
    or any ``all`` makes the union ``all``; absent/empty intervals
    contribute nothing."""
    kinds = set()
    ids: List[np.ndarray] = []
    keys: List[np.ndarray] = []
    for d in descs:
        if d is None or d["kind"] == "all":
            return {"kind": "all"}
        if d["kind"] == "none":
            continue
        kinds.add(d["kind"])
        if d["kind"] == "rows":
            ids.append(d["ids"])
        else:
            keys.append(d["keys"])
    CHECK(len(kinds) <= 1, f"mixed journal kinds in one merge: {kinds}")
    if not kinds:
        return {"kind": "none"}
    if "rows" in kinds:
        return {"kind": "rows",
                "ids": np.unique(np.concatenate(ids)).astype(np.int64)}
    return {"kind": "keys",
            "keys": np.unique(np.concatenate(keys)).astype(np.int64)}


def descriptor_nbytes(desc: Optional[dict]) -> int:
    if not desc:
        return 0
    arr = desc.get("ids") if desc.get("kind") == "rows" \
        else desc.get("keys")
    return int(arr.nbytes) if isinstance(arr, np.ndarray) else 0


# -- blob encode/decode ----------------------------------------------------


def _full_payload(ts) -> dict:
    """One table snapshot's complete state as a bundle payload."""
    if isinstance(ts, MatrixSnapshot):
        rows = ts._rows if ts._rows is not None else ts.full()
        return {"fam": "matrix", "num_rows": int(ts.num_rows),
                "num_cols": int(ts.num_cols),
                "rows": np.ascontiguousarray(rows)}
    if isinstance(ts, KVSnapshot):
        keys, vals = ts.items()
        return {"fam": "kv", "keys": np.ascontiguousarray(keys),
                "values": np.ascontiguousarray(vals)}
    if isinstance(ts, VectorSnapshot):
        return {"fam": "vector",
                "values": np.ascontiguousarray(ts._values)}
    CHECK(False, f"no fan-out payload for snapshot family "
                 f"{type(ts).__name__}")


def _delta_payload(ts, desc: dict) -> Optional[dict]:
    """One table's delta payload from its merged dirty descriptor;
    None = clean (omit the table — the replica carries its mirror
    forward). Values come from the IMMUTABLE captured snapshot."""
    if desc["kind"] == "none":
        return None
    if desc["kind"] == "all":
        return _full_payload(ts)
    if desc["kind"] == "rows":
        CHECK(isinstance(ts, MatrixSnapshot),
              f"rows descriptor against {type(ts).__name__}")
        ids = desc["ids"]
        if ids.size == 0:
            return None
        return {"fam": "matrix", "num_rows": int(ts.num_rows),
                "num_cols": int(ts.num_cols),
                "ids": ids.astype(np.int64),
                "rows": np.ascontiguousarray(ts.lookup_union(ids))}
    CHECK(isinstance(ts, KVSnapshot),
          f"keys descriptor against {type(ts).__name__}")
    keys = desc["keys"]
    if keys.size == 0:
        return None
    return {"fam": "kv", "keys": keys.astype(np.int64),
            "values": np.ascontiguousarray(ts.lookup_union(keys))}


def _bundle(kind: str, snap: Snapshot, prev_version: int,
            tables: Dict[int, dict]) -> bytes:
    body = pickle.dumps({
        "v": FORMAT_VERSION, "kind": kind,
        "version": int(snap.version), "prev_version": int(prev_version),
        "window_epoch": int(snap.window_epoch),
        "created_wall": float(snap.created_wall),
        "sent_wall": time.time(),
        "tables": tables,
    }, protocol=pickle.HIGHEST_PROTOCOL)
    return seal.seal_frame(body)


def encode_base(snap: Snapshot) -> bytes:
    """Full-base blob: every exported table's complete state at
    ``snap.version`` (first join / resync). Value rows ride bf16 for
    lossy-opted tables under ``-mv_compress``."""
    return _bundle("base", snap, -1,
                   {tid: compress.pack_payload(tid, _full_payload(ts))
                    for tid, ts in snap.tables.items()})


def encode_delta(snap: Snapshot, prev_version: int,
                 descs: Dict[int, Optional[dict]]) -> bytes:
    """Delta blob ``prev_version -> snap.version``. ``descs`` maps
    table id -> merged dirty descriptor over that interval; a table id
    present in the snapshot but ABSENT from ``descs`` is one created
    after ``prev_version`` and ships full."""
    tables: Dict[int, dict] = {}
    for tid, ts in snap.tables.items():
        desc = descs.get(tid)
        payload = (_full_payload(ts) if desc is None
                   else _delta_payload(ts, desc))
        if payload is not None:
            # -mv_compress: ids/keys -> bitmap-RLE (lossless); rows ->
            # int8 (delta) / bf16 (full) for lossy-opted tables only
            tables[tid] = compress.pack_payload(tid, payload)
    return _bundle("delta", snap, prev_version, tables)


def decode(blob: bytes) -> dict:
    """Verify the CRC trailer, unpickle, and sanity-check the bundle.
    Raises ``WireCorruption`` on a torn/flipped blob BEFORE parsing."""
    bundle = pickle.loads(seal.open_frame(blob))
    CHECK(isinstance(bundle, dict)
          and bundle.get("v") == FORMAT_VERSION
          and bundle.get("kind") in ("base", "delta"),
          f"unrecognized fan-out bundle "
          f"(v={bundle.get('v') if isinstance(bundle, dict) else '?'})")
    # materialize any tagged codec envelopes (an unknown codec tag —
    # a NEWER writer — fails loudly here, before the mirror sees it)
    for payload in bundle["tables"].values():
        compress.unpack_payload(payload)
    return bundle


# -- replica-side mirrors --------------------------------------------------


def _merge_kv(keys: np.ndarray, vals: np.ndarray,
              new_keys: np.ndarray, new_vals: np.ndarray):
    """Merge (new_keys, new_vals) into a sorted (keys, vals) pair —
    existing keys updated, unseen keys inserted; returns fresh arrays
    (the previous version keeps its own)."""
    pos = np.searchsorted(keys, new_keys)
    pos_c = np.minimum(pos, max(len(keys) - 1, 0))
    exists = (keys[pos_c] == new_keys) if len(keys) else \
        np.zeros(len(new_keys), dtype=bool)
    out_keys = keys.copy()
    out_vals = vals.copy()
    if exists.any():
        out_vals[pos_c[exists]] = new_vals[exists]
    if (~exists).any():
        ins = pos[~exists]
        out_keys = np.insert(out_keys, ins, new_keys[~exists])
        out_vals = np.insert(out_vals, ins, new_vals[~exists])
    return out_keys, out_vals


class MirrorStore:
    """Per-replica logical table mirrors + snapshot builder. ``apply``
    consumes one decoded bundle and returns the serving ``Snapshot`` to
    install; previous versions' arrays are never mutated (copy-on-
    apply), so the retention/pin contract of the surrounding
    ``SnapshotStore`` carries over unchanged."""

    def __init__(self):
        #: tid -> {"fam", arrays...} — the NEWEST version's state
        self._tables: Dict[int, dict] = {}
        self.version = -1

    def apply(self, bundle: dict) -> Snapshot:
        kind = bundle["kind"]
        version = int(bundle["version"])
        CHECK(version > self.version,
              f"fan-out bundle v{version} is not newer than mirror "
              f"v{self.version}")
        if kind == "base":
            self._tables = {tid: self._from_payload(p)
                            for tid, p in bundle["tables"].items()}
        else:
            prev = int(bundle["prev_version"])
            CHECK(prev <= self.version,
                  f"delta v{prev}->v{version} skips past mirror "
                  f"v{self.version} — resync with a base blob")
            for tid, p in bundle["tables"].items():
                cur = self._tables.get(tid)
                self._tables[tid] = self._apply_payload(cur, p)
        self.version = version
        return self._snapshot(bundle)

    # -- payload application ------------------------------------------------

    @staticmethod
    def _from_payload(p: dict) -> dict:
        fam = p["fam"]
        if fam == "matrix":
            CHECK("ids" not in p,
                  "row-delta payload for a table the mirror has never "
                  "seen — resync with a base blob")
            return {"fam": fam,
                    "rows": np.array(p["rows"], copy=True)}
        if fam == "kv":
            keys = np.asarray(p["keys"], np.int64)
            order = np.argsort(keys, kind="stable")
            return {"fam": fam, "keys": np.array(keys[order], copy=True),
                    "values": np.array(np.asarray(p["values"])[order],
                                       copy=True)}
        CHECK(fam == "vector", f"unknown payload family {fam!r}")
        return {"fam": fam, "values": np.array(p["values"], copy=True)}

    def _apply_payload(self, cur: Optional[dict], p: dict) -> dict:
        if cur is None or "ids" not in p and p["fam"] == "matrix":
            # new table, or a whole-table matrix payload: replace
            return self._from_payload(p)
        fam = p["fam"]
        CHECK(cur["fam"] == fam,
              f"fan-out family flip {cur['fam']} -> {fam}")
        if fam == "matrix":
            rows = cur["rows"].copy()
            rows[np.asarray(p["ids"], np.int64)] = p["rows"]
            return {"fam": fam, "rows": rows}
        if fam == "kv":
            new_keys = np.asarray(p["keys"], np.int64)
            order = np.argsort(new_keys, kind="stable")
            keys, vals = _merge_kv(cur["keys"], cur["values"],
                                   new_keys[order],
                                   np.asarray(p["values"])[order])
            return {"fam": fam, "keys": keys, "values": vals}
        return self._from_payload(p)     # vector: always whole-state

    # -- snapshot construction ----------------------------------------------

    def _snapshot(self, bundle: dict) -> Snapshot:
        tables = {}
        for tid, st in self._tables.items():
            if st["fam"] == "matrix":
                tables[tid] = MatrixSnapshot.host(st["rows"])
            elif st["fam"] == "kv":
                tables[tid] = KVSnapshot(st["keys"], st["values"])
            else:
                tables[tid] = VectorSnapshot(st["values"])
        return Snapshot(version=int(bundle["version"]),
                        created_wall=float(bundle["created_wall"]),
                        window_epoch=int(bundle["window_epoch"]),
                        tables=tables)

    def mirror_bytes(self) -> int:
        """Exact mirror footprint (newest version's arrays; older
        retained versions are the SnapshotStore's ledger entry)."""
        total = 0
        for st in self._tables.values():
            for v in st.values():
                if isinstance(v, np.ndarray):
                    total += int(v.nbytes)
        return total
