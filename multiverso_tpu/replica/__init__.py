"""Replica plane: delta-snapshot fan-out and a jax-free read tier.

PR 5's serving plane splits reads from the verb stream but still serves
them from the TRAINING process — every reader shares its cores and GIL
(~3k GIL-bound verbs/s measured, PR 9), capping QPS far below the north
star. This package is the classic parameter-server read/update split
(Li et al., OSDI'14) taken to separate processes:

* :mod:`delta` — the versioned delta codec: per-table "rows dirtied
  since version V" blobs (the SparseMatrixTable dirty-row idiom lifted
  to a publish journal for matrix/sparse, a write-set journal for
  kv/array), a full-base blob for first join, all sealed with the PR 3
  CRC trailer (``parallel/seal.py``), plus the replica-side mirror
  store that applies them.
* :mod:`publisher` — the trainer side: ``MV_PublishSnapshot``'s capture
  hook drains each table's journal at the fenced cut, and a fan-out
  thread ships base+delta blobs to subscribed replicas — same-host
  replicas over dedicated PR 9 shm-ring channels (1.9–2.4 GB/s
  measured), cross-host replicas over a dedicated round-24 tcp wire
  stream (the reader binds a listener before joining; the publisher
  dials it at first ship), and relay-mode replicas through the PR 7
  coordinator's length-prefixed CRC-framed socket mailbox.
* :mod:`replica` — the jax-free (numpy-only import path, asserted)
  reader process: joins through the coordinator as a non-SPMD
  ``role=replica`` member with a heartbeat lease but NO verb stream,
  maintains local version mirrors under the same retention/pin
  contract as ``SnapshotStore``, and serves lookups through a reused
  ``ServingFrontend`` (admission/micro-batch/shed semantics identical,
  host gather path only).

Flags live HERE so zoo's eager import registers them before MV_Init's
ParseCMDFlags (the sync/server.py flag-home rule).
"""

from __future__ import annotations

from typing import List, Optional

from multiverso_tpu.utils.configure import (MV_DEFINE_bool,
                                            MV_DEFINE_double,
                                            MV_DEFINE_int,
                                            MV_DEFINE_string)

MV_DEFINE_bool("mv_replica_fanout", False,
               "replica plane: journal per-table publish dirty sets and "
               "fan published snapshots out to subscribed replica "
               "reader processes as versioned base+delta blobs "
               "(same-host: shm ring; cross-host: tcp wire stream; "
               "relay: coordinator mailbox)")
MV_DEFINE_string("mv_replica_addr", "",
                 "replica subscription coordinator endpoint host:port. "
                 "Empty: reuse the elastic coordinator when -mv_elastic "
                 "is up, else rank 0 hosts one on loopback with an "
                 "ephemeral port (single-process worlds; multi-process "
                 "worlds without -mv_elastic must name a port)")
MV_DEFINE_int("mv_replica_ring_bytes", 8 << 20,
              "per-subscriber fan-out capacity: shm ring bytes "
              "(same-host) or tcp chunk cap (cross-host); frames "
              "larger than this ship as multiple flow-controlled "
              "chunks")
MV_DEFINE_double("mv_replica_lease_s", 0.0,
                 "replica heartbeat lease: a replica silent for this "
                 "long is declared dead and its subscription evicted "
                 "at the next fan-out tick (0 = derive from "
                 "-mv_deadline_s like the elastic lease, floor 2s, "
                 "default 5s)")

from multiverso_tpu.replica import delta  # noqa: E402,F401


def start_plane(zoo) -> bool:
    """Bring the publisher up when ``-mv_replica_fanout`` is set
    (Zoo.Start). Returns True when active on this rank."""
    from multiverso_tpu.replica import publisher
    return publisher.start_plane(zoo)


def shutdown_plane() -> None:
    """Stop the fan-out thread and drop every subscription wire
    (Zoo.Stop)."""
    from multiverso_tpu.replica import publisher
    publisher.shutdown_plane()


def note_publish(engine, snap) -> None:
    """Publish-cut hook (serving/snapshot._capture_all, ON the engine
    thread with every stream fenced): drain the per-table journals into
    the dirty-set record for ``snap.version`` and kick the fan-out
    thread. No-op (one attribute read) when the plane is off."""
    from multiverso_tpu.replica import publisher
    publisher.note_publish(engine, snap)


def maybe_attach_journal(server_table) -> None:
    """RegisterTable hook (sync/server.py): attach the publish dirty
    journal when this rank fans out. No-op when the plane is off."""
    from multiverso_tpu.replica import publisher
    publisher.maybe_attach_journal(server_table)


def status_report() -> Optional[dict]:
    """Local publisher view for /healthz (per-replica lines) — never
    collective, served from the fan-out thread's cached roster."""
    from multiverso_tpu.replica import publisher
    return publisher.status_report()


def peek_sample() -> Optional[dict]:
    """Watchdog probe: {replica_subscribers, replica_lag_versions} from
    local publisher state, or None when the plane is off."""
    from multiverso_tpu.replica import publisher
    return publisher.peek_sample()


def ledger_bytes() -> Optional[dict]:
    """Accounting-ledger probe: journal + retained dirty-set bytes on
    the fan-out rank (None when the plane is off)."""
    from multiverso_tpu.replica import publisher
    return publisher.ledger_bytes()


def status_lines() -> List[str]:
    """Dashboard line for DisplayAll — [] when the plane never ran."""
    rep = status_report()
    if rep is None:
        return []
    subs = rep.get("subscribers", [])
    live = [s for s in subs if s.get("state") == "live"]
    return ["[Replica] subscribers = %d live / %d known, latest = v%s, "
            "max_lag = %s, fanout = %d bytes" % (
                len(live), len(subs), rep.get("latest"),
                rep.get("max_lag"), rep.get("fanout_bytes", 0))]
