"""Backend dispatch for the row gather/scatter table ops.

``use_pallas`` is governed by the ``use_pallas`` flag:
``auto`` (default) — Pallas on TPU, XLA elsewhere; ``on`` — Pallas
everywhere (interpreter mode off-TPU; used by tests); ``off`` — XLA.

The XLA fallback relies on jit'd gather + ``.at[].set`` — on a CPU test
mesh that is both correct and fast enough; on TPU the Pallas kernels avoid
materializing gather/scatter HLO over the whole shard.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from multiverso_tpu.utils.configure import GetFlag, MV_DEFINE_string

MV_DEFINE_string("use_pallas", "auto",
                 "row-op kernels: auto (TPU only) / on / off")


def use_pallas() -> bool:
    mode = str(GetFlag("use_pallas")).lower()
    if mode == "on":
        return True
    if mode == "off":
        return False
    return jax.default_backend() == "tpu"


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def gather_rows(data: jax.Array, ids: jax.Array) -> jax.Array:
    """rows[i] = data[ids[i]]; all ids must be in range (caller maps
    out-of-shard lanes to the trash row)."""
    if use_pallas():
        from multiverso_tpu.ops.pallas_rows import pallas_gather_rows
        return pallas_gather_rows(data, ids, interpret=_interpret())
    return jnp.take(data, ids, axis=0)


def scatter_set_rows(data: jax.Array, ids: jax.Array,
                     rows: jax.Array) -> jax.Array:
    """data[ids[i]] = rows[i]; duplicates only on the trash row."""
    if use_pallas():
        from multiverso_tpu.ops.pallas_rows import pallas_scatter_set_rows
        return pallas_scatter_set_rows(data, ids, rows, interpret=_interpret())
    return data.at[ids].set(rows)
