"""Backend dispatch for the row gather/scatter/update table ops.

``use_pallas`` is governed by the ``use_pallas`` flag:
``auto`` (default) — reads via XLA's native gather everywhere, writes via
the coalesced Pallas DMA kernels on TPU (the measured-fastest split: TPU
vector loads gather random 512B rows at ~100 GB/s while XLA scatter
crawls at ~6 GB/s, so each half rides its fast lane); ``on`` — Pallas for
every verb incl. the fused single-kernel RMW (interpreter mode off-TPU;
used by tests); ``off`` — XLA only.

The XLA fallback relies on jit'd gather + ``.at[].set`` — on a CPU test
mesh that is both correct and fast enough.

Row DMAs slice HBM along the lane dim, so Pallas needs the row byte-width
tile-aligned (128 lanes for 4-byte dtypes). The table layer pads its
storage column dim to ``padded_cols`` so the hot path stays eligible —
measured ~5.6x on the reference 1Mx50 row-op benchmark even for plain XLA
(aligned rows vs 200-byte ragged rows), with the fused Pallas update
another ~1.6x on top.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from multiverso_tpu.utils.configure import GetFlag, MV_DEFINE_string

MV_DEFINE_string("use_pallas", "auto",
                 "row-op kernels: auto (TPU only) / on / off")
MV_DEFINE_string("matrix_pad_cols", "auto",
                 "pad matrix storage cols to the 128-lane tile: auto/on/off")

LANE = 128


def _pallas_eligible(data) -> bool:
    """Row DMAs slice HBM along the lane dim, so rows must be tile-aligned:
    128 lanes for 4-byte dtypes (Mosaic: 'slice shape along dimension 1 must
    be aligned to tiling (128)'). Rows so wide that even the minimum chunk's
    VMEM blocks overflow the kernel budget take the XLA path instead —
    pallas_rows._chunk_for owns that budget law and returns 0 when there is
    nothing left to shrink."""
    from multiverso_tpu.ops.pallas_rows import _chunk_for
    return (data.dtype.itemsize == 4 and data.shape[-1] % LANE == 0
            and _chunk_for(data.shape[-1], data.dtype.itemsize) > 0)


def use_pallas(data=None) -> bool:
    mode = str(GetFlag("use_pallas")).lower()
    if mode == "on":
        # forced on (interpreter mode off-TPU; tests): still respect the
        # lowering constraints — an ineligible shape would be a Mosaic
        # compile error (or a zero chunk) rather than a kernel choice
        return data is None or _pallas_eligible(data)
    if mode == "off":
        return False
    return (jax.default_backend() == "tpu"
            and (data is None or _pallas_eligible(data)))


def padded_cols(num_cols: int, itemsize: int = 4) -> int:
    """Storage column count for a logical ``num_cols``, governed by the
    ``matrix_pad_cols`` flag: ``auto``/``on`` — pad 4-byte dtypes up to the
    128-lane tile; ``off`` — never. Aligned rows are what make the row hot
    path fast (ragged 200-byte rows measured ~5.6x slower even on the plain
    XLA path) and what the Pallas row-DMA kernels require. The pad trades
    HBM capacity for alignment; padded columns hold zeros and every updater
    is identity on a zero delta, so they stay zero."""
    mode = str(GetFlag("matrix_pad_cols")).lower()
    if mode == "off" or itemsize != 4:
        return num_cols
    return -(-num_cols // LANE) * LANE


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _forced_on(data) -> bool:
    """``use_pallas=on`` (test mode): force the Pallas kernel for verbs
    whose default path is XLA, so tests keep covering the kernels."""
    return (str(GetFlag("use_pallas")).lower() == "on"
            and _pallas_eligible(data))


def dedup_rows(ids: jax.Array, deltas: jax.Array):
    """Traced duplicate combine: sum the deltas of equal ids into ONE
    surviving lane; the other duplicate lanes become pad lanes (id -1,
    zero delta). Pad lanes in (-1, zero-delta form) pass through.

    This is the on-device equivalent of the host-side ``np.add.at``
    pre-combine the table layer applies before scatter (scatter-set order
    on duplicates is undefined — matrix_table.py module docstring), with
    identical semantics: duplicates combine by SUM before the updater
    runs. It is what makes merged multi-process device-plane batches
    safe for every updater without a host round-trip.

    Cost: one argsort over the id bucket + a segment-sum over the delta
    payload — O(n log n + n·cols), fully fused into the caller's program.
    """
    n = ids.shape[0]
    order = jnp.argsort(ids)
    sids = jnp.take(ids, order)
    sdeltas = jnp.take(deltas, order, axis=0)
    head = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sids[1:] != sids[:-1]])
    seg = jnp.cumsum(head) - 1          # segment index per sorted lane
    out_deltas = jax.ops.segment_sum(sdeltas, seg, num_segments=n)
    # every lane of a segment writes the same id value, so the scatter's
    # undefined duplicate order is harmless; unused segments stay -1 (pad)
    out_ids = jnp.full((n,), -1, ids.dtype).at[seg].set(sids)
    return out_ids, out_deltas


def gather_rows(data: jax.Array, ids: jax.Array) -> jax.Array:
    """rows[i] = data[ids[i]]; all ids must be in range (caller maps
    out-of-shard lanes to the trash row).

    Reads ride XLA's native gather on every backend: measured on v5e it
    runs at ~100 GB/s on RANDOM 512-byte rows — 5x the per-row-DMA Pallas
    kernel and faster even than its coalesced contiguous branch (vector
    loads beat DMA descriptors for reads). ``use_pallas=on`` still forces
    the Pallas kernel so tests cover it."""
    if _forced_on(data):
        from multiverso_tpu.ops.pallas_rows import pallas_gather_rows
        return pallas_gather_rows(data, ids, interpret=_interpret())
    return jnp.take(data, ids, axis=0)


def scatter_set_rows(data: jax.Array, ids: jax.Array,
                     rows: jax.Array) -> jax.Array:
    """data[ids[i]] = rows[i]; duplicates only on the trash row.

    Writes are the mirror image of reads on TPU: XLA's scatter measured
    ~3-6 GB/s (it serializes), while the Pallas row-DMA kernel does
    ~25 GB/s random and 60-200 GB/s on coalesced contiguous runs — so
    writes keep the Pallas path wherever it is eligible."""
    if use_pallas(data):
        from multiverso_tpu.ops.pallas_rows import pallas_scatter_set_rows
        return pallas_scatter_set_rows(data, ids, rows, interpret=_interpret())
    return data.at[ids].set(rows)


def update_rows(data: jax.Array, ids: jax.Array, deltas: jax.Array,
                combine) -> jax.Array:
    """data[ids[i]] = combine(data[ids[i]], deltas[i]) — the server-side
    Add for aux-free elementwise updaters. ``combine`` must satisfy
    combine(rows, 0) == rows (see pallas_rows contract) and be
    identity-stable (one object per table) so the jit cache holds.

    Default TPU path is the HYBRID: XLA vector-gather for the read half
    (~100 GB/s random — see gather_rows), combine fused elementwise, and
    the coalesced Pallas scatter for the write half. Measured ~1.5x over
    the all-DMA fused kernel on random row sets (250us vs 365us for 10k
    512B rows) and comparable on contiguous sets (both coalesce).
    ``use_pallas=on`` forces the fused single-kernel RMW so tests cover
    it; the XLA fallback is gather + combine + scatter."""
    if _forced_on(data):
        from multiverso_tpu.ops.pallas_rows import pallas_update_rows
        return pallas_update_rows(data, ids, deltas, combine,
                                  interpret=_interpret())
    if use_pallas(data):
        from multiverso_tpu.ops.pallas_rows import pallas_scatter_set_rows
        rows = jnp.take(data, ids, axis=0)
        return pallas_scatter_set_rows(data, ids, combine(rows, deltas),
                                       interpret=_interpret())
    rows = jnp.take(data, ids, axis=0)
    return data.at[ids].set(combine(rows, deltas))
