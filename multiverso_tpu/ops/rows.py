"""Backend dispatch for the row gather/scatter/update table ops.

``use_pallas`` is governed by the ``use_pallas`` flag:
``auto`` (default) — reads via XLA's native gather everywhere, writes via
the coalesced Pallas DMA kernels on TPU (the measured-fastest split: TPU
vector loads gather random 512B rows at ~100 GB/s while XLA scatter
crawls at ~6 GB/s, so each half rides its fast lane); ``on`` — Pallas for
every verb incl. the fused single-kernel RMW (interpreter mode off-TPU;
used by tests); ``off`` — XLA only.

The XLA fallback relies on jit'd gather + ``.at[].set`` — on a CPU test
mesh that is both correct and fast enough.

Row DMAs slice HBM along the lane dim, so Pallas needs the row byte-width
tile-aligned (128 lanes for 4-byte dtypes). The table layer pads its
storage column dim to ``padded_cols`` so the hot path stays eligible —
measured ~5.6x on the reference 1Mx50 row-op benchmark even for plain XLA
(aligned rows vs 200-byte ragged rows), with the fused Pallas update
another ~1.6x on top.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from multiverso_tpu.utils.configure import (GetFlag, MV_DEFINE_string,
                                            cached_str_flag)

#: one constant feeds both the flag registration and the cached
#: accessor's fallback, so the two defaults cannot drift apart
_USE_PALLAS_DEFAULT = "auto"
MV_DEFINE_string("use_pallas", _USE_PALLAS_DEFAULT,
                 "row-op kernels: auto (TPU only) / on / off")
MV_DEFINE_string("matrix_pad_cols", "auto",
                 "pad matrix storage cols to the 128-lane tile: auto/on/off")
#: use_pallas/_forced_on run per row-op dispatch (every verb on the
#: apply path) — listener-cached read, not a registry walk per call
_use_pallas_flag = cached_str_flag("use_pallas", _USE_PALLAS_DEFAULT)

LANE = 128
#: Pallas row kernels take the id vector as a SCALAR-PREFETCH operand in
#: SMEM (1MB/core on v5e): a 262144-id batch (exactly 1MB of i32) OOM'd
#: SMEM by its 1.1KB of spill slots. Id vectors above this BYTE budget
#: (half of SMEM — headroom for spills/other scalars) route to the XLA
#: path; matrix_table's merge cap uses the same constant so merged
#: windows never outgrow the fast path they were built for.
SMEM_IDS_BYTES = 512 * 1024


def _pallas_eligible(data) -> bool:
    """Row DMAs slice HBM along the lane dim, so rows must be tile-aligned:
    128 lanes for 4-byte dtypes (Mosaic: 'slice shape along dimension 1 must
    be aligned to tiling (128)'). Rows so wide that even the minimum chunk's
    VMEM blocks overflow the kernel budget take the XLA path instead —
    pallas_rows._chunk_for owns that budget law and returns 0 when there is
    nothing left to shrink."""
    from multiverso_tpu.ops.pallas_rows import _chunk_for
    return (data.dtype.itemsize == 4 and data.shape[-1] % LANE == 0
            and _chunk_for(data.shape[-1], data.dtype.itemsize) > 0)


def use_pallas(data=None, ids=None) -> bool:
    if ids is not None and ids.shape[0] * 4 > SMEM_IDS_BYTES:
        return False   # id vector would overflow the SMEM prefetch
    mode = _use_pallas_flag()
    if mode == "on":
        # forced on (interpreter mode off-TPU; tests): still respect the
        # lowering constraints — an ineligible shape would be a Mosaic
        # compile error (or a zero chunk) rather than a kernel choice
        return data is None or _pallas_eligible(data)
    if mode == "off":
        return False
    return (jax.default_backend() == "tpu"
            and (data is None or _pallas_eligible(data)))


def padded_cols(num_cols: int, itemsize: int = 4) -> int:
    """Storage column count for a logical ``num_cols``, governed by the
    ``matrix_pad_cols`` flag: ``auto``/``on`` — pad 4-byte dtypes up to the
    128-lane tile; ``off`` — never. Aligned rows are what make the row hot
    path fast (ragged 200-byte rows measured ~5.6x slower even on the plain
    XLA path) and what the Pallas row-DMA kernels require. The pad trades
    HBM capacity for alignment; padded columns hold zeros and every updater
    is identity on a zero delta, so they stay zero."""
    mode = str(GetFlag("matrix_pad_cols")).lower()
    if mode == "off" or itemsize != 4:
        return num_cols
    return -(-num_cols // LANE) * LANE


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _forced_on(data, ids=None) -> bool:
    """``use_pallas=on`` (test mode): force the Pallas kernel for verbs
    whose default path is XLA, so tests keep covering the kernels."""
    if ids is not None and ids.shape[0] * 4 > SMEM_IDS_BYTES:
        return False
    return _use_pallas_flag() == "on" and _pallas_eligible(data)


def dedup_rows(ids: jax.Array, deltas: jax.Array):
    """Traced duplicate combine: sum the deltas of equal ids into ONE
    surviving lane; the other duplicate lanes become pad lanes (id -1,
    zero delta). Pad lanes in (-1, zero-delta form) pass through.

    This is the on-device equivalent of the host-side ``np.add.at``
    pre-combine the table layer applies before scatter (scatter-set order
    on duplicates is undefined — matrix_table.py module docstring), with
    identical semantics: duplicates combine by SUM before the updater
    runs. It is what makes merged multi-process device-plane batches
    safe for every updater without a host round-trip.

    Cost: one argsort over the id bucket + a segment-sum over the delta
    payload — O(n log n + n·cols), fully fused into the caller's program.
    """
    n = ids.shape[0]
    order = jnp.argsort(ids)
    sids = jnp.take(ids, order)
    sdeltas = jnp.take(deltas, order, axis=0)
    head = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sids[1:] != sids[:-1]])
    seg = jnp.cumsum(head) - 1          # segment index per sorted lane
    out_deltas = jax.ops.segment_sum(sdeltas, seg, num_segments=n)
    # every lane of a segment writes the same id value, so the scatter's
    # undefined duplicate order is harmless; unused segments stay -1 (pad)
    out_ids = jnp.full((n,), -1, ids.dtype).at[seg].set(sids)
    return out_ids, out_deltas


def _dense_backend_ok() -> bool:
    """The dense-run lax.cond is a TPU-only optimization: on the CPU
    backend XLA fails to alias the donated table through a conditional
    whose branches read-modify-write it — every call copies the whole
    table (measured ~300x). TPU aliases it fine (measured: dense rounds
    9-18 Gelem/s, random unharmed)."""
    return jax.default_backend() == "tpu"


def _dense_run(ids: jax.Array, n_rows: int):
    """Traced detector for the DENSE fast path: the non-trash lanes are a
    PREFIX of the lane vector holding strictly consecutive row ids, and
    the bucket-sized slice [start, start+bucket) fits inside the live
    rows (never touches the trash row, so dynamic_slice cannot clamp).
    Returns (ok, start, count).

    Lead-trash batches (a shard seeing the middle of a cross-shard run)
    and interior trash (dedup_rows output) route to the general path on
    purpose: the prefix form needs NO lane rolls — the slice lanes line
    up with the batch lanes 1:1, which measured ~3x faster than the
    roll-compensated general-segment variant on v5e (and rolls plus a
    read-back slice defeated XLA's in-place aliasing of the table
    buffer, turning every round into a whole-table copy)."""
    trash = n_rows - 1
    bucket = ids.shape[0]
    mine = ids != trash
    count = jnp.sum(mine)
    lane = jnp.arange(bucket)
    start = ids[0]
    ok = (jnp.all(mine == (lane < count))
          & jnp.all(jnp.where(mine, ids == start + lane, True))
          & (count > 0) & (start + bucket <= trash))
    return ok, start, count


def gather_rows(data: jax.Array, ids: jax.Array, *,
                dense: bool = True) -> jax.Array:
    """rows[i] = data[ids[i]]; all ids must be in range (caller maps
    out-of-shard lanes to the trash row). Trash/pad lanes may return
    ARBITRARY row content — every caller masks or trash-routes them.

    Reads ride XLA's native gather (``mode='clip'`` — the jnp default
    'fill' adds an out-of-bounds select measured 3x slower on v5e).
    ``use_pallas=on`` still forces the Pallas kernel so tests cover it.

    NO dense-run cond here, deliberately: a lax.cond over a LIVE
    (non-donated) table defeats XLA's buffer aliasing — each branch gets
    an operand copy of the whole table (measured ~150x on the CPU
    backend: 512MB copied per Get). The dense bulk-slice fast path lives
    only in the verbs that consume/donate the table (scatter_set_rows,
    update_rows, update_gather_rows), where the in-place chain survives
    the cond. ``dense`` is accepted for signature symmetry."""
    del dense
    if _forced_on(data, ids):
        from multiverso_tpu.ops.pallas_rows import pallas_gather_rows
        return pallas_gather_rows(data, ids, interpret=_interpret())
    return jnp.take(data, ids, axis=0, mode="clip")


def scatter_set_rows(data: jax.Array, ids: jax.Array,
                     rows: jax.Array, *, dense: bool = True) -> jax.Array:
    """data[ids[i]] = rows[i]; duplicates only on the trash row.

    Writes are the mirror image of reads on TPU: XLA's scatter measured
    ~3-6 GB/s (it serializes), while the Pallas row-DMA kernel does
    ~30 GB/s random (17ns/row DMA-issue floor on v5e) and 60-200 GB/s
    on coalesced contiguous runs — so writes keep the Pallas path
    wherever it is eligible. A runtime-detected dense run takes the bulk
    slice-merge-update path (~300 GB/s r+w) instead."""
    if _forced_on(data, ids):
        # test mode: keep the Pallas kernel covered even for dense runs
        from multiverso_tpu.ops.pallas_rows import pallas_scatter_set_rows
        return pallas_scatter_set_rows(data, ids, rows,
                                       interpret=_interpret())
    fallback_pallas = use_pallas(data, ids)

    def general(_):
        if fallback_pallas:
            from multiverso_tpu.ops.pallas_rows import pallas_scatter_set_rows
            return pallas_scatter_set_rows(data, ids, rows,
                                           interpret=_interpret())
        return data.at[ids].set(rows)

    if (not dense or not _dense_backend_ok()
            or ids.shape[0] >= data.shape[0]):
        return general(None)   # static guards (see gather_rows)
    ok, start, count = _dense_run(ids, data.shape[0])
    bucket = ids.shape[0]

    def dense_fn(_):
        # bulk RMW: pad lanes must keep OLD rows (a blind bucket write
        # would clobber the live rows after the run's end)
        old = jax.lax.dynamic_slice(data, (start, 0),
                                    (bucket, data.shape[1]))
        keep = (jnp.arange(bucket) < count)[:, None]
        return jax.lax.dynamic_update_slice(
            data, jnp.where(keep, rows, old), (start, 0))

    return jax.lax.cond(ok, dense_fn, general, None)


def update_rows(data: jax.Array, ids: jax.Array, deltas: jax.Array,
                combine, *, dense: bool = True) -> jax.Array:
    """data[ids[i]] = combine(data[ids[i]], deltas[i]) — the server-side
    Add for aux-free elementwise updaters. ``combine`` must satisfy
    combine(rows, 0) == rows (see pallas_rows contract) and be
    identity-stable (one object per table) so the jit cache holds.

    Default TPU path is the HYBRID: XLA vector-gather for the read half
    (clip mode, see gather_rows), combine fused elementwise, and the
    Pallas scatter for the write half. A runtime-detected dense run
    instead does ONE bulk dynamic_slice -> combine -> dynamic_update_slice
    (~290 GB/s r+w measured v5e — the 64-row chunk DMAs can't touch bulk
    copies). ``use_pallas=on`` forces the fused single-kernel RMW so
    tests cover it; the XLA fallback is gather + combine + scatter."""
    if _forced_on(data, ids):
        from multiverso_tpu.ops.pallas_rows import pallas_update_rows
        return pallas_update_rows(data, ids, deltas, combine,
                                  interpret=_interpret())
    # ONE implementation with update_gather_rows: the dropped rows output
    # is an intermediate both branches compute anyway (zero extra work)
    return _update_gather_impl(data, ids, deltas, combine,
                               use_pallas(data, ids), dense)[0]


def update_gather_rows(data: jax.Array, ids: jax.Array, deltas: jax.Array,
                       combine, *, dense: bool = True):
    """The fused PS round: data[ids] = combine(data[ids], deltas) AND
    return the post-update rows — ONE row read serves both the update and
    the Get (the reference's test_matrix_perf Add-then-Get-same-rows
    round pays two). Returns (new_data, rows); trash/pad lanes of
    ``rows`` are arbitrary (callers mask). Dense runs ride the bulk
    slice path end to end."""
    if _forced_on(data, ids):
        from multiverso_tpu.ops.pallas_rows import pallas_update_rows
        new_data = pallas_update_rows(data, ids, deltas, combine,
                                      interpret=_interpret())
        return new_data, jnp.take(new_data, ids, axis=0, mode="clip")
    return _update_gather_impl(data, ids, deltas, combine,
                               use_pallas(data, ids), dense)


def _update_gather_impl(data, ids, deltas, combine, pallas_write,
                        allow_dense):
    bucket = ids.shape[0]
    trash = data.shape[0] - 1

    def dense_fn(_):
        sl = jax.lax.dynamic_slice(data, (start, 0), (bucket, data.shape[1]))
        # pad/foreign lanes' deltas are trash-bound — zero them so the
        # bulk path never applies them to live rows; their positions get
        # combine(row, 0) == row (the contract)
        dz = jnp.where((ids != trash)[:, None], deltas, 0)
        new = combine(sl, dz)
        out = jax.lax.dynamic_update_slice(data, new, (start, 0))
        return out, new   # prefix layout: the Get half IS ``new``

    def general(_):
        rows = jnp.take(data, ids, axis=0, mode="clip")
        new = combine(rows, deltas)
        if pallas_write:
            from multiverso_tpu.ops.pallas_rows import pallas_scatter_set_rows
            out = pallas_scatter_set_rows(data, ids, new,
                                          interpret=_interpret())
        else:
            out = data.at[ids].set(new)
        return out, new

    if (not allow_dense or not _dense_backend_ok()
            or bucket >= data.shape[0]):
        return general(None)   # static guards (see gather_rows)
    ok, start, _ = _dense_run(ids, data.shape[0])
    return jax.lax.cond(ok, dense_fn, general, None)
