"""Backend dispatch for the row gather/scatter table ops.

``use_pallas`` is governed by the ``use_pallas`` flag:
``auto`` (default) — Pallas on TPU, XLA elsewhere; ``on`` — Pallas
everywhere (interpreter mode off-TPU; used by tests); ``off`` — XLA.

The XLA fallback relies on jit'd gather + ``.at[].set`` — on a CPU test
mesh that is both correct and fast enough; on TPU the Pallas kernels avoid
materializing gather/scatter HLO over the whole shard.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from multiverso_tpu.utils.configure import GetFlag, MV_DEFINE_string

MV_DEFINE_string("use_pallas", "auto",
                 "row-op kernels: auto (TPU only) / on / off")


def _pallas_eligible(data) -> bool:
    """Row DMAs slice HBM along the lane dim, so rows must be tile-aligned:
    128 lanes for 4-byte dtypes (Mosaic: 'slice shape along dimension 1 must
    be aligned to tiling (128)')."""
    return data.dtype.itemsize == 4 and data.shape[-1] % 128 == 0


def use_pallas(data=None) -> bool:
    mode = str(GetFlag("use_pallas")).lower()
    if mode == "on":
        # forced on: always in interpreter mode (tests); on a real TPU still
        # respect the lowering constraint — an ineligible shape would be a
        # Mosaic compile error, not a kernel choice
        return _interpret() or data is None or _pallas_eligible(data)
    if mode == "off":
        return False
    return (jax.default_backend() == "tpu"
            and (data is None or _pallas_eligible(data)))


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def gather_rows(data: jax.Array, ids: jax.Array) -> jax.Array:
    """rows[i] = data[ids[i]]; all ids must be in range (caller maps
    out-of-shard lanes to the trash row)."""
    if use_pallas(data):
        from multiverso_tpu.ops.pallas_rows import pallas_gather_rows
        return pallas_gather_rows(data, ids, interpret=_interpret())
    return jnp.take(data, ids, axis=0)


def scatter_set_rows(data: jax.Array, ids: jax.Array,
                     rows: jax.Array) -> jax.Array:
    """data[ids[i]] = rows[i]; duplicates only on the trash row."""
    if use_pallas(data):
        from multiverso_tpu.ops.pallas_rows import pallas_scatter_set_rows
        return pallas_scatter_set_rows(data, ids, rows, interpret=_interpret())
    return data.at[ids].set(rows)
