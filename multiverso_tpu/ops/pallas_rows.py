"""Pallas TPU kernels: dynamic row gather / scatter / fused update on a
table shard.

These are the device half of the PS data plane. A ``Get`` over a row set is
one row-DMA per requested row out of the shard in HBM; an ``Add`` is the
mirrored write; the fused update kernel does read-modify-write in one pass
(row DMA in -> vector update in VMEM -> row DMA out), which is the
server-side Add of reference src/updater/updater.cpp:21-29 collapsed into a
single kernel instead of gather + XLA elementwise + scatter.

Row ids arrive as *scalar-prefetch* operands (SMEM) so DMA source/target
addresses are computed in-kernel.

Lowering constraints shape the design: a VMEM block must have its
second-to-last dim divisible by 8 (or equal to the array dim), so single
rows can't be blocks. Instead the grid runs over chunks of ``CHUNK`` ids;
the table shard itself stays in HBM (``memory_space=ANY``) and the kernel
issues one async row-copy per id — CHUNK outstanding DMAs per grid step,
waited together, while Mosaic pipelines the chunk blocks across steps.
CHUNK=64 measured ~1.3x over CHUNK=8 on v5e (deeper DMA pipelining); 128+
regresses (VMEM block pressure).

Coalescing: per-row DMAs cost ~68ns each on v5e regardless of locality —
pure descriptor-issue overhead (measured: random and contiguous id sets
gather at the same 7.5 GB/s). So each kernel checks, per chunk, whether
its ids are strictly consecutive (``_contig``: a scalar-core AND-chain
over the prefetched ids) and, when they are, rides ONE multi-row DMA for
the whole chunk instead of CHUNK row DMAs. Dense id sets — the WE
identity-remap blocks, reference test_matrix_perf's get-all phases, any
sorted run-heavy workload — collapse to sequential-copy bandwidth, while
random sparse sets keep the per-row path at unchanged cost (the check
adds ~5% scalar work per chunk). Ids are NOT sorted here: sorting would
force a same-sized permutation gather on the output (measured to cost as
much as the gather itself), so callers with natural locality get the win
and random callers pay nothing.

Contract (enforced by the caller, multiverso_tpu/tables/matrix_table.py):

* every id is in ``[0, num_rows)`` of the *local shard* — out-of-shard and
  padding lanes are pre-mapped to the shard's trash row;
* duplicate ids only occur on the trash row (the caller pre-combines
  duplicates), whose content is don't-care — so concurrent DMAs touching
  the same row (including the fused kernel's read-modify-write) can only
  collide on the trash row, never on live data. Ragged tails are handled
  in-kernel: gather over-fetches id 0 (read-only), scatter replicates the
  last pair (same bytes, same row), and the fused update *lane-guards* the
  tail with ``pl.when`` — a duplicated pad id there would write stale row
  bytes over the real lane's update.

On non-TPU backends the kernels run in interpreter mode (tests); the table
layer normally uses the XLA fallback there (rows.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# renamed across jax releases (TPUMemorySpace -> MemorySpace); resolve
# whichever this jax ships
_MemorySpace = getattr(pltpu, "MemorySpace", None) \
    or pltpu.TPUMemorySpace


def _contig(vals):
    """Traced predicate: the chunk's ids are strictly consecutive
    (ids[j] == ids[0] + j). Measured cost ~0.2us of scalar-core compares
    per chunk against the ~4us a per-row chunk body costs — the coalesced
    single-DMA branch it unlocks is worth 20-60x on dense id sets (see
    module docstring 'Coalescing')."""
    ok = vals[1] - vals[0] == 1
    for j in range(2, len(vals)):
        ok = jnp.logical_and(ok, vals[j] - vals[j - 1] == 1)
    return ok

CHUNK = 64
# Conservative slice of the ~16MB/core VMEM for a kernel's blocks.
# _chunk_for shrinks the chunk for wide rows so the blocks always fit; rows
# so wide that even MIN_CHUNK overflows make it return 0, which
# rows._pallas_eligible uses to route those tables to the XLA path.
VMEM_BUDGET = 4 * 1024 * 1024
MIN_CHUNK = 8
#: the fused RMW kernel's VMEM block count (deltas block double-buffered by
#: Mosaic's pipeline + scratch) — the worst case of the three kernels, and
#: therefore what eligibility is judged against
FUSED_BLOCKS = 3


def _chunk_for(cols: int, itemsize: int, blocks: int = FUSED_BLOCKS) -> int:
    """Largest chunk (<= CHUNK, >= MIN_CHUNK, power of two) for which
    ``blocks`` VMEM blocks of (chunk, cols) fit the budget, or 0 when even
    MIN_CHUNK does not. ``blocks`` is per kernel: the fused update holds
    FUSED_BLOCKS, gather/scatter hold 2 (one block, double-buffered).
    Callers derive chunk from static shapes, so it is a compile-time
    constant."""
    c = CHUNK
    while c > MIN_CHUNK and blocks * c * cols * itemsize > VMEM_BUDGET:
        c //= 2
    if blocks * c * cols * itemsize > VMEM_BUDGET:
        return 0
    return c


def _make_gather_kernel(chunk, coalesce):
    """``coalesce`` is static (table has >= chunk rows): a smaller table
    could never satisfy _contig at runtime, and its multi-row slice would
    be ill-formed at trace time — so the branch is only emitted when it
    can exist."""
    def _gather_kernel(ids_ref, data_ref, out_ref, sem):
        i = pl.program_id(0)
        vals = [ids_ref[i * chunk + j] for j in range(chunk)]

        def per_row():
            copies = []
            for j in range(chunk):
                copies.append(pltpu.make_async_copy(
                    data_ref.at[pl.ds(vals[j], 1), :],
                    out_ref.at[pl.ds(j, 1), :],
                    sem.at[j]))
            for c in copies:
                c.start()
            for c in copies:
                c.wait()

        if not coalesce:
            per_row()
            return
        contig = _contig(vals)

        @pl.when(contig)
        def _():
            # consecutive ids: the whole chunk is ONE multi-row DMA
            cp = pltpu.make_async_copy(
                data_ref.at[pl.ds(vals[0], chunk), :],
                out_ref.at[pl.ds(0, chunk), :],
                sem.at[0])
            cp.start()
            cp.wait()

        pl.when(jnp.logical_not(contig))(per_row)
    return _gather_kernel


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_gather_rows(data: jax.Array, ids: jax.Array,
                       interpret: bool = False) -> jax.Array:
    """rows[i] = data[ids[i]] — one row DMA per id, chunk per grid step."""
    chunk = _chunk_for(data.shape[1], data.dtype.itemsize, blocks=2)
    assert chunk, "caller must gate on rows._pallas_eligible"
    orig_n = ids.shape[0]
    if orig_n % chunk:
        # tail pad with id 0: a read-only over-fetch, sliced off below
        pad = chunk - orig_n % chunk
        ids = jnp.concatenate([ids, jnp.zeros(pad, ids.dtype)])
    n = ids.shape[0]
    cols = data.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // chunk,),
        in_specs=[
            pl.BlockSpec(memory_space=_MemorySpace.ANY),  # data: HBM
        ],
        out_specs=pl.BlockSpec((chunk, cols), lambda i, ids: (i, 0)),
        scratch_shapes=[pltpu.SemaphoreType.DMA((chunk,))],
    )
    out = pl.pallas_call(
        _make_gather_kernel(chunk, coalesce=data.shape[0] >= chunk),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, cols), data.dtype),
        interpret=interpret,
    )(ids, data)
    return out[:orig_n]


def _make_scatter_kernel(chunk, coalesce):
    def _scatter_kernel(ids_ref, rows_ref, data_ref, out_ref, sem):
        del data_ref  # alias donor; out_ref IS the table buffer
        i = pl.program_id(0)
        vals = [ids_ref[i * chunk + j] for j in range(chunk)]

        def per_row():
            copies = []
            for j in range(chunk):
                copies.append(pltpu.make_async_copy(
                    rows_ref.at[pl.ds(j, 1), :],
                    out_ref.at[pl.ds(vals[j], 1), :],
                    sem.at[j]))
            for c in copies:
                c.start()
            for c in copies:
                c.wait()

        if not coalesce:
            per_row()
            return
        contig = _contig(vals)

        @pl.when(contig)
        def _():
            cp = pltpu.make_async_copy(
                rows_ref.at[pl.ds(0, chunk), :],
                out_ref.at[pl.ds(vals[0], chunk), :],
                sem.at[0])
            cp.start()
            cp.wait()

        pl.when(jnp.logical_not(contig))(per_row)
    return _scatter_kernel


@functools.partial(jax.jit, static_argnames=("interpret",), donate_argnums=(0,))
def pallas_scatter_set_rows(data: jax.Array, ids: jax.Array,
                            rows: jax.Array,
                            interpret: bool = False) -> jax.Array:
    """data[ids[i]] = rows[i], in place (data is donated/aliased).

    Rows the ids never name keep their HBM content — only touched rows
    move, which is the whole point of the PS row protocol.
    """
    chunk = _chunk_for(data.shape[1], data.dtype.itemsize, blocks=2)
    assert chunk, "caller must gate on rows._pallas_eligible"
    if ids.shape[0] % chunk:
        # tail pad by replicating the last (id, row) pair: the extra DMAs
        # rewrite the same bytes to the same row — a no-op on memory content
        pad = chunk - ids.shape[0] % chunk
        ids = jnp.concatenate([ids] + [ids[-1:]] * pad)
        rows = jnp.concatenate([rows] + [rows[-1:]] * pad)
    n = ids.shape[0]
    cols = data.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // chunk,),
        in_specs=[
            pl.BlockSpec((chunk, cols), lambda i, ids: (i, 0)),   # rows: VMEM
            pl.BlockSpec(memory_space=_MemorySpace.ANY),  # data: HBM
        ],
        out_specs=pl.BlockSpec(memory_space=_MemorySpace.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((chunk,))],
    )
    return pl.pallas_call(
        _make_scatter_kernel(chunk, coalesce=data.shape[0] >= chunk),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(data.shape, data.dtype),
        input_output_aliases={2: 0},  # operand index counts the prefetch arg
        interpret=interpret,
    )(ids, rows, data)


def _make_update_kernel(combine, orig_n, chunk, coalesce):
    """RMW kernel. ``orig_n`` is the true id count: when it isn't a chunk
    multiple, tail lanes are skipped via pl.when (a duplicated pad id would
    RACE — the dup lane would write the row's pre-update bytes back over
    the real lane's update). Full-chunk batches compile with no guards.

    Coalescing: pad ids are zeros, which break strict +1 contiguity, so
    the single-DMA branch is unreachable for ragged chunks — pad lanes can
    only take the guarded per-row branch. ``coalesce`` statically drops
    the branch for tables smaller than one chunk (see _make_gather_kernel).
    """
    ragged = orig_n % chunk != 0

    def _update_kernel(ids_ref, deltas_ref, data_ref, out_ref, scratch,
                       rsem, wsem):
        del data_ref  # alias donor; out_ref IS the table buffer
        i = pl.program_id(0)
        vals = [ids_ref[i * chunk + j] for j in range(chunk)]

        def lane(j, fn):
            if ragged:
                pl.when(i * chunk + j < orig_n)(fn)
            else:
                fn()

        def cp(j, write):
            """The lane-j row DMA descriptor: table row <-> scratch row."""
            tbl = out_ref.at[pl.ds(vals[j], 1), :]
            buf = scratch.at[pl.ds(j, 1), :]
            if write:
                return pltpu.make_async_copy(buf, tbl, wsem.at[j])
            return pltpu.make_async_copy(tbl, buf, rsem.at[j])

        def per_row(write):
            for j in range(chunk):
                lane(j, lambda j=j: cp(j, write).start())
            for j in range(chunk):
                lane(j, lambda j=j: cp(j, write).wait())

        if not coalesce:
            per_row(False)
            scratch[...] = combine(scratch[...], deltas_ref[...])
            per_row(True)
            return

        contig = _contig(vals)

        def whole(write):
            tbl = out_ref.at[pl.ds(vals[0], chunk), :]
            buf = scratch.at[pl.ds(0, chunk), :]
            if write:
                return pltpu.make_async_copy(buf, tbl, wsem.at[0])
            return pltpu.make_async_copy(tbl, buf, rsem.at[0])

        @pl.when(contig)
        def _():
            whole(False).start()
            whole(False).wait()

        pl.when(jnp.logical_not(contig))(lambda: per_row(False))

        scratch[...] = combine(scratch[...], deltas_ref[...])

        @pl.when(contig)
        def _():
            whole(True).start()
            whole(True).wait()

        pl.when(jnp.logical_not(contig))(lambda: per_row(True))
    return _update_kernel


@functools.partial(jax.jit, static_argnames=("combine", "interpret"),
                   donate_argnums=(0,))
def pallas_update_rows(data: jax.Array, ids: jax.Array, deltas: jax.Array,
                       combine, interpret: bool = False) -> jax.Array:
    """data[ids[i]] = combine(data[ids[i]], deltas[i]), in place — the
    fused server-side Add (read rows -> vector update in VMEM -> write
    back), one pass over the touched rows.

    ``combine`` must be a jax-traceable elementwise fn of (rows, deltas)
    with ``combine(rows, 0) == rows`` (see module contract). It is a static
    arg: one compile per (shape, combine) pair — combines are per-table
    updater singletons, so this never retraces in steady state.
    """
    chunk = _chunk_for(data.shape[1], data.dtype.itemsize)
    assert chunk, "caller must gate on rows._pallas_eligible"
    orig_n = ids.shape[0]
    if orig_n % chunk:
        # tail pad to a chunk multiple; the padded lanes are skipped inside
        # the kernel (see _make_update_kernel — pad *values* are never read)
        pad = chunk - orig_n % chunk
        ids = jnp.concatenate([ids, jnp.zeros(pad, ids.dtype)])
        deltas = jnp.concatenate(
            [deltas, jnp.zeros((pad, deltas.shape[1]), deltas.dtype)])
    n = ids.shape[0]
    cols = data.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // chunk,),
        in_specs=[
            pl.BlockSpec((chunk, cols), lambda i, ids: (i, 0)),  # deltas
            pl.BlockSpec(memory_space=_MemorySpace.ANY),    # data: HBM
        ],
        out_specs=pl.BlockSpec(memory_space=_MemorySpace.ANY),
        scratch_shapes=[pltpu.VMEM((chunk, cols), data.dtype),
                        pltpu.SemaphoreType.DMA((chunk,)),
                        pltpu.SemaphoreType.DMA((chunk,))],
    )
    return pl.pallas_call(
        _make_update_kernel(combine, orig_n, chunk,
                            coalesce=data.shape[0] >= chunk),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(data.shape, data.dtype),
        input_output_aliases={2: 0},  # operand index counts the prefetch arg
        interpret=interpret,
    )(ids, deltas, data)
