"""Pallas TPU kernels: dynamic row gather / scatter on a table shard.

These are the device half of the PS data plane. A ``Get`` over a row set is
one row-DMA per requested row out of the shard in HBM; an ``Add`` is the
mirrored write. The row ids arrive as *scalar-prefetch* operands so the DMA
addresses are known before each grid step runs
(``pltpu.PrefetchScalarGridSpec``).

Contract (enforced by the caller, multiverso_tpu/tables/matrix_table.py):

* every id is in ``[0, num_rows)`` of the *local shard* — out-of-shard and
  padding lanes are pre-mapped to the shard's trash row;
* duplicate ids only occur on the trash row (the caller pre-combines
  duplicates), whose content is don't-care — so the scatter's
  revisit-a-block hazard cannot corrupt live data.

On non-TPU backends the kernels run in interpreter mode (tests); the table
layer normally uses the XLA fallback there (rows.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _gather_kernel(ids_ref, data_ref, out_ref):
    del ids_ref  # consumed by the index_map
    out_ref[...] = data_ref[...]


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_gather_rows(data: jax.Array, ids: jax.Array,
                       interpret: bool = False) -> jax.Array:
    """rows[i] = data[ids[i]] — one grid step (one row DMA) per id."""
    n = ids.shape[0]
    cols = data.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, cols), lambda i, ids: (ids[i], 0)),
        ],
        out_specs=pl.BlockSpec((1, cols), lambda i, ids: (i, 0)),
    )
    return pl.pallas_call(
        _gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, cols), data.dtype),
        interpret=interpret,
    )(ids, data)


def _scatter_kernel(ids_ref, rows_ref, data_ref, out_ref):
    del ids_ref, data_ref  # index_map consumes ids; data is the alias donor
    out_ref[...] = rows_ref[...]


@functools.partial(jax.jit, static_argnames=("interpret",), donate_argnums=(0,))
def pallas_scatter_set_rows(data: jax.Array, ids: jax.Array,
                            rows: jax.Array,
                            interpret: bool = False) -> jax.Array:
    """data[ids[i]] = rows[i], in place (data is donated/aliased).

    Rows the grid never maps keep their HBM content — only touched rows
    move, which is the whole point of the PS row protocol.
    """
    n = ids.shape[0]
    cols = data.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, cols), lambda i, ids: (i, 0)),        # rows
            pl.BlockSpec((1, cols), lambda i, ids: (ids[i], 0)),   # data (alias)
        ],
        out_specs=pl.BlockSpec((1, cols), lambda i, ids: (ids[i], 0)),
    )
    return pl.pallas_call(
        _scatter_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(data.shape, data.dtype),
        input_output_aliases={2: 0},  # operand index counts the prefetch arg
        interpret=interpret,
    )(ids, rows, data)
