"""Pallas TPU kernels: dynamic row gather / scatter / fused update on a
table shard.

These are the device half of the PS data plane. A ``Get`` over a row set is
one row-DMA per requested row out of the shard in HBM; an ``Add`` is the
mirrored write; the fused update kernel does read-modify-write in one pass
(row DMA in -> vector update in VMEM -> row DMA out), which is the
server-side Add of reference src/updater/updater.cpp:21-29 collapsed into a
single kernel instead of gather + XLA elementwise + scatter.

Row ids arrive as *scalar-prefetch* operands (SMEM) so DMA source/target
addresses are computed in-kernel.

Lowering constraints shape the design: a VMEM block must have its
second-to-last dim divisible by 8 (or equal to the array dim), so single
rows can't be blocks. Instead the grid runs over chunks of ``CHUNK`` ids;
the table shard itself stays in HBM (``memory_space=ANY``) and the kernel
issues one async row-copy per id — CHUNK outstanding DMAs per grid step,
waited together, while Mosaic pipelines the chunk blocks across steps.
CHUNK=64 measured ~1.3x over CHUNK=8 on v5e (deeper DMA pipelining); 128+
regresses (VMEM block pressure).

Contract (enforced by the caller, multiverso_tpu/tables/matrix_table.py):

* every id is in ``[0, num_rows)`` of the *local shard* — out-of-shard and
  padding lanes are pre-mapped to the shard's trash row;
* duplicate ids only occur on the trash row (the caller pre-combines
  duplicates), whose content is don't-care — so concurrent DMAs touching
  the same row (including the fused kernel's read-modify-write) can only
  collide on the trash row, never on live data. Ragged tails are handled
  in-kernel: gather over-fetches id 0 (read-only), scatter replicates the
  last pair (same bytes, same row), and the fused update *lane-guards* the
  tail with ``pl.when`` — a duplicated pad id there would write stale row
  bytes over the real lane's update.

On non-TPU backends the kernels run in interpreter mode (tests); the table
layer normally uses the XLA fallback there (rows.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

CHUNK = 64


def _gather_kernel(ids_ref, data_ref, out_ref, sem):
    i = pl.program_id(0)
    copies = []
    for j in range(CHUNK):
        row = ids_ref[i * CHUNK + j]
        copies.append(pltpu.make_async_copy(
            data_ref.at[pl.ds(row, 1), :],
            out_ref.at[pl.ds(j, 1), :],
            sem.at[j]))
    for c in copies:
        c.start()
    for c in copies:
        c.wait()


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_gather_rows(data: jax.Array, ids: jax.Array,
                       interpret: bool = False) -> jax.Array:
    """rows[i] = data[ids[i]] — one row DMA per id, CHUNK per grid step."""
    orig_n = ids.shape[0]
    if orig_n % CHUNK:
        # tail pad with id 0: a read-only over-fetch, sliced off below
        pad = CHUNK - orig_n % CHUNK
        ids = jnp.concatenate([ids, jnp.zeros(pad, ids.dtype)])
    n = ids.shape[0]
    cols = data.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // CHUNK,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.MemorySpace.ANY),  # data: HBM
        ],
        out_specs=pl.BlockSpec((CHUNK, cols), lambda i, ids: (i, 0)),
        scratch_shapes=[pltpu.SemaphoreType.DMA((CHUNK,))],
    )
    out = pl.pallas_call(
        _gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, cols), data.dtype),
        interpret=interpret,
    )(ids, data)
    return out[:orig_n]


def _scatter_kernel(ids_ref, rows_ref, data_ref, out_ref, sem):
    del data_ref  # alias donor; out_ref IS the table buffer
    i = pl.program_id(0)
    copies = []
    for j in range(CHUNK):
        row = ids_ref[i * CHUNK + j]
        copies.append(pltpu.make_async_copy(
            rows_ref.at[pl.ds(j, 1), :],
            out_ref.at[pl.ds(row, 1), :],
            sem.at[j]))
    for c in copies:
        c.start()
    for c in copies:
        c.wait()


@functools.partial(jax.jit, static_argnames=("interpret",), donate_argnums=(0,))
def pallas_scatter_set_rows(data: jax.Array, ids: jax.Array,
                            rows: jax.Array,
                            interpret: bool = False) -> jax.Array:
    """data[ids[i]] = rows[i], in place (data is donated/aliased).

    Rows the ids never name keep their HBM content — only touched rows
    move, which is the whole point of the PS row protocol.
    """
    if ids.shape[0] % CHUNK:
        # tail pad by replicating the last (id, row) pair: the extra DMAs
        # rewrite the same bytes to the same row — a no-op on memory content
        pad = CHUNK - ids.shape[0] % CHUNK
        ids = jnp.concatenate([ids] + [ids[-1:]] * pad)
        rows = jnp.concatenate([rows] + [rows[-1:]] * pad)
    n = ids.shape[0]
    cols = data.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // CHUNK,),
        in_specs=[
            pl.BlockSpec((CHUNK, cols), lambda i, ids: (i, 0)),   # rows: VMEM
            pl.BlockSpec(memory_space=pltpu.MemorySpace.ANY),  # data: HBM
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.MemorySpace.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((CHUNK,))],
    )
    return pl.pallas_call(
        _scatter_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(data.shape, data.dtype),
        input_output_aliases={2: 0},  # operand index counts the prefetch arg
        interpret=interpret,
    )(ids, rows, data)


def _make_update_kernel(combine, orig_n):
    """RMW kernel. ``orig_n`` is the true id count: when it isn't a CHUNK
    multiple, tail lanes are skipped via pl.when (a duplicated pad id would
    RACE — the dup lane would write the row's pre-update bytes back over
    the real lane's update). Full-chunk batches compile with no guards."""
    ragged = orig_n % CHUNK != 0

    def _update_kernel(ids_ref, deltas_ref, data_ref, out_ref, scratch,
                       rsem, wsem):
        del data_ref  # alias donor; out_ref IS the table buffer
        i = pl.program_id(0)

        def lane(j, fn):
            if ragged:
                pl.when(i * CHUNK + j < orig_n)(fn)
            else:
                fn()

        def rd(j):
            def go():
                row = ids_ref[i * CHUNK + j]
                pltpu.make_async_copy(out_ref.at[pl.ds(row, 1), :],
                                      scratch.at[pl.ds(j, 1), :],
                                      rsem.at[j]).start()
            return go

        def rd_wait(j):
            def go():
                row = ids_ref[i * CHUNK + j]
                pltpu.make_async_copy(out_ref.at[pl.ds(row, 1), :],
                                      scratch.at[pl.ds(j, 1), :],
                                      rsem.at[j]).wait()
            return go

        def wr(j):
            def go():
                row = ids_ref[i * CHUNK + j]
                pltpu.make_async_copy(scratch.at[pl.ds(j, 1), :],
                                      out_ref.at[pl.ds(row, 1), :],
                                      wsem.at[j]).start()
            return go

        def wr_wait(j):
            def go():
                row = ids_ref[i * CHUNK + j]
                pltpu.make_async_copy(scratch.at[pl.ds(j, 1), :],
                                      out_ref.at[pl.ds(row, 1), :],
                                      wsem.at[j]).wait()
            return go

        for j in range(CHUNK):
            lane(j, rd(j))
        for j in range(CHUNK):
            lane(j, rd_wait(j))
        scratch[...] = combine(scratch[...], deltas_ref[...])
        for j in range(CHUNK):
            lane(j, wr(j))
        for j in range(CHUNK):
            lane(j, wr_wait(j))
    return _update_kernel


@functools.partial(jax.jit, static_argnames=("combine", "interpret"),
                   donate_argnums=(0,))
def pallas_update_rows(data: jax.Array, ids: jax.Array, deltas: jax.Array,
                       combine, interpret: bool = False) -> jax.Array:
    """data[ids[i]] = combine(data[ids[i]], deltas[i]), in place — the
    fused server-side Add (read rows -> vector update in VMEM -> write
    back), one pass over the touched rows.

    ``combine`` must be a jax-traceable elementwise fn of (rows, deltas)
    with ``combine(rows, 0) == rows`` (see module contract). It is a static
    arg: one compile per (shape, combine) pair — combines are per-table
    updater singletons, so this never retraces in steady state.
    """
    orig_n = ids.shape[0]
    if orig_n % CHUNK:
        # tail pad to a CHUNK multiple; the padded lanes are skipped inside
        # the kernel (see _make_update_kernel — pad *values* are never read)
        pad = CHUNK - orig_n % CHUNK
        ids = jnp.concatenate([ids, jnp.zeros(pad, ids.dtype)])
        deltas = jnp.concatenate(
            [deltas, jnp.zeros((pad, deltas.shape[1]), deltas.dtype)])
    n = ids.shape[0]
    cols = data.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // CHUNK,),
        in_specs=[
            pl.BlockSpec((CHUNK, cols), lambda i, ids: (i, 0)),  # deltas
            pl.BlockSpec(memory_space=pltpu.MemorySpace.ANY),    # data: HBM
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.MemorySpace.ANY),
        scratch_shapes=[pltpu.VMEM((CHUNK, cols), data.dtype),
                        pltpu.SemaphoreType.DMA((CHUNK,)),
                        pltpu.SemaphoreType.DMA((CHUNK,))],
    )
    return pl.pallas_call(
        _make_update_kernel(combine, orig_n),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(data.shape, data.dtype),
        input_output_aliases={2: 0},  # operand index counts the prefetch arg
        interpret=interpret,
    )(ids, deltas, data)
