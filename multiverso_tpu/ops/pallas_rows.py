"""Pallas TPU kernels: dynamic row gather / scatter on a table shard.

These are the device half of the PS data plane. A ``Get`` over a row set is
one row-DMA per requested row out of the shard in HBM; an ``Add`` is the
mirrored write. Row ids arrive as *scalar-prefetch* operands (SMEM) so DMA
source/target addresses are computed in-kernel.

Lowering constraints shape the design: a VMEM block must have its
second-to-last dim divisible by 8 (or equal to the array dim), so single
rows can't be blocks. Instead the grid runs over chunks of ``CHUNK=8`` ids;
the table shard itself stays in HBM (``memory_space=ANY``) and the kernel
issues one async row-copy per id — 8 outstanding DMAs per grid step, waited
together, while Mosaic pipelines the chunk blocks across steps.

Contract (enforced by the caller, multiverso_tpu/tables/matrix_table.py):

* ``ids`` length is a multiple of 8 (the table layer pads row-id batches to
  power-of-two buckets >= 8);
* every id is in ``[0, num_rows)`` of the *local shard* — out-of-shard and
  padding lanes are pre-mapped to the shard's trash row;
* duplicate ids only occur on the trash row (the caller pre-combines
  duplicates), whose content is don't-care — so concurrent DMA writes to
  the same row can only land on the trash row, never on live data.

On non-TPU backends the kernels run in interpreter mode (tests); the table
layer normally uses the XLA fallback there (rows.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

CHUNK = 8


def _gather_kernel(ids_ref, data_ref, out_ref, sem):
    i = pl.program_id(0)
    copies = []
    for j in range(CHUNK):
        row = ids_ref[i * CHUNK + j]
        copies.append(pltpu.make_async_copy(
            data_ref.at[pl.ds(row, 1), :],
            out_ref.at[pl.ds(j, 1), :],
            sem.at[j]))
    for c in copies:
        c.start()
    for c in copies:
        c.wait()


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_gather_rows(data: jax.Array, ids: jax.Array,
                       interpret: bool = False) -> jax.Array:
    """rows[i] = data[ids[i]] — one row DMA per id, 8 per grid step."""
    orig_n = ids.shape[0]
    if orig_n % CHUNK:
        # tail pad with id 0: a read-only over-fetch, sliced off below
        pad = CHUNK - orig_n % CHUNK
        ids = jnp.concatenate([ids, jnp.zeros(pad, ids.dtype)])
    n = ids.shape[0]
    cols = data.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // CHUNK,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.MemorySpace.ANY),  # data: HBM
        ],
        out_specs=pl.BlockSpec((CHUNK, cols), lambda i, ids: (i, 0)),
        scratch_shapes=[pltpu.SemaphoreType.DMA((CHUNK,))],
    )
    out = pl.pallas_call(
        _gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, cols), data.dtype),
        interpret=interpret,
    )(ids, data)
    return out[:orig_n]


def _scatter_kernel(ids_ref, rows_ref, data_ref, out_ref, sem):
    del data_ref  # alias donor; out_ref IS the table buffer
    i = pl.program_id(0)
    copies = []
    for j in range(CHUNK):
        row = ids_ref[i * CHUNK + j]
        copies.append(pltpu.make_async_copy(
            rows_ref.at[pl.ds(j, 1), :],
            out_ref.at[pl.ds(row, 1), :],
            sem.at[j]))
    for c in copies:
        c.start()
    for c in copies:
        c.wait()


@functools.partial(jax.jit, static_argnames=("interpret",), donate_argnums=(0,))
def pallas_scatter_set_rows(data: jax.Array, ids: jax.Array,
                            rows: jax.Array,
                            interpret: bool = False) -> jax.Array:
    """data[ids[i]] = rows[i], in place (data is donated/aliased).

    Rows the ids never name keep their HBM content — only touched rows
    move, which is the whole point of the PS row protocol.
    """
    if ids.shape[0] % CHUNK:
        # tail pad by replicating the last (id, row) pair: the extra DMAs
        # rewrite the same bytes to the same row — a no-op on memory content
        pad = CHUNK - ids.shape[0] % CHUNK
        ids = jnp.concatenate([ids] + [ids[-1:]] * pad)
        rows = jnp.concatenate([rows] + [rows[-1:]] * pad)
    n = ids.shape[0]
    cols = data.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // CHUNK,),
        in_specs=[
            pl.BlockSpec((CHUNK, cols), lambda i, ids: (i, 0)),   # rows: VMEM
            pl.BlockSpec(memory_space=pltpu.MemorySpace.ANY),  # data: HBM
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.MemorySpace.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((CHUNK,))],
    )
    return pl.pallas_call(
        _scatter_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(data.shape, data.dtype),
        input_output_aliases={2: 0},  # operand index counts the prefetch arg
        interpret=interpret,
    )(ids, rows, data)
