"""TPU kernels for the parameter-server hot path.

The reference's hot loops are the server's per-row updater application and
the serialize/memcpy path (reference src/updater/updater.cpp:21-29 OpenMP
loops; src/net/mpi_net.h:300-349 serialize memcpys). Here they are device
kernels: Pallas row gather / scatter on TPU (one DMA per requested row,
no full-table traffic), with an XLA fallback for CPU test meshes.
"""

from multiverso_tpu.ops.rows import (dedup_rows, gather_rows, padded_cols,
                                     scatter_set_rows, update_gather_rows,
                                     update_rows, use_pallas)

__all__ = ["dedup_rows", "gather_rows", "padded_cols", "scatter_set_rows",
           "update_gather_rows", "update_rows", "use_pallas"]
