"""never-collective: no static path from a restricted root to a collective.

The PR 2 law ("reporter threads never issue collectives" —
telemetry/export.py's module docstring) generalized: a timer/handler
thread that issues a collective interleaves with the engine's window
exchanges and corrupts the SPMD verb stream. The restricted ROOTS are
every entry point that runs on such a thread; the SINKS are every
collective primitive this build owns plus the well-known external
collective attributes (callgraph.EXTERNAL_COLLECTIVE_ATTRS). Any
statically reachable root→sink path is a finding, reported with the
full call chain.

Config rot is itself an error: a configured root or sink that no
longer names a graph node fails the run, so a refactor can't silently
retire the protection (the tier-1 baseline test also re-derives that
the root set covers the conventions DESIGN.md documents).

Deliberately NOT a root: ``Dashboard.DisplayAll`` and
``metrics.Registry.snapshot_all_hosts`` are the package's two
*explicitly* collective observability surfaces — every process must
call them at the same point, like MV_Barrier. The law protects the
surfaces that run on sampling/handler threads, where nobody
coordinates ranks.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from multiverso_tpu.analysis import callgraph
from multiverso_tpu.analysis.core import (Checker, Finding, PackageIndex,
                                          register)

#: restricted roots: node id -> the convention it encodes
DEFAULT_ROOTS: Dict[str, str] = {
    "telemetry/ops.py:_OpsHandler.do_GET":
        "ops HTTP handler (serves on the HTTP thread, engine unquiesced)",
    "telemetry/watchdog.py:Watchdog._run":
        "watchdog daemon loop",
    "telemetry/watchdog.py:Watchdog.tick":
        "watchdog tick (also called from /alerts handlers)",
    "telemetry/export.py:StatsReporter._run":
        "-stats_interval_s reporter thread",
    "telemetry/export.py:StatsReporter.emit":
        "reporter emit (also the final flush on stop)",
    "telemetry/accounting.py:memory_report":
        "memory ledger probe (sampled from watchdog/ops threads)",
    "telemetry/accounting.py:refresh":
        "ledger gauge refresh (/metrics scrape path)",
    "utils/dashboard.py:Dashboard.Display":
        "local dashboard render (DisplayAll is the collective sibling)",
    "utils/dashboard.py:Dashboard._ops_lines":
        "dashboard [Ops] line (renders during teardown)",
    # round 17 — replica plane: the reader process's serve loop (no
    # SPMD stream exists in that process at all) and the trainer's
    # fan-out thread (runs beside the engine; its per-replica ring is
    # point-to-point to a non-SPMD reader and carries a reasoned
    # suppression at the def — see replica/publisher.py)
    "replica/replica.py:_LookupHandler.handle":
        "replica lookup serve loop (jax-free reader process)",
    "replica/publisher.py:ReplicaPublisher._run":
        "replica fan-out thread (ships beside the engine stream)",
    # round 20 — the policy plane's evaluation daemon: it STAGES
    # actions (local queue / coordinator RPC) and, single-process,
    # installs at an engine cut (a mailbox hand-off) — never a
    # collective; the collective drain leg lives in MV_PolicySync on
    # app threads by construction, and this root keeps it there
    "policy/engine.py:PolicyEngine._run":
        "policy evaluation daemon (alert->action loop)",
    "policy/engine.py:PolicyEngine.step":
        "policy evaluation step (also driven directly by tests)",
    # round 22 — the fleet plane's two legs: rollup builds run on lease
    # heartbeat daemons (a collective there deadlocks the beat against
    # the engine stream), and the coordinator-side fold runs on RPC
    # handler threads serving members that are mid-collective
    "telemetry/fleet.py:build_rollup":
        "fleet rollup build (lease heartbeat daemon threads)",
    "telemetry/fleet.py:FleetAccumulator.ingest":
        "coordinator-side fleet rollup fold (RPC handler threads)",
    # round 23 — coordinator HA: the standby's takeover replays the op
    # log and serves INSIDE a jax-free standby process that has no
    # SPMD stream — a collective reachable from it would hang the
    # successor forever (no rank will ever match it)
    "elastic/standby.py:StandbyServer.force_takeover":
        "standby lease takeover (log replay + successor bind, "
        "jax-free standby process)",
}

#: collective primitives: node id -> what it is
DEFAULT_SINKS: Dict[str, str] = {
    "parallel/multihost.py:capped_exchange":
        "the engine's one host-byte collective",
    "parallel/multihost.py:host_barrier": "cross-host barrier",
    "parallel/multihost.py:host_allreduce_sum": "allreduce",
    "parallel/multihost.py:host_allgather_bytes": "allgather",
    "parallel/multihost.py:host_allgather_objects": "object allgather",
    "parallel/multihost.py:host_allgather_objects_capped":
        "capped object allgather",
    "parallel/multihost.py:broadcast_from_master": "broadcast",
    "parallel/multihost.py:merge_collective_add": "collective row merge",
    "parallel/multihost.py:sum_collective_add": "collective value sum",
    "parallel/multihost.py:union_collective_ids": "collective id union",
    "parallel/multihost.py:Group.exchange": "membership-group exchange",
    "parallel/multihost.py:Group.barrier": "membership-group barrier",
    "parallel/shm_wire.py:ShmWire.exchange": "shm-wire exchange",
    "parallel/tcp_wire.py:TcpWire.exchange": "tcp-wire exchange",
    "zoo.py:Zoo._barrier_wait": "zoo rendezvous barrier leg",
}


@register
class NeverCollectiveChecker(Checker):
    name = "never-collective"
    description = ("no statically reachable path from a restricted root "
                   "(HTTP handler / watchdog / reporter / ledger probe / "
                   "dashboard render) to a collective primitive")

    def __init__(self,
                 roots: Optional[Dict[str, str]] = None,
                 sinks: Optional[Dict[str, str]] = None) -> None:
        super().__init__()
        self.roots = DEFAULT_ROOTS if roots is None else roots
        self.sinks = DEFAULT_SINKS if sinks is None else sinks
        #: filled by check(): root node -> set of reachable nodes
        self.closures: Dict[str, set] = {}

    def check(self, pkg: PackageIndex) -> List[Finding]:
        graph = callgraph.build_graph(pkg)
        self.scanned.update(pkg.rel_paths)
        out: List[Finding] = []

        def _cfg_finding(node: str, what: str, label: str) -> Finding:
            # anchor to where the stale config entry LIVES (this
            # module), not to the vanished module or an arbitrary
            # package file — that is the file the fix edits
            cfg = "analysis/collective.py"
            path = cfg if pkg.file(cfg) is not None \
                else node.split(":", 1)[0]
            return Finding(
                self.name, path, 1,
                f"configured {what} {node!r} ({label}) names no graph "
                f"node — the refactor that moved it must update "
                f"analysis/collective.py, not retire the protection")

        sink_nodes = set()
        for node, label in self.sinks.items():
            if not graph.has_node(node):
                out.append(_cfg_finding(node, "collective sink", label))
            else:
                sink_nodes.add(node)
        # external collective attrs are sinks wherever they appear
        external = {t for targets in graph.edges.values()
                    for t in targets if t.startswith("<external>:")}
        sink_nodes |= external

        for root, label in sorted(self.roots.items()):
            if not graph.has_node(root):
                out.append(_cfg_finding(root, "restricted root", label))
                continue
            seen, parent = graph.reachable([root])
            self.closures[root] = seen
            rel, line = graph.node_lines[root]
            for sink in sorted(seen & sink_nodes):
                chain = " -> ".join(graph.path_to(parent, sink))
                sink_label = self.sinks.get(
                    sink, "external collective attribute")
                out.append(Finding(
                    self.name, rel, line,
                    f"{root} ({label}) statically reaches collective "
                    f"{sink} ({sink_label}): {chain}"))
        return out
