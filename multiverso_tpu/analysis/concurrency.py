"""Concurrency-domain checkers over the thread inventory (DESIGN.md §18).

Four laws, each consuming :mod:`threads`' domain closures:

* ``cross-domain-state`` — an attribute written from >= 2 thread
  domains with no common lexical lock scope is a data race candidate.
  Conservative by construction: only ``self``/``cls`` attribute stores
  and declared-``global`` stores count as writes, ``__init__`` writes
  are exempt (construction happens-before thread start), and lock
  scopes match by NAME (``with self._lock:``), so two same-named locks
  on different objects can mask a true race (false-negative direction;
  the honesty limits are documented in DESIGN.md §18).
* ``device-work-domain`` — jax/jnp calls, the jit'd row-op kernels and
  the mirror-syncing table ``state`` property must be unreachable from
  sampling/handler/fan-out threads: PR 10's probe-never-syncs-mirror
  regression test generalized to the whole package.
* ``lock-order`` — per-function ``with``-nesting composed through the
  call graph into a lock acquisition-order graph; a cycle is a
  potential deadlock, and re-acquiring a non-reentrant ``Lock`` under
  itself is the one-lock form of the same bug.
* ``blocking-domain`` — the PR 3 bounded-blocking law upgraded from
  per-line regex to reachability: an unbounded ``.wait()``/``.join()``
  (or a ``.recv()``/``.accept()`` in a module that never arms a socket
  timeout) reachable from a handler or engine-thread root stalls a
  thread the runtime cannot afford to lose, even when a per-line
  ``unbounded-ok:`` justification makes it legal elsewhere.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from multiverso_tpu.analysis import callgraph, threads
from multiverso_tpu.analysis.core import (Checker, Finding, PackageIndex,
                                          register)

#: fields never walked: annotation expressions reference jnp/jax types
#: without running device work
_SKIP_FIELDS = frozenset({"annotation", "returns"})

#: defs whose writes are construction, not concurrency (the instance is
#: not yet shared when they run)
_INIT_QUALS = frozenset({"__init__", "__new__", "__post_init__"})

_BLOCKING_ATTRS = frozenset({"wait", "join"})
_RECV_ATTRS = frozenset({"recv", "recv_into", "accept"})
_LOCK_CTORS = frozenset({"Lock", "RLock", "Condition", "Semaphore",
                         "BoundedSemaphore"})
#: constructors whose product is NOT safely re-acquirable by the same
#: thread (threading.Lock/Condition deadlock on re-entry)
_NON_REENTRANT = frozenset({"Lock", "Condition"})


@dataclass(frozen=True)
class WriteSite:
    attr_key: Tuple[str, str]       #: (owner key "rel:Class", attr)
    line: int
    locks: FrozenSet[str]           #: lock NAMES held at the write


@dataclass
class DefFacts:
    """Concurrency-relevant facts of one top-level def."""

    node: str                       #: call-graph node id "rel:qual"
    rel: str
    qual: str
    line: int
    writes: List[WriteSite] = field(default_factory=list)
    #: qualified lock keys acquired anywhere in this def, with lines
    acquires: List[Tuple[str, int]] = field(default_factory=list)
    #: (outer key, inner key, line) lexical with-nesting pairs
    lex_pairs: List[Tuple[str, str, int]] = field(default_factory=list)
    #: (held lock key, called name, line) for call-composed ordering
    calls_under: List[Tuple[str, str, int]] = field(default_factory=list)
    #: (line, description) unbounded blocking sites
    blocking: List[Tuple[int, str]] = field(default_factory=list)
    #: (line, description) jax/device touches
    device: List[Tuple[int, str]] = field(default_factory=list)


@dataclass
class ModuleFacts:
    rel: str
    defs: List[DefFacts] = field(default_factory=list)
    jax_aliases: Set[str] = field(default_factory=set)
    has_settimeout: bool = False
    module_globals: Set[str] = field(default_factory=set)


def _jax_aliases(tree: ast.AST) -> Set[str]:
    """Local names bound to jax modules/symbols (``import jax``,
    ``import jax.numpy as jnp``, ``from jax import jit``...)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "jax" or alias.name.startswith("jax."):
                    out.add(alias.asname or alias.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.level == 0:
            mod = node.module or ""
            if mod == "jax" or mod.startswith("jax."):
                for alias in node.names:
                    out.add(alias.asname or alias.name)
    return out


def _has_settimeout(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("settimeout", "setdefaulttimeout"):
            if node.args and not (isinstance(node.args[0], ast.Constant)
                                  and node.args[0].value is None):
                return True
    return False


def _unbounded_blocking(call: ast.Call,
                        has_settimeout: bool) -> Optional[str]:
    """The bounded-blocking bound test, shared shape with
    rules.BoundedBlockingChecker: no argument, or every argument a
    literal ``None``, is the unbounded wait spelled out."""
    fn = call.func
    if not isinstance(fn, ast.Attribute):
        return None
    attr = fn.attr
    if attr.lower() in _BLOCKING_ATTRS:
        bounds = [a for a in call.args
                  if not (isinstance(a, ast.Constant) and a.value is None)]
        bounds += [k for k in call.keywords
                   if not (isinstance(k.value, ast.Constant)
                           and k.value.value is None)]
        if not bounds:
            return f"unbounded .{attr}()"
        return None
    if attr in _RECV_ATTRS and not has_settimeout:
        return (f"possibly-unbounded .{attr}() (this module never arms "
                f"a socket timeout)")
    return None


def _lock_ref(expr: ast.AST, rel: str, cls: Optional[str],
              module_globals: Set[str]
              ) -> Optional[Tuple[str, Optional[str]]]:
    """(name, qualified-key-or-None) for a with-context expression that
    looks like a lock: a plain Name or a self/attr chain — Calls
    (``open(...)``, ``trace.span(...)``) are not locks. A bare Name
    qualifies as a module-level lock ONLY when it really is a module
    global: a LOCAL alias (``lk = self._a; with lk:``) keys by name
    alone, or two methods aliasing different member locks to one local
    name would merge into a single lock-order node and manufacture
    cycles."""
    if isinstance(expr, ast.Name):
        if expr.id in module_globals:
            return expr.id, f"{rel}:<module>.{expr.id}"
        return expr.id, None
    if isinstance(expr, ast.Attribute):
        chain = callgraph._attr_chain(expr)
        if chain is None:
            return expr.attr, None
        if chain[0] in ("self", "cls") and cls is not None \
                and len(chain) == 2:
            return chain[-1], f"{rel}:{cls}.{chain[-1]}"
        return chain[-1], None
    return None


def _children(node: ast.AST):
    for name, fld in ast.iter_fields(node):
        if name in _SKIP_FIELDS:
            continue
        if isinstance(fld, ast.AST):
            yield fld
        elif isinstance(fld, list):
            for x in fld:
                if isinstance(x, ast.AST):
                    yield x


def _scan_def(df: DefFacts, root: ast.AST, rel: str, cls: Optional[str],
              mf: ModuleFacts,
              lock_kinds: Dict[str, str]) -> None:
    """One recursive pass filling ``df``: writes with the lexical lock
    stack, acquisitions/nesting/calls-under-lock, blocking and device
    sites. Nested defs/lambdas stay attributed to this def (call-graph
    node granularity) but RESET the lock stack — their bodies run
    later, outside the lexically enclosing ``with``."""
    declared_globals: Set[str] = {
        n for node in ast.walk(root) if isinstance(node, ast.Global)
        for n in node.names}
    owner = f"{rel}:{cls}" if cls else f"{rel}:<module>"

    def _note_write(tgt: ast.AST, line: int, locks) -> None:
        if isinstance(tgt, ast.Attribute) \
                and isinstance(tgt.value, ast.Name) \
                and tgt.value.id in ("self", "cls") and cls is not None:
            df.writes.append(WriteSite((owner, tgt.attr), line,
                                       frozenset(n for n, _ in locks)))
        elif isinstance(tgt, ast.Subscript):
            _note_write(tgt.value, line, locks)
        elif isinstance(tgt, ast.Name) \
                and (tgt.id in declared_globals
                     or (tgt.id in mf.module_globals
                         and df.qual == "<module>")):
            df.writes.append(WriteSite(
                (f"{rel}:<module>", tgt.id), line,
                frozenset(n for n, _ in locks)))
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                _note_write(e, line, locks)

    def _note_lock_ctor(node: ast.Assign) -> None:
        v = node.value
        if not (isinstance(v, ast.Call)):
            return
        fn = v.func
        name = (fn.id if isinstance(fn, ast.Name)
                else fn.attr if isinstance(fn, ast.Attribute) else None)
        if name not in _LOCK_CTORS:
            return
        for t in node.targets:
            ref = None
            if isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id in ("self", "cls") and cls is not None:
                ref = f"{rel}:{cls}.{t.attr}"
            elif isinstance(t, ast.Name) and df.qual == "<module>":
                ref = f"{rel}:<module>.{t.id}"
            if ref is not None:
                lock_kinds[ref] = name

    def _walk(node: ast.AST, locks: Tuple[Tuple[str, Optional[str]], ...]
              ) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            for c in _children(node):
                _walk(c, ())
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new = []
            for item in node.items:
                ref = _lock_ref(item.context_expr, rel, cls,
                                mf.module_globals)
                if ref is not None:
                    name, key = ref
                    if key is not None:
                        df.acquires.append((key, node.lineno))
                        for _, held_key in locks:
                            if held_key is not None:
                                df.lex_pairs.append(
                                    (held_key, key, node.lineno))
                        for _, hk in new:
                            if hk is not None:
                                df.lex_pairs.append(
                                    (hk, key, node.lineno))
                    new.append((name, key))
                else:
                    _walk(item.context_expr, locks)
            inner = locks + tuple(new)
            for stmt in node.body:
                _walk(stmt, inner)
            return
        if isinstance(node, ast.Assign):
            _note_lock_ctor(node)
            for t in node.targets:
                _note_write(t, node.lineno, locks)
        elif isinstance(node, ast.AugAssign):
            _note_write(node.target, node.lineno, locks)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            _note_write(node.target, node.lineno, locks)
        elif isinstance(node, ast.Call):
            what = _unbounded_blocking(node, mf.has_settimeout)
            if what is not None:
                df.blocking.append((node.lineno, what))
            fn = node.func
            cname = (fn.id if isinstance(fn, ast.Name)
                     else fn.attr if isinstance(fn, ast.Attribute)
                     else None)
            if cname is not None:
                for _, key in locks:
                    if key is not None:
                        df.calls_under.append((key, cname, node.lineno))
        if isinstance(node, ast.Attribute):
            chain = callgraph._attr_chain(node)
            if chain is not None and chain[0] in mf.jax_aliases:
                df.device.append((node.lineno, ".".join(chain)))
                return      # the nested chain would double-report
        elif isinstance(node, ast.Name) and node.id in mf.jax_aliases \
                and isinstance(getattr(node, "ctx", None), ast.Load):
            df.device.append((node.lineno, node.id))
        for c in _children(node):
            _walk(c, locks)

    _walk(root, ())


def _module_facts(sf, lock_kinds: Dict[str, str]) -> ModuleFacts:
    mf = ModuleFacts(rel=sf.rel)
    mf.jax_aliases = _jax_aliases(sf.tree)
    mf.has_settimeout = _has_settimeout(sf.tree)
    body = callgraph.flat_body(sf.tree.body)
    for node in body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    mf.module_globals.add(t.id)

    covered = set()
    for qual, cls_node, node in callgraph.iter_top_defs(sf.tree):
        covered.add(node)
        df = DefFacts(node=f"{sf.rel}:{qual}", rel=sf.rel, qual=qual,
                      line=node.lineno)
        _scan_def(df, node, sf.rel,
                  cls_node.name if cls_node is not None else None,
                  mf, lock_kinds)
        mf.defs.append(df)
    mod_df = DefFacts(node=f"{sf.rel}:<module>", rel=sf.rel,
                      qual="<module>", line=1)
    for node in body:
        if node not in covered and not isinstance(node, ast.ClassDef):
            _scan_def(mod_df, node, sf.rel, None, mf, lock_kinds)
    mf.defs.append(mod_df)
    return mf


@dataclass
class PackageFacts:
    pkg: PackageIndex
    by_rel: Dict[str, ModuleFacts]
    lock_kinds: Dict[str, str]      #: qualified lock key -> ctor name

    def defs(self, rels) -> List[DefFacts]:
        out: List[DefFacts] = []
        for rel in sorted(rels):
            mf = self.by_rel.get(rel)
            if mf is not None:
                out.extend(mf.defs)
        return out


_FACTS_CACHE: Dict[str, PackageFacts] = {}


def facts_for(pkg: PackageIndex) -> PackageFacts:
    # same staleness rule as callgraph.build_graph / threads
    # .inventory_for: a FRESH index for the same root (re-scan after a
    # source edit) must rebuild, never serve facts parsed from the old
    # source
    pf = _FACTS_CACHE.get(pkg.root)
    if pf is None or pf.pkg is not pkg:
        lock_kinds: Dict[str, str] = {}
        by_rel = {sf.rel: _module_facts(sf, lock_kinds)
                  for sf in pkg.files if sf.tree is not None}
        pf = _FACTS_CACHE[pkg.root] = PackageFacts(pkg, by_rel,
                                                   lock_kinds)
    return pf


def _fmt_key(attr_key: Tuple[str, str]) -> str:
    owner, attr = attr_key
    rel, _, cls = owner.partition(":")
    return f"{cls}.{attr}" if cls != "<module>" \
        else f"{rel.rsplit('/', 1)[-1]}:{attr}"


@register
class CrossDomainStateChecker(Checker):
    """Attributes written from >= 2 thread domains need one common
    lexical lock scope across EVERY write site."""

    name = "cross-domain-state"
    description = ("an attribute written from >= 2 thread domains with "
                   "no common lexical lock scope is a data-race "
                   "candidate")
    ALLOW = {
        # each wire instance is owned by exactly one thread per
        # (channel, rank); the class-level write aggregation the rule
        # performs is instance-blind there by design (DESIGN.md §18)
        "parallel/shm_wire.py":
            "single-owner wire instances; class-level aggregation is "
            "instance-blind",
        # same posture for the tcp wire, plus its accept loop: that
        # thread writes _conn/_accept_exc only during install, strictly
        # BEFORE any exchange runs (connect() joins it), under _lock
        "parallel/tcp_wire.py":
            "single-owner wire instances; the accept loop writes only "
            "during install, before any exchange, under the wire lock",
    }

    def check(self, pkg: PackageIndex) -> List[Finding]:
        inv = threads.inventory_for(pkg)
        pf = facts_for(pkg)
        eligible = {sf.rel for sf in self.iter_files(pkg)}
        groups: Dict[Tuple[str, str], List] = {}
        for df in pf.defs(eligible):
            tail = df.qual.rsplit(".", 1)[-1]
            if tail in _INIT_QUALS or df.qual == "<module>":
                continue
            doms = inv.domains_of(df.node)
            if not doms:
                continue
            for w in df.writes:
                groups.setdefault(w.attr_key, []).append((df, w, doms))
        out: List[Finding] = []
        for key in sorted(groups):
            sites = groups[key]
            domains = set()
            for _, _, doms in sites:
                domains |= doms
            if len(domains) < 2:
                continue
            common = None
            for _, w, _ in sites:
                common = w.locks if common is None else common & w.locks
            if common:
                continue
            sites.sort(key=lambda s: (s[0].rel, s[1].line))
            df0, w0, _ = sites[0]
            detail = "; ".join(
                f"{df.rel}:{w.line} in {df.qual} "
                f"[{','.join(sorted(doms))}]"
                + (f" under {','.join(sorted(w.locks))}" if w.locks
                   else " unlocked")
                for df, w, doms in sites[:6])
            more = f" (+{len(sites) - 6} more)" if len(sites) > 6 else ""
            out.append(Finding(
                self.name, df0.rel, w0.line,
                f"{_fmt_key(key)} is written from {len(domains)} thread "
                f"domains ({', '.join(sorted(domains))}) with no common "
                f"lock scope: {detail}{more} — guard every write with "
                f"one lock, or suppress with the reason the race is "
                f"benign"))
        return out


@register
class DeviceWorkDomainChecker(Checker):
    """No static path from a sampling/handler/fan-out domain to
    jax/device work — the probe-never-syncs-mirror law generalized."""

    name = "device-work-domain"
    description = ("jax/device-work sinks must be unreachable from "
                   "sampling/HTTP/fan-out/reader thread domains")

    #: domains that must stay off the device
    RESTRICTED = frozenset({"watchdog", "reporter", "ops-http", "fanout",
                            "replica-reader", "replica-serve",
                            "replica-hb", "policy"})
    #: in-package defs that ARE device work even without a lexical jnp
    #: touch: (module-rel regex, qualname regex, label)
    DEVICE_ZONES: List[Tuple[str, str, str]] = [
        (r"^ops/rows\.py$", r".*", "jit'd row-op kernels"),
        (r"^ops/pallas_rows\.py$", r".*", "pallas kernels"),
        (r"^tables/matrix_table\.py$", r"^MatrixServerTable\.state$",
         "mirror-syncing state property getter"),
    ]

    def check(self, pkg: PackageIndex) -> List[Finding]:
        inv = threads.inventory_for(pkg)
        pf = facts_for(pkg)
        eligible = {sf.rel for sf in self.iter_files(pkg)}
        zones = [(re.compile(m), re.compile(q), label)
                 for m, q, label in self.DEVICE_ZONES]
        zone_live = [False] * len(zones)
        device: Dict[str, str] = {}
        for df in pf.defs(eligible):
            for zi, (mpat, qpat, label) in enumerate(zones):
                if mpat.search(df.rel):
                    zone_live[zi] = True
                    if qpat.search(df.qual):
                        device.setdefault(df.node, label)
            if df.device:
                line, what = df.device[0]
                device.setdefault(
                    df.node, f"touches {what} at line {line}")
        out: List[Finding] = []
        # the HOT_ZONES config-rot law, applied to the device-sink
        # inventory: a zone file pattern matching NO file means the
        # protected module moved — never retire the sink silently
        cfg = "analysis/concurrency.py"
        anchor = cfg if pkg.file(cfg) is not None else "<config>"
        for zi, live in enumerate(zone_live):
            if not live:
                mpat, _, label = self.DEVICE_ZONES[zi]
                out.append(Finding(
                    self.name, anchor, 1,
                    f"device-zone config rot: no file matches {mpat!r} "
                    f"({label}) — the protected module moved or was "
                    f"renamed; update DEVICE_ZONES or the rule is "
                    f"vacuous there"))
        seen = set()
        for domain in sorted(self.RESTRICTED & set(inv.closures)):
            hits = inv.closures[domain] & set(device)
            for node in sorted(hits):
                chain_nodes = inv.chain(domain, node)
                root = chain_nodes[0]
                if (root, node) in seen:
                    continue
                seen.add((root, node))
                rel, line = inv.graph.node_lines[root]
                chain = " -> ".join(chain_nodes)
                out.append(Finding(
                    self.name, rel, line,
                    f"{root} ({domain} domain: "
                    f"{inv.root_labels.get(root, 'thread root')}) "
                    f"statically reaches device work {node} "
                    f"({device[node]}): {chain} — sampling/handler/"
                    f"fan-out threads must never issue device ops"))
        return out


@register
class LockOrderChecker(Checker):
    """Compose per-function ``with``-nesting through the call graph
    into a lock acquisition-order graph; cycles are potential
    deadlocks.

    Honesty bound (the callgraph fallback's sibling, false-positive
    direction): a call under a lock composes by callee NAME against
    the def's resolved edges, so ``with self._a: x.sync()`` also picks
    up a *different* ``.sync`` target called elsewhere in the same def
    — an over-approximated edge can manufacture a cycle that cannot
    happen, never hide one that can. Cycles are "potential deadlock"
    findings to be read, and a wrong one is suppressed with its why."""

    name = "lock-order"
    description = ("lock acquisition-order cycles (lexical with-nesting "
                   "composed through the call graph) are potential "
                   "deadlocks")

    def check(self, pkg: PackageIndex) -> List[Finding]:
        graph = callgraph.build_graph(pkg)
        pf = facts_for(pkg)
        eligible = {sf.rel for sf in self.iter_files(pkg)}
        defs = pf.defs(eligible)
        acq_direct: Dict[str, Set[str]] = {}
        for df in defs:
            if df.acquires:
                acq_direct[df.node] = {k for k, _ in df.acquires}

        closure_cache: Dict[str, Set[str]] = {}

        def closure_acquires(node: str) -> Set[str]:
            got = closure_cache.get(node)
            if got is not None:
                return got
            closure_cache[node] = set()     # cycle guard
            seen, _ = graph.reachable([node])
            seen.add(node)
            acc: Set[str] = set()
            for n in seen:
                acc |= acq_direct.get(n, set())
            closure_cache[node] = acc
            return acc

        def _callee_name(node: str) -> str:
            return node.split(":", 1)[-1].rsplit(".", 1)[-1]

        #: (a, b) -> (rel, line, how) first evidence
        edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        for df in defs:
            for a, b, line in df.lex_pairs:
                edges.setdefault((a, b),
                                 (df.rel, line, f"nested with in "
                                                f"{df.qual}"))
            for held, cname, line in df.calls_under:
                for target in graph.edges.get(df.node, ()):
                    if target.startswith("<external>"):
                        continue
                    if _callee_name(target) != cname:
                        continue
                    for inner in closure_acquires(target):
                        edges.setdefault(
                            (held, inner),
                            (df.rel, line,
                             f"{df.qual} calls {target} while holding "
                             f"it"))
        out: List[Finding] = []
        adj: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            if a != b:
                adj.setdefault(a, set()).add(b)
        # self-loops: re-acquiring a non-reentrant lock under itself
        for (a, b), (rel, line, how) in sorted(edges.items()):
            if a == b and pf.lock_kinds.get(a) in _NON_REENTRANT:
                out.append(Finding(
                    self.name, rel, line,
                    f"lock {a} (threading."
                    f"{pf.lock_kinds[a]}) is re-acquired under itself "
                    f"({how}) — a non-reentrant lock self-deadlocks "
                    f"here"))
        # cycles across distinct locks: DFS with path reconstruction
        reported: Set[frozenset] = set()

        def _dfs(start: str) -> Optional[List[str]]:
            stack = [(start, [start])]
            seen_local = set()
            while stack:
                node, path = stack.pop()
                for nxt in sorted(adj.get(node, ())):
                    if nxt == start:
                        return path + [start]
                    if nxt not in seen_local:
                        seen_local.add(nxt)
                        stack.append((nxt, path + [nxt]))
            return None

        for start in sorted(adj):
            cyc = _dfs(start)
            if cyc is None:
                continue
            key = frozenset(cyc)
            if key in reported:
                continue
            reported.add(key)
            rel, line, how = edges[(cyc[0], cyc[1])]
            steps = []
            for i in range(len(cyc) - 1):
                erel, eline, ehow = edges[(cyc[i], cyc[i + 1])]
                steps.append(f"{cyc[i]} -> {cyc[i + 1]} "
                             f"({erel}:{eline}, {ehow})")
            out.append(Finding(
                self.name, rel, line,
                f"lock acquisition-order cycle (potential deadlock): "
                + "; ".join(steps)))
        return out


@register
class BlockingDomainChecker(Checker):
    """Unbounded blocking reachable from handler or engine-thread
    roots — reachability form of the PR 3 bounded-blocking law."""

    name = "blocking-domain"
    description = ("unbounded wait/join/recv reachable from handler or "
                   "engine-thread domains — these threads must bound "
                   "every wait")

    #: the threads the runtime cannot afford to park forever: engine
    #: verb/apply threads (a stuck engine wedges every rank), request
    #: handlers (a stuck handler leaks server threads), and the policy
    #: daemon (round 20: a parked actuator is a silent dead-man switch)
    RESTRICTED = frozenset({"engine-shard", "apply-pool", "ops-http",
                            "replica-serve", "replica-hb", "elastic",
                            "policy"})
    ALLOW = {
        # pallas DMA semaphore waits: device-side copy completion
        # inside traced kernels — not host-thread blocking (the same
        # exemption the per-line bounded-blocking rule carries)
        "ops/pallas_rows.py":
            "pallas DMA semaphore .wait() inside traced kernels",
    }

    def check(self, pkg: PackageIndex) -> List[Finding]:
        inv = threads.inventory_for(pkg)
        pf = facts_for(pkg)
        eligible = {sf.rel for sf in self.iter_files(pkg)}
        out: List[Finding] = []
        for df in pf.defs(eligible):
            if not df.blocking:
                continue
            doms = sorted(inv.domains_of(df.node) & self.RESTRICTED)
            if not doms:
                continue
            chain = " -> ".join(inv.chain(doms[0], df.node))
            for line, what in df.blocking:
                out.append(Finding(
                    self.name, df.rel, line,
                    f"{what} in {df.qual} is reachable from the "
                    f"{', '.join(doms)} domain(s) ({chain}) — handler "
                    f"and engine threads must bound every wait (a "
                    f"per-line 'unbounded-ok' justification does not "
                    f"cover these threads)"))
        return out
