"""The four AST checkers (the call-graph one lives in collective.py).

Each rule is the machine-checked form of a convention an earlier PR
established by hand:

* ``no-bare-print`` — PR 2: all output rides the leveled logger.
* ``bounded-blocking`` — PR 3: every ``.wait()``/``.join()`` either
  takes a timeout or carries an ``unbounded-ok:`` justification.
* ``hot-path-flag-cache`` — PR 8/9: flag reads on engine verb/window/
  apply hot paths go through the listener-cached accessors
  (utils/configure.cached_*_flag), never a GetFlag registry walk.
* ``spmd-stream-guard`` — PR 10's drill lesson: verb-submitting calls
  must not sit under rank-dependent conditions; a rank-guarded verb
  diverges the SPMD lockstep verb streams and the next exchange waits
  forever.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Tuple

from multiverso_tpu.analysis.callgraph import iter_top_defs
from multiverso_tpu.analysis.core import (Checker, Finding, PackageIndex,
                                          SourceFile, register)


def _defs_with_quals(tree: ast.AST) -> Iterable[Tuple[str, ast.AST]]:
    """(qualname, def-node) for every top-level function and method —
    including defs under module/class-level ``if``/``try`` scaffolding;
    nested defs/lambdas stay inside their enclosing def's subtree
    (callgraph.iter_top_defs owns the granularity rule)."""
    for qual, _, node in iter_top_defs(tree):
        yield qual, node


@register
class NoBarePrintChecker(Checker):
    """AST upgrade of the PR 2 regex lint: a bare ``print(...)`` call
    anywhere in the package bypasses the leveled logger (and its
    sink/level contract). Unlike the regex, the AST form cannot be
    fooled by strings containing ``print(`` and cannot miss a call
    split across lines."""

    name = "no-bare-print"
    description = ("route output through utils/log.py or the telemetry "
                   "exporters, never bare print()")
    #: the logger's own sinks are the one legitimate print site
    ALLOW = {"utils/log.py": "the logger's own stdout/stderr sinks"}

    def check(self, pkg: PackageIndex) -> List[Finding]:
        out: List[Finding] = []
        for sf in self.iter_files(pkg):
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Name) \
                        and node.func.id == "print":
                    out.append(Finding(
                        self.name, sf.rel, node.lineno,
                        "bare print() — route output through "
                        "utils/log.py or the telemetry exporters"))
        return out


@register
class BoundedBlockingChecker(Checker):
    """AST upgrade of the PR 3 regex lint: every no-argument
    ``.wait()`` / ``.join()`` (any capitalization — the package's own
    primitives are ``Waiter.Wait`` / ``ASyncBuffer.Join``) must carry
    an ``unbounded-ok:`` justification within the 3 preceding lines.
    The AST form resolves attribute chains and multi-line calls the
    regex missed (``a.b.c.wait(\\n)``), and skips strings/comments by
    construction. A call with a positional argument or a ``timeout=``
    keyword is bounded and passes — unless every argument is a literal
    ``None`` (``t.join(None)`` / ``evt.wait(timeout=None)`` block
    forever by stdlib semantics; the spelled-out-None form is the same
    unbounded wait and needs the same justification)."""

    name = "bounded-blocking"
    description = ("no unbounded .wait()/.join() without a "
                   "timeout-capable path or an 'unbounded-ok:' "
                   "justification")
    ALLOW = {
        # pallas DMA semaphore waits: device-side copy completion inside
        # traced kernels — not host thread blocking, no timeout concept
        "ops/pallas_rows.py":
            "pallas DMA semaphore .wait() inside traced kernels",
    }
    _BLOCKING = frozenset({"wait", "join"})
    #: how far above the call the justification may sit (legacy contract)
    JUSTIFY_WINDOW = 3

    def check(self, pkg: PackageIndex) -> List[Finding]:
        out: List[Finding] = []
        for sf in self.iter_files(pkg):
            for node in ast.walk(sf.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr.lower() in self._BLOCKING):
                    continue
                bounds = [a for a in node.args
                          if not (isinstance(a, ast.Constant)
                                  and a.value is None)]
                bounds += [k for k in node.keywords
                           if not (isinstance(k.value, ast.Constant)
                                   and k.value.value is None)]
                if bounds:
                    continue        # a real bound is present —
                                    # join(None)/wait(timeout=None) is
                                    # the unbounded wait spelled out
                line = node.lineno
                lo = max(0, line - 1 - self.JUSTIFY_WINDOW)
                context = sf.lines[lo:line]
                if any("unbounded-ok:" in ln for ln in context):
                    continue
                out.append(Finding(
                    self.name, sf.rel, line,
                    f"unbounded .{node.func.attr}() — use a "
                    f"timeout-capable path or justify with "
                    f"'unbounded-ok: <why>' within "
                    f"{self.JUSTIFY_WINDOW} lines above"))
        return out


@register
class HotPathFlagCacheChecker(Checker):
    """Flag reads inside engine/verb/apply hot paths must go through
    the listener-cached accessors (``cached_*_flag``), not a
    ``GetFlag``/``HasFlag`` registry walk: the registry takes an RLock
    per read, and the PR 9 measurements put blocking verb dispatch at
    ~3k verbs/s GIL-bound — a lock per verb is real money. The hot
    zones are configured explicitly below; everything else (init,
    construction, CLI, teardown) may read the registry freely."""

    name = "hot-path-flag-cache"
    description = ("GetFlag/HasFlag inside engine/verb/apply hot paths "
                   "— use utils.configure.cached_*_flag accessors")
    _FLAG_READS = frozenset({"GetFlag", "HasFlag"})

    #: per-HOT_ZONES-entry matched-def counts from the last check() —
    #: the tier-1 baseline asserts every entry is live on the real
    #: package, so a renamed module can never silently retire a zone
    zone_hits: List[int]

    #: (module-rel regex, def-qualname regex, zone label). A def whose
    #: qualname matches in a module whose rel matches is a hot zone.
    HOT_ZONES: List[Tuple[str, str, str]] = [
        (r"^sync/server\.py$",
         r"^(?:Server|ShardedServer|SyncServer|_EngineShard)\."
         r"(?:_mh_|_pl_|_local_window|_admit|_get_entry|_add_entry|"
         r"_process_add_run|Process|Receive|_fence_entry|_fs_wrap_reply|"
         r"_flight_exchanged|_note_|_ph_)",
         "engine verb/window/apply machinery"),
        (r"^sync/server\.py$",
         r"^(?:_ExchangeStage\.(?:_loop|_exchange_one|_gate|_wait_applied|"
         r"feed_)|_ApplyPool\.(?:submit|_loop))",
         "pipelined exchange stage / apply pool"),
        (r"^ops/rows\.py$",
         r"^(?:use_pallas|_forced_on|_pallas_eligible|dedup_rows|"
         r"gather_rows|scatter_set_rows|update_rows|update_gather_rows|"
         r"_update_gather_impl|_dense_run)",
         "row-op dispatch predicates run per verb"),
        (r"^tables/.*\.py$",
         r"\.(?:Add|Get|AddAsync|GetAsync)$|\._?[Aa]pply",
         "worker verb paths / server applies"),
        (r"^telemetry/flight\.py$", r"^record$",
         "flight record rides every verb"),
        # round 21 — the codec layer's enable/opt-in predicates and
        # pack/unpack entry points ride every replica bundle, window
        # exchange, and serve frame
        (r"^parallel/compress\.py$",
         r"^(?:enabled|lossy_opted|config_token|pack_payload|"
         r"unpack_payload|pack_window_values|materialize_window|"
         r"pack_serve_rows|decode_array)$",
         "compression codecs ride every hot byte path"),
    ]

    def check(self, pkg: PackageIndex) -> List[Finding]:
        zones = [(re.compile(m), re.compile(q), label)
                 for m, q, label in self.HOT_ZONES]
        self.zone_hits = [0] * len(zones)
        zone_files: Dict[int, str] = {}    # zone index -> first file hit
        out: List[Finding] = []
        for sf in self.iter_files(pkg):
            mine = [(zi, q, label) for zi, (m, q, label) in enumerate(zones)
                    if m.search(sf.rel)]
            if not mine:
                continue
            for zi, _, _ in mine:
                zone_files.setdefault(zi, sf.rel)
            for qual, node in _defs_with_quals(sf.tree):
                labels = []
                for zi, q, label in mine:
                    if q.search(qual):
                        labels.append(label)
                        self.zone_hits[zi] += 1
                if not labels:
                    continue
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Call):
                        continue
                    fn = sub.func
                    name = (fn.id if isinstance(fn, ast.Name)
                            else fn.attr if isinstance(fn, ast.Attribute)
                            else None)
                    if name in self._FLAG_READS:
                        out.append(Finding(
                            self.name, sf.rel, sub.lineno,
                            f"{name}() inside hot path {qual} "
                            f"({labels[0]}) — cache it with "
                            f"utils.configure.cached_*_flag"))
        out.extend(self._config_rot(pkg, zone_files))
        return out

    def _config_rot(self, pkg: PackageIndex,
                    zone_files: Dict[int, str]) -> List[Finding]:
        """A module matched by a zone's file pattern in which NO zone
        sharing that pattern matches any def is config rot: a wholesale
        rename of the protected classes/methods would otherwise retire
        the rule silently while the baseline stays green (the same law
        collective.py applies to its root/sink inventory). A file
        pattern matching NO file at all is the module-level form of
        the same rot (sync/server.py renamed away), anchored — like
        collective.py's — at the config source, since that is the
        file the fix edits. Grouped by file pattern so fixture trees
        that mirror a module without every one of its internals stay
        drivable; per-entry liveness on the real package is pinned by
        the tier-1 baseline via :attr:`zone_hits`."""
        by_pattern: Dict[str, List[int]] = {}
        for zi, (mpat, _, _) in enumerate(self.HOT_ZONES):
            by_pattern.setdefault(mpat, []).append(zi)
        cfg = "analysis/rules.py"
        anchor = cfg if pkg.file(cfg) is not None else None
        out: List[Finding] = []
        for mpat, zis in sorted(by_pattern.items()):
            hit_files = [zone_files[zi] for zi in zis if zi in zone_files]
            labels = ", ".join(self.HOT_ZONES[zi][2] for zi in zis)
            if not hit_files:
                # keep the path field path-shaped for annotators even
                # when the config source itself is outside the tree
                out.append(Finding(
                    self.name, anchor or "<config>", 1,
                    f"hot-zone config rot: no file matches {mpat!r} "
                    f"({labels}) — the protected module moved or was "
                    f"renamed; update HOT_ZONES or the rule is vacuous "
                    f"there"))
                continue
            if any(self.zone_hits[zi] for zi in zis):
                continue
            out.append(Finding(
                self.name, hit_files[0], 1,
                f"hot-zone config rot: no def in files matching "
                f"{mpat!r} matches any of its zone qualname patterns "
                f"({labels}) — the protected code moved; update "
                f"HOT_ZONES or the rule is vacuous here"))
        return out


@register
class SpmdStreamGuardChecker(Checker):
    """Verb-submitting calls lexically guarded by a rank-dependent
    condition: the diverged-verb-stream bug class. Every rank must
    issue the same verb stream in the same order (DESIGN.md §14's SPMD
    collective contract); ``if rank == 0: table.Add(...)`` admits a
    verb on one rank only, and the next window exchange waits out its
    full deadline (exactly how the PR 10 drill flake died). Both arms
    of a rank-guarded ``if`` are suspect — the else-branch runs on a
    rank-dependent subset too. The guard-clause spelling is the same
    bug (``if rank != 0: return`` then ``table.Add(...)``), so verbs
    downstream of a rank-dependent early exit in the same block are
    flagged too; a rank-dependent ``raise`` is NOT treated as an exit
    (an error path crashes loudly on the ranks it hits — it does not
    silently diverge the stream the way a quiet return does). In a
    boolean chain only the operands AFTER the first rank-dependent one
    are conditionally evaluated (short-circuit order), so a verb ahead
    of the rank test runs on every rank and passes. Comprehensions are
    the same law in clause order: a rank-dependent ``if`` filter (or a
    rank-dependent ``for`` iterable) makes the element expression and
    every later clause run a rank-dependent number of times, so
    ``[t.Add(d) for d in batch if rank == 0]`` is the lexical-guard
    bug in disguise — while a verb in the FIRST iterable evaluates on
    every rank before any rank clause and passes. Statement ``for``
    loops are the iteration form of the same law: a rank-dependent
    iterable (``for i in range(rank):``) runs the body a
    rank-dependent number of times; the ``else`` clause is exempt (it
    runs exactly once per rank however many iterations preceded
    it)."""

    name = "spmd-stream-guard"
    description = ("verb submissions under rank-dependent guards "
                   "diverge the SPMD verb streams")
    ALLOW = {
        # the collective transports themselves legitimately branch on
        # rank INSIDE one collective's implementation (peer segment
        # layout, master-side merge); the verb-stream law binds the
        # layers that SUBMIT verbs, not the wire that carries windows
        "parallel/multihost.py": "collective internals branch on rank",
        "parallel/shm_wire.py": "peer-indexed ring layout",
    }
    #: method names that submit verbs into the engine stream — the row
    #: and handle spellings wrap AddAsync/GetAsync and submit just the
    #: same (tables/matrix_table.py), so they are the same law
    VERB_ATTRS = frozenset({"Add", "Get", "AddAsync", "GetAsync",
                            "AddRows", "GetRows", "AddAsyncHandle",
                            "GetAsyncHandle", "AddFireForget",
                            "Barrier"})
    #: module-level verb surfaces
    VERB_NAMES = frozenset({"MV_Barrier", "MV_Aggregate",
                            "MV_PublishSnapshot", "MV_SaveCheckpoint",
                            "MV_LoadCheckpoint", "MV_ElasticSync"})
    RANK_TOKENS = frozenset({"rank", "my_rank", "world_rank", "dist_rank",
                             "local_rank", "node_rank", "rank_id",
                             "worker_id", "server_id", "process_id",
                             "process_index", "MV_Rank", "MV_WorkerId",
                             "MV_ServerId"})

    def _rank_dependent(self, test: ast.AST) -> bool:
        for node in ast.walk(test):
            if isinstance(node, ast.Name) and node.id in self.RANK_TOKENS:
                return True
            if isinstance(node, ast.Attribute) \
                    and node.attr in self.RANK_TOKENS:
                return True
        return False

    def _verb_calls(self, nodes) -> Iterable[ast.Call]:
        for root in nodes:
            for node in ast.walk(root):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                if isinstance(fn, ast.Attribute) \
                        and fn.attr in self.VERB_ATTRS:
                    yield node
                elif isinstance(fn, ast.Name) \
                        and fn.id in self.VERB_NAMES:
                    yield node

    #: statements that quietly leave the block (``raise`` is excluded:
    #: error paths fail loudly rather than diverging the stream)
    _EXITS = (ast.Return, ast.Continue, ast.Break)

    def _block_exits(self, stmts) -> bool:
        return any(isinstance(s, self._EXITS) for s in stmts)

    def _guard_tails(self, stmts) -> Iterable[Tuple[int, list]]:
        """(guard_line, trailing_stmts) for each rank-dependent guard
        clause: an ``if`` whose one arm quietly exits the block while
        the other falls through, making everything after it run on a
        rank-dependent subset. Both-arms-exit is dead tail for every
        rank (no divergence); neither-arm-exits falls through on every
        rank (the in-body handling already covers the arms)."""
        for i, st in enumerate(stmts):
            if isinstance(st, ast.If) and self._rank_dependent(st.test) \
                    and self._block_exits(st.body) \
                    != self._block_exits(st.orelse):
                yield st.lineno, stmts[i + 1:]

    def check(self, pkg: PackageIndex) -> List[Finding]:
        # nested/stacked rank guards reach the same call node from
        # several ancestors — one violation must count once, keyed on
        # the call itself (line alone would collapse DISTINCT calls
        # sharing a line, e.g. both arms of a ternary). ast.walk
        # visits outer guards first, so the surviving finding names
        # the outermost guard — the one to fix.
        seen = set()
        out: List[Finding] = []

        def emit(sf, call, guard_line):
            key = (sf.rel, call.lineno, call.col_offset)
            if key not in seen:
                seen.add(key)
                out.append(self._finding(sf, call, guard_line))

        for sf in self.iter_files(pkg):
            for node in ast.walk(sf.tree):
                if isinstance(node, (ast.If, ast.While)):
                    if not self._rank_dependent(node.test):
                        continue
                    guarded = list(node.body)
                    if isinstance(node, ast.If):
                        guarded += node.orelse
                    for call in self._verb_calls(guarded):
                        emit(sf, call, node.lineno)
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    # rank-dependent iteration count; else-clause
                    # exempt (runs once per rank regardless)
                    if not self._rank_dependent(node.iter):
                        continue
                    for call in self._verb_calls(node.body):
                        emit(sf, call, node.lineno)
                elif isinstance(node, ast.IfExp):
                    if not self._rank_dependent(node.test):
                        continue
                    for call in self._verb_calls([node.body, node.orelse]):
                        emit(sf, call, node.lineno)
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.DictComp, ast.GeneratorExp)):
                    # clauses evaluate left-to-right (gen0.iter,
                    # gen0.ifs, gen1.iter, ...) with the element
                    # innermost-last, so everything after the first
                    # rank-dependent clause runs a rank-dependent
                    # number of times
                    clauses = []
                    for gen in node.generators:
                        clauses.append(gen.iter)
                        clauses.extend(gen.ifs)
                    first = next((i for i, c in enumerate(clauses)
                                  if self._rank_dependent(c)), None)
                    if first is None:
                        continue
                    elts = ([node.key, node.value]
                            if isinstance(node, ast.DictComp)
                            else [node.elt])
                    for call in self._verb_calls(clauses[first + 1:]
                                                 + elts):
                        emit(sf, call, node.lineno)
                elif isinstance(node, ast.BoolOp):
                    # short-circuit order: operands BEFORE the first
                    # rank-dependent one evaluate on every rank
                    first = next((i for i, v in enumerate(node.values)
                                  if self._rank_dependent(v)), None)
                    if first is None:
                        continue
                    for call in self._verb_calls(node.values[first + 1:]):
                        emit(sf, call, node.lineno)
            for block in self._stmt_blocks(sf.tree):
                for guard_line, tail in self._guard_tails(block):
                    for call in self._verb_calls(tail):
                        emit(sf, call, guard_line)
        return out

    @staticmethod
    def _stmt_blocks(tree: ast.AST) -> Iterable[list]:
        for node in ast.walk(tree):
            for fld in ("body", "orelse", "finalbody"):
                block = getattr(node, fld, None)
                if isinstance(block, list) and block:
                    yield block

    def _finding(self, sf: SourceFile, call: ast.Call,
                 guard_line: int) -> Finding:
        fn = call.func
        what = (fn.attr if isinstance(fn, ast.Attribute) else fn.id)
        return Finding(
            self.name, sf.rel, call.lineno,
            f"verb-submitting call {what}() under the rank-dependent "
            f"guard at line {guard_line} — every rank must issue the "
            f"same verb stream (diverged streams deadlock the next "
            f"window exchange)")
