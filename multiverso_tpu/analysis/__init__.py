"""mvlint — the static invariant-analysis plane (DESIGN.md §16).

The port's load-bearing conventions (never-collective reporter/handler
threads, bounded blocking, logger-routed output, hot-path flag caching,
SPMD lockstep verb streams) were guarded by two regex lints and 2-proc
drills that catch violations only after they deadlock. This package
turns them into machine-checked laws:

* :mod:`core` — package index, checker registry, the inline
  suppression contract (``# mv-lint: ok(<rule>): <reason>``; stale or
  reasonless suppressions are themselves errors);
* :mod:`callgraph` — the package-wide static call graph;
* :mod:`rules` — the four AST checkers;
* :mod:`collective` — the call-graph never-collective checker;
* :mod:`threads` — the thread-root inventory: every spawned thread
  classified into a named concurrency domain, with per-domain BFS
  closures and the two-way config-rot law (DESIGN.md §18);
* :mod:`concurrency` — the four domain checkers (cross-domain-state,
  device-work-domain, lock-order, blocking-domain);
* :mod:`cli` — ``python -m multiverso_tpu.analysis`` and the
  ``mvlint`` console script (text / ``--json``, exit codes 0 clean /
  1 findings / 2 usage).

The analysis modules themselves import neither jax nor any runtime
state — scanning is pure source analysis, so the CLI also works on a
box that can't start a world (``python -m`` still pays the parent
package import, as any submodule execution does).
"""

from multiverso_tpu.analysis.core import (AnalysisResult, Checker,  # noqa: F401
                                          CHECKERS, Finding,
                                          all_checker_names, load_package,
                                          run_analysis)
