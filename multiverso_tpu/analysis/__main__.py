import sys

from multiverso_tpu.analysis.cli import main

sys.exit(main())
