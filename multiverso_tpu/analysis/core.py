"""mvlint framework core: package index, checker registry, suppressions.

The analysis plane turns this build's load-bearing conventions (DESIGN.md
§16) into machine-checked laws: every checker parses the package once
(``PackageIndex``), reports :class:`Finding` records, and the runner
applies the inline suppression contract before anything reaches the CLI
or the tier-1 baseline test.

Suppression contract
--------------------
A finding is suppressed ONLY by an inline comment that names the rule
and carries a reason::

    x = GetFlag("foo")   # mv-lint: ok(hot-path-flag-cache): cold init path

The comment may trail the offending line or sit on its own line(s)
directly above it (stacking — one rule per comment). A marker binds
to the SIMPLE STATEMENT its line belongs to, like ``noqa`` on a
logical line: it excuses every finding of its rule within that
statement — so a marker trailing the closing line of a call that
spans lines still lands on the finding anchored at the call's first
line, and two violations sharing a statement (both arms of a one-line
ternary) need one reason that speaks for both; the checkers report
each distinctly beforehand, so nothing is hidden unreviewed.
Compound-statement headers (``if``/``for``/...) keep exact-line
scope — a marker there must not quietly excuse the whole block.
Three failure modes are themselves findings, so the suppression
inventory can never rot silently:

* ``mvlint-suppression`` — malformed marker (missing rule or reason),
* ``mvlint-suppression`` — unknown rule name,
* ``stale-suppression`` — a well-formed suppression that matched no
  finding in this run (the violation it excused is gone; delete it).

Stale detection only judges suppressions for rules that actually ran,
so ``--rules`` subsets never produce false staleness.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

#: marker grammar (leading hash elided here so this comment is not
#: itself a marker attempt): "mv-lint: ok(<rule>): <reason>"
_SUPPRESS_RE = re.compile(
    r"#\s*mv-lint:\s*ok\(\s*(?P<rule>[A-Za-z0-9_-]+)\s*\)\s*"
    r"(?::\s*(?P<reason>\S.*))?")
#: anything that LOOKS like a marker attempt, for malformed-marker errors
_SUPPRESS_ATTEMPT_RE = re.compile(r"#\s*mv-lint\b")


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str           # rel posix path inside the scanned package
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}


@dataclass
class Suppression:
    rule: str
    reason: str
    comment_line: int   # where the marker sits
    target_line: int    # the code line it excuses
    used: bool = False


#: statements WITHOUT a body — the suppression anchor unit. Compound
#: statements (if/for/with/def...) are excluded: a marker trailing an
#: `if` header must not quietly excuse the whole block.
_SIMPLE_STMTS = (ast.Expr, ast.Assign, ast.AugAssign, ast.AnnAssign,
                 ast.Return, ast.Delete, ast.Raise, ast.Assert,
                 ast.Import, ast.ImportFrom, ast.Global, ast.Nonlocal,
                 ast.Pass, ast.Break, ast.Continue)


@dataclass
class SourceFile:
    """One parsed module: text, AST, and its suppression table."""

    rel: str
    abspath: str
    text: str
    lines: List[str] = field(default_factory=list)
    tree: Optional[ast.AST] = None
    parse_error: Optional[str] = None
    suppressions: List[Suppression] = field(default_factory=list)
    #: malformed/unknown markers, reported as findings by the runner
    bad_markers: List[Tuple[int, str]] = field(default_factory=list)
    #: lazy (start, end) spans of every simple statement, for the
    #: multi-line-statement suppression match
    _spans: Optional[List[Tuple[int, int]]] = field(default=None,
                                                    repr=False)

    def _stmt_span(self, line: int) -> Optional[Tuple[int, int]]:
        """Smallest simple-statement span covering ``line``."""
        if self._spans is None:
            spans: List[Tuple[int, int]] = []
            if self.tree is not None:
                for node in ast.walk(self.tree):
                    end = getattr(node, "end_lineno", None)
                    if isinstance(node, _SIMPLE_STMTS) and end:
                        spans.append((node.lineno, end))
            self._spans = spans
        best: Optional[Tuple[int, int]] = None
        for a, b in self._spans:
            if a <= line <= b and (best is None
                                   or (b - a) < (best[1] - best[0])):
                best = (a, b)
        return best

    def suppression_for(self, rule: str, line: int) -> Optional[Suppression]:
        for s in self.suppressions:
            if s.rule != rule:
                continue
            if s.target_line == line:
                return s
            # a call spanning lines anchors its finding at call.lineno
            # while a trailing marker sits on the closing line (and an
            # own-line marker above targets the statement's first
            # line): the marker binds to the whole SIMPLE statement
            span = self._stmt_span(s.target_line)
            if span is not None and span[0] <= line <= span[1]:
                return s
        return None


def _comment_tokens(sf: SourceFile) -> List[Tuple[int, str, bool]]:
    """(line, comment_text, own_line) for every REAL comment token —
    tokenize-based so marker text inside strings/docstrings (this
    module's own documentation, say) is never mistaken for a marker."""
    out: List[Tuple[int, str, bool]] = []
    try:
        toks = tokenize.generate_tokens(io.StringIO(sf.text).readline)
        for tok in toks:
            if tok.type == tokenize.COMMENT:
                line = tok.start[0]
                before = sf.lines[line - 1][: tok.start[1]].strip()
                out.append((line, tok.string, not before))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass    # unparseable files surface via sf.parse_error
    return out


def _parse_suppressions(sf: SourceFile) -> None:
    """Fill ``sf.suppressions`` / ``sf.bad_markers`` from the comments.

    An own-line marker targets the next line that holds code (stacked
    markers and blank lines are skipped over); a trailing marker targets
    its own line.
    """
    n = len(sf.lines)
    for i, comment, own_line in _comment_tokens(sf):
        if not _SUPPRESS_ATTEMPT_RE.search(comment):
            continue
        m = _SUPPRESS_RE.search(comment)
        if not m:
            sf.bad_markers.append(
                (i, "malformed mv-lint marker — the grammar is "
                    "'# mv-lint: ok(<rule>): <reason>'"))
            continue
        rule, reason = m.group("rule"), m.group("reason")
        if not reason or not reason.strip():
            sf.bad_markers.append(
                (i, f"mv-lint suppression for {rule!r} carries no reason "
                    f"— suppressions must say why"))
            continue
        if not own_line:
            target = i
        else:
            target = 0
            j = i + 1
            while j <= n:
                nxt = sf.lines[j - 1].strip()
                if nxt and not nxt.startswith("#"):
                    target = j
                    break
                j += 1
            if target == 0:
                sf.bad_markers.append(
                    (i, f"mv-lint suppression for {rule!r} precedes no "
                        f"code line"))
                continue
        sf.suppressions.append(
            Suppression(rule=rule, reason=reason.strip(),
                        comment_line=i, target_line=target))


class PackageIndex:
    """Every ``*.py`` under one package root, parsed once."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.files: List[SourceFile] = []
        self._by_rel: Dict[str, SourceFile] = {}
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__"
                                 and not d.endswith(".egg-info"))
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                abspath = os.path.join(dirpath, fn)
                rel = os.path.relpath(abspath, self.root).replace(os.sep, "/")
                try:
                    with open(abspath, encoding="utf-8") as f:
                        text = f.read()
                except (OSError, UnicodeDecodeError) as exc:
                    # an unreadable/undecodable module is a finding
                    # (mvlint-parse), never an uncaught traceback that
                    # exits 1 masquerading as "findings present"
                    sf = SourceFile(rel=rel, abspath=abspath, text="")
                    sf.parse_error = f"failed to read/decode: {exc}"
                    self.files.append(sf)
                    self._by_rel[rel] = sf
                    continue
                sf = SourceFile(rel=rel, abspath=abspath, text=text,
                                lines=text.splitlines())
                try:
                    sf.tree = ast.parse(text, filename=abspath)
                except SyntaxError as exc:
                    sf.parse_error = f"{exc.msg} (line {exc.lineno})"
                _parse_suppressions(sf)
                self.files.append(sf)
                self._by_rel[rel] = sf

    @property
    def rel_paths(self) -> Set[str]:
        return set(self._by_rel)

    def file(self, rel: str) -> Optional[SourceFile]:
        return self._by_rel.get(rel)


#: memoized indexes: the tier-1 baseline test and the two migrated lint
#: tests all analyze the same tree — parse it once per process
_INDEX_CACHE: Dict[str, PackageIndex] = {}


def load_package(root: Optional[str] = None) -> PackageIndex:
    """Index ``root`` (default: the installed multiverso_tpu package)."""
    if root is None:
        root = default_root()
    root = os.path.abspath(root)
    idx = _INDEX_CACHE.get(root)
    if idx is None:
        idx = _INDEX_CACHE[root] = PackageIndex(root)
    return idx


def default_root() -> str:
    import multiverso_tpu
    return os.path.dirname(os.path.abspath(multiverso_tpu.__file__))


class Checker:
    """Base checker: subclass, set ``name``/``description``, implement
    :meth:`check`. ``ALLOW`` maps rel paths to the reason the whole file
    is exempt (the per-file allowlists the PR 2/3 regex lints carried);
    allowlisted files are skipped and excluded from ``scanned`` so the
    migrated tests keep their exact legacy semantics."""

    name: str = ""
    description: str = ""
    #: rel path -> why the whole file is exempt from this rule
    ALLOW: Dict[str, str] = {}

    def __init__(self) -> None:
        self.scanned: Set[str] = set()

    def iter_files(self, pkg: PackageIndex) -> Iterable[SourceFile]:
        for sf in pkg.files:
            if sf.rel in self.ALLOW:
                continue
            self.scanned.add(sf.rel)
            if sf.tree is None:
                continue    # parse errors surface via the runner
            yield sf

    def check(self, pkg: PackageIndex) -> List[Finding]:
        raise NotImplementedError


#: the registry the CLI and the tier-1 baseline iterate
CHECKERS: Dict[str, type] = {}


def register(cls):
    """Class decorator adding a checker to the registry."""
    assert cls.name and cls.name not in CHECKERS, cls
    CHECKERS[cls.name] = cls
    return cls


def all_checker_names() -> List[str]:
    return sorted(CHECKERS)


@dataclass
class AnalysisResult:
    findings: List[Finding]           # unsuppressed, sorted
    suppressed: List[Finding]         # excused by a valid marker
    checkers: List[Checker]           # instances that ran (scanned sets)
    package_root: str

    @property
    def clean(self) -> bool:
        return not self.findings

    def as_dict(self) -> dict:
        return {
            "package_root": self.package_root,
            "rules": [c.name for c in self.checkers],
            "clean": self.clean,
            "findings": [f.as_dict() for f in self.findings],
            "suppressed": [f.as_dict() for f in self.suppressed],
        }


def run_analysis(root: Optional[str] = None,
                 rules: Optional[List[str]] = None) -> AnalysisResult:
    """Run ``rules`` (default: every registered checker) over ``root``
    and apply the suppression contract. Checker modules register on
    import; import them before calling this with ``rules=None``."""
    # the sibling modules register their checkers at import time; pull
    # them in so a bare run_analysis() sees the full registry
    from multiverso_tpu.analysis import (collective, concurrency,  # noqa: F401
                                         rules as _rules, threads)  # noqa: F401

    names = rules if rules is not None else all_checker_names()
    if rules is not None and not names:
        # a clean result means "every requested checker ran" — an
        # explicitly empty list (a filtered-to-nothing CI variable)
        # must not run zero checkers and read as a clean pass
        raise KeyError("empty rule list — pass rules=None to run "
                       "every checker")
    unknown = [n for n in names if n not in CHECKERS]
    if unknown:
        # validated BEFORE the package parse so a --rules typo fails
        # instantly instead of paying the full-tree index first
        raise KeyError(f"unknown rule(s): {', '.join(unknown)} — "
                       f"known: {', '.join(all_checker_names())}")
    pkg = load_package(root)
    checkers = [CHECKERS[n]() for n in names]

    raw: List[Finding] = []
    for c in checkers:
        raw.extend(c.check(pkg))

    findings: List[Finding] = []
    suppressed: List[Finding] = []
    for f in raw:
        sf = pkg.file(f.path)
        sup = sf.suppression_for(f.rule, f.line) if sf is not None else None
        if sup is not None:
            sup.used = True
            suppressed.append(f)
        else:
            findings.append(f)

    ran = {c.name for c in checkers}
    for sf in pkg.files:
        for line, msg in sf.bad_markers:
            findings.append(Finding("mvlint-suppression", sf.rel, line, msg))
        for sup in sf.suppressions:
            if sup.rule not in CHECKERS:
                findings.append(Finding(
                    "mvlint-suppression", sf.rel, sup.comment_line,
                    f"suppression names unknown rule {sup.rule!r} — "
                    f"known: {', '.join(all_checker_names())}"))
            elif sup.rule in ran and not sup.used:
                allow = getattr(CHECKERS[sup.rule], "ALLOW", {})
                if sf.rel in allow:
                    # the rule never scans this file, so the marker
                    # can never be used — say THAT, not "the
                    # violation is gone"
                    findings.append(Finding(
                        "stale-suppression", sf.rel, sup.comment_line,
                        f"suppression for {sup.rule!r} is redundant — "
                        f"the whole file is allowlisted for that rule "
                        f"({allow[sf.rel]}); delete the marker"))
                else:
                    findings.append(Finding(
                        "stale-suppression", sf.rel, sup.comment_line,
                        f"suppression for {sup.rule!r} matched no "
                        f"finding — the violation it excused is gone; "
                        f"delete it"))
        if sf.parse_error is not None:
            findings.append(Finding(
                "mvlint-parse", sf.rel, 1,
                f"module failed to parse: {sf.parse_error}"))

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    suppressed.sort(key=lambda f: (f.path, f.line, f.rule))
    return AnalysisResult(findings=findings, suppressed=suppressed,
                          checkers=checkers, package_root=pkg.root)
