"""Package-wide static call graph for the never-collective checker.

Construction (and its honesty bounds, DESIGN.md §16): one AST pass per
module collects defs, classes (with in-package base resolution) and
import aliases; a second pass turns every call / callable reference in
every top-level def into edges. Resolution, strongest first:

1. dotted module chains through import aliases (``multihost.host_barrier``),
   following ``from X import f`` re-exports transitively;
2. ``self.``/``cls.`` methods through the class's in-package MRO;
3. ``ClassName.m`` / ``ClassName(...).m`` / local ``x = ClassName(...)``
   one-pass constructor type inference;
4. anything else that is still a method call falls back to EVERY
   in-package method of that name (dynamic-dispatch over-approximation —
   a path through a fallback edge can be a false positive, never a
   silently missed true one);
5. bare-name calls resolve through local defs and ``from``-imports only;
   an unresolved bare name (builtins, stdlib) drops out of the graph.

Lambdas and nested defs merge into their enclosing top-level def, so
``bounded(lambda: capped_exchange(...))`` correctly charges the caller.
Defs under module/class-level ``if``/``try``/``with`` scaffolding (the
version-shim idiom — parallel/mesh.py's ``shard_map``) are top-level
definitions too (:func:`flat_body`), not module code.
Non-call references to resolvable functions (callbacks passed by name)
also produce edges. What the graph cannot see: getattr-by-string,
property getters that do work, and calls that cross an actor mailbox
(a ``msg.reply``/queue hop ends the static chain — by design: the verb
stream discipline is about which THREAD issues a collective).

Node ids are ``"<rel>:<qualname>"`` (``zoo.py:Zoo._barrier_wait``,
``parallel/multihost.py:capped_exchange``, ``<module>`` for top-level
code). Calls to well-known external collective attributes (``psum``,
``all_gather``...) produce ``<external>:<name>`` sink nodes.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from multiverso_tpu.analysis.core import PackageIndex, SourceFile

#: attribute names that are collective primitives wherever they resolve
#: (jax/gloo surfaces the package may grow calls to)
EXTERNAL_COLLECTIVE_ATTRS = frozenset({
    "psum", "pmax", "pmin", "pmean", "all_gather", "all_reduce",
    "allreduce", "allgather", "alltoall", "reduce_scatter",
    "broadcast_one_to_all", "sync_global_devices", "process_allgather",
})

_MODULE_NODE = "<module>"

#: method names that collide with builtin container/string/IO/threading
#: methods. An UNRESOLVED receiver calling one of these is almost always
#: a dict/list/file/lock — fanning out to every same-named package
#: method would wire `snap.get(...)` into MatrixTableHandler.get and
#: drown the graph in false paths. Such names resolve only through
#: typed receivers (self/cls, class names, constructor inference,
#: module attributes) — a documented honesty bound, DESIGN.md §16. The
#: package's own verb surfaces are capitalized (Add/Get/Wait/Join), so
#: the lowercase exclusions cost little.
_COMMON_METHOD_NAMES = frozenset({
    "get", "set", "add", "pop", "append", "extend", "insert", "remove",
    "discard", "clear", "copy", "update", "keys", "values", "items",
    "setdefault", "popitem", "sort", "reverse", "index", "count",
    "join", "split", "rsplit", "partition", "strip", "lstrip", "rstrip",
    "lower", "upper", "title", "format", "replace", "startswith",
    "endswith", "encode", "decode", "read", "readline", "readlines",
    "write", "writelines", "flush", "close", "open", "seek", "tell",
    "send", "recv", "put", "get_nowait", "put_nowait", "run", "start",
    "stop", "wait", "notify", "notify_all", "acquire", "release",
    "submit", "result", "cancel", "done", "shutdown", "connect",
    "bind", "listen", "accept", "fileno", "terminate", "kill", "poll",
    "communicate", "tobytes", "tolist", "item", "reshape", "astype",
    "mean", "sum", "max", "min", "all", "any", "group", "match",
    "search", "findall", "sub", "finditer", "fullmatch",
})


def walk_shallow(node: ast.AST):
    """ast.walk that does NOT descend into nested defs/lambdas — for
    passes where a nested callback's statements must not masquerade as
    the enclosing def's (e.g. a nested ``return Worker()`` is not the
    outer function's return value)."""
    stack = list(_shallow_children(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            stack.extend(_shallow_children(n))


def _shallow_children(node: ast.AST):
    for child in ast.iter_child_nodes(node):
        yield child


def iter_top_defs(tree: ast.AST):
    """(qualname, owning ClassDef or None, def node) for every
    top-level function and method — the ONE place that owns the
    graph-node granularity rule (flat_body guard flattening; nested
    defs/lambdas merge into the enclosing def)."""
    for node in flat_body(tree.body):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.name, None, node
        elif isinstance(node, ast.ClassDef):
            for sub in flat_body(node.body):
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    yield f"{node.name}.{sub.name}", node, sub


def flat_body(body) -> "list":
    """Module/class-body statements with conditional/guard scaffolding
    flattened: a def under a module-level ``if``/``try``/``with`` (the
    version-shim and optional-dependency-fallback idioms —
    parallel/mesh.py's ``shard_map`` shim is the in-package example) is
    still a top-level definition for graph purposes. The guard's own
    expressions (``if`` tests, ``except`` types, ``with`` context
    expressions) are yielded too, so module-level guard code keeps its
    edges. Does NOT descend into defs/lambdas — nested defs stay merged
    into their enclosing def."""
    out = []
    for node in body:
        if isinstance(node, ast.If):
            out.append(node.test)
            out.extend(flat_body(node.body))
            out.extend(flat_body(node.orelse))
        elif isinstance(node, ast.Try):
            out.extend(flat_body(node.body))
            for h in node.handlers:
                if h.type is not None:
                    out.append(h.type)
                out.extend(flat_body(h.body))
            out.extend(flat_body(node.orelse))
            out.extend(flat_body(node.finalbody))
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                out.append(item.context_expr)
            out.extend(flat_body(node.body))
        else:
            out.append(node)
    return out


@dataclass
class ClassInfo:
    name: str
    rel: str
    bases: List[Tuple[str, str]] = field(default_factory=list)  # (rel, name)
    methods: Dict[str, int] = field(default_factory=dict)       # name -> line
    #: instance-attribute types inferred from ``self.X = ClassName(...)``
    #: assignments in any method; a conflicting re-assignment poisons
    #: the entry (None) so a wrong type never resolves a chain
    attr_types: Dict[str, Optional[Tuple[str, str]]] = \
        field(default_factory=dict)


@dataclass
class ModuleInfo:
    rel: str
    dotted: str
    sf: SourceFile
    functions: Dict[str, int] = field(default_factory=dict)     # qual -> line
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    #: local name -> ("mod", rel) | ("sym", rel, name)
    imports: Dict[str, tuple] = field(default_factory=dict)


class CallGraph:
    def __init__(self, pkg: PackageIndex):
        self.pkg = pkg
        self.pkg_name = os.path.basename(pkg.root)
        self.modules: Dict[str, ModuleInfo] = {}
        self.dotted: Dict[str, str] = {}            # dotted -> rel
        self.edges: Dict[str, Set[str]] = {}
        self.node_lines: Dict[str, Tuple[str, int]] = {}  # node -> (rel, line)
        #: method name -> every "<rel>:<Class.m>" node (fallback targets)
        self.methods_by_name: Dict[str, Set[str]] = {}
        #: def node -> the in-package class its calls return
        self.ret_types: Dict[str, Tuple[str, str]] = {}
        self.stats = {"calls": 0, "resolved": 0, "fallback": 0,
                      "dropped": 0}
        self._build()

    # ---------------------------------------------------------- building

    def _build(self) -> None:
        for sf in self.pkg.files:
            if sf.tree is None:
                continue
            rel = sf.rel
            parts = rel[:-3].split("/")     # strip .py
            if parts[-1] == "__init__":
                parts = parts[:-1]
            dotted = ".".join([self.pkg_name] + parts)
            mi = ModuleInfo(rel=rel, dotted=dotted, sf=sf)
            self.modules[rel] = mi
            self.dotted[dotted] = rel
        for mi in self.modules.values():
            self._collect_defs(mi)
        for mi in self.modules.values():
            self._collect_imports(mi)
        # base-class names resolve only after every module's defs exist
        for mi in self.modules.values():
            self._resolve_bases(mi)
        # return types feed attr types (self.x = factory()) which feed
        # the edge pass — strict order
        for mi in self.modules.values():
            self._infer_return_types(mi)
        for mi in self.modules.values():
            self._infer_attr_types(mi)
        for mi in self.modules.values():
            self._collect_edges(mi)

    def _collect_defs(self, mi: ModuleInfo) -> None:
        for node in flat_body(mi.sf.tree.body):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mi.functions[node.name] = node.lineno
            elif isinstance(node, ast.ClassDef):
                ci = ClassInfo(name=node.name, rel=mi.rel)
                mi.classes[node.name] = ci
                for sub in flat_body(node.body):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        ci.methods[sub.name] = sub.lineno
                        qual = f"{node.name}.{sub.name}"
                        mi.functions[qual] = sub.lineno
                        nid = f"{mi.rel}:{qual}"
                        self.methods_by_name.setdefault(
                            sub.name, set()).add(nid)
        for qual, line in mi.functions.items():
            self.node_lines[f"{mi.rel}:{qual}"] = (mi.rel, line)
        self.node_lines[f"{mi.rel}:{_MODULE_NODE}"] = (mi.rel, 1)

    def _collect_imports(self, mi: ModuleInfo) -> None:
        pkg_prefix = self.pkg_name + "."
        for node in ast.walk(mi.sf.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.name
                    if name != self.pkg_name \
                            and not name.startswith(pkg_prefix):
                        # external module: record it (with its dotted
                        # origin) so attribute calls on it
                        # (subprocess.run, np.sum) resolve to
                        # "external" and DON'T hit the method-name
                        # fallback — stdlib receivers must not fan out
                        # to every same-named package method
                        local = alias.asname or name.split(".")[0]
                        mi.imports.setdefault(local, ("ext", name))
                        continue
                    rel = self._dotted_rel(name)
                    if rel is None:
                        continue
                    if alias.asname:
                        mi.imports[alias.asname] = ("mod", rel)
                    else:
                        # "import a.b.c" binds "a"; chains walk down
                        root_rel = self._dotted_rel(name.split(".")[0])
                        if root_rel is not None:
                            mi.imports[name.split(".")[0]] = \
                                ("mod", root_rel)
            elif isinstance(node, ast.ImportFrom):
                target = self._from_target(mi, node)
                if target is None:
                    if node.level == 0:
                        # external from-import: the external marker
                        # keeps the source module AND original symbol
                        # name, so an aliased `from threading import
                        # Thread as Worker` still reads as a spawn
                        for alias in node.names:
                            mi.imports.setdefault(
                                alias.asname or alias.name,
                                ("ext", node.module or "", alias.name))
                    continue
                for alias in node.names:
                    local = alias.asname or alias.name
                    sub_rel = self._dotted_rel(
                        f"{target}.{alias.name}")
                    if sub_rel is not None:
                        mi.imports[local] = ("mod", sub_rel)
                    else:
                        rel = self._dotted_rel(target)
                        if rel is not None:
                            mi.imports[local] = ("sym", rel, alias.name)

    def _from_target(self, mi: ModuleInfo,
                     node: ast.ImportFrom) -> Optional[str]:
        if node.level == 0:
            mod = node.module or ""
            if mod == self.pkg_name or mod.startswith(self.pkg_name + "."):
                return mod
            return None
        # relative import: climb from this module's dotted package
        base = mi.dotted.split(".")
        if not mi.rel.endswith("__init__.py"):
            base = base[:-1]
        climb = node.level - 1
        if climb > len(base):
            return None
        base = base[: len(base) - climb] if climb else base
        return ".".join(base + ([node.module] if node.module else []))

    def _dotted_rel(self, dotted: str) -> Optional[str]:
        return self.dotted.get(dotted)

    def _resolve_bases(self, mi: ModuleInfo) -> None:
        for node in flat_body(mi.sf.tree.body):
            if not isinstance(node, ast.ClassDef):
                continue
            ci = mi.classes[node.name]
            for b in node.bases:
                ref = self._lookup_class(mi, b)
                if ref is not None:
                    ci.bases.append(ref)

    def _ann_class(self, mi: ModuleInfo,
                   ann: Optional[ast.AST]) -> Optional[Tuple[str, str]]:
        """Resolve a return annotation to an in-package class:
        ``-> Monitor``, ``-> "Monitor"`` (forward ref),
        ``-> Optional[KvIndex]`` / ``-> KvIndex | None`` unwrap."""
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            return self._class_by_name(mi, ann.value)
        if isinstance(ann, ast.Name):
            return self._class_by_name(mi, ann.id)
        if isinstance(ann, ast.Attribute):
            return self._lookup_class(mi, ann)
        if isinstance(ann, ast.Subscript):
            # Optional[X]: unwrap; other generics (List[X]...) are NOT
            # the instance itself — skip them
            base = ann.value
            name = (base.id if isinstance(base, ast.Name)
                    else base.attr if isinstance(base, ast.Attribute)
                    else None)
            if name == "Optional":
                return self._ann_class(mi, ann.slice)
            return None
        if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
            left = self._ann_class(mi, ann.left)
            if left is not None:
                return left
            return self._ann_class(mi, ann.right)
        return None

    def _infer_return_types(self, mi: ModuleInfo) -> None:
        """Factory-return inference: a def whose return ANNOTATION
        names an in-package class (Optional unwrapped), or whose every
        class-typed ``return`` agrees on one class (directly or through
        a ``x = ClassName(...)`` local), types its call results — so
        ``mon = Dashboard.Get(name)`` resolves ``mon.Add`` through the
        real Monitor instead of the dynamic-dispatch fallback."""
        def _infer(qual: str, node: ast.AST) -> None:
            cref = self._ann_class(mi, node.returns)
            if cref is None:
                # SHALLOW walks: a nested callback's assignments and
                # returns are not the enclosing def's (a nested
                # `return Worker()` must not type the outer call)
                local_types: Dict[str, Tuple[str, str]] = {}
                for sub in walk_shallow(node):
                    if isinstance(sub, ast.Assign) \
                            and isinstance(sub.value, ast.Call) \
                            and isinstance(sub.value.func, ast.Name):
                        c = self._class_by_name(mi, sub.value.func.id)
                        if c is not None:
                            for tgt in sub.targets:
                                if isinstance(tgt, ast.Name):
                                    local_types[tgt.id] = c
                seen: set = set()
                for sub in walk_shallow(node):
                    if not isinstance(sub, ast.Return) \
                            or sub.value is None:
                        continue
                    v = sub.value
                    if isinstance(v, ast.Call) \
                            and isinstance(v.func, ast.Name):
                        seen.add(self._class_by_name(mi, v.func.id))
                    elif isinstance(v, ast.Name):
                        seen.add(local_types.get(v.id))
                    elif isinstance(v, ast.Constant) and v.value is None:
                        continue
                    else:
                        seen.add(None)
                if len(seen) == 1:
                    cref = seen.pop()
            if cref is not None:
                self.ret_types[f"{mi.rel}:{qual}"] = cref

        for qual, _, node in iter_top_defs(mi.sf.tree):
            _infer(qual, node)

    def _call_result_type(self, mi: ModuleInfo, call: ast.Call,
                          local_types=None, own_class=None
                          ) -> Optional[Tuple[str, str]]:
        """The in-package class a call returns: a constructor call, or
        a call to a def with an inferred return type."""
        fn = call.func
        if isinstance(fn, ast.Name):
            cref = self._class_by_name(mi, fn.id)
            if cref is not None:
                return cref
            state = self._resolve_symbol(mi.rel, fn.id)
        elif isinstance(fn, ast.Attribute):
            chain = _attr_chain(fn)
            if chain is None:
                return None
            state = self._chain_resolve(mi, chain, local_types, own_class)
        else:
            return None
        if state is not None and state[0] == "class":
            return (state[1], state[2])
        if state is not None and state[0] == "func":
            return self.ret_types.get(f"{state[1]}:{state[2]}")
        return None

    def _infer_attr_types(self, mi: ModuleInfo) -> None:
        """One-pass instance-attribute type inference:
        ``self.X = ClassName(...)`` (or ``mod.ClassName(...)``) in ANY
        method types attribute ``X`` for the class, so later chains
        (``self.store.get(...)``) resolve through the real class
        instead of dropping to the dynamic-dispatch name fallback.
        Conflicting re-assignments poison the entry — a wrong type must
        never resolve a chain."""
        for _, cls_node, sub in iter_top_defs(mi.sf.tree):
            if cls_node is None:
                continue
            ci = mi.classes[cls_node.name]
            for st in ast.walk(sub):
                if not (isinstance(st, ast.Assign)
                        and isinstance(st.value, ast.Call)):
                    continue
                cref = self._call_result_type(mi, st.value)
                for tgt in st.targets:
                    if not (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        continue
                    attr = tgt.attr
                    if attr in ci.attr_types:
                        if ci.attr_types[attr] != cref:
                            ci.attr_types[attr] = None  # conflict
                    else:
                        ci.attr_types[attr] = cref

    def _mro_attr_type(self, rel: str, cname: str, attr: str,
                       _seen=None) -> Optional[Tuple[str, str]]:
        seen = _seen or set()
        if (rel, cname) in seen:
            return None
        seen.add((rel, cname))
        mi = self.modules.get(rel)
        if mi is None or cname not in mi.classes:
            return None
        ci = mi.classes[cname]
        if attr in ci.attr_types:
            return ci.attr_types[attr]
        for brel, bname in ci.bases:
            got = self._mro_attr_type(brel, bname, attr, seen)
            if got is not None:
                return got
        return None

    def _lookup_class(self, mi: ModuleInfo,
                      expr: ast.AST) -> Optional[Tuple[str, str]]:
        """Resolve a base-class expression to an in-package (rel, name)."""
        if isinstance(expr, ast.Name):
            return self._class_by_name(mi, expr.id)
        if isinstance(expr, ast.Attribute):
            chain = _attr_chain(expr)
            if chain is None:
                return None
            state = self._chain_resolve(mi, chain)
            if state is not None and state[0] == "class":
                return (state[1], state[2])
        return None

    def _class_by_name(self, mi: ModuleInfo,
                       name: str, _seen=None) -> Optional[Tuple[str, str]]:
        if name in mi.classes:
            return (mi.rel, name)
        imp = mi.imports.get(name)
        if imp is None:
            return None
        if imp[0] == "sym":
            tgt = self.modules.get(imp[1])
            if tgt is None:
                return None
            seen = _seen or set()
            if (imp[1], imp[2]) in seen:
                return None
            seen.add((imp[1], imp[2]))
            return self._class_by_name(tgt, imp[2], seen)
        return None

    # ------------------------------------------------------ symbol lookup

    def _resolve_symbol(self, rel: str, name: str,
                        _seen=None) -> Optional[tuple]:
        """Resolve ``name`` inside module ``rel`` to
        ("func", rel, qual) | ("class", rel, cname) | ("mod", rel)."""
        mi = self.modules.get(rel)
        if mi is None:
            return None
        if name in mi.classes:
            return ("class", rel, name)
        if name in mi.functions and "." not in name:
            return ("func", rel, name)
        imp = mi.imports.get(name)
        if imp is None:
            return None
        if imp[0] == "ext":
            return imp      # carries (module, origin-symbol) when known
        if imp[0] == "mod":
            return ("mod", imp[1])
        seen = _seen or set()
        if (imp[1], imp[2]) in seen:
            return None
        seen.add((imp[1], imp[2]))
        return self._resolve_symbol(imp[1], imp[2], seen)

    def _chain_resolve(self, mi: ModuleInfo, chain: List[str],
                       local_types: Optional[Dict[str, Tuple[str, str]]]
                       = None,
                       own_class: Optional[ClassInfo] = None
                       ) -> Optional[tuple]:
        """Walk a dotted name chain to a ("func"|"class"|"mod") state."""
        head, rest = chain[0], chain[1:]
        state: Optional[tuple]
        if head in ("self", "cls") and own_class is not None:
            state = ("class", own_class.rel, own_class.name)
        elif local_types and head in local_types:
            crel, cname = local_types[head]
            state = ("class", crel, cname)
        else:
            state = self._resolve_symbol(mi.rel, head)
        for part in rest:
            if state is None:
                return None
            kind = state[0]
            if kind == "ext":
                continue        # external chains stay external
            if kind == "mod":
                sub = self.modules.get(state[1])
                if sub is None:
                    return None
                nxt = self._dotted_rel(f"{sub.dotted}.{part}")
                if nxt is not None:
                    state = ("mod", nxt)
                else:
                    state = self._resolve_symbol(state[1], part)
            elif kind == "class":
                m = self._mro_method(state[1], state[2], part)
                if m is None:
                    # not a method: a typed instance attribute keeps
                    # the chain resolving (self.store.get -> the real
                    # SnapshotStore.get, not the name fallback)
                    at = self._mro_attr_type(state[1], state[2], part)
                    m = ("class", at[0], at[1]) if at is not None \
                        else None
                state = m           # ("func", rel, Class.m) or None
            else:
                return None         # attribute of a function: opaque
        return state

    def _mro_method(self, rel: str, cname: str, method: str,
                    _seen=None) -> Optional[tuple]:
        seen = _seen or set()
        if (rel, cname) in seen:
            return None
        seen.add((rel, cname))
        mi = self.modules.get(rel)
        if mi is None or cname not in mi.classes:
            return None
        ci = mi.classes[cname]
        if method in ci.methods:
            return ("func", rel, f"{cname}.{method}")
        for brel, bname in ci.bases:
            got = self._mro_method(brel, bname, method, seen)
            if got is not None:
                return got
        return None

    # ---------------------------------------------------------- edge pass

    def _collect_edges(self, mi: ModuleInfo) -> None:
        mod_owner = f"{mi.rel}:{_MODULE_NODE}"
        for node in flat_body(mi.sf.tree.body):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._edges_for_def(mi, f"{mi.rel}:{node.name}", node, None)
            elif isinstance(node, ast.ClassDef):
                ci = mi.classes[node.name]
                for sub in flat_body(node.body):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        owner = f"{mi.rel}:{node.name}.{sub.name}"
                        self._edges_for_def(mi, owner, sub, ci)
            else:
                # everything else (incl. flattened guard expressions)
                # is module-level code
                self._edges_for_def(mi, mod_owner, node, None)

    def spawn_kind(self, rel: str, call: ast.Call) -> Optional[str]:
        """"Thread"/"Timer" when ``call`` constructs an EXTERNAL
        (threading) Thread/Timer — in-package classes sharing the name
        (the utils Timer stopwatch) resolve through the import table
        and return None, and an ALIASED from-import (``from threading
        import Thread as Worker``) still reads as a spawn through the
        import record's origin symbol."""
        fn = call.func
        if isinstance(fn, ast.Name):
            state = self._resolve_symbol(rel, fn.id)
            if state is not None and state[0] == "ext" \
                    and len(state) >= 3 and state[1] == "threading" \
                    and state[2] in ("Thread", "Timer"):
                return state[2]
            if fn.id in ("Thread", "Timer") \
                    and (state is None or state[0] == "ext"):
                return fn.id
            return None
        if isinstance(fn, ast.Attribute) and fn.attr in ("Thread",
                                                         "Timer"):
            chain = _attr_chain(fn)
            if chain is None:
                return None
            state = self._resolve_symbol(rel, chain[0])
            if state is None or state[0] == "ext":
                return fn.attr
        return None

    def _edges_for_def(self, mi: ModuleInfo, owner: str, root: ast.AST,
                       own_class: Optional[ClassInfo]) -> None:
        local_types: Dict[str, Tuple[str, str]] = {}
        # pass 1: one-shot constructor type inference (x = ClassName(...))
        # plus the THREAD-BOUNDARY CUT: the target= callback of a
        # threading.Thread/Timer spawn (and every RegisterHandler
        # argument) runs on the NEW/actor thread, not this one — like
        # a mailbox hop, the static chain must end at the spawn (the
        # thread inventory classifies the target's domain explicitly).
        # The cut covers the callback expression's WHOLE subtree, so a
        # lambda or functools.partial wrapper is cut too, not just a
        # bare name/attribute ref. Without the cut, every spawner's
        # domain swallows its spawned thread's closure.
        spawn_callbacks: set = set()

        def _cut(expr: ast.AST) -> None:
            spawn_callbacks.update(ast.walk(expr))

        for node in ast.walk(root):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                cref = self._call_result_type(mi, node.value,
                                              local_types, own_class)
                if cref is not None:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            local_types[tgt.id] = cref
            if not isinstance(node, ast.Call):
                continue
            kind = self.spawn_kind(mi.rel, node)
            if kind is not None:
                for kw in node.keywords:
                    if kw.arg in ("target", "function"):
                        _cut(kw.value)
                if len(node.args) >= 2:
                    # positional callbacks: Thread(group, target, ...)
                    # and Timer(interval, function, ...) both carry the
                    # callable at args[1]; args[0] evaluates on THIS
                    # thread and keeps its edges
                    _cut(node.args[1])
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "RegisterHandler":
                for arg in node.args:
                    _cut(arg)
                for kw in node.keywords:
                    _cut(kw.value)
        # pass 2: calls + callable references
        for node in ast.walk(root):
            if node in spawn_callbacks:
                continue
            if isinstance(node, ast.Call):
                self._edge_for_call(mi, owner, node, local_types, own_class)
            elif isinstance(node, (ast.Name, ast.Attribute)) \
                    and isinstance(getattr(node, "ctx", None), ast.Load):
                self._edge_for_ref(mi, owner, node, local_types, own_class)

    def _add_edge(self, owner: str, target: str) -> None:
        self.edges.setdefault(owner, set()).add(target)

    def _edge_for_call(self, mi: ModuleInfo, owner: str, call: ast.Call,
                       local_types, own_class) -> None:
        self.stats["calls"] += 1
        func = call.func
        if isinstance(func, ast.Name):
            state = self._resolve_symbol(mi.rel, func.id)
            if (state is None or state[0] == "ext") \
                    and func.id in EXTERNAL_COLLECTIVE_ATTRS:
                # `from jax...multihost_utils import process_allgather`
                # then a bare-name call: still a collective sink — an
                # in-package def of the same name resolves first and
                # wins (its body is scanned instead)
                self._add_edge(owner, f"<external>:{func.id}")
                self.stats["resolved"] += 1
                return
            self._edge_for_state(owner, state, mi)
            return
        if isinstance(func, ast.Attribute):
            attr = func.attr
            chain = _attr_chain(func)
            state = None
            if chain is not None:
                state = self._chain_resolve(mi, chain, local_types,
                                            own_class)
            elif isinstance(func.value, ast.Call) \
                    and isinstance(func.value.func, ast.Name):
                if func.value.func.id == "super" \
                        and own_class is not None:
                    # super().m(...): resolve through the bases only —
                    # without this, super().ProcessGet used to take the
                    # name fallback and wire the caller into EVERY
                    # table's ProcessGet
                    for brel, bname in own_class.bases:
                        state = self._mro_method(brel, bname, attr)
                        if state is not None:
                            break
                else:
                    # ClassName(...).method(...) — or a typed factory
                    # call result
                    cref = self._class_by_name(mi, func.value.func.id)
                    if cref is None:
                        cref = self._call_result_type(
                            mi, func.value, local_types, own_class)
                    if cref is not None:
                        state = self._mro_method(cref[0], cref[1], attr)
            if state is not None and state[0] != "ext":
                self._edge_for_state(owner, state, mi)
                return
            if attr in EXTERNAL_COLLECTIVE_ATTRS:
                self._add_edge(owner, f"<external>:{attr}")
                self.stats["resolved"] += 1
                return
            if state is not None:       # ("ext",): known-external receiver
                self.stats["dropped"] += 1
                return
            targets = self.methods_by_name.get(attr)
            if targets and not attr.startswith("__") \
                    and attr not in _COMMON_METHOD_NAMES:
                self.stats["fallback"] += 1
                for t in targets:
                    self._add_edge(owner, t)
            else:
                self.stats["dropped"] += 1

    def _edge_for_state(self, owner: str, state: Optional[tuple],
                        mi: ModuleInfo) -> None:
        if state is None:
            self.stats["dropped"] += 1
            return
        kind = state[0]
        if kind == "func":
            self.stats["resolved"] += 1
            self._add_edge(owner, f"{state[1]}:{state[2]}")
        elif kind == "class":
            init = self._mro_method(state[1], state[2], "__init__")
            self.stats["resolved"] += 1
            if init is not None:
                self._add_edge(owner, f"{init[1]}:{init[2]}")
        else:
            self.stats["dropped"] += 1

    def _edge_for_ref(self, mi: ModuleInfo, owner: str, node: ast.AST,
                      local_types, own_class) -> None:
        """Callback references: a bare/dotted name resolving to an
        in-package function creates an edge even without a call."""
        if isinstance(node, ast.Name):
            state = self._resolve_symbol(mi.rel, node.id)
        else:
            chain = _attr_chain(node)
            if chain is None:
                return
            state = self._chain_resolve(mi, chain, local_types, own_class)
        if state is not None and state[0] == "func":
            self._add_edge(owner, f"{state[1]}:{state[2]}")

    # ------------------------------------------------------- reachability

    def reachable(self, roots: List[str]
                  ) -> Tuple[Set[str], Dict[str, str]]:
        """BFS closure + parent map (for path reconstruction)."""
        seen: Set[str] = set()
        parent: Dict[str, str] = {}
        frontier = [r for r in roots if r in self.node_lines
                    or r in self.edges]
        seen.update(frontier)
        while frontier:
            nxt = []
            for n in frontier:
                for t in self.edges.get(n, ()):
                    if t not in seen:
                        seen.add(t)
                        parent[t] = n
                        nxt.append(t)
            frontier = nxt
        return seen, parent

    def path_to(self, parent: Dict[str, str], node: str) -> List[str]:
        out = [node]
        while node in parent:
            node = parent[node]
            out.append(node)
        return list(reversed(out))

    def has_node(self, node: str) -> bool:
        return node in self.node_lines


def _attr_chain(node: ast.Attribute) -> Optional[List[str]]:
    """``a.b.c`` -> ["a", "b", "c"]; None when the chain root is not a
    plain name (subscripts, calls, literals)."""
    parts = [node.attr]
    cur = node.value
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return list(reversed(parts))
    return None


_GRAPH_CACHE: Dict[str, CallGraph] = {}


def build_graph(pkg: PackageIndex) -> CallGraph:
    g = _GRAPH_CACHE.get(pkg.root)
    if g is None or g.pkg is not pkg:
        g = _GRAPH_CACHE[pkg.root] = CallGraph(pkg)
    return g
