"""Package-wide static call graph for the never-collective checker.

Construction (and its honesty bounds, DESIGN.md §16): one AST pass per
module collects defs, classes (with in-package base resolution) and
import aliases; a second pass turns every call / callable reference in
every top-level def into edges. Resolution, strongest first:

1. dotted module chains through import aliases (``multihost.host_barrier``),
   following ``from X import f`` re-exports transitively;
2. ``self.``/``cls.`` methods through the class's in-package MRO;
3. ``ClassName.m`` / ``ClassName(...).m`` / local ``x = ClassName(...)``
   one-pass constructor type inference;
4. anything else that is still a method call falls back to EVERY
   in-package method of that name (dynamic-dispatch over-approximation —
   a path through a fallback edge can be a false positive, never a
   silently missed true one);
5. bare-name calls resolve through local defs and ``from``-imports only;
   an unresolved bare name (builtins, stdlib) drops out of the graph.

Lambdas and nested defs merge into their enclosing top-level def, so
``bounded(lambda: capped_exchange(...))`` correctly charges the caller.
Defs under module/class-level ``if``/``try``/``with`` scaffolding (the
version-shim idiom — parallel/mesh.py's ``shard_map``) are top-level
definitions too (:func:`flat_body`), not module code.
Non-call references to resolvable functions (callbacks passed by name)
also produce edges. What the graph cannot see: getattr-by-string,
property getters that do work, and calls that cross an actor mailbox
(a ``msg.reply``/queue hop ends the static chain — by design: the verb
stream discipline is about which THREAD issues a collective).

Node ids are ``"<rel>:<qualname>"`` (``zoo.py:Zoo._barrier_wait``,
``parallel/multihost.py:capped_exchange``, ``<module>`` for top-level
code). Calls to well-known external collective attributes (``psum``,
``all_gather``...) produce ``<external>:<name>`` sink nodes.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from multiverso_tpu.analysis.core import PackageIndex, SourceFile

#: attribute names that are collective primitives wherever they resolve
#: (jax/gloo surfaces the package may grow calls to)
EXTERNAL_COLLECTIVE_ATTRS = frozenset({
    "psum", "pmax", "pmin", "pmean", "all_gather", "all_reduce",
    "allreduce", "allgather", "alltoall", "reduce_scatter",
    "broadcast_one_to_all", "sync_global_devices", "process_allgather",
})

_MODULE_NODE = "<module>"

#: method names that collide with builtin container/string/IO/threading
#: methods. An UNRESOLVED receiver calling one of these is almost always
#: a dict/list/file/lock — fanning out to every same-named package
#: method would wire `snap.get(...)` into MatrixTableHandler.get and
#: drown the graph in false paths. Such names resolve only through
#: typed receivers (self/cls, class names, constructor inference,
#: module attributes) — a documented honesty bound, DESIGN.md §16. The
#: package's own verb surfaces are capitalized (Add/Get/Wait/Join), so
#: the lowercase exclusions cost little.
_COMMON_METHOD_NAMES = frozenset({
    "get", "set", "add", "pop", "append", "extend", "insert", "remove",
    "discard", "clear", "copy", "update", "keys", "values", "items",
    "setdefault", "popitem", "sort", "reverse", "index", "count",
    "join", "split", "rsplit", "partition", "strip", "lstrip", "rstrip",
    "lower", "upper", "title", "format", "replace", "startswith",
    "endswith", "encode", "decode", "read", "readline", "readlines",
    "write", "writelines", "flush", "close", "open", "seek", "tell",
    "send", "recv", "put", "get_nowait", "put_nowait", "run", "start",
    "stop", "wait", "notify", "notify_all", "acquire", "release",
    "submit", "result", "cancel", "done", "shutdown", "connect",
    "bind", "listen", "accept", "fileno", "terminate", "kill", "poll",
    "communicate", "tobytes", "tolist", "item", "reshape", "astype",
    "mean", "sum", "max", "min", "all", "any", "group", "match",
    "search", "findall", "sub", "finditer", "fullmatch",
})


def flat_body(body) -> "list":
    """Module/class-body statements with conditional/guard scaffolding
    flattened: a def under a module-level ``if``/``try``/``with`` (the
    version-shim and optional-dependency-fallback idioms —
    parallel/mesh.py's ``shard_map`` shim is the in-package example) is
    still a top-level definition for graph purposes. The guard's own
    expressions (``if`` tests, ``except`` types, ``with`` context
    expressions) are yielded too, so module-level guard code keeps its
    edges. Does NOT descend into defs/lambdas — nested defs stay merged
    into their enclosing def."""
    out = []
    for node in body:
        if isinstance(node, ast.If):
            out.append(node.test)
            out.extend(flat_body(node.body))
            out.extend(flat_body(node.orelse))
        elif isinstance(node, ast.Try):
            out.extend(flat_body(node.body))
            for h in node.handlers:
                if h.type is not None:
                    out.append(h.type)
                out.extend(flat_body(h.body))
            out.extend(flat_body(node.orelse))
            out.extend(flat_body(node.finalbody))
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                out.append(item.context_expr)
            out.extend(flat_body(node.body))
        else:
            out.append(node)
    return out


@dataclass
class ClassInfo:
    name: str
    rel: str
    bases: List[Tuple[str, str]] = field(default_factory=list)  # (rel, name)
    methods: Dict[str, int] = field(default_factory=dict)       # name -> line


@dataclass
class ModuleInfo:
    rel: str
    dotted: str
    sf: SourceFile
    functions: Dict[str, int] = field(default_factory=dict)     # qual -> line
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    #: local name -> ("mod", rel) | ("sym", rel, name)
    imports: Dict[str, tuple] = field(default_factory=dict)


class CallGraph:
    def __init__(self, pkg: PackageIndex):
        self.pkg = pkg
        self.pkg_name = os.path.basename(pkg.root)
        self.modules: Dict[str, ModuleInfo] = {}
        self.dotted: Dict[str, str] = {}            # dotted -> rel
        self.edges: Dict[str, Set[str]] = {}
        self.node_lines: Dict[str, Tuple[str, int]] = {}  # node -> (rel, line)
        #: method name -> every "<rel>:<Class.m>" node (fallback targets)
        self.methods_by_name: Dict[str, Set[str]] = {}
        self.stats = {"calls": 0, "resolved": 0, "fallback": 0,
                      "dropped": 0}
        self._build()

    # ---------------------------------------------------------- building

    def _build(self) -> None:
        for sf in self.pkg.files:
            if sf.tree is None:
                continue
            rel = sf.rel
            parts = rel[:-3].split("/")     # strip .py
            if parts[-1] == "__init__":
                parts = parts[:-1]
            dotted = ".".join([self.pkg_name] + parts)
            mi = ModuleInfo(rel=rel, dotted=dotted, sf=sf)
            self.modules[rel] = mi
            self.dotted[dotted] = rel
        for mi in self.modules.values():
            self._collect_defs(mi)
        for mi in self.modules.values():
            self._collect_imports(mi)
        # base-class names resolve only after every module's defs exist
        for mi in self.modules.values():
            self._resolve_bases(mi)
        for mi in self.modules.values():
            self._collect_edges(mi)

    def _collect_defs(self, mi: ModuleInfo) -> None:
        for node in flat_body(mi.sf.tree.body):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mi.functions[node.name] = node.lineno
            elif isinstance(node, ast.ClassDef):
                ci = ClassInfo(name=node.name, rel=mi.rel)
                mi.classes[node.name] = ci
                for sub in flat_body(node.body):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        ci.methods[sub.name] = sub.lineno
                        qual = f"{node.name}.{sub.name}"
                        mi.functions[qual] = sub.lineno
                        nid = f"{mi.rel}:{qual}"
                        self.methods_by_name.setdefault(
                            sub.name, set()).add(nid)
        for qual, line in mi.functions.items():
            self.node_lines[f"{mi.rel}:{qual}"] = (mi.rel, line)
        self.node_lines[f"{mi.rel}:{_MODULE_NODE}"] = (mi.rel, 1)

    def _collect_imports(self, mi: ModuleInfo) -> None:
        pkg_prefix = self.pkg_name + "."
        for node in ast.walk(mi.sf.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.name
                    if name != self.pkg_name \
                            and not name.startswith(pkg_prefix):
                        # external module: record it so attribute calls
                        # on it (subprocess.run, np.sum) resolve to
                        # "external" and DON'T hit the method-name
                        # fallback — stdlib receivers must not fan out
                        # to every same-named package method
                        local = alias.asname or name.split(".")[0]
                        mi.imports.setdefault(local, ("ext",))
                        continue
                    rel = self._dotted_rel(name)
                    if rel is None:
                        continue
                    if alias.asname:
                        mi.imports[alias.asname] = ("mod", rel)
                    else:
                        # "import a.b.c" binds "a"; chains walk down
                        root_rel = self._dotted_rel(name.split(".")[0])
                        if root_rel is not None:
                            mi.imports[name.split(".")[0]] = \
                                ("mod", root_rel)
            elif isinstance(node, ast.ImportFrom):
                target = self._from_target(mi, node)
                if target is None:
                    if node.level == 0:
                        # external from-import: same external marker for
                        # the bound names (threading.Thread, Path, ...)
                        for alias in node.names:
                            mi.imports.setdefault(
                                alias.asname or alias.name, ("ext",))
                    continue
                for alias in node.names:
                    local = alias.asname or alias.name
                    sub_rel = self._dotted_rel(
                        f"{target}.{alias.name}")
                    if sub_rel is not None:
                        mi.imports[local] = ("mod", sub_rel)
                    else:
                        rel = self._dotted_rel(target)
                        if rel is not None:
                            mi.imports[local] = ("sym", rel, alias.name)

    def _from_target(self, mi: ModuleInfo,
                     node: ast.ImportFrom) -> Optional[str]:
        if node.level == 0:
            mod = node.module or ""
            if mod == self.pkg_name or mod.startswith(self.pkg_name + "."):
                return mod
            return None
        # relative import: climb from this module's dotted package
        base = mi.dotted.split(".")
        if not mi.rel.endswith("__init__.py"):
            base = base[:-1]
        climb = node.level - 1
        if climb > len(base):
            return None
        base = base[: len(base) - climb] if climb else base
        return ".".join(base + ([node.module] if node.module else []))

    def _dotted_rel(self, dotted: str) -> Optional[str]:
        return self.dotted.get(dotted)

    def _resolve_bases(self, mi: ModuleInfo) -> None:
        for node in flat_body(mi.sf.tree.body):
            if not isinstance(node, ast.ClassDef):
                continue
            ci = mi.classes[node.name]
            for b in node.bases:
                ref = self._lookup_class(mi, b)
                if ref is not None:
                    ci.bases.append(ref)

    def _lookup_class(self, mi: ModuleInfo,
                      expr: ast.AST) -> Optional[Tuple[str, str]]:
        """Resolve a base-class expression to an in-package (rel, name)."""
        if isinstance(expr, ast.Name):
            return self._class_by_name(mi, expr.id)
        if isinstance(expr, ast.Attribute):
            chain = _attr_chain(expr)
            if chain is None:
                return None
            state = self._chain_resolve(mi, chain)
            if state is not None and state[0] == "class":
                return (state[1], state[2])
        return None

    def _class_by_name(self, mi: ModuleInfo,
                       name: str, _seen=None) -> Optional[Tuple[str, str]]:
        if name in mi.classes:
            return (mi.rel, name)
        imp = mi.imports.get(name)
        if imp is None:
            return None
        if imp[0] == "sym":
            tgt = self.modules.get(imp[1])
            if tgt is None:
                return None
            seen = _seen or set()
            if (imp[1], imp[2]) in seen:
                return None
            seen.add((imp[1], imp[2]))
            return self._class_by_name(tgt, imp[2], seen)
        return None

    # ------------------------------------------------------ symbol lookup

    def _resolve_symbol(self, rel: str, name: str,
                        _seen=None) -> Optional[tuple]:
        """Resolve ``name`` inside module ``rel`` to
        ("func", rel, qual) | ("class", rel, cname) | ("mod", rel)."""
        mi = self.modules.get(rel)
        if mi is None:
            return None
        if name in mi.classes:
            return ("class", rel, name)
        if name in mi.functions and "." not in name:
            return ("func", rel, name)
        imp = mi.imports.get(name)
        if imp is None:
            return None
        if imp[0] == "ext":
            return ("ext",)
        if imp[0] == "mod":
            return ("mod", imp[1])
        seen = _seen or set()
        if (imp[1], imp[2]) in seen:
            return None
        seen.add((imp[1], imp[2]))
        return self._resolve_symbol(imp[1], imp[2], seen)

    def _chain_resolve(self, mi: ModuleInfo, chain: List[str],
                       local_types: Optional[Dict[str, Tuple[str, str]]]
                       = None,
                       own_class: Optional[ClassInfo] = None
                       ) -> Optional[tuple]:
        """Walk a dotted name chain to a ("func"|"class"|"mod") state."""
        head, rest = chain[0], chain[1:]
        state: Optional[tuple]
        if head in ("self", "cls") and own_class is not None:
            state = ("class", own_class.rel, own_class.name)
        elif local_types and head in local_types:
            crel, cname = local_types[head]
            state = ("class", crel, cname)
        else:
            state = self._resolve_symbol(mi.rel, head)
        for part in rest:
            if state is None:
                return None
            kind = state[0]
            if kind == "ext":
                continue        # external chains stay external
            if kind == "mod":
                sub = self.modules.get(state[1])
                if sub is None:
                    return None
                nxt = self._dotted_rel(f"{sub.dotted}.{part}")
                if nxt is not None:
                    state = ("mod", nxt)
                else:
                    state = self._resolve_symbol(state[1], part)
            elif kind == "class":
                m = self._mro_method(state[1], state[2], part)
                state = m           # ("func", rel, Class.m) or None
            else:
                return None         # attribute of a function: opaque
        return state

    def _mro_method(self, rel: str, cname: str, method: str,
                    _seen=None) -> Optional[tuple]:
        seen = _seen or set()
        if (rel, cname) in seen:
            return None
        seen.add((rel, cname))
        mi = self.modules.get(rel)
        if mi is None or cname not in mi.classes:
            return None
        ci = mi.classes[cname]
        if method in ci.methods:
            return ("func", rel, f"{cname}.{method}")
        for brel, bname in ci.bases:
            got = self._mro_method(brel, bname, method, seen)
            if got is not None:
                return got
        return None

    # ---------------------------------------------------------- edge pass

    def _collect_edges(self, mi: ModuleInfo) -> None:
        mod_owner = f"{mi.rel}:{_MODULE_NODE}"
        for node in flat_body(mi.sf.tree.body):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._edges_for_def(mi, f"{mi.rel}:{node.name}", node, None)
            elif isinstance(node, ast.ClassDef):
                ci = mi.classes[node.name]
                for sub in flat_body(node.body):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        owner = f"{mi.rel}:{node.name}.{sub.name}"
                        self._edges_for_def(mi, owner, sub, ci)
            else:
                # everything else (incl. flattened guard expressions)
                # is module-level code
                self._edges_for_def(mi, mod_owner, node, None)

    def _edges_for_def(self, mi: ModuleInfo, owner: str, root: ast.AST,
                       own_class: Optional[ClassInfo]) -> None:
        local_types: Dict[str, Tuple[str, str]] = {}
        # pass 1: one-shot constructor type inference (x = ClassName(...))
        for node in ast.walk(root):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Name):
                cref = self._class_by_name(mi, node.value.func.id)
                if cref is not None:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            local_types[tgt.id] = cref
        # pass 2: calls + callable references
        for node in ast.walk(root):
            if isinstance(node, ast.Call):
                self._edge_for_call(mi, owner, node, local_types, own_class)
            elif isinstance(node, (ast.Name, ast.Attribute)) \
                    and isinstance(getattr(node, "ctx", None), ast.Load):
                self._edge_for_ref(mi, owner, node, local_types, own_class)

    def _add_edge(self, owner: str, target: str) -> None:
        self.edges.setdefault(owner, set()).add(target)

    def _edge_for_call(self, mi: ModuleInfo, owner: str, call: ast.Call,
                       local_types, own_class) -> None:
        self.stats["calls"] += 1
        func = call.func
        if isinstance(func, ast.Name):
            state = self._resolve_symbol(mi.rel, func.id)
            if (state is None or state[0] == "ext") \
                    and func.id in EXTERNAL_COLLECTIVE_ATTRS:
                # `from jax...multihost_utils import process_allgather`
                # then a bare-name call: still a collective sink — an
                # in-package def of the same name resolves first and
                # wins (its body is scanned instead)
                self._add_edge(owner, f"<external>:{func.id}")
                self.stats["resolved"] += 1
                return
            self._edge_for_state(owner, state, mi)
            return
        if isinstance(func, ast.Attribute):
            attr = func.attr
            chain = _attr_chain(func)
            state = None
            if chain is not None:
                state = self._chain_resolve(mi, chain, local_types,
                                            own_class)
            elif isinstance(func.value, ast.Call) \
                    and isinstance(func.value.func, ast.Name):
                # ClassName(...).method(...)
                cref = self._class_by_name(mi, func.value.func.id)
                if cref is not None:
                    state = self._mro_method(cref[0], cref[1], attr)
            if state is not None and state[0] != "ext":
                self._edge_for_state(owner, state, mi)
                return
            if attr in EXTERNAL_COLLECTIVE_ATTRS:
                self._add_edge(owner, f"<external>:{attr}")
                self.stats["resolved"] += 1
                return
            if state is not None:       # ("ext",): known-external receiver
                self.stats["dropped"] += 1
                return
            targets = self.methods_by_name.get(attr)
            if targets and not attr.startswith("__") \
                    and attr not in _COMMON_METHOD_NAMES:
                self.stats["fallback"] += 1
                for t in targets:
                    self._add_edge(owner, t)
            else:
                self.stats["dropped"] += 1

    def _edge_for_state(self, owner: str, state: Optional[tuple],
                        mi: ModuleInfo) -> None:
        if state is None:
            self.stats["dropped"] += 1
            return
        kind = state[0]
        if kind == "func":
            self.stats["resolved"] += 1
            self._add_edge(owner, f"{state[1]}:{state[2]}")
        elif kind == "class":
            init = self._mro_method(state[1], state[2], "__init__")
            self.stats["resolved"] += 1
            if init is not None:
                self._add_edge(owner, f"{init[1]}:{init[2]}")
        else:
            self.stats["dropped"] += 1

    def _edge_for_ref(self, mi: ModuleInfo, owner: str, node: ast.AST,
                      local_types, own_class) -> None:
        """Callback references: a bare/dotted name resolving to an
        in-package function creates an edge even without a call."""
        if isinstance(node, ast.Name):
            state = self._resolve_symbol(mi.rel, node.id)
        else:
            chain = _attr_chain(node)
            if chain is None:
                return
            state = self._chain_resolve(mi, chain, local_types, own_class)
        if state is not None and state[0] == "func":
            self._add_edge(owner, f"{state[1]}:{state[2]}")

    # ------------------------------------------------------- reachability

    def reachable(self, roots: List[str]
                  ) -> Tuple[Set[str], Dict[str, str]]:
        """BFS closure + parent map (for path reconstruction)."""
        seen: Set[str] = set()
        parent: Dict[str, str] = {}
        frontier = [r for r in roots if r in self.node_lines
                    or r in self.edges]
        seen.update(frontier)
        while frontier:
            nxt = []
            for n in frontier:
                for t in self.edges.get(n, ()):
                    if t not in seen:
                        seen.add(t)
                        parent[t] = n
                        nxt.append(t)
            frontier = nxt
        return seen, parent

    def path_to(self, parent: Dict[str, str], node: str) -> List[str]:
        out = [node]
        while node in parent:
            node = parent[node]
            out.append(node)
        return list(reversed(out))

    def has_node(self, node: str) -> bool:
        return node in self.node_lines


def _attr_chain(node: ast.Attribute) -> Optional[List[str]]:
    """``a.b.c`` -> ["a", "b", "c"]; None when the chain root is not a
    plain name (subscripts, calls, literals)."""
    parts = [node.attr]
    cur = node.value
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return list(reversed(parts))
    return None


_GRAPH_CACHE: Dict[str, CallGraph] = {}


def build_graph(pkg: PackageIndex) -> CallGraph:
    g = _GRAPH_CACHE.get(pkg.root)
    if g is None or g.pkg is not pkg:
        g = _GRAPH_CACHE[pkg.root] = CallGraph(pkg)
    return g
