"""Thread-root inventory: every thread this package spawns, classified
into named concurrency DOMAINS (DESIGN.md §18).

The runtime outgrew the reference's one-thread-per-actor story: engine
shard actors, the pipelined exchange stage, a parallel apply pool, the
replica fan-out thread, the watchdog/reporter samplers, ops HTTP
handlers, the serving dispatcher, elastic coordinator RPC threads and a
jax-free reader process all share state. Every cross-thread law the
repo enforces (probe-never-syncs-mirror, handler-never-RPC, bounded
blocking) needs ONE ground truth for "which code runs on which
thread" — this module is that inventory, and the checkers in
:mod:`concurrency` consume it.

A DOMAIN is a named family of threads with one spawn discipline (all
engine shard loops are one domain; every ops HTTP connection thread is
one domain). Domain membership of a function = BFS reachability from
any of the domain's configured root nodes over the static call graph.
The same honesty bounds as :mod:`collective` apply — mailbox hops end
chains, callback refs over-approximate — plus one more: reachability
is DOMAIN-granular, so two threads of the SAME domain racing each
other (e.g. two worker threads) are out of scope here (the table layer
owns that contract).

Config-rot law (same as the never-collective root/sink inventory and
HOT_ZONES): an inventory entry whose root pattern matches no def, or
whose SPAWN SITE (the ``threading.Thread(target=...)`` call that
starts the domain's threads) has disappeared, is itself a finding —
a refactor can move a thread, never silently retire its
classification. The law also runs forward: a ``threading.Thread`` /
``threading.Timer`` spawn site the inventory does not claim is an
UNCLASSIFIED thread — new threads must declare their domain here
before the analysis plane can vouch for them.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from multiverso_tpu.analysis import callgraph
from multiverso_tpu.analysis.core import (Checker, Finding, PackageIndex,
                                          register)

#: where the inventory lives — config-rot findings anchor here (the
#: file the fix edits), falling back to a path-shaped placeholder on
#: trees that do not carry the analysis package
CONFIG_REL = "analysis/threads.py"


@dataclass(frozen=True)
class DomainRoot:
    """One inventory entry: a family of graph nodes that run on the
    domain's threads, plus (when the domain is thread-spawned) the
    lexical spawn site that starts them."""

    domain: str
    rel: str                      #: module holding the root defs
    qual: Optional[str]           #: anchored regex over qualnames;
                                  #: None = spawn-claim-only entry
    label: str
    #: (rel, enclosing-def qualname) of the ``Thread``/``Timer`` call
    #: that spawns this domain's threads; None for roots that are not
    #: thread-spawned (handler entries dispatched by a server loop,
    #: the process main thread)
    spawn: Optional[Tuple[str, str]] = None


#: the domain inventory. Domains (DESIGN.md §18): engine-shard (actor
#: mailbox loops + the exchange stage + engine message handlers),
#: apply-pool, fanout, watchdog, reporter, ops-http, serving-dispatch,
#: replica-reader / replica-serve / replica-hb (the reader process's
#: three thread kinds), elastic (coordinator RPC + member heartbeats),
#: worker (the public API surface + model-layer loader threads — the
#: "worker/main" domain; deliberately MANY threads, see the
#: domain-granularity bound above), helper (bounded-call runner +
#: chaos redelivery timers, whose payloads are caller-defined).
INVENTORY: List[DomainRoot] = [
    # -- engine side
    DomainRoot("engine-shard", "actor.py", r"^Actor\._main$",
               "actor mailbox loop (the server engine thread)",
               spawn=("actor.py", "Actor.Start")),
    DomainRoot("engine-shard", "sync/server.py",
               r"^_ExchangeStage\._main$",
               "pipelined exchange-stage thread",
               spawn=("sync/server.py", "_ExchangeStage.__init__")),
    DomainRoot("engine-shard", "sync/server.py",
               r"^(?:Server|SyncServer|_EngineShard)\."
               r"(?:_get_entry|_add_entry|_store_load_entry|"
               r"ProcessFinishTrain|_fence_entry)$",
               "engine verb/cut handlers (Actor dispatch targets)"),
    DomainRoot("apply-pool", "sync/server.py", r"^_ApplyPool\._loop$",
               "parallel apply-pool worker",
               spawn=("sync/server.py", "_ApplyPool.__init__")),
    # -- sampling / observability side
    DomainRoot("watchdog", "telemetry/watchdog.py", r"^Watchdog\._run$",
               "watchdog tick daemon",
               spawn=("telemetry/watchdog.py", "Watchdog.start")),
    DomainRoot("reporter", "telemetry/export.py",
               r"^StatsReporter\._run$",
               "-stats_interval_s reporter thread",
               spawn=("telemetry/export.py", "StatsReporter.__init__")),
    DomainRoot("ops-http", "telemetry/ops.py", r"^_OpsHandler\.do_GET$",
               "ops HTTP handler (per-connection server threads)",
               spawn=("telemetry/ops.py", "OpsServer.__init__")),
    # -- serving / replica planes
    DomainRoot("serving-dispatch", "serving/frontend.py",
               r"^ServingFrontend\._loop$",
               "serving micro-batch dispatcher",
               spawn=("serving/frontend.py",
                      "ServingFrontend._ensure_thread")),
    DomainRoot("fanout", "replica/publisher.py",
               r"^ReplicaPublisher\._run$",
               "replica fan-out thread",
               spawn=("replica/publisher.py", "ReplicaPublisher.start")),
    DomainRoot("replica-reader", "replica/replica.py",
               r"^Replica\.recv_loop$",
               "replica receive/apply loop (reader process main)"),
    DomainRoot("replica-serve", "replica/replica.py",
               r"^_LookupHandler\.handle$",
               "replica lookup serve loop (per-connection threads)",
               spawn=("replica/replica.py", "Replica._start_serve_server")),
    DomainRoot("replica-hb", "replica/replica.py", r"^Replica\._hb_loop$",
               "replica heartbeat lease thread",
               spawn=("replica/replica.py", "Replica.start")),
    # -- elastic plane
    DomainRoot("elastic", "elastic/coordinator.py",
               r"^Coordinator\._dispatch$",
               "coordinator RPC dispatch (per-connection threads)",
               spawn=("elastic/coordinator.py", "Coordinator.serve")),
    DomainRoot("elastic", "elastic/coordinator.py",
               r"^MemberClient\.start_heartbeats$",
               "member heartbeat thread (the _beat closure)",
               spawn=("elastic/coordinator.py",
                      "MemberClient.start_heartbeats")),
    # -- coordinator HA (round 23): the op-log replication threads.
    # Their own "standby" domain, NOT "elastic": the shipper's ack
    # wait and the standby's replay hold plain locks by design
    # (control-plane, never on a verb path), so they must not inherit
    # the elastic domain's blocking-restriction posture.
    DomainRoot("standby", "elastic/standby.py",
               r"^LogShipper\._ack_loop$",
               "primary-side op-log ack reader (standby watermark)",
               spawn=("elastic/standby.py", "LogShipper.__init__")),
    DomainRoot("standby", "elastic/standby.py",
               r"^LogShipper\._ping_loop$",
               "primary-side takeover-lease keepalive",
               spawn=("elastic/standby.py", "LogShipper.__init__")),
    DomainRoot("standby", "elastic/standby.py",
               r"^StandbyServer\._feed$",
               "standby op-log intake (per-stream server threads)",
               spawn=("elastic/standby.py", "StandbyServer.__init__")),
    DomainRoot("standby", "elastic/standby.py",
               r"^StandbyServer\._watch$",
               "standby takeover-lease monitor",
               spawn=("elastic/standby.py", "StandbyServer.__init__")),
    # -- worker/main: the STEADY-STATE concurrent surfaces only. The
    # cut-riding API calls (checkpoint save/load, snapshot publish,
    # elastic transitions) and the setup/teardown calls (MV_Init,
    # MV_CreateTable, MV_ShutDown) are deliberately NOT roots: their
    # payloads run on the engine thread at a fenced stream position
    # (Zoo.CallOnEngine) or in join-ordered quiesced phases, and the
    # static graph merges those payload closures into the caller — a
    # documented honesty bound (DESIGN.md §18), so including them
    # would attribute engine-thread writes to the worker domain.
    DomainRoot("worker", "api.py",
               r"^MV_(?:Barrier|Aggregate|ServingLookup|"
               r"PinVersion|UnpinVersion)$",
               "public API steady-state verb surface (user threads)"),
    DomainRoot("worker", "models/logreg/logreg.py", r"^LogReg\._train$",
               "logreg training loop (app main thread) + its "
               "epoch-line harvest spawn",
               spawn=("models/logreg/logreg.py", "LogReg._train")),
    DomainRoot("worker", "models/wordembedding/distributed.py",
               r"^DistributedWordEmbedding\.train$",
               "wordembedding training loop (app main thread)"),
    DomainRoot("worker", "models/logreg/data.py", r"^WindowReader\._run$",
               "logreg async window reader",
               spawn=("models/logreg/data.py", "WindowReader.__init__")),
    DomainRoot("worker", "models/wordembedding/data.py",
               r"^start_loader$",
               "wordembedding corpus loader thread",
               spawn=("models/wordembedding/data.py", "start_loader")),
    DomainRoot("worker", "utils/async_buffer.py", None,
               "async prefetch fill thread (target: the caller's fill "
               "callable — an attribute, so claim-only)",
               spawn=("utils/async_buffer.py", "ASyncBuffer._launch")),
    # -- policy plane (round 20): the alert->action daemon. Its
    # watchdog-listener intake (PolicyEngine.on_watchdog_tick) runs on
    # the WATCHDOG thread and is enqueue-only by contract; the
    # decision/staging work all hangs off _run. Actuation in
    # multi-process worlds happens at MV_PolicySync on app threads
    # (deliberately NOT a root — the cut-riding exclusion above).
    DomainRoot("policy", "policy/engine.py", r"^PolicyEngine\._run$",
               "policy evaluation daemon (alert->action loop)",
               spawn=("policy/engine.py", "PolicyEngine.start")),
    # -- tcp wire (round 24): the only thread the transport owns is
    # the install-time accept loop — it collects the mesh's inbound
    # dials, closes the listeners and EXITS; steady-state exchanges
    # run entirely on the caller's thread (the selectors loop), so no
    # exchange-side root exists to register
    DomainRoot("tcp-wire", "parallel/tcp_wire.py",
               r"^TcpWire\._accept_loop$",
               "tcp wire mesh accept loop (install-time, exits once "
               "the mesh is up)",
               spawn=("parallel/tcp_wire.py", "TcpWire.connect")),
    # -- infrastructure helpers
    DomainRoot("helper", "failsafe/deadline.py", r"^_Runner\._loop$",
               "bounded-call runner thread",
               spawn=("failsafe/deadline.py", "_Runner.__init__")),
    DomainRoot("helper", "failsafe/chaos.py", r"^schedule_redelivery$",
               "chaos redelivery timer (the _redeliver closure)",
               spawn=("failsafe/chaos.py", "schedule_redelivery")),
]


def all_domains() -> List[str]:
    return sorted({e.domain for e in INVENTORY})


@dataclass(frozen=True)
class SpawnSite:
    rel: str
    qual: str       #: enclosing top-level def ("<module>" at module level)
    line: int
    what: str       #: "Thread" | "Timer"
    target: str     #: unparsed target= expression ("" when none)


def _spawn_sites(pkg: PackageIndex,
                 graph: callgraph.CallGraph) -> List[SpawnSite]:
    """Every ``threading.Thread(...)`` / ``threading.Timer(...)`` call,
    attributed to its enclosing top-level def (nested defs and closures
    merge into the enclosing def, matching the call-graph node
    granularity). In-package classes that merely SHARE the name (the
    utils Timer stopwatch) resolve through the import table and are
    skipped — only external (threading) spawns count."""
    out: List[SpawnSite] = []
    for rel, mi in graph.modules.items():

        def _scan(owner_qual: str, root: ast.AST) -> None:
            for node in ast.walk(root):
                if not isinstance(node, ast.Call):
                    continue
                what = graph.spawn_kind(rel, node)
                if what is None:
                    continue
                target = ""
                for kw in node.keywords:
                    # Thread spells it target=, Timer also accepts
                    # function= — the callgraph cut handles both, the
                    # finding's hint must too
                    if kw.arg in ("target", "function"):
                        target = ast.unparse(kw.value)
                if not target and len(node.args) >= 2:
                    # positional callbacks — Thread(group, target, ...)
                    # / Timer(interval, function, args=None): the
                    # callable is args[1], never the trailing
                    # args/kwargs lists
                    target = ast.unparse(node.args[1])
                out.append(SpawnSite(rel=rel, qual=owner_qual,
                                     line=node.lineno, what=what,
                                     target=target))

        covered = set()
        for qual, _, node in callgraph.iter_top_defs(mi.sf.tree):
            covered.add(node)
            _scan(qual, node)
        for node in callgraph.flat_body(mi.sf.tree.body):
            if node not in covered and not isinstance(node, ast.ClassDef):
                _scan("<module>", node)
    return out


class ThreadInventory:
    """The expanded inventory over one package: per-domain root nodes,
    per-domain BFS closures (+ parent maps for chain reconstruction),
    the detected spawn sites, and the config-rot record."""

    def __init__(self, pkg: PackageIndex):
        self.pkg = pkg
        self.graph = callgraph.build_graph(pkg)
        self.spawns = _spawn_sites(pkg, self.graph)
        self.roots: Dict[str, Set[str]] = {}        # domain -> nodes
        self.root_labels: Dict[str, str] = {}       # node -> label
        self.closures: Dict[str, Set[str]] = {}
        self.parents: Dict[str, Dict[str, str]] = {}
        #: (message, anchor-rel-or-None, line) config-rot records
        self.rot: List[Tuple[str, Optional[str], int]] = []
        self.unclaimed: List[SpawnSite] = []
        self._expand()
        self._bfs()

    def _expand(self) -> None:
        node_quals = [(n, n.split(":", 1)[0], n.split(":", 1)[1])
                      for n in self.graph.node_lines]
        #: (rel, qual) -> number of inventory entries claiming it; a
        #: def holding MORE spawns than claims reports the surplus, so
        #: a second thread added beside a claimed spawn cannot ride
        #: the existing entry unclassified
        claimed: Dict[Tuple[str, str], int] = {}
        for entry in INVENTORY:
            if entry.qual is not None:
                pat = re.compile(entry.qual)
                hits = [n for n, rel, q in node_quals
                        if rel == entry.rel and pat.search(q)]
                if not hits:
                    self.rot.append((
                        f"thread-domain config rot: root pattern "
                        f"{entry.qual!r} in {entry.rel!r} "
                        f"({entry.domain}: {entry.label}) matches no "
                        f"def — the code moved; update "
                        f"analysis/threads.py INVENTORY, never retire "
                        f"the classification", None, 1))
                else:
                    s = self.roots.setdefault(entry.domain, set())
                    s.update(hits)
                    for n in hits:
                        self.root_labels.setdefault(n, entry.label)
            if entry.spawn is not None:
                claimed[entry.spawn] = claimed.get(entry.spawn, 0) + 1
                if not any(sp.rel == entry.spawn[0]
                           and sp.qual == entry.spawn[1]
                           for sp in self.spawns):
                    self.rot.append((
                        f"thread-domain config rot: spawn site "
                        f"{entry.spawn[1]!r} in {entry.spawn[0]!r} "
                        f"({entry.domain}: {entry.label}) no longer "
                        f"spawns a thread — the spawn moved; update "
                        f"analysis/threads.py INVENTORY", None, 1))
        by_site: Dict[Tuple[str, str], List[SpawnSite]] = {}
        for sp in self.spawns:
            by_site.setdefault((sp.rel, sp.qual), []).append(sp)
        for key, sites in sorted(by_site.items()):
            n_claims = claimed.get(key, 0)
            if n_claims >= len(sites):
                continue
            # claims cover the FIRST spawns in source order; the
            # surplus (a new thread added beside a claimed spawn)
            # reports unclassified
            sites.sort(key=lambda s: s.line)
            self.unclaimed.extend(sites[n_claims:])

    def _bfs(self) -> None:
        for domain, roots in self.roots.items():
            seen, parent = self.graph.reachable(sorted(roots))
            self.closures[domain] = seen
            self.parents[domain] = parent

    def domains_of(self, node: str) -> Set[str]:
        return {d for d, seen in self.closures.items() if node in seen}

    def chain(self, domain: str, node: str) -> List[str]:
        return self.graph.path_to(self.parents.get(domain, {}), node)

    def domain_root_for(self, domain: str, node: str) -> str:
        """The root whose BFS tree holds ``node`` (chain head)."""
        return self.chain(domain, node)[0]


_INV_CACHE: Dict[str, ThreadInventory] = {}


def inventory_for(pkg: PackageIndex) -> ThreadInventory:
    inv = _INV_CACHE.get(pkg.root)
    if inv is None or inv.pkg is not pkg:
        inv = _INV_CACHE[pkg.root] = ThreadInventory(pkg)
    return inv


@register
class ThreadDomainsChecker(Checker):
    """The inventory's own law: every configured root/spawn is live
    (config rot otherwise), and every detected thread spawn is claimed
    by a domain entry (an unclassified thread is a finding — new
    threads must be classified before PR N+1 piles actuators on
    them)."""

    name = "thread-domains"
    description = ("thread spawn sites must be classified into a "
                   "concurrency domain (analysis/threads.py INVENTORY) "
                   "and the inventory must stay live (config rot)")

    def check(self, pkg: PackageIndex) -> List[Finding]:
        inv = inventory_for(pkg)
        self.scanned.update(pkg.rel_paths)
        anchor = CONFIG_REL if pkg.file(CONFIG_REL) is not None \
            else "<config>"
        out: List[Finding] = []
        for msg, rel, line in inv.rot:
            out.append(Finding(self.name, rel or anchor, line, msg))
        for sp in inv.unclaimed:
            tgt = f" (target={sp.target})" if sp.target else ""
            out.append(Finding(
                self.name, sp.rel, sp.line,
                f"unclassified thread spawn: threading.{sp.what} in "
                f"{sp.qual}{tgt} — every spawned thread needs a "
                f"DomainRoot entry in analysis/threads.py so the "
                f"concurrency checkers know whose thread runs it"))
        return out
