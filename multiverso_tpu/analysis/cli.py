"""``python -m multiverso_tpu.analysis`` — the mvlint CLI.

Exit code contract (the tier-1 test pins it, so CI can gate on it):

* ``0`` — every checker ran, zero unsuppressed findings, zero stale
  suppressions;
* ``1`` — findings (violations, stale/malformed suppressions, parse
  failures);
* ``2`` — usage errors (unknown rule, bad flag, unreadable root,
  unwritable diag dir).

``--json`` prints the machine-readable result to stdout and, when a
diagnostics directory is configured (``--diag-dir`` or the package's
``-mv_diag_dir`` flag), also drops ``analysis_rank<R>.json`` next to
the flight/trace/telemetry artifacts — same layout
:func:`multiverso_tpu.telemetry.ops.dump_diagnostics` uses, so one
directory still holds everything a postmortem (or a CI gate) needs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from multiverso_tpu.analysis import core


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m multiverso_tpu.analysis",
        description="mvlint: static invariant analysis over the package "
                    "(AST rules + the never-collective call-graph "
                    "checker)")
    p.add_argument("--root", default=None,
                   help="package root to scan (default: the installed "
                        "multiverso_tpu package)")
    p.add_argument("--rules", default=None,
                   help="comma-separated subset of rules (default: all); "
                        "see --list")
    p.add_argument("--list", action="store_true", dest="list_rules",
                   help="list registered rules and exit")
    p.add_argument("--json", action="store_true",
                   help="emit the machine-readable result to stdout "
                        "(and to the diagnostics dir when configured)")
    p.add_argument("--diag-dir", default=None,
                   help="directory for the analysis_rank<R>.json "
                        "artifact (default: the -mv_diag_dir flag)")
    return p


def _out(text: str) -> None:
    sys.stdout.write(text + "\n")


def main(argv: Optional[List[str]] = None) -> int:
    # checker modules register on import
    from multiverso_tpu.analysis import (collective, concurrency,  # noqa: F401
                                         rules, threads)  # noqa: F401
    try:
        args = _parser().parse_args(argv)
    except SystemExit as exc:       # argparse exits 2 on usage errors
        return int(exc.code or 0)

    if args.list_rules:
        for name in core.all_checker_names():
            _out(f"{name}: {core.CHECKERS[name].description}")
        return 0

    rule_names = None
    if args.rules:
        rule_names = [r.strip() for r in args.rules.split(",") if r.strip()]
        if not rule_names:
            # exit 0 means "every checker ran": a --rules that names
            # nothing (e.g. an unset CI variable interpolated into
            # --rules "$RULES,") must not read as a clean pass
            _out(f"usage error: --rules {args.rules!r} names no rules")
            return 2
    if args.root is not None and not os.path.isdir(args.root):
        _out(f"usage error: --root {args.root!r} is not a directory")
        return 2
    try:
        result = core.run_analysis(root=args.root, rules=rule_names)
    except KeyError as exc:
        _out(f"usage error: {exc.args[0]}")
        return 2

    if args.json:
        payload = result.as_dict()
        _out(json.dumps(payload, indent=1, sort_keys=True))
        try:
            _write_artifact(args.diag_dir, payload)
        except OSError as exc:
            # an unwritable diag dir must not masquerade as exit 1
            # ("findings") or crash past the pinned 0/1/2 contract
            _out(f"usage error: cannot write diag artifact: {exc}")
            return 2
    else:
        for f in result.findings:
            _out(f.render())
        scanned = {rel for c in result.checkers for rel in c.scanned}
        _out(f"mvlint: {len(result.findings)} finding(s), "
             f"{len(result.suppressed)} suppressed, "
             f"{len(result.checkers)} rule(s) over "
             f"{len(scanned)} file(s)")
    return 0 if result.clean else 1


def _write_artifact(diag_dir: Optional[str], payload: dict) -> None:
    """Drop analysis_rank<R>.json into the -mv_diag_dir layout."""
    d = diag_dir
    if not d:
        try:
            from multiverso_tpu.telemetry import flight
            d = flight.diag_dir()
        except Exception:
            d = ""
    if not d:
        return
    try:
        from multiverso_tpu.telemetry import flight
        r = flight._rank()
    except Exception:
        r = 0
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"analysis_rank{r}.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
