"""Policy engine: guarded conversion of sustained alerts into actions.

The decision core of the self-driving runtime (DESIGN.md §20). One
:class:`PolicyEngine` per process consumes the watchdog's tick records
(the alert->action hand-off registered at plane start), converts
SUSTAINED alerts into typed action proposals through a stack of guards,
stages them at-most-once, and — in single-process worlds — installs
them at a fenced engine cut. Multi-process worlds split the roles: the
policy thread only STAGES (at the coordinator's ``policy_put``, keyed
``(epoch, action id)``), and the app-paced ``MV_PolicySync`` rendezvous
pulls the one agreed action list on every rank and installs it at each
rank's lockstep stream position — the same discipline every other cut
(checkpoint, publish, elastic transition) already demands.

The three closed loops:

=================  =====================================================
alert              action
=================  =====================================================
shard_imbalance    ``route`` — a table->shard routing-map override
                   (rebalance.plan_routing picks the hottest table of
                   the hottest engine stream and the coolest target
                   slot), installed via ShardedServer.install_routing
                   inside a cross-stream cut.
apply_pool_sat     ``tune`` — raise ``-mv_apply_workers`` one step
                   within the ``-mv_policy_workers_min/max`` rails (the
                   engine's apply pool rebuilds at the next window).
mailbox_backlog    ``tune`` — raise ``-mv_pipeline_depth`` one step
                   within the ``-mv_policy_depth_min/max`` rails (the
                   exchange stage reads the cap per gate).
straggler          ``drain`` — escalation: the SICK rank (the alert is
                   a local proxy that fires on the culprit) proposes
                   its own guarded elastic drain; at the next
                   MV_PolicySync it runs MV_ElasticLeave while the
                   survivors run the matching MV_ElasticSync.
=================  =====================================================

Guards (every one a flag; ``-mv_policy`` itself is the runtime kill
switch, read through a listener cache on every evaluation):

* SUSTAIN — an alert must stay active ``-mv_policy_sustain``
  consecutive policy evaluations before it may act (drains need twice
  that: irreversible actions earn extra evidence).
* COOLDOWN — after an install, the triggering rule may not act again
  for ``-mv_policy_cooldown_s`` (chaos ``policy.flap`` + the regression
  test pin the no-amplification claim: alert flap never becomes action
  flap).
* WINDOW BUDGET — at most ``-mv_policy_max_actions`` installs per
  ``-mv_policy_window_s`` rolling window, across all rules.
* RAILS — tunables clamp to their min/max flags; a rule already at its
  rail proposes nothing.
* PER-RULE ENABLES — ``-mv_policy_rules`` ("all" or a comma list).
* REVERT — every installed route/tune is tracked: if the triggering
  alert is still active after ``-mv_policy_revert_after`` further
  evaluations, the inverse action is staged and the rule is BURNED
  (no new action) until its alert clears — the self-driving loop must
  never oscillate on a correction that did not help.

Every transition is a typed event: ``policy.*`` counters, a
``policy.staged`` / ``policy.route`` / ``policy.tune`` /
``policy.drain`` / ``policy.revert`` flight record stamped with
``(mepoch, head-stream SEQ)`` — the same keying the alert events carry,
so forensics aligns an action with its triggering alert — and a bounded
action history served at ``/actions``.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Deque, Dict, List, Optional

from multiverso_tpu.failsafe import chaos
from multiverso_tpu.failsafe import deadline as fdeadline
from multiverso_tpu.telemetry import flight as tflight
from multiverso_tpu.telemetry import metrics as tmetrics
from multiverso_tpu.telemetry import watchdog as twatchdog
from multiverso_tpu.utils.configure import (GetFlag, MV_DEFINE_bool,
                                            MV_DEFINE_double,
                                            MV_DEFINE_int,
                                            MV_DEFINE_string, SetCMDFlag,
                                            cached_bool_flag,
                                            cached_float_flag,
                                            cached_int_flag,
                                            cached_str_flag)
from multiverso_tpu.utils.log import Log

MV_DEFINE_bool("mv_policy", False,
               "policy plane (self-driving runtime): convert sustained "
               "watchdog alerts into guarded, flight-recorded engine-"
               "cut actions (hot-table re-routing, adaptive apply-"
               "workers/pipeline-depth, straggler drain escalation). "
               "Off by default; ALSO the runtime kill switch — setting "
               "it false mid-run (MV_SetFlag) stops all acting at the "
               "next evaluation while the plane keeps watching")
MV_DEFINE_string("mv_policy_addr", "",
                 "policy control authority endpoint host:port for "
                 "multi-process worlds WITHOUT -mv_elastic (rank 0 "
                 "hosts it; with -mv_elastic the policy ops ride the "
                 "membership coordinator instead). Single-process "
                 "worlds stage locally and ignore this")
MV_DEFINE_string("mv_policy_rules", "all",
                 "comma list of alert rules the policy may act on "
                 "(shard_imbalance, apply_pool_sat, mailbox_backlog, "
                 "straggler), or 'all'")
MV_DEFINE_double("mv_policy_cooldown_s", 10.0,
                 "minimum seconds between installed actions for one "
                 "rule — the anti-flap guard (chaos policy.flap "
                 "rehearses it)")
MV_DEFINE_double("mv_policy_window_s", 60.0,
                 "rolling window for -mv_policy_max_actions")
MV_DEFINE_int("mv_policy_max_actions", 4,
              "max actions installed per -mv_policy_window_s window, "
              "across all rules")
MV_DEFINE_int("mv_policy_sustain", 2,
              "consecutive policy evaluations an alert must stay "
              "active before it may act (drains require 2x)")
MV_DEFINE_int("mv_policy_revert_after", 6,
              "evaluations after an install before a still-active "
              "triggering alert stages the inverse action and burns "
              "the rule until it clears")
MV_DEFINE_int("mv_policy_workers_min", 1,
              "lower rail for adaptive -mv_apply_workers")
MV_DEFINE_int("mv_policy_workers_max", 16,
              "upper rail for adaptive -mv_apply_workers")
MV_DEFINE_int("mv_policy_depth_min", 1,
              "lower rail for adaptive -mv_pipeline_depth")
MV_DEFINE_int("mv_policy_depth_max", 8,
              "upper rail for adaptive -mv_pipeline_depth")
MV_DEFINE_int("mv_policy_min_members", 1,
              "a policy drain may never shrink the world below this "
              "many members")

_enabled = cached_bool_flag("mv_policy", False)
_rules_flag = cached_str_flag("mv_policy_rules", "all")
_cooldown_s = cached_float_flag("mv_policy_cooldown_s", 10.0)
_window_s = cached_float_flag("mv_policy_window_s", 60.0)
_max_actions = cached_int_flag("mv_policy_max_actions", 4)
_sustain = cached_int_flag("mv_policy_sustain", 2)
_revert_after = cached_int_flag("mv_policy_revert_after", 6)
_workers_min = cached_int_flag("mv_policy_workers_min", 1)
_workers_max = cached_int_flag("mv_policy_workers_max", 16)
_depth_min = cached_int_flag("mv_policy_depth_min", 1)
_depth_max = cached_int_flag("mv_policy_depth_max", 8)
_min_members = cached_int_flag("mv_policy_min_members", 1)

#: alert rules the policy knows how to act on
ACTABLE_RULES = ("shard_imbalance", "apply_pool_sat", "mailbox_backlog",
                 "straggler")

#: the rule whose verdict the chaos ``policy.flap`` site oscillates
#: (a tunable loop, so the rehearsal exercises a REAL decider)
FLAP_RULE = "mailbox_backlog"

#: the ``policy.*`` counter family, registered eagerly at plane start
#: (the PR 6 scrape-at-zero rule)
COUNTER_FAMILY = ("policy.evals", "policy.proposed", "policy.staged",
                  "policy.stage_dedup_hits", "policy.installed",
                  "policy.reverted", "policy.drains",
                  "policy.rejected")


def rule_enabled(rule: str) -> bool:
    spec = _rules_flag().strip()
    if spec in ("", "all"):
        return True
    return rule in {r.strip() for r in spec.split(",")}


def reduce_conflicts(actions: List[dict]) -> List[dict]:
    """Deterministic conflict reduction over one pulled action list:
    at most one action per ``conflict`` key (two ranks proposing
    different targets for one table, two drains in one window), FIRST
    in action-id sort order wins — every rank reduces the identical
    pulled list identically, so installs stay rank-agreed."""
    out: List[dict] = []
    seen = set()
    for a in sorted(actions, key=lambda a: str(a.get("id", ""))):
        key = a.get("conflict") or a.get("id")
        if key in seen:
            continue
        seen.add(key)
        out.append(a)
    return out


class LocalStager:
    """Single-process stager: the coordinator ``policy_put``/
    ``policy_pull`` contract (at-most-once by (epoch, id), drain-on-
    pull, persistent seen-set) without a socket."""

    def __init__(self):
        self._lock = threading.Lock()
        self._staged: List[dict] = []
        self._seen: set = set()
        self.dups = 0

    def put(self, action: dict, epoch: int = 0) -> bool:
        with self._lock:
            key = (int(epoch), str(action["id"]))
            if key in self._seen:
                self.dups += 1
                tmetrics.counter("policy.stage_dedup_hits").inc()
                return True
            self._seen.add(key)
            self._staged.append((key, dict(action)))
            return False

    def pull(self, world: int = 1, timeout: Optional[float] = None,
             armed: bool = True) -> tuple:
        with self._lock:
            staged = sorted(self._staged,
                            key=lambda ka: str(ka[1].get("id", "")))
            self._staged = []
            if not armed:
                # vetoed, never installed: forget the dedup keys so
                # the correction can re-stage after re-arming (the
                # coordinator op does the same)
                for k, _a in staged:
                    self._seen.discard(k)
            return [a for _k, a in staged], bool(armed)


class CoordStager:
    """Multi-process stager over the coordinator's policy control ops
    (elastic/coordinator.py): ``put`` retries transients (a chaos-
    duplicated delivery is absorbed by the (epoch, id) dedup), ``pull``
    is a plain call — arrivals are rendezvous generations, so a blind
    re-send would desync them (the elastic sync rule)."""

    def __init__(self, client):
        self.client = client

    def put(self, action: dict, epoch: int = 0) -> bool:
        resp = self.client.call_retry("policy_put", action=dict(action),
                                      epoch=int(epoch), timeout=10.0)
        return bool(resp.get("dup"))

    def pull(self, world: int, timeout: Optional[float] = None,
             armed: bool = True) -> tuple:
        resp = self.client.call("policy_pull", world=int(world),
                                armed=bool(armed),
                                timeout=float(timeout or 60.0))
        return (list(resp.get("actions", ())),
                bool(resp.get("acting", True)))


class EngineApplier:
    """Installs one route/tune batch at ONE fenced engine cut (a
    ``Request_StoreLoad`` payload — the cross-stream cut on a sharded
    engine): with every stream fenced, the routing map swaps and the
    tuned flags set at one consistent stream position, and the
    ``policy.*`` flight events are stamped with that position's
    ``(mepoch, SEQ)``.

    The cut message goes STRAIGHT to the engine mailbox instead of
    through ``Zoo.CallOnEngine``: a policy install is a control-plane
    swap, not a data-ordering point — buffered fire-and-forget Adds may
    legally flush at their own next ordering point (the count-capped
    write-combine buffer is program-structural, so every SPMD rank
    holds the same buffer state at its lockstep sync position and the
    streams stay agreed) — and skipping the flush keeps the ``policy``
    concurrency domain statically off the worker-table surfaces, which
    is what lets the PR 13 domain checkers hold it to its own state."""

    def routing_report(self) -> Optional[dict]:
        try:
            from multiverso_tpu.zoo import Zoo
            eng = Zoo.Get().server_engine
            rr = getattr(eng, "routing_report", None)
            return rr() if rr is not None else None
        except Exception:
            return None

    def install_actions(self, actions: List[dict]) -> List[tuple]:
        from multiverso_tpu.message import Message, MsgType
        from multiverso_tpu.parallel import multihost
        from multiverso_tpu.utils.waiter import Waiter
        from multiverso_tpu.zoo import Zoo
        eng = Zoo.Get().server_engine

        def _payload():
            out = []
            for a in actions:
                if a["kind"] == "route":
                    install = getattr(eng, "install_routing", None)
                    if install is None:
                        Log.Error("policy: route action %s on an "
                                  "unsharded engine — skipped", a["id"])
                        res = {"applied": []}
                    else:
                        res = {"applied": install(
                            {int(a["table"]): int(a["dst"])})}
                else:               # tune
                    frm = GetFlag(a["flag"])
                    SetCMDFlag(a["flag"], a["to"])
                    res = {"frm": frm, "to": a["to"]}
                kind = "revert" if a.get("revert_of") else a["kind"]
                tflight.record(f"policy.{kind}", seq=eng._mh_seq,
                               epoch=eng.window_epoch,
                               mepoch=multihost.membership_epoch(),
                               detail=f"rule={a['rule']} id={a['id']}")
                out.append((dict(a), res))
            return out

        waiter = Waiter(1)
        msg = Message(msg_type=MsgType.Request_StoreLoad,
                      payload={"fn": _payload}, waiter=waiter)
        eng.Receive(msg)
        if not waiter.Wait(60.0):
            fdeadline.raise_deadline("policy action install",
                                     seconds=60.0)
        if isinstance(msg.result, Exception):
            raise msg.result
        return msg.result


class PolicyEngine:
    """The per-process policy evaluator + (optionally) its daemon
    thread. Tests drive :meth:`step` directly with synthetic watchdog
    tick records and a fake applier; the live plane feeds it through
    the watchdog tick listener."""

    def __init__(self, stager, me: int = 0, world: int = 1,
                 applier=None):
        self.stager = stager
        self.me = int(me)
        self.world = int(world)
        self.applier = applier if applier is not None else EngineApplier()
        self._lock = threading.Lock()
        self._ticks: Deque[dict] = collections.deque(maxlen=64)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.evals = 0
        #: installs agreed so far — the rank-agreed generation stamped
        #: into action ids so a repeat of the same correction after a
        #: revert is a NEW id, while N ranks proposing one correction
        #: still collide into one staged action
        self.installed_count = 0
        self._sustain: Dict[str, int] = {}
        self._burned: set = set()
        self._cool_until: Dict[str, float] = {}
        self._installs: Deque[float] = collections.deque()
        #: installed actions under revert watch:
        #: {"action", "res", "rule", "evals_left"}
        self._tracking: List[dict] = []
        #: insertion-ordered (dict keys): the trim below evicts the
        #: OLDEST proposals, so an in-flight action's id cannot be
        #: evicted right after it was added
        self._proposed_ids: Dict[str, None] = {}
        self._prev_shards: Optional[Dict[int, dict]] = None
        #: bounded action history, newest last (the /actions body)
        self.history: Deque[dict] = collections.deque(maxlen=128)
        #: per-ENGINE tallies for /actions + /healthz (the metrics
        #: counters are process-global and outlive worlds; a fresh
        #: world's report must start at zero)
        self.n_staged = 0
        self.n_installed = 0
        self.n_reverted = 0
        self.n_drains = 0
        self.n_rejected = 0
        self.n_dedup = 0
        for name in COUNTER_FAMILY:
            tmetrics.counter(name)

    # -- intake (watchdog thread) -------------------------------------------

    def on_watchdog_tick(self, rec: dict) -> None:
        """The alert->action hand-off: called by the watchdog after
        every evaluate. Enqueue-only — the policy thread does the
        work; the watchdog tick must stay cheap."""
        self._ticks.append(rec)
        self._wake.set()

    # -- daemon lifecycle ---------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run,
                                        name="mv-policy", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(0.2)
            self._wake.clear()
            while True:
                try:
                    rec = self._ticks.popleft()
                except IndexError:
                    break
                try:
                    self.step(rec)
                except Exception as exc:    # the loop must never die
                    Log.Error("policy evaluation failed: %r", exc)

    def stop(self) -> None:
        """Stop + join BOUNDED (the watchdog.stop contract)."""
        self._stop.set()
        self._wake.set()
        if self._thread is None:
            return
        from multiverso_tpu.failsafe.errors import DeadlineExceeded
        try:
            fdeadline.bounded(lambda: self._thread.join(timeout=5),
                              "policy thread join", fatal=False)
        except DeadlineExceeded as exc:
            Log.Error("policy stop timed out (%r) — abandoning its "
                      "daemon thread", exc)

    # -- one evaluation -----------------------------------------------------

    def step(self, rec: dict) -> List[dict]:
        """One policy evaluation over one watchdog tick record.
        Returns the actions staged this evaluation (guards applied)."""
        with self._lock:
            self.evals += 1
            tmetrics.counter("policy.evals").inc()
            active = set(rec.get("active", ()))
            cz = chaos.get()
            if cz is not None:
                flap = cz.policy_flap()
                if flap is True:
                    active.add(FLAP_RULE)
                elif flap is False:
                    active.discard(FLAP_RULE)
            for r in list(self._sustain):
                if r not in active:
                    self._sustain[r] = 0
            for r in active:
                self._sustain[r] = self._sustain.get(r, 0) + 1
            # a burned rule un-burns only when its alert CLEARS
            self._burned &= active
            shard_deltas = self._note_shards(rec)
            if not _enabled():
                # the kill switch: keep watching (sustain/burn state
                # stays warm), act on nothing, track nothing new
                return []
            reverts = self._judge_tracking(active)
            staged: List[dict] = []
            for a in reverts:
                if self._stage(a):
                    staged.append(a)
            for rule in sorted(active):
                a = self._decide(rule, rec, shard_deltas)
                if a is None:
                    continue
                tmetrics.counter("policy.proposed").inc()
                reason = self._guard(rule, a, pending=len(staged))
                if reason is not None:
                    tmetrics.counter("policy.rejected").inc()
                    self.n_rejected += 1
                    continue
                if self._stage(a):
                    staged.append(a)
        # single-process worlds: the policy thread is also the actuator
        # (no SPMD agreement to wait for). OUTSIDE the lock: the
        # install blocks on an engine cut. No drain_runner — drains
        # are structurally impossible single-process.
        if self.world <= 1 and staged:
            self.actuate()
        return staged

    def _note_shards(self, rec: dict) -> Optional[dict]:
        """Per-slot load/verb deltas between this tick's engine shard
        states and the previous tick's — the routing decider's input."""
        shards = (rec.get("sample") or {}).get("shards")
        if not shards:
            return None
        cur = {s["shard"]: s for s in shards}
        prev, self._prev_shards = self._prev_shards, cur
        if prev is None or len(cur) < 2:
            return None
        load = {}
        verbs: Dict[int, Dict[int, int]] = {}
        for slot, s in cur.items():
            p = prev.get(slot, {})
            load[slot] = max(0.0, s.get("apply_busy_s", 0.0)
                             - p.get("apply_busy_s", 0.0))
            pv = p.get("table_verbs", {})
            verbs[slot] = {t: max(0, n - pv.get(t, 0))
                           for t, n in s.get("table_verbs", {}).items()}
        return {"load": load, "verbs": verbs}

    # -- deciders -----------------------------------------------------------

    def _decide(self, rule: str, rec: dict,
                shard_deltas: Optional[dict]) -> Optional[dict]:
        if rule == "shard_imbalance":
            return self._decide_route(shard_deltas)
        if rule == "apply_pool_sat":
            return self._decide_tune("mv_apply_workers", 2,
                                     _workers_min(), _workers_max(),
                                     rule)
        if rule == "mailbox_backlog":
            return self._decide_tune("mv_pipeline_depth", 1,
                                     _depth_min(), _depth_max(), rule)
        if rule == "straggler":
            return self._decide_drain()
        return None

    def _decide_route(self, deltas: Optional[dict]) -> Optional[dict]:
        if deltas is None:
            return None
        report = self.applier.routing_report()
        if report is None:
            return None
        from multiverso_tpu.elastic import rebalance
        plan = rebalance.plan_routing(deltas["load"], deltas["verbs"],
                                      report["routing"],
                                      report["live_slots"])
        if plan is None:
            return None
        tid, src, dst = plan
        gen = self.installed_count
        return {"id": f"route:t{tid}:s{src}>s{dst}:g{gen}",
                "kind": "route", "rule": "shard_imbalance",
                "table": tid, "src": src, "dst": dst,
                "conflict": f"route:t{tid}"}

    def _decide_tune(self, flag: str, step: int, lo: int, hi: int,
                     rule: str) -> Optional[dict]:
        try:
            cur = int(GetFlag(flag))
        except Exception:
            # the tuned flags are DEFINED in sync/server.py (zoo
            # imports it eagerly; offline test harnesses may not have)
            try:
                import multiverso_tpu.sync.server  # noqa: F401
                cur = int(GetFlag(flag))
            except Exception:
                return None
        new = min(max(cur + step, lo), hi)
        if new == cur:
            return None         # already at the rail
        gen = self.installed_count
        return {"id": f"tune:{flag}:{cur}>{new}:g{gen}", "kind": "tune",
                "rule": rule, "flag": flag, "frm": cur, "to": new,
                "conflict": f"tune:{flag}"}

    def _decide_drain(self) -> Optional[dict]:
        """Straggler escalation: the SICK rank proposes its own drain
        (the alert is a local proxy firing on the culprit). Extra
        guards for an irreversible action: elastic plane live, not the
        authority rank, the shrunk world keeps >=
        -mv_policy_min_members, and DOUBLE the sustain evidence."""
        if self.world <= 1 or self.me == 0:
            return None
        if self._sustain.get("straggler", 0) < 2 * max(1, _sustain()):
            return None
        from multiverso_tpu import elastic
        if not elastic.enabled() or elastic.is_departed():
            return None
        members = elastic.members()
        if self.me not in members:
            return None
        if len(members) - 1 < max(1, _min_members()):
            return None
        gen = self.installed_count
        return {"id": f"drain:r{self.me}:g{gen}", "kind": "drain",
                "rule": "straggler", "rank": self.me,
                "conflict": "drain"}

    # -- guards + staging ---------------------------------------------------

    def _guard(self, rule: str, action: dict,
               pending: int = 0) -> Optional[str]:
        """First failing guard's name, or None (clear to stage).
        ``pending`` counts actions already staged THIS evaluation, so
        one tick cannot blow through the window budget before any of
        its installs land. Caller holds the lock."""
        if not rule_enabled(rule):
            return "rule_disabled"
        if rule in self._burned:
            return "burned"
        if self._sustain.get(rule, 0) < max(1, _sustain()):
            return "sustain"
        if any(tr["rule"] == rule for tr in self._tracking):
            # one correction at a time: the previous action for this
            # rule has not been judged (improved vs revert) yet
            return "awaiting_verdict"
        now = time.monotonic()
        if now < self._cool_until.get(rule, 0.0):
            return "cooldown"
        horizon = now - max(1e-9, _window_s())
        while self._installs and self._installs[0] < horizon:
            self._installs.popleft()
        if len(self._installs) + pending >= max(1, _max_actions()):
            return "window_budget"
        if action["id"] in self._proposed_ids:
            return "already_proposed"
        return None

    def _stage(self, action: dict) -> bool:
        """Stage one action (at-most-once at the stager). Caller holds
        the lock. True when newly staged by THIS rank."""
        self._proposed_ids[action["id"]] = None
        if len(self._proposed_ids) > 512:
            for k in list(self._proposed_ids)[:256]:
                del self._proposed_ids[k]
        dup = self.stager.put(action, epoch=self._mepoch())
        mep, seq = twatchdog.stream_pos()
        tflight.record("policy.staged", seq=seq, mepoch=mep,
                       detail=f"rule={action['rule']} id={action['id']}"
                              f"{' dup' if dup else ''}")
        tmetrics.counter("policy.staged").inc()
        self.n_staged += 1
        if dup:
            self.n_dedup += 1
        self._note(action, "staged" if not dup else "staged-dup")
        return not dup

    @staticmethod
    def _mepoch() -> int:
        try:
            from multiverso_tpu.parallel import multihost
            return int(multihost.membership_epoch())
        except Exception:
            return 0

    # -- revert tracking ----------------------------------------------------

    def _judge_tracking(self, active: set) -> List[dict]:
        """Age every installed action under watch; return the revert
        actions to stage (triggering alert still active after
        -mv_policy_revert_after evaluations). Caller holds the lock."""
        reverts: List[dict] = []
        for tr in list(self._tracking):
            if tr["rule"] not in active:
                # the triggering gauge improved: the action stands
                self._tracking.remove(tr)
                self._note(tr["action"], "improved")
                continue
            tr["evals_left"] -= 1
            if tr["evals_left"] > 0:
                continue
            self._tracking.remove(tr)
            rv = self._build_revert(tr)
            # burned either way: no NEW action for this rule until its
            # alert clears — a correction that did not help must not
            # loop
            self._burned.add(tr["rule"])
            if rv is not None:
                reverts.append(rv)
                self._note(tr["action"], "revert-staged")
            else:
                # nothing to invert (e.g. a route whose install was an
                # idempotent no-op) — say so instead of promising a
                # revert that never comes
                self._note(tr["action"], "unrevertible")
        return reverts

    @staticmethod
    def _build_revert(tr: dict) -> Optional[dict]:
        a, res = tr["action"], tr.get("res") or {}
        if a["kind"] == "route":
            applied = res.get("applied") or []
            if not applied:
                return None
            tid, prev, new = applied[0]
            return {"id": f"revert:{a['id']}", "kind": "route",
                    "rule": a["rule"], "table": tid, "src": new,
                    "dst": prev, "conflict": f"route:t{tid}",
                    "revert_of": a["id"]}
        if a["kind"] == "tune":
            frm = res.get("frm", a.get("frm"))
            if frm is None:
                return None
            return {"id": f"revert:{a['id']}", "kind": "tune",
                    "rule": a["rule"], "flag": a["flag"],
                    "frm": a.get("to"), "to": frm,
                    "conflict": f"tune:{a['flag']}",
                    "revert_of": a["id"]}
        return None                 # drains have no revert path

    # -- actuation ----------------------------------------------------------

    def actuate(self, timeout: Optional[float] = None,
                drain_runner=None) -> List[dict]:
        """Pull + actuate the AGREED staged-action list — the ONE
        actuation core (the policy thread's single-process path and
        MV_PolicySync both run exactly this, so a guard added here
        covers both). Sequence: pull (rendezvous in multi-process
        worlds, carrying this rank's kill-switch state), reduce
        conflicts deterministically, honour the AGREED kill verdict
        (any disarmed rank vetoes the whole batch — it is discarded on
        every rank rather than half-installed), install route/tune at
        the fenced cut, then at most ONE drain through
        ``drain_runner`` (only the app-paced sync point passes one —
        the policy thread must never run the collective drain legs)."""
        acts, acting = self.stager.pull(world=max(1, self.world),
                                        timeout=timeout,
                                        armed=bool(_enabled()))
        acts = reduce_conflicts(acts)
        if not acting:
            with self._lock:
                for a in acts:
                    # the proposal window forgets the id too (the
                    # stager un-saw its key): after re-arming, the
                    # same correction may stage again instead of
                    # wedging on "already_proposed"
                    self._proposed_ids.pop(a.get("id"), None)
                    self._note(a, "discarded-killed")
            if acts:
                Log.Info("policy: kill switch down on >=1 rank — %d "
                         "agreed action(s) discarded world-wide",
                         len(acts))
            return []
        drains = [a for a in acts if a["kind"] == "drain"]
        local = [a for a in acts if a["kind"] != "drain"]
        out = self.install_batch(local)
        for a in drains[:1]:
            if drain_runner is None:
                Log.Error("policy: drain action %s outside a policy "
                          "sync point — dropped", a["id"])
                self._note(a, "dropped")
            elif drain_runner(a):
                out.append(a)
        for a in drains[1:]:
            # a second drain would address a world the first just
            # changed — it re-proposes against the new view if real
            self._note(a, "dropped")
        return out

    def install_batch(self, actions: List[dict]) -> List[dict]:
        """Install one agreed route/tune batch at a fenced engine cut
        and book the guard state (cooldowns, window budget, revert
        tracking). Every rank of an SPMD world calls this with the
        IDENTICAL list, so the bookkeeping stays rank-agreed."""
        if not actions:
            return []
        results = self.applier.install_actions(actions)
        now = time.monotonic()
        with self._lock:
            for a, res in results:
                self._installs.append(now)
                self._cool_until[a["rule"]] = now + max(
                    0.0, _cooldown_s())
                self.installed_count += 1
                tmetrics.counter("policy.installed").inc()
                self.n_installed += 1
                if a.get("revert_of"):
                    tmetrics.counter("policy.reverted").inc()
                    self.n_reverted += 1
                    self._note(a, "reverted")
                else:
                    self._tracking.append(
                        {"action": a, "res": res, "rule": a["rule"],
                         "evals_left": max(1, _revert_after())})
                    self._note(a, "installed", res)
        return [a for a, _ in results]

    def note_drain(self, action: dict) -> None:
        """Bookkeeping for an executed drain (sync_point runs the
        collective part; this records the guard state)."""
        now = time.monotonic()
        with self._lock:
            self._installs.append(now)
            self._cool_until[action["rule"]] = now + max(
                0.0, _cooldown_s())
            self.installed_count += 1
            tmetrics.counter("policy.installed").inc()
            tmetrics.counter("policy.drains").inc()
            self.n_installed += 1
            self.n_drains += 1
            self._note(action, "drained")

    # -- surfaces -----------------------------------------------------------

    def _note(self, action: dict, status: str, res=None) -> None:
        rec = {"t": time.time(), "id": action.get("id"),
               "kind": action.get("kind"), "rule": action.get("rule"),
               "status": status}
        if res:
            rec["result"] = res
        self.history.append(rec)

    def report(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "armed": bool(_enabled()),
                "world": self.world,
                "evals": self.evals,
                "installed": self.n_installed,
                "reverted": self.n_reverted,
                "drains": self.n_drains,
                "staged": self.n_staged,
                "rejected": self.n_rejected,
                "stage_dedup_hits": self.n_dedup,
                "burned": sorted(self._burned),
                "tracking": [{"id": tr["action"]["id"],
                              "rule": tr["rule"],
                              "evals_left": tr["evals_left"]}
                             for tr in self._tracking],
                "guards": {
                    "rules": _rules_flag(),
                    "cooldown_s": _cooldown_s(),
                    "window_s": _window_s(),
                    "max_actions_per_window": _max_actions(),
                    "sustain_evals": _sustain(),
                    "revert_after_evals": _revert_after(),
                    "workers_rail": [_workers_min(), _workers_max()],
                    "depth_rail": [_depth_min(), _depth_max()],
                    "min_members": _min_members(),
                },
                "actions": list(self.history),
            }
