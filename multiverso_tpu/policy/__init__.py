"""Policy plane: the self-driving runtime (DESIGN.md §20).

PR 10 gave the runtime eyes (7 typed hysteresis alert rules, /alerts,
the byte ledger) and PR 7 gave it hands (fenced engine cuts, live shard
rebalancing, drain/re-admit) — but a human still read /alerts and acted
by hand, the reference's watch-the-Dashboard posture with better
instruments. This package is the wire between them: a guarded control
loop that converts SUSTAINED watchdog alerts into typed, hysteresis-
guarded, flight-recorded engine-cut actions — off by default behind
``-mv_policy``, which doubles as the runtime kill switch.

Roles (engine.py carries the decision core + guard stack):

* the **policy thread** (one per rank, concurrency domain ``policy`` —
  analysis/threads.py INVENTORY) consumes the watchdog's tick records
  and STAGES action proposals, at-most-once keyed ``(epoch, action
  id)``: locally in single-process worlds, at the coordinator's
  ``policy_put`` control op otherwise (the elastic coordinator when
  ``-mv_elastic`` is up, else a policy-only authority rank 0 hosts at
  ``-mv_policy_addr``).
* **actuation** happens at a fenced engine cut. Single-process worlds
  install straight from the policy thread. Multi-process worlds
  actuate ONLY at :func:`sync_point` (``MV_PolicySync``) — an
  app-paced lockstep call (the MV_SaveCheckpoint discipline) that
  pulls the ONE agreed action list from the coordinator's rendezvous
  and installs it at every rank's identical stream position; elastic
  drains run their collective leave/sync legs here and nowhere else.

Surfaces: ``policy.*`` counters, ``policy.staged/route/tune/drain/
revert`` flight events stamped ``(mepoch, SEQ)`` (aligned with their
triggering ``alert.*`` events by forensics), the ``/actions`` ops
endpoint, and a ``policy`` line in ``/healthz``.
"""

from __future__ import annotations

import threading
from typing import List, Optional

from multiverso_tpu.parallel import multihost
from multiverso_tpu.policy import engine as _engine
from multiverso_tpu.telemetry import flight as tflight
from multiverso_tpu.telemetry import watchdog as twatchdog
from multiverso_tpu.utils.configure import GetFlag
from multiverso_tpu.utils.log import CHECK, Log


class _PlaneState:
    def __init__(self):
        self.engine: Optional[_engine.PolicyEngine] = None
        self.coordinator = None         # policy-only authority (rank 0,
        self.client = None              # non-elastic multi-proc worlds)
        self.lock = threading.Lock()


_state = _PlaneState()


def enabled() -> bool:
    """Plane up (regardless of the kill switch's current position)."""
    return _state.engine is not None


def peek() -> Optional[_engine.PolicyEngine]:
    return _state.engine


def start_plane(zoo) -> bool:
    """Bring up the policy plane when ``-mv_policy`` is set (Zoo.Start,
    after the watchdog and the elastic plane). Returns True when up."""
    st = _state
    if not bool(GetFlag("mv_policy")):
        return False
    CHECK(zoo.server_engine is not None,
          "-mv_policy needs the server engine (not -ma mode): every "
          "policy action installs at an engine cut")
    wd = twatchdog.peek()
    CHECK(wd is not None,
          "-mv_policy needs the watchdog armed (-mv_watchdog_s=N): "
          "the policy plane acts on its typed alerts")
    me = multihost.process_index()
    world = multihost.process_count()
    with st.lock:
        if st.engine is not None:
            return True
        if world > 1:
            from multiverso_tpu import elastic
            from multiverso_tpu.elastic.coordinator import (Coordinator,
                                                            MemberClient)
            lease = 10.0
            endpoints = None
            ep = elastic.coordinator_endpoint()
            if ep is not None:
                # the membership coordinator already runs on rank 0 —
                # the policy control ops ride the same authority (and
                # its ordered failover list: agreement must follow the
                # authority to its successor after a takeover)
                host, port = ep
                endpoints = elastic.coordinator_endpoints()
            else:
                addr = str(GetFlag("mv_policy_addr"))
                host, _, port_s = addr.rpartition(":")
                CHECK(addr and host and port_s.isdigit(),
                      "-mv_policy in a multi-process world needs "
                      "-mv_policy_addr host:port every rank can reach "
                      "(or -mv_elastic, whose coordinator it rides); "
                      f"got {addr!r}")
                port = int(port_s)
                if me == 0:
                    st.coordinator = Coordinator(host, port, lease)
                    port = st.coordinator.port
            st.client = MemberClient(host, port, me, lease,
                                     endpoints=endpoints)
            stager = _engine.CoordStager(st.client)
        else:
            stager = _engine.LocalStager()
        eng = _engine.PolicyEngine(stager, me=me, world=world)
        eng.start()
        wd.add_tick_listener(eng.on_watchdog_tick)
        st.engine = eng
    Log.Info("policy: plane up — rank %d of %d, rules=%s, cooldown "
             "%.1fs, kill switch -mv_policy", me, world,
             str(GetFlag("mv_policy_rules")),
             float(GetFlag("mv_policy_cooldown_s")))
    return True


def shutdown_plane() -> None:
    """Stop the policy thread + any hosted authority (Zoo.Stop,
    BEFORE the watchdog stops — no tick may land on a dead engine).
    Idempotent."""
    st = _state
    with st.lock:
        eng, st.engine = st.engine, None
        coord, st.coordinator = st.coordinator, None
        st.client = None
    if eng is not None:
        eng.stop()
    if coord is not None:
        coord.stop()


def sync_point(timeout: float = 60.0) -> List[dict]:
    """``MV_PolicySync``: the app-paced ACTUATION point of a
    multi-process world — every ACTIVE rank calls it at the same loop
    position (the MV_SaveCheckpoint / MV_ElasticSync discipline). Runs
    the engine's one actuation core: pull the agreed staged-action
    list from the coordinator rendezvous (which also agrees the
    kill-switch verdict — one disarmed rank vetoes the batch
    world-wide), install route/tune actions at this rank's fenced
    engine cut, and run at most one elastic drain (the drained rank's
    MV_ElasticLeave against the survivors' MV_ElasticSync). Returns
    the actions actuated. Single-process worlds flush the local stage
    queue the same way (the policy thread usually beat them to it).
    No-op ([]) while the plane is down — or on a DEPARTED elastic
    member, which is no longer part of any rendezvous."""
    eng = _state.engine
    if eng is None:
        return []
    from multiverso_tpu import elastic
    if elastic.enabled() and elastic.is_departed():
        return []
    # world size from the CURRENT membership view: keep it in sync
    # with what the engine believes (a drain changes it mid-run)
    eng.world = max(1, multihost.world_size())
    return eng.actuate(timeout=timeout,
                       drain_runner=lambda a: _execute_drain(eng, a))


def _execute_drain(eng: _engine.PolicyEngine, action: dict) -> bool:
    """The collective leg of a drain action, on the calling (worker)
    thread: the sick rank leaves, every other rank syncs — one staged
    transition, applied at the members' lockstep positions. Re-checks
    the world guards against the CURRENT view (the action may have
    been staged before a membership change)."""
    from multiverso_tpu import elastic
    if not elastic.enabled() or elastic.is_departed():
        Log.Error("policy: drain %s without a live elastic membership "
                  "— dropped", action["id"])
        eng._note(action, "dropped")
        return False
    members = elastic.members()
    rank = int(action["rank"])
    if rank not in members or rank == 0 or \
            len(members) - 1 < max(1, _engine._min_members()):
        Log.Error("policy: drain %s no longer legal for members %s — "
                  "dropped", action["id"], list(members))
        eng._note(action, "dropped")
        return False
    mep, seq = twatchdog.stream_pos()
    tflight.record("policy.drain", seq=seq, mepoch=mep,
                   detail=f"rule={action['rule']} id={action['id']} "
                          f"rank={rank}")
    eng.note_drain(action)
    if multihost.process_index() == rank:
        epoch = elastic.leave()
        Log.Info("policy: drained self (rank %d) at epoch %d — "
                 "MV_ElasticJoin re-admits", rank, epoch)
    else:
        elastic.sync()
    return True


def status_line() -> Optional[dict]:
    """The /healthz ``policy`` line (LOCAL, never collective): None
    while the plane is down."""
    eng = _state.engine
    if eng is None:
        return None
    last = eng.history[-1] if eng.history else None
    return {"armed": bool(_engine._enabled()),
            "evals": eng.evals,
            "installed": eng.n_installed,
            "reverted": eng.n_reverted,
            "drains": eng.n_drains,
            "last_action": (f"{last['status']}:{last['id']}"
                            if last else None)}


def actions_report() -> dict:
    """The ``/actions`` body. When the plane is down the body says so
    instead of claiming idleness."""
    eng = _state.engine
    if eng is None:
        return {"enabled": False, "actions": [],
                "note": "policy plane off — arm with -mv_policy=true "
                        "(+ -mv_watchdog_s=N for its eyes)"}
    return eng.report()
