"""Multi-host (multi-process) runtime wiring over ``jax.distributed``.

The reference scales across machines with MPI/ZMQ point-to-point messaging
(SURVEY.md §2c): every process runs worker+server actors and Get/Add
requests cross the network per table shard. The TPU-native equivalent is a
**multi-controller SPMD job**: one process per host, all processes
participating in a single global device mesh, parameter shards laid across
every host's HBM, and the "network" being XLA collectives over ICI (intra
slice) / DCN (across slices) — the scaling-book model.

The SPMD constraint this imposes (and the honest behavioral mapping):

* computations on globally-sharded arrays are **collective** — every
  process must issue the same program in the same order. Table verbs in
  multihost mode therefore follow the *collective contract*: every process
  calls the same Get/Add sequence (normal SPMD training loops — and the
  device plane — do this naturally).
* the reference's *asynchrony* (workers never wait for each other) lives
  **within** each host among its worker threads, exactly as in the 1-host
  world; cross-host progress is synchronous at collective boundaries. This
  is the documented reinterpretation SURVEY.md §7 anticipates ("bounded
  async via microbatched rounds") — on TPU fabric, lockstep collectives are
  the fast path, not a compromise.

What this module provides:

* ``maybe_initialize`` — bring up ``jax.distributed`` from flags
  (``-dist_coordinator/-dist_rank/-dist_size``) or automatic TPU-pod
  detection (``-multihost=auto`` uses it only when the env indicates a
  multi-process job; ``on`` forces; ``off`` never).
* ``process_index/process_count`` — identity (Zoo rank/size).
* ``host_barrier`` — cross-host barrier (device-level sync over the global
  mesh), the Controller-barrier equivalent (reference controller.cpp:12-36).
* ``host_allreduce_sum`` — cross-host elementwise sum of a host numpy
  array, used by ``MV_Aggregate`` to extend the in-process rendezvous
  allreduce across hosts (reference MV_Aggregate → MPI_Allreduce,
  src/multiverso.cpp:53-56).
* ``broadcast_from_master`` — host-0 value to all hosts (the binding's
  master-initializes convention, reference tables.py:49-58).

All of them degrade to no-ops / identity in a single-process job, so the
1-host world (tests, the reference's unittest fixture pattern) runs the
same code paths.
"""

from __future__ import annotations

import os
import time as _time
from typing import Optional

import numpy as np

from multiverso_tpu.utils.configure import (GetFlag, MV_DEFINE_int,
                                            MV_DEFINE_string)
from multiverso_tpu.utils.log import CHECK, Log

MV_DEFINE_string("multihost", "auto", "multi-process init: auto / on / off")
# reference ZMQ deployment flags (zmq_net.h:20-21), kept for flag parity:
# a machine file maps line N -> rank N endpoints; on TPU it feeds the same
# explicit jax.distributed wiring MV_NetBind/MV_NetConnect use
MV_DEFINE_string("machine_file", "",
                 "hosts file, one endpoint per line = rank order "
                 "(reference ZMQ -machine_file; feeds net wiring)")
MV_DEFINE_int("port", 55555,
              "default port when a machine-file line has none "
              "(reference ZMQ -port)")
MV_DEFINE_string("dist_coordinator", "",
                 "coordinator address host:port (jax.distributed)")
MV_DEFINE_int("dist_rank", -1, "this process index (jax.distributed)")
MV_DEFINE_int("dist_size", -1, "total process count (jax.distributed)")
# Round 12 — pluggable host wire (the reference's ZMQ-vs-MPI backend
# split, PAPER.md L2: transports are deployment choices, not protocol
# changes). "auto": same-host worlds ride the shared-memory wire
# (parallel/shm_wire.py — gloo measured ~410 MB/s between two
# processes of ONE machine; shm is a memcpy), cross-host worlds take
# the framed tcp wire (round 24, parallel/tcp_wire.py) when the
# engine/replica asked for more than one exchange channel, else gloo.
# "gloo" forces the socket allgather; "shm"/"tcp" REQUIRE their wire
# and CHECK-fail when it cannot come up.
MV_DEFINE_string("mv_wire", "auto",
                 "windowed-engine host wire: auto (shm when every rank "
                 "shares a host; tcp when hosts differ and >1 channel "
                 "is needed; else gloo) / shm (require) / tcp "
                 "(require) / gloo")
# Round 24 — the loopback cross-host drills: CI has one box, but the
# cross-host selection/labeling code path must still be exercised for
# real. The override changes THIS rank's host IDENTITY (wire
# selection votes, telemetry + critpath labels) while dialing always
# rides the genuinely advertised endpoints — the honest split between
# "which code path runs" and "which sockets carry bytes".
MV_DEFINE_string("mv_wire_hostname", "",
                 "override this rank's host identity in wire selection "
                 "and telemetry/critpath labels (loopback cross-host "
                 "drills fake distinct hosts on one box; dialing still "
                 "rides real endpoints). Empty = the real hostname")
MV_DEFINE_int("mv_shm_ring_bytes", 4 << 20,
              "shared-memory wire: per-(channel, rank) data area bytes "
              "(frames larger than this chunk through it)")
# Round 12 — elastic follow-on 4 (ROADMAP): the PJRT coordination
# service declares a silent member dead after ~100s of missed
# heartbeats (10s interval x 10 misses) and then tears the survivors
# down — a long-lived SHRUNK world (elastic plane, the dead member
# never returns) must outlive that corpse detection. MV_Init plumbs
# this budget into jax.distributed.initialize's heartbeat knobs when
# the installed jax exposes them (signature-checked; older/newer jax
# without the kwargs logs and keeps runtime defaults). 0 = leave the
# runtime defaults; -mv_elastic worlds default to 600s.
MV_DEFINE_int("mv_pjrt_heartbeat_s", 0,
              "PJRT coordination-service liveness budget in seconds "
              "(missed-heartbeat window before a silent member is "
              "declared dead); 0 = runtime default (~100s), or 600 "
              "when -mv_elastic is on")

_initialized = False
_owns_runtime = False   # True only when WE called jax.distributed.initialize

#: observability: HOST collective rounds issued through this module (and
#: mesh.fetch's reassembly allgather). The r4 verdict's scale-out
#: critique was "one host collective per table verb"; the windowed
#: engine protocol (sync/server.py) is judged by THIS counter per verb
#: (bench two_proc_collectives_per_op). XLA-level collectives (psum
#: etc. inside jit programs) ride ICI and are deliberately not counted
#: — they are the fast path, not the protocol cost.
STATS = {"host_collective_rounds": 0,
         #: wall seconds spent inside capped_exchange (the windowed
         #: engine's one host-collective path) — lets the bench decompose
         #: the 2-proc cost into protocol rounds vs shared-core compute.
         #: Wire encode/decode timing moved to the telemetry histograms
         #: server.wire.{encode,decode}_s (telemetry/metrics.py) — the
         #: bench reads those from MV_MetricsSnapshot now.
         "exchange_seconds": 0.0}


def note_collective(n: int = 1) -> None:
    STATS["host_collective_rounds"] += n


#: per-call timing of the LAST capped_exchange on this process — the
#: engine's phase stamping (round 11, sync/server.py) reads it right
#: after its window exchange returns, on the same thread, to split the
#: time BLOCKED IN THE COLLECTIVE (``coll_s``) from local staging work
#: and to anchor cross-rank clock alignment on the exchange-done wall
#: stamp (every rank leaves the same allgather at ~the same instant — a
#: free sync pulse per window; telemetry/critpath.py). The dict is
#: replaced atomically per call (readers see an old or a new record,
#: never a torn one); cost when nobody reads it is four float stores.
_exchange_last = {"enter_m": 0.0, "done_m": 0.0, "done_w": 0.0,
                  "coll_s": 0.0}


def _stamp_exchange(enter_m: float, coll_s: float, done_m: float,
                    done_w: float) -> None:
    global _exchange_last
    # mv-lint: ok(cross-domain-state): one atomic dict-REF store per exchange (the torn-read-free design documented above); the worker-domain reachability is the MA-mode aggregate path, and MA worlds run no engine thread
    _exchange_last = {"enter_m": enter_m, "done_m": done_m,
                      "done_w": done_w, "coll_s": coll_s}


def last_exchange_stats() -> dict:
    """Timing of this process's most recent :func:`capped_exchange`:
    ``enter_m``/``done_m`` (perf_counter), ``done_w`` (wall clock at
    collective exit — the rendezvous pulse) and ``coll_s`` (seconds
    blocked inside the collective op(s), excluding local staging)."""
    return _exchange_last


# -- elastic membership groups (round 10, elastic/) ----------------------
# The boot world is jax.distributed's: process_index/process_count are
# frozen at init, and every host-byte exchange above rides gloo
# allgathers over ALL boot processes. An elastic epoch installs a GROUP
# — the subset of boot ranks currently in the world — and the exchange
# layer re-forms around it: singleton groups take the single-process
# identity paths (no collectives at all, which is also what makes a
# survivor's world sound after a peer died mid-allgather: the abandoned
# gloo stream is simply never touched again), and multi-member groups
# ride the coordinator-relayed exchange the elastic plane provides
# (gloo cannot subset the boot world, and after ANY transition the
# boot-world collective stream can no longer be trusted to be aligned).
# process_index()/process_count() deliberately keep their boot meaning
# (device ownership, forensic rank identity); membership-aware code
# asks world_rank()/world_size().

class Group:
    """One membership epoch's view of the world.

    ``members`` are boot ranks, sorted; ``exchange(blob, key)`` is the
    group's allgather-bytes primitive (None = identity / unused for
    singleton groups); ``barrier(name)`` its rendezvous."""

    def __init__(self, epoch: int, members, exchange=None, barrier=None):
        self.epoch = int(epoch)
        self.members = tuple(sorted(int(m) for m in members))
        self._exchange = exchange
        self._barrier = barrier

    @property
    def size(self) -> int:
        return len(self.members)

    def rank(self) -> int:
        """This process's position in the member list, -1 if departed."""
        try:
            return self.members.index(process_index())
        except ValueError:
            return -1

    def _require_member(self, what: str) -> None:
        if self.rank() < 0:
            from multiverso_tpu.failsafe.errors import MembershipChanged
            raise MembershipChanged(
                f"{what} from a departed member", epoch=self.epoch,
                members=self.members, departed=(process_index(),))

    def exchange(self, blob: bytes, key) -> list:
        if self.size <= 1 and self.rank() >= 0:
            return [blob]
        self._require_member("collective exchange")
        CHECK(self._exchange is not None,
              "multi-member elastic group without an exchange transport")
        note_collective()
        return self._exchange(blob, key)

    def barrier(self, name: str) -> None:
        if self.size <= 1 and self.rank() >= 0:
            return
        self._require_member("collective barrier")
        CHECK(self._barrier is not None,
              "multi-member elastic group without a barrier transport")
        note_collective()
        self._barrier(name)


_group: Optional[Group] = None

# -- pluggable host wire (round 12 shm, round 24 tcp) --------------------
#: the installed transport behind capped_exchange (None = gloo). Boot
#: world only: elastic groups (installed above) take precedence, and a
#: membership transition never routes through a wire the dead member
#: still owns segments of.
_wire = None


def active_wire():
    """The installed host wire (ShmWire same-host / TcpWire
    cross-host — round 24), or None when exchanges ride gloo."""
    return _wire


def wire_name() -> str:
    """Label of the transport capped_exchange currently rides —
    dashboards/healthz; 'relay' while an elastic group is installed."""
    if _group is not None and _group.size > 1:
        return "relay"
    if _wire is not None:
        return getattr(_wire, "name", "shm")
    return "gloo" if (_initialized and process_count() > 1) else "local"


def host_label() -> str:
    """This rank's host identity for wire selection and telemetry
    labels: ``-mv_wire_hostname`` when set (the loopback cross-host
    drills fake distinct hosts on one box — selection and labels
    follow the override while dialing rides real endpoints), else the
    real hostname. Registry-safe (flight dumps run at teardown)."""
    import socket
    try:
        v = str(GetFlag("mv_wire_hostname"))
    except Exception:       # registry torn down
        v = ""
    if v:
        return v
    try:
        return socket.gethostname()
    except OSError:
        return "localhost"


def wire_channels() -> int:
    """Independent exchange channels the active transport offers. The
    gloo allgather is ONE globally-ordered collective stream (channel
    0 only); the shm wire offers one stream per channel — what lets
    engine shards exchange concurrently in a multi-process world."""
    return _wire.channels if _wire is not None else 1


def maybe_install_wire(channels: int) -> str:
    """Select + install the host wire for this world (Zoo.Start, after
    jax.distributed is up, BEFORE the engine starts). One gloo
    rendezvous exchanges (host label, nonce) across the boot world:
    same-host worlds ride the shm wire, hosts-differ worlds take the
    tcp wire when more than one channel is needed (``-mv_wire=tcp``
    forces it regardless), gloo is the loud fallback. Either wire is
    proven by a smoke exchange before anything trusts it, and ANY
    setup failure degrades the WHOLE world to gloo symmetrically
    (CHECK-fails only under ``-mv_wire=shm``/``tcp``, where the
    fallback was explicitly refused). Returns the active transport
    name."""
    global _wire
    mode = str(GetFlag("mv_wire")).lower()
    CHECK(mode in ("auto", "shm", "tcp", "gloo"),
          f"-mv_wire must be auto/shm/tcp/gloo, got {mode!r}")
    if not _initialized or process_count() <= 1 or mode == "gloo":
        return wire_name()
    if _wire is not None:
        return getattr(_wire, "name", "shm")
    import secrets
    info = host_allgather_objects(
        (host_label(), secrets.token_hex(4)))
    hosts = [h for h, _ in info]
    token = info[0][1]          # rank 0's nonce names the session
    spans_hosts = any(h != hosts[0] for h in hosts)
    if mode == "tcp" or (spans_hosts and mode == "auto"
                         and max(1, int(channels)) > 1):
        return _install_tcp_wire(mode, token, max(1, int(channels)),
                                 hosts)
    if spans_hosts:
        CHECK(mode != "shm",
              f"-mv_wire=shm but ranks span hosts: {hosts}")
        Log.Debug("multihost: ranks span hosts (%s) and %d channel(s) "
                  "suffice — staying on gloo (-mv_wire=tcp forces the "
                  "tcp wire)", hosts, max(1, int(channels)))
        return "gloo"
    from multiverso_tpu.parallel import shm_wire

    # Every rank runs the IDENTICAL gloo collective sequence below —
    # a local failure becomes an ok=False VOTE instead of a skipped
    # round, because a rank that raises past a matched collective
    # leaves its peers permanently off-by-one on the gloo stream (an
    # asymmetric create failure must degrade the WHOLE world to gloo,
    # not desync it). A failed vote at any step: everyone cleans up
    # and returns gloo; the vote round itself realigned the world.
    # payload_crc=False: every engine blob already carries the
    # failsafe wire's CRC32 trailer (parallel/wire.py, verified before
    # parsing) — a second full-blob CRC pass would halve the wire's
    # bandwidth to guard what is already guarded. The frame headers
    # stay CRC'd and truncation stays structurally detected
    # (shm_wire.py docstring).
    state = {"wire": None, "exc": None}
    try:
        state["wire"] = shm_wire.ShmWire(
            token, process_index(), process_count(),
            max(1, int(channels)), int(GetFlag("mv_shm_ring_bytes")),
            payload_crc=False)
    except Exception as e:
        state["exc"] = e

    def _vote(step: str) -> bool:
        votes = host_allgather_objects(state["exc"] is None)
        if all(votes):
            return True
        if state["wire"] is not None:
            state["wire"].close()
        CHECK(mode != "shm",
              f"-mv_wire=shm but the wire failed to come up at "
              f"{step}: {state['exc']!r} (votes {votes})")
        Log.Error("multihost: shm wire setup failed at %s on rank(s) "
                  "%s (%r here) — falling back to gloo", step,
                  [i for i, v in enumerate(votes) if not v],
                  state["exc"])
        return False

    if not _vote("segment create"):
        return "gloo"
    try:        # segments exist on every rank (the vote proved it)
        state["wire"].attach_peers()
    except Exception as e:
        state["exc"] = e
    if not _vote("peer attach"):
        return "gloo"
    try:
        hello = b"mv-shm-hello-%d" % process_index()
        got = state["wire"].exchange(hello, 0)
        CHECK(got == [b"mv-shm-hello-%d" % r
                      for r in range(process_count())],
              f"shm wire smoke exchange returned {got!r}")
    except Exception as e:
        state["exc"] = e
    if not _vote("smoke exchange"):
        return "gloo"
    _wire = state["wire"]
    Log.Info("multihost: same-host shared-memory wire up — %d channels "
             "x %d MiB (token %s)", _wire.channels, _wire.cap >> 20,
             token)
    return "shm"


def _install_tcp_wire(mode: str, token: str, channels: int,
                      hosts) -> str:
    """The tcp leg of maybe_install_wire: bind listeners, allgather
    (ok, endpoints) in ONE collective round, dial the mesh, vote, and
    smoke-exchange before install. The vote protocol is the shm path's,
    verbatim in shape: every rank runs the IDENTICAL collective
    sequence, so an asymmetric local failure becomes an ok=False vote
    that degrades the WHOLE world to gloo instead of desyncing the
    boot collective stream. payload_crc=False for the same reason as
    shm: engine blobs arrive pre-sealed (parallel/seal.py) and the
    frame layer's own seal still guards headers + chunks."""
    global _wire
    from multiverso_tpu.parallel import tcp_wire
    state = {"wire": None, "exc": None}
    try:
        state["wire"] = tcp_wire.TcpWire(
            token, process_index(), process_count(), channels,
            int(GetFlag("mv_shm_ring_bytes")), payload_crc=False)
    except Exception as e:
        state["exc"] = e

    def _vote(step: str) -> bool:
        votes = host_allgather_objects(state["exc"] is None)
        if all(votes):
            return True
        if state["wire"] is not None:
            state["wire"].close()
        CHECK(mode != "tcp",
              f"-mv_wire=tcp but the wire failed to come up at "
              f"{step}: {state['exc']!r} (votes {votes})")
        Log.Error("multihost: tcp wire setup failed at %s on rank(s) "
                  "%s (%r here) — falling back to gloo", step,
                  [i for i, v in enumerate(votes) if not v],
                  state["exc"])
        return False

    # bind vote + endpoint rendezvous in ONE collective round
    eps = (state["wire"].listen_endpoints()
           if state["wire"] is not None else None)
    votes = host_allgather_objects((state["exc"] is None, eps))
    if not all(ok for ok, _ in votes):
        if state["wire"] is not None:
            state["wire"].close()
        CHECK(mode != "tcp",
              f"-mv_wire=tcp but the wire failed to bind its "
              f"listeners: {state['exc']!r}")
        Log.Error("multihost: tcp wire listener bind failed on "
                  "rank(s) %s (%r here) — falling back to gloo",
                  [i for i, (ok, _) in enumerate(votes) if not ok],
                  state["exc"])
        return "gloo"
    world_eps = {r: e for r, (_, e) in enumerate(votes)}
    try:
        state["wire"].connect(world_eps, timeout_s=30.0)
    except Exception as e:
        state["exc"] = e
    if not _vote("mesh connect"):
        return "gloo"
    try:
        hello = b"mv-tcp-hello-%d" % process_index()
        got = state["wire"].exchange(hello, 0, timeout_s=30.0)
        CHECK(got == [b"mv-tcp-hello-%d" % r
                      for r in range(process_count())],
              f"tcp wire smoke exchange returned {got!r}")
    except Exception as e:
        state["exc"] = e
    if not _vote("smoke exchange"):
        return "gloo"
    _wire = state["wire"]
    Log.Info("multihost: cross-host tcp wire up — %d channels x %d "
             "KiB chunks, hosts %s (token %s)", _wire.channels,
             _wire.chunk >> 10, sorted(set(hosts)), token)
    return "tcp"


def close_wire() -> None:
    """Tear the installed wire down (Zoo.Stop / net_reset). Idempotent;
    own segments are unlinked."""
    global _wire
    w, _wire = _wire, None
    if w is not None:
        w.close()


class wire_bypass:
    """Bench/drill helper: run the body on the RAW gloo collective
    path while a host wire is installed (the A/B the shm/tcp-vs-gloo
    bench rows need). COLLECTIVE discipline applies: every rank must
    enter and exit at the same stream position, or the two transports'
    streams interleave divergently."""

    def __enter__(self):
        global _wire
        self._saved = _wire
        _wire = None
        return self

    def __exit__(self, *exc):
        global _wire
        _wire = self._saved

#: collective isolation (elastic rebuild_world): the host-byte exchange
#: layer answers as a single-member world while a transition fence
#: rebuilds tables — constructors re-run boot-time agreement
#: collectives (e.g. SparseMatrixTable's -num_workers check), but the
#: agreement was already established at boot and the fence has no
#: matched peer round to pair them with. world_rank()/world_size() are
#: NOT isolated: the rebuilt tables must bind the new view's identity.
_isolated = False


class collective_isolation:
    def __enter__(self):
        global _isolated
        self._prev = _isolated
        _isolated = True
        return self

    def __exit__(self, *exc):
        global _isolated
        _isolated = self._prev


#: a boot-world member DIED (silent death, elastic shrink): the
#: jax.distributed runtime's shutdown barrier would block on the dead
#: task and the coordination client then TERMINATES the survivor —
#: net_finalize skips the runtime shutdown instead (the process exit
#: reaps it)
_boot_world_broken = False


def mark_boot_world_broken() -> None:
    global _boot_world_broken
    if not _boot_world_broken:
        _boot_world_broken = True
        Log.Error("multihost: a boot-world member died — the "
                  "jax.distributed runtime will not be shut down "
                  "cleanly (survivors skip its shutdown barrier)")


def install_group(group: Optional[Group]) -> None:
    """Install the membership view every exchange routes through from
    now on (None restores the boot world). Called by the elastic plane
    at an epoch transition — on the engine thread, at the fenced stream
    position, so no exchange is in flight across the swap."""
    global _group
    _group = group
    if group is not None:
        Log.Info("multihost: membership epoch %d installed — members %s "
                 "(this process %s)", group.epoch, list(group.members),
                 "rank %d" % group.rank() if group.rank() >= 0
                 else "DEPARTED")


def current_group() -> Optional[Group]:
    return _group


def membership_epoch() -> int:
    """The installed membership epoch (0 = boot world)."""
    return _group.epoch if _group is not None else 0


def world_size() -> int:
    """Active member count of the CURRENT world (boot process count
    until an elastic epoch is installed)."""
    if _group is not None:
        return _group.size
    return process_count() if _initialized else 1


def world_rank() -> int:
    """This process's rank in the CURRENT world ordering (= boot rank
    until an elastic epoch is installed); -1 when this process has
    departed the world."""
    if _group is not None:
        return _group.rank()
    return process_index() if _initialized else 0

# Explicit-endpoint bring-up state (MV_NetBind / MV_NetConnect): the
# launcher-free deployment path. The reference's ZMQ transport let a
# process declare its own (rank, endpoint) and the full world without MPI
# (zmq_net.h:64-110); the TPU equivalent wires the same two declarations
# into jax.distributed — rank 0's endpoint IS the coordinator.
_net_rank: Optional[int] = None
_net_endpoint: Optional[str] = None
_net_world: Optional[dict] = None  # rank -> endpoint


def net_bind(rank: int, endpoint: str) -> int:
    """Declare THIS process's rank and endpoint (reference
    ZMQNetWrapper::Bind, zmq_net.h:64-81). Must precede MV_Init. For
    rank 0 the endpoint is the coordinator address the whole world
    rendezvouses on (net_connect cross-checks its rank-0 entry against
    it); other ranks' endpoints are identity records, matching the
    reference where every rank binds its own recv socket."""
    global _net_rank, _net_endpoint, _net_world
    if _initialized:
        Log.Error("MV_NetBind after the distributed runtime is up")
        return -1
    try:
        rank, endpoint = int(rank), str(endpoint)
    except (TypeError, ValueError):
        return -1
    if rank < 0 or not endpoint:
        return -1
    _net_rank = rank
    _net_endpoint = endpoint
    # re-binding invalidates a previously declared world: its validation
    # (rank membership, rank-0 endpoint cross-check) was against the old
    # identity — require a fresh MV_NetConnect
    _net_world = None
    return 0


def net_connect(ranks, endpoints) -> int:
    """Declare the full world as parallel (ranks, endpoints) lists
    (reference ZMQNetWrapper::Connect, zmq_net.h:83-110). Requires a prior
    net_bind; this process's bound rank must appear in ``ranks``. The
    next MV_Init brings up jax.distributed from this wiring."""
    global _net_world
    if _initialized:
        Log.Error("MV_NetConnect after the distributed runtime is up")
        return -1
    if _net_rank is None:
        Log.Error("MV_NetConnect before MV_NetBind")
        return -1
    try:
        ranks = [int(r) for r in ranks]
        endpoints = [str(e) for e in endpoints]
    except (TypeError, ValueError):
        return -1  # malformed declarations return -1 like every other error
    if len(ranks) != len(endpoints) or not ranks:
        return -1
    if sorted(ranks) != list(range(len(ranks))):
        # jax.distributed numbers processes 0..n-1; gaps or duplicates
        # would crash or hang the rendezvous later — reject at declaration
        Log.Error("MV_NetConnect ranks must be exactly 0..n-1, got %s",
                  ranks)
        return -1
    world = dict(zip(ranks, endpoints))
    if _net_rank not in world:
        Log.Error("MV_NetConnect world must contain the bound rank")
        return -1
    if _net_rank == 0 and world[0] != _net_endpoint:
        # rank 0's bind endpoint IS the coordinator it will listen on; a
        # mismatching connect entry would make the world rendezvous on an
        # address nothing binds
        Log.Error("rank 0 bind endpoint %s != connect entry %s",
                  _net_endpoint, world[0])
        return -1
    _net_world = world
    return 0


def net_reset() -> None:
    """Forget explicit wiring (tests / MV_ShutDown symmetry). Also
    clears the standing exchange caps: a NEW world may mix reused
    interpreters (evolved caps) with fresh ranks (defaults), and
    mismatched caps mean mismatched allgather buffer shapes — caps must
    restart from defaults on every world, like the engine's per-instance
    _mh_caps do. Also forgets any installed elastic membership group —
    a new world starts at epoch 0 (boot membership)."""
    global _net_rank, _net_endpoint, _net_world, _group
    _net_rank = _net_endpoint = _net_world = None
    _group = None
    _OBJ_CAPS.clear()
    close_wire()    # a new world re-selects (and re-tokens) its wire


def net_finalize() -> None:
    """MV_NetFinalize: forget declarations AND shut down jax.distributed
    when THIS runtime initialized it (reference finalizes its transport,
    src/multiverso.cpp:66-68). A runtime the user brought up themselves
    (maybe_initialize merely adopted it) is left alone — finalizing it
    would kill their coordinator under them. Safe to call repeatedly; a
    shutdown failure (e.g. live computations) logs and leaves the
    runtime up."""
    global _initialized, _owns_runtime
    net_reset()
    if not _initialized or not _owns_runtime:
        return
    if _boot_world_broken:
        # a dead boot member can never reach the runtime's shutdown
        # barrier; entering it would hang this survivor and then
        # TERMINATE it (coordination client fatal-error path). Leave
        # the runtime to process exit.
        Log.Info("net_finalize: boot world broken — skipping "
                 "jax.distributed.shutdown()")
        _initialized = False
        _owns_runtime = False
        return
    import jax
    try:
        jax.distributed.shutdown()
        _initialized = False
        _owns_runtime = False
    except Exception as exc:  # pragma: no cover - runtime-state specific
        Log.Error("net_finalize: jax.distributed.shutdown failed: %r", exc)


def _split_endpoint(ep: str):
    """host[:port] -> (host, port_or_None); IPv6 uses [addr]:port."""
    if ep.startswith("["):
        host, _, rest = ep[1:].partition("]")
        return host, (rest[1:] if rest.startswith(":") else None)
    host, sep, port = ep.rpartition(":")
    if sep and port.isdigit() and ":" not in host:
        return host, port
    return ep, None  # no port (or a bare IPv6 literal)


def _parse_machine_file(path: str) -> list:
    """Hosts file -> rank-ordered endpoint list (reference
    ParseMachineFile, zmq_net.h:236-258): one host[:port] per line
    (IPv6 as [addr]:port), blanks/comments skipped, the ``-port`` flag
    filling missing ports. Missing/empty files are loud errors — a
    misconfigured cluster must never silently run single-process."""
    default_port = int(GetFlag("port"))
    CHECK(os.path.exists(path), f"-machine_file not found: {path!r}")
    endpoints = []
    with open(path) as f:
        for line in f:
            ep = line.strip()
            if not ep or ep.startswith("#"):
                continue
            host, port = _split_endpoint(ep)
            if port is None:
                port = default_port
            endpoints.append(f"[{host}]:{port}" if ":" in host
                             else f"{host}:{port}")
    CHECK(endpoints, f"-machine_file {path!r} lists no endpoints")
    return endpoints


def _match_local_rank(endpoints: list):
    """This host's rank = the unique machine-file line resolving to a
    local address (reference net_util local-IP matching). None when no
    line — or more than one — matches (same-host multi-process needs an
    explicit -dist_rank, exactly as ambiguous for the reference)."""
    import socket
    local = {"127.0.0.1", "::1"}
    try:
        local.update(info[4][0] for info in socket.getaddrinfo(
            socket.gethostname(), None))
    except OSError:
        pass
    matches = []
    for i, ep in enumerate(endpoints):
        host = _split_endpoint(ep)[0]
        try:
            addrs = {info[4][0]
                     for info in socket.getaddrinfo(host, None)}
        except OSError:
            continue
        if addrs & local or host == socket.gethostname():
            matches.append(i)
    return matches[0] if len(matches) == 1 else None


def _env_says_multiprocess() -> bool:
    """TPU-pod/cluster env autodetection (mirrors what
    jax.distributed.initialize() itself can infer)."""
    if (os.environ.get("JAX_COORDINATOR_ADDRESS")
            or os.environ.get("COORDINATOR_ADDRESS")
            or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")):
        return True
    # Cloud TPU multi-host slices advertise their worker set directly
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return len([h for h in hosts.split(",") if h.strip()]) > 1


def _enable_cpu_collectives() -> None:
    """Opt the CPU backend into cross-process collectives (gloo) before
    the backend exists. jax's default CPU collectives implementation is
    ``'none'``, under which EVERY multi-process computation — including
    the ``device_put`` equality check inside table creation — fails with
    "Multiprocess computations aren't implemented on the CPU backend";
    a 2-process CPU world (tests, single-host bring-up, the bench's
    subprocess children) therefore needs gloo. Only applies when the job
    explicitly targets CPU (``jax_platforms``/``JAX_PLATFORMS``): TPU
    pods keep their platform default. Best-effort — a jax/jaxlib without
    the knob (or without gloo) just keeps its default behavior."""
    import jax
    try:
        plats = str(jax.config.jax_platforms
                    or os.environ.get("JAX_PLATFORMS", ""))
    except AttributeError:  # pragma: no cover - very old jax
        plats = os.environ.get("JAX_PLATFORMS", "")
    if "cpu" not in plats.lower().split(","):
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception as exc:  # pragma: no cover - jaxlib without gloo
        Log.Debug("multihost: CPU gloo collectives unavailable (%r)", exc)


def pjrt_heartbeat_kwargs() -> dict:
    """The coordination-service heartbeat kwargs MV_Init plumbs into
    ``jax.distributed.initialize`` (ROADMAP elastic follow-on 4): the
    ``-mv_pjrt_heartbeat_s`` liveness budget split into an interval and
    a missed-heartbeat count, for BOTH the service and client sides.
    Empty when the budget is 0 (runtime defaults); an -mv_elastic world
    with the flag unset defaults to 600s — a long-lived shrunk world
    must outlive the runtime's ~100s corpse detection."""
    try:
        secs = int(GetFlag("mv_pjrt_heartbeat_s"))
    except Exception:
        secs = 0
    if secs <= 0:
        try:
            if bool(GetFlag("mv_elastic")):
                secs = 600
        except Exception:
            pass
    if secs <= 0:
        return {}
    interval = max(10, secs // 10)
    missing = max(2, -(-secs // interval))
    return {"service_heartbeat_interval_seconds": interval,
            "service_max_missing_heartbeats": missing,
            "client_heartbeat_interval_seconds": interval,
            "client_max_missing_heartbeats": missing}


def _supported_heartbeat_kwargs(params) -> dict:
    """The subset of :func:`pjrt_heartbeat_kwargs` this jax's
    state-level initializer actually accepts (param-name filtered, so
    a jax that renamed or dropped the knobs degrades to {})."""
    return {k: v for k, v in pjrt_heartbeat_kwargs().items()
            if k in params}


def _dist_initialize(**kw) -> None:
    """``jax.distributed.initialize`` with the heartbeat budget plumbed
    through when this jax exposes the knobs (the public wrapper hides
    them; the state-level initializer the wrapper delegates to takes
    them). Any plumbing surprise falls back to the plain public call —
    heartbeat tuning must never break bring-up."""
    import jax
    hb = pjrt_heartbeat_kwargs()
    if hb:
        try:
            import inspect

            from jax._src import distributed as _jdist
            from jax._src import xla_bridge as _xb
            supported = _supported_heartbeat_kwargs(
                inspect.signature(_jdist.State.initialize).parameters)
            if supported and not _xb.backends_are_initialized():
                _jdist.global_state.initialize(**kw, **supported)
                Log.Info("multihost: PJRT coordination-service "
                         "heartbeats raised (%s)",
                         ", ".join(f"{k}={v}"
                                   for k, v in sorted(supported.items())))
                return
            if not supported:
                Log.Info("multihost: this jax exposes no heartbeat "
                         "knobs — -mv_pjrt_heartbeat_s ignored, "
                         "runtime defaults kept")
        except Exception as exc:
            Log.Error("multihost: PJRT heartbeat plumbing failed (%r) "
                      "— plain initialize", exc)
    jax.distributed.initialize(**kw)


def maybe_initialize() -> bool:
    """Initialize jax.distributed per flags/env. Returns True when a
    multi-process runtime is (already or newly) up. Idempotent.

    Must run before anything initializes the XLA backend —
    ``jax.distributed.initialize()`` refuses once backends exist, so this
    function deliberately avoids jax calls (process_count etc.) on the
    decide-to-init path."""
    global _initialized, _owns_runtime
    mode = str(GetFlag("multihost")).lower()
    if mode == "off":
        return False
    coordinator = str(GetFlag("dist_coordinator"))
    rank = int(GetFlag("dist_rank"))
    size = int(GetFlag("dist_size"))
    explicit = bool(coordinator) and rank >= 0 and size > 0
    if not explicit and _net_world is not None:
        # MV_NetBind/MV_NetConnect wiring: rank 0's endpoint coordinates
        coordinator, rank, size = (_net_world[0], _net_rank,
                                   len(_net_world))
        explicit = True
    if _initialized:
        return True
    if not explicit and str(GetFlag("machine_file")):
        # reference ZMQ deployment: line N of the hosts file is rank N
        # (zmq_net.h ParseMachineFile); rank comes from -dist_rank or by
        # matching this host's addresses like the reference's net_util
        endpoints = _parse_machine_file(str(GetFlag("machine_file")))
        if endpoints:
            mf_rank = rank if rank >= 0 else _match_local_rank(endpoints)
            CHECK(mf_rank is not None and 0 <= mf_rank < len(endpoints),
                  f"-machine_file: cannot infer this process's rank (give "
                  f"-dist_rank); endpoints={endpoints}")
            coordinator, rank, size = endpoints[0], mf_rank, len(endpoints)
            explicit = True
    if not explicit and mode != "on" and not _env_says_multiprocess():
        return False
    if _initialized:
        return True
    import jax
    try:
        _enable_cpu_collectives()
        if explicit:
            _dist_initialize(coordinator_address=coordinator,
                             num_processes=size, process_id=rank)
        else:
            _dist_initialize()
        _initialized = True
        _owns_runtime = True
        Log.Info("multihost: jax.distributed up — process %d of %d",
                 jax.process_index(), jax.process_count())
        return True
    except Exception as exc:  # pragma: no cover - env-specific
        # "already initialized" / "must be called before any JAX
        # computations": a runtime may already be up (user or launcher
        # initialized first) — honor it when it is actually multi-process
        text = str(exc).lower()
        if "already" in text or "before" in text:
            if jax.process_count() > 1:
                _initialized = True
                return True
        CHECK(mode != "on" and not explicit,
              f"multihost requested but jax.distributed failed: {exc}")
        Log.Debug("multihost: auto-init skipped (%s)", exc)
        return False


def process_index() -> int:
    import jax
    return jax.process_index()


def process_count() -> int:
    import jax
    return jax.process_count()


def host_barrier(name: str = "mv_barrier") -> None:
    """Block until every member of the CURRENT world reaches this point
    (no-op single-member). Collective: every member must call it
    (reference controller barrier, controller.cpp:12-36)."""
    if _isolated:
        return
    if _group is not None:
        _group.barrier(name)
        return
    if process_count() <= 1:
        return
    from jax.experimental import multihost_utils
    note_collective()
    multihost_utils.sync_global_devices(name)


def host_allreduce_sum(data: np.ndarray) -> np.ndarray:
    """Elementwise sum of ``data`` across the current world's members
    (identity single-member). Collective."""
    if _isolated:
        return data
    if _group is not None:
        if _group.size <= 1:
            return data
        parts = host_allgather_objects(np.asarray(data))
        return np.sum(parts, axis=0).astype(data.dtype)
    if process_count() <= 1:
        return data
    from jax.experimental import multihost_utils
    note_collective()
    gathered = multihost_utils.process_allgather(data)  # (procs, *shape)
    return np.asarray(gathered).sum(axis=0).astype(data.dtype)


def host_allgather_bytes(data: bytes) -> list:
    """Every member's byte blob, ordered by world rank (collective;
    single-member: ``[data]``). Blobs may differ in length — lengths are
    exchanged first, then payloads ride one fixed-shape allgather padded
    to the global max (elastic groups ride the group transport in one
    keyed round instead)."""
    if _isolated:
        return [data]
    if _group is not None:
        return _group.exchange(data, "HAB")
    if process_count() <= 1:
        return [data]
    from jax.experimental import multihost_utils
    note_collective(2)   # length round + payload round
    lens = np.asarray(multihost_utils.process_allgather(
        np.array([len(data)], np.int64))).reshape(-1)
    cap = int(lens.max())
    if cap == 0:
        return [b""] * process_count()
    # quantize the padded capacity to the quarter-octave ladder
    # (mesh.next_bucket): process_allgather compiles per SHAPE, so
    # exact-max caps mint a fresh XLA program for every distinct payload
    # size; the ladder bounds the program set to ~4*log2(sizes) while
    # capping pad waste at ~25% — on the windowed engine's exchange the
    # padded bytes ARE the wire cost, and pow2 wasted up to 2x
    from multiverso_tpu.parallel.mesh import next_bucket
    cap = next_bucket(cap, min_bucket=1024)
    buf = np.zeros(cap, np.uint8)
    if data:
        buf[:len(data)] = np.frombuffer(data, np.uint8)
    gathered = np.asarray(
        multihost_utils.process_allgather(buf)).reshape(process_count(), cap)
    return [gathered[i, :int(lens[i])].tobytes()
            for i in range(process_count())]


def capped_exchange(blob: bytes, caps: dict, key, channel: int = 0) -> list:
    """Every process's byte blob in ONE collective round (steady state).

    The 2-round shape of host_allgather_bytes (lengths first, then the
    padded payload) pays two collective latencies per exchange — the
    dominant cost of small windows on the engine's windowed protocol.
    Here each exchange rides a STANDING per-``key`` capacity all ranks
    remember identically (``caps`` evolves only from exchanged data):
    blobs that fit inline in the cap'd buffer (1-byte fit flag + 8-byte
    little-endian length header — explicit ``'<i8'``, so heterogeneous-
    endianness worlds can't misread each other's lengths) complete in
    one round; if ANY rank overflowed, every rank runs one more round
    at the ladder cap of the now-known max length. After either path
    the standing cap snaps to the ladder rung of this exchange's max
    need, so per-key steady workloads (an engine window headed by the
    same verb) stay on the 1-round path. Collective; single-process
    returns ``[blob]``. In an elastic epoch the exchange rides the
    group transport instead (the gloo boot-world allgather cannot
    subset the world); ``caps`` are not consulted there — the relay is
    length-framed by construction.

    ``channel`` (round 12) selects an INDEPENDENT exchange stream on a
    transport that offers them (the shm wire: one per engine shard).
    The gloo path is one globally-ordered collective stream — callers
    must stay on channel 0 there (the engine clamps its shard count to
    the transport's channel count for exactly this reason)."""
    if _isolated:
        return [blob]
    if _group is not None:
        # the elastic group relay IS the collective: its whole wall is
        # blocked-in-collective time for the phase split
        _t0 = _time.perf_counter()
        out = _group.exchange(blob, key)
        _done = _time.perf_counter()
        _stamp_exchange(_t0, _done - _t0, _done, _time.time())
        return out
    if process_count() <= 1:
        return [blob]
    if _wire is not None:
        # installed wire (shm same-host / tcp cross-host): length-
        # framed by construction (caps unused); the whole call is the
        # collective for the phase split
        note_collective()
        _t0 = _time.perf_counter()
        out = _wire.exchange(blob, channel)
        _done_m, _done_w = _time.perf_counter(), _time.time()
        _stamp_exchange(_t0, _done_m - _t0, _done_m, _done_w)
        STATS["exchange_seconds"] += _done_m - _t0
        return out
    CHECK(channel == 0,
          "gloo host wire has ONE collective stream — channel "
          f"{channel} needs a multi-channel wire (-mv_wire=shm/tcp)")
    from jax.experimental import multihost_utils

    from multiverso_tpu.parallel.mesh import next_bucket
    _t0 = _time.perf_counter()   # after imports: first-call module-import
    need = len(blob) + 9         # cost must not be charged as exchange
    cap = caps.get(key, 4096)
    buf = np.zeros(cap, np.uint8)
    buf[0] = 1 if need <= cap else 0
    buf[1:9] = np.array([len(blob)], "<i8").view(np.uint8)
    if need <= cap and blob:
        buf[9:9 + len(blob)] = np.frombuffer(blob, np.uint8)
    note_collective()
    _tc = _time.perf_counter()
    gathered = np.asarray(
        multihost_utils.process_allgather(buf)).reshape(process_count(),
                                                        cap)
    _done_m, _done_w = _time.perf_counter(), _time.time()
    coll_s = _done_m - _tc
    lens = [int(np.frombuffer(gathered[i, 1:9].tobytes(), "<i8")[0])
            for i in range(process_count())]
    fits = [bool(gathered[i, 0]) for i in range(process_count())]
    caps[key] = next_bucket(max(lens) + 9, min_bucket=4096)
    if all(fits):
        _stamp_exchange(_t0, coll_s, _done_m, _done_w)
        STATS["exchange_seconds"] += _time.perf_counter() - _t0
        return [gathered[i, 9:9 + lens[i]].tobytes()
                for i in range(process_count())]
    # overflow: one more round at the (now agreed) ladder cap
    big = caps[key]
    buf2 = np.zeros(big, np.uint8)
    if blob:
        buf2[: len(blob)] = np.frombuffer(blob, np.uint8)
    note_collective()
    _tc = _time.perf_counter()
    gathered2 = np.asarray(
        multihost_utils.process_allgather(buf2)).reshape(process_count(),
                                                         big)
    _done_m, _done_w = _time.perf_counter(), _time.time()
    coll_s += _done_m - _tc
    _stamp_exchange(_t0, coll_s, _done_m, _done_w)
    STATS["exchange_seconds"] += _time.perf_counter() - _t0
    return [gathered2[i, : lens[i]].tobytes()
            for i in range(process_count())]


#: standing caps for host_allgather_objects(key=...) — lockstep callers
#: that tag their exchange get the capped 1-round path (caps evolve
#: identically everywhere because every tagged call site is collective)
_OBJ_CAPS: dict = {}


def host_allgather_objects_capped(obj, key) -> list:
    """host_allgather_objects through the standing-cap 1-round exchange
    (capped_exchange). ``key`` must be a value every rank passes
    identically at this lockstep call site — e.g. a call-site label —
    or buffer shapes diverge and the world hangs. Use for small,
    latency-sensitive agreements (the device planes' bucket rounds)."""
    if world_size() <= 1:
        return [obj]
    import pickle
    return [pickle.loads(b) for b in
            capped_exchange(pickle.dumps(obj), _OBJ_CAPS, key)]


def host_allgather_objects(obj) -> list:
    """Every member's picklable object, ordered by world rank
    (collective; single-member: ``[obj]``). Used by the table layer to
    merge per-process host-plane payloads — e.g. each process's row-id/delta
    batch of one logical Add — so reference PS semantics (every worker's
    Add accumulates, whichever process it ran on) hold across hosts."""
    if world_size() <= 1:
        return [obj]
    import pickle
    blobs = host_allgather_bytes(pickle.dumps(obj))
    return [pickle.loads(b) for b in blobs]


def merge_collective_add(option, *arrays, with_parts: bool = False):
    """Merge every process's payload of one collective row/key Add:
    allgathers ``(arrays..., option)``, CHECKs the option agrees on every
    process (divergent option scalars — worker_id, lr, momentum — would
    feed different jit'd updates into the same globally-sharded state and
    silently corrupt it), and returns per-position concatenations in
    process order. Identity single-process.

    ``with_parts``: also return the per-rank first arrays (the id sets),
    in rank order — SparseMatrixTable derives its per-keeper freshness
    transitions from them without a second host collective."""
    if world_size() <= 1:
        return (arrays, [arrays[0]]) if with_parts else arrays
    parts = host_allgather_objects((arrays, option))
    opts = [p[1] for p in parts]
    CHECK(all(o == opts[0] for o in opts),
          f"collective Add options diverge across processes: {opts}")
    merged = tuple(np.concatenate([p[0][i] for p in parts])
                   for i in range(len(arrays)))
    if with_parts:
        return merged, [p[0][0] for p in parts]
    return merged


def sum_collective_add(option, values: np.ndarray,
                       with_parts: bool = False):
    """Sum every process's delta of one collective whole-table Add (same
    option agreement CHECK as merge_collective_add). Identity
    single-process. ``with_parts``: also return the per-rank id sets —
    ``None`` per rank (a whole-table push)."""
    if world_size() <= 1:
        return (values, [None]) if with_parts else values
    parts = host_allgather_objects((values, option))
    opts = [p[1] for p in parts]
    CHECK(all(o == opts[0] for o in opts),
          f"collective Add options diverge across processes: {opts}")
    summed = np.sum([p[0] for p in parts], axis=0).astype(values.dtype)
    if with_parts:
        return summed, [None] * len(parts)
    return summed


def union_collective_ids(ids: np.ndarray) -> Optional[np.ndarray]:
    """Sorted union of every process's id/key set of one collective Get —
    the one identical set all processes gather so their device programs
    match. None single-process (caller keeps its local fast path)."""
    if world_size() <= 1:
        return None
    return np.unique(np.concatenate(host_allgather_objects(ids)))


def broadcast_from_master(data: np.ndarray) -> np.ndarray:
    """The world's lowest-rank member's value to everyone (identity
    single-member). Collective."""
    if _isolated:
        return data
    if _group is not None:
        if _group.size <= 1:
            return data
        return host_allgather_objects(np.asarray(data))[0]
    if process_count() <= 1:
        return data
    from jax.experimental import multihost_utils
    note_collective()
    return np.asarray(multihost_utils.broadcast_one_to_all(data))
