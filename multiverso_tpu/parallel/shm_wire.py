"""Same-host shared-memory wire for the windowed engine's exchange.

The reference treats transports as pluggable — its allreduce engine
picks between a ZMQ socket wire and MPI collectives per deployment
(PAPER.md L2, allreduce_engine.cpp). The TPU build's equivalent split:
``multihost.capped_exchange`` is the engine's one host-byte collective,
and gloo (a socket allgather) is its only implementation — measured at
~410 MB/s between two processes of the SAME machine (bench
``matrix_table_2proc_host_exchange_MB_s``), i.e. the window wire pays
socket-stack prices for what is physically a memcpy. This module is
the same-host transport: every rank owns one POSIX shared-memory
segment per (channel, rank) and an exchange round is N-1 memcpys in,
N-1 memcpys out.

Protocol (per channel — channels are INDEPENDENT exchange streams, one
per engine shard, so sharded engines exchange concurrently without
sharing a collective order):

* A segment is ``header | consumed[nprocs] | data[cap]``. The writer
  (the owning rank) publishes frames as one or more chunks of at most
  ``cap`` bytes; the header carries ``(seq, round, total, chunk_off,
  chunk_len, crc32)`` and is finalized by the ``seq`` store — readers
  accept a chunk only once ``seq`` reaches the value they expect, so a
  torn frame is never consumed (x86-TSO store order; the CRC trailer
  is the backstop).
* ``seq`` counts chunks monotonically per segment; ``round`` counts
  exchanges per channel. Both sides advance them in lockstep (the
  exchange IS collective), so a rank re-entering an exchange alone
  surfaces as a loud ``round`` mismatch (WireCorruption) instead of
  silently pairing different windows — the same SEQ-stamp posture as
  the engine's window blobs.
* Flow control: ``consumed[j]`` (written by reader j into the writer's
  segment) is the last chunk seq rank j fully consumed. The writer
  overwrites the single data area only after every reader consumed the
  previous chunk. Readers and the writer interleave inside one
  exchange call (everybody writes chunk 0 first, then drains peers
  while draining their own backpressure), so multi-chunk frames cannot
  deadlock.
* ``crc32`` covers the WHOLE blob and is verified after reassembly —
  a mismatch (or a ``total`` that the chunks never reach — truncation)
  raises ``WireCorruption``, counted in ``shm_wire.crc_failures``.

Waits honour ``-mv_deadline_s`` (``failsafe.deadline.timeout_or_none``)
directly — a dead peer raises ``DeadlineExceeded`` from the spin
itself, so an abandoned exchange never leaves a hot-spinning thread
behind. With the flag unset the wait blocks exactly like the gloo
collective would, backing off to short sleeps.

Selection lives in ``multihost.maybe_install_wire``: ``-mv_wire=auto``
installs this wire when every rank of the boot world reports the same
hostname (one gloo rendezvous exchanges hostnames + rank 0's session
token), verified by a smoke exchange; any setup failure falls back to
gloo loudly. Elastic epochs (> 0) ride the coordinator relay as
before — the group transport takes precedence over this wire.
"""

from __future__ import annotations

import time
from multiprocessing import shared_memory
from typing import Dict, List, Optional

import numpy as np

from multiverso_tpu.failsafe import deadline as fdeadline
from multiverso_tpu.failsafe.errors import WireCorruption
# checksums ride the seal module's fast_crc (round 19): hardware CRC32C
# when the native engine is loadable, zlib.crc32 otherwise — legal for
# this wire because both ends of an shm ring are the same build on the
# same host, so they always pick the same engine
from multiverso_tpu.parallel.seal import fast_crc
from multiverso_tpu.telemetry import metrics as tmetrics
from multiverso_tpu.utils.log import CHECK, Log

#: header field offsets (little-endian u64 unless noted)
_OFF_SEQ = 0          # chunks written to this segment, monotonic
_OFF_ROUND = 8        # exchange round of the current frame
_OFF_TOTAL = 16       # whole-blob byte length of the current frame
_OFF_CHUNK_OFF = 24   # byte offset of the current chunk within the blob
_OFF_CHUNK_LEN = 32   # byte length of the current chunk
_OFF_CRC = 40         # u32: crc32 of the WHOLE blob (payload_crc mode)
_OFF_MAGIC = 44       # u32: segment layout magic
_OFF_HCRC = 48        # u32: crc32 of the frame header fields + seq
_HDR = 64

_MAGIC = 0x4D56_5348  # "MVSH"

#: hot spins before the waiter starts sleeping (an exchange peer is
#: usually microseconds away; sleeping immediately would add ~50us of
#: scheduler latency per chunk)
_HOT_SPINS = 400
_SLEEP_S = 50e-6


#: how often a stalled exchange consults the elastic membership lease
#: (see _peer_loss_probe); ~4x per second keeps the detection latency
#: far under any -mv_deadline_s worth arming
_PROBE_PERIOD_S = 0.25


def _peer_loss_probe(what: str):
    """A stalled exchange asks the elastic authority whether a peer is
    DEAD (lease expired) — a socket transport gets this for free (the
    dead peer's connection resets and the collective errors out fast),
    but shared memory has no connection to break: without the probe a
    silent death costs the FULL collective deadline before the engine
    can convert it, and the worker's own verb deadline wins that race.
    Returns the typed MembershipChanged to raise, or None (no elastic
    plane / every lease fresh / probe failed — keep waiting)."""
    try:
        from multiverso_tpu import elastic
        if not elastic.enabled():
            return None
        return elastic.peer_loss(what)
    except Exception:       # the deadline still bounds the wait
        return None


def _header_crc(seq: int, rnd: int, total: int, off: int, ln: int,
                crc: int) -> int:
    """CRC over the frame header's logical fields INCLUDING the seq
    value the chunk publishes under — always verified (a torn header
    mis-sizes the copy), and cheap: ~50 bytes per chunk."""
    return fast_crc(b"%d|%d|%d|%d|%d|%d"
                    % (seq, rnd, total, off, ln, crc)) & 0xFFFFFFFF


def segment_name(token: str, channel: int, rank: int) -> str:
    """POSIX shm name of (channel, rank)'s segment — short (the POSIX
    limit is system-dependent) and unique per world via ``token``."""
    return f"mv{token}c{channel}r{rank}"


def _attach(name: str) -> shared_memory.SharedMemory:
    """Attach an existing segment WITHOUT handing its lifetime to this
    process's resource tracker (py<3.13 registers attachments too and
    would unlink the owner's segment at our exit)."""
    try:
        return shared_memory.SharedMemory(name=name, track=False)
    except TypeError:       # Python < 3.13: no track parameter
        # suppress registration for the attach (unregistering AFTER
        # would also drop the creator's entry when both ends live in
        # one process — e.g. the in-process fault drills)
        from multiprocessing import resource_tracker
        orig = resource_tracker.register

        def _no_shm_register(name_, rtype):
            if rtype != "shared_memory":
                orig(name_, rtype)

        resource_tracker.register = _no_shm_register
        try:
            return shared_memory.SharedMemory(name=name)
        finally:
            resource_tracker.register = orig


class _Segment:
    """One (channel, rank) segment and its numpy field views."""

    def __init__(self, shm: shared_memory.SharedMemory, nprocs: int,
                 cap: int, owned: bool):
        self.shm = shm
        self.owned = owned
        self.cap = cap
        buf = shm.buf
        self.u64 = np.frombuffer(buf, np.uint64, count=_HDR // 8)
        self.u32 = np.frombuffer(buf, np.uint32, count=_HDR // 4)
        self.consumed = np.frombuffer(buf, np.uint64, count=nprocs,
                                      offset=_HDR)
        self.data = np.frombuffer(buf, np.uint8,
                                  count=cap, offset=_HDR + 8 * nprocs)

    def seq(self) -> int:
        return int(self.u64[_OFF_SEQ // 8])

    def close(self) -> None:
        # release the numpy views FIRST: SharedMemory.close() refuses
        # while exported memoryviews are alive
        self.u64 = self.u32 = self.consumed = self.data = None
        try:
            self.shm.close()
        except Exception:
            pass
        if self.owned:
            try:
                self.shm.unlink()
            except Exception:   # already unlinked (double close)
                pass


class ShmWire:
    """Same-host allgather-bytes transport over shared memory.

    One instance per process per world; ``exchange(blob, channel)`` is
    collective per channel — every rank of the world must call it for
    the same channel in the same per-channel order (the engine's SPMD
    window contract already guarantees exactly that, per shard)."""

    #: transport label (multihost.wire_name reads this off the
    #: installed instance)
    name = "shm"

    def __init__(self, token: str, rank: int, nprocs: int,
                 channels: int, data_bytes: int,
                 payload_crc: bool = True):
        CHECK(nprocs >= 2, "ShmWire needs a multi-process world")
        CHECK(channels >= 1, "ShmWire needs at least one channel")
        #: whole-blob CRC per frame. The engine install turns this
        #: OFF: every engine window/head-marker blob already carries
        #: the failsafe wire's seal trailer (parallel/seal.py,
        #: verified BEFORE parsing), and a second full-blob pass costs
        #: real bandwidth — zlib.crc32 MEASURED at ~0.8 GB/s on this
        #: host class (PR 9 bench; slower than the memcpy it would
        #: guard). Round 19: the pass now rides seal.fast_crc
        #: (hardware CRC32C, ~8x zlib here), so payload_crc=True is
        #: merely cheap rather than bandwidth-halving — the engine
        #: still skips it because the blobs arrive pre-sealed. The
        #: frame HEADER is always CRC'd (cheap), and truncation stays
        #: structurally detected via the total/chunk accounting
        #: either way.
        self.payload_crc = bool(payload_crc)
        self.token = token
        self.rank = rank
        self.nprocs = nprocs
        self.channels = channels
        self.cap = max(int(data_bytes), 4096)
        self._size = _HDR + 8 * nprocs + self.cap
        #: own (writer) segments, one per channel — created HERE;
        #: peers attach after the world's creation barrier
        self._own: Dict[int, _Segment] = {}
        #: attached peer segments: (channel, rank) -> _Segment
        self._peer: Dict[tuple, _Segment] = {}
        #: per-channel exchange round + per-segment chunk-seq cursors
        self._round = [0] * channels
        self._wseq = [0] * channels
        self._rseq: Dict[tuple, int] = {}
        self._closed = False
        self._t_crc = tmetrics.counter("shm_wire.crc_failures")
        self._t_rounds = tmetrics.counter("shm_wire.exchanges")
        self._t_bytes = tmetrics.counter("shm_wire.bytes_out")
        # round 13 — saturation telemetry (watchdog plane): seconds this
        # rank's WRITER spent stalled with chunks still to publish (its
        # readers lag — backpressure on the ring, distinct from the
        # reader-side wait for a slow peer's frame, which critpath
        # attributes to the peer), and the largest frame ever published
        # (ring occupancy high-watermark vs -mv_shm_ring_bytes)
        self._t_wstall = tmetrics.counter("shm_wire.writer_stall_s")
        self._t_hw = tmetrics.gauge("shm_wire.frame_hw_bytes")
        self._t_occ = tmetrics.gauge("shm_wire.ring_occupancy_pct")
        self.writer_stall_s = 0.0
        self.frame_hw_bytes = 0
        for ch in range(channels):
            shm = shared_memory.SharedMemory(
                name=segment_name(token, ch, rank), create=True,
                size=self._size)
            shm.buf[:_HDR + 8 * nprocs] = bytes(_HDR + 8 * nprocs)
            seg = _Segment(shm, nprocs, self.cap, owned=True)
            seg.u32[_OFF_MAGIC // 4] = _MAGIC
            self._own[ch] = seg

    # -- wiring --------------------------------------------------------------

    def attach_peers(self) -> None:
        """Attach every peer's segments (call after a world barrier
        that proves creation completed on every rank)."""
        for ch in range(self.channels):
            for r in range(self.nprocs):
                if r == self.rank:
                    continue
                seg = _Segment(_attach(segment_name(self.token, ch, r)),
                               self.nprocs, self.cap, owned=False)
                CHECK(int(seg.u32[_OFF_MAGIC // 4]) == _MAGIC,
                      f"shm wire segment {segment_name(self.token, ch, r)} "
                      f"has a foreign layout")
                self._peer[(ch, r)] = seg
                self._rseq[(ch, r)] = 0

    def close(self) -> None:
        """Detach everything; unlink own segments. Idempotent."""
        if self._closed:
            return
        self._closed = True
        for seg in self._peer.values():
            seg.close()
        for seg in self._own.values():
            seg.close()
        self._peer.clear()
        self._own.clear()

    # -- the exchange --------------------------------------------------------

    def _chunks(self, blob: bytes) -> List[tuple]:
        """(offset, length) chunk plan — at least one chunk, so empty
        frames still publish a header readers can consume."""
        if not blob:
            return [(0, 0)]
        return [(off, min(self.cap, len(blob) - off))
                for off in range(0, len(blob), self.cap)]

    def exchange(self, blob: bytes, channel: int,
                 timeout_s: Optional[float] = None) -> List[bytes]:
        """Every rank's blob for this channel's next round, rank order.
        Collective per channel; bounded by ``-mv_deadline_s``, or by
        ``timeout_s`` when given (the replica fan-out thread passes its
        lease-derived bound explicitly — a dead reader must cost one
        bounded wait, whatever the engine's deadline flag says). NOTE a
        timed-out exchange leaves the channel's round counter advanced:
        the caller must scrap the wire, never retry the round."""
        CHECK(not self._closed, "shm wire used after close")
        CHECK(0 <= channel < self.channels,
              f"shm wire channel {channel} out of range "
              f"(wire has {self.channels})")
        rnd = self._round[channel]
        self._round[channel] += 1
        own = self._own[channel]
        if len(blob) > self.frame_hw_bytes:
            # high-watermark only (one compare per exchange): the gauge
            # answers "how close do frames come to the ring cap" —
            # multi-chunk frames (> cap) serialize through the single
            # data area and are exactly what the writer-stall measures
            self.frame_hw_bytes = len(blob)
            self._t_hw.set(float(len(blob)))
            self._t_occ.set(min(100.0, 100.0 * len(blob) / self.cap))
        crc = (fast_crc(blob) & 0xFFFFFFFF) if self.payload_crc else 0
        plan = self._chunks(blob)
        blob_view = memoryview(blob)
        peers = [r for r in range(self.nprocs) if r != self.rank]
        # reader state per peer: [assembled bytearray|None, total|None,
        # chunks_read, done, crc(latched), crc(running)]
        rstate = {r: [None, None, 0, False, 0, 0] for r in peers}
        wseq0 = self._wseq[channel]
        wi = 0                        # next own chunk to write
        deadline = (timeout_s if timeout_s is not None
                    else fdeadline.timeout_or_none())
        t0 = time.perf_counter()
        last_probe = t0
        spins = 0
        wstall_s = 0.0          # writer blocked on reader acks (local)
        while True:
            progressed = False
            # -- write side: publish the next chunk once every reader
            # consumed the previous one (single-buffer reuse)
            if wi < len(plan):
                floor = wseq0 + wi      # required consumed level
                if all(int(own.consumed[r]) >= floor for r in peers):
                    off, ln = plan[wi]
                    if ln:
                        own.data[:ln] = np.frombuffer(
                            blob_view[off:off + ln], np.uint8)
                    seq_next = wseq0 + wi + 1
                    own.u64[_OFF_ROUND // 8] = rnd
                    own.u64[_OFF_TOTAL // 8] = len(blob)
                    own.u64[_OFF_CHUNK_OFF // 8] = off
                    own.u64[_OFF_CHUNK_LEN // 8] = ln
                    own.u32[_OFF_CRC // 4] = crc
                    own.u32[_OFF_HCRC // 4] = _header_crc(
                        seq_next, rnd, len(blob), off, ln, crc)
                    # seq LAST: the store that makes the chunk visible
                    own.u64[_OFF_SEQ // 8] = seq_next
                    wi += 1
                    progressed = True
            # -- read side: drain whatever peers have published
            for r in peers:
                st = rstate[r]
                if st[3]:
                    continue
                seg = self._peer[(channel, r)]
                want = self._rseq[(channel, r)] + 1
                if seg.seq() < want:
                    continue
                peer_round = int(seg.u64[_OFF_ROUND // 8])
                if peer_round != rnd:
                    raise WireCorruption(
                        f"shm wire desync on channel {channel}: rank "
                        f"{r} is at exchange round {peer_round}, rank "
                        f"{self.rank} at {rnd} — a rank re-entered the "
                        f"exchange alone; the stream cannot be trusted")
                total = int(seg.u64[_OFF_TOTAL // 8])
                off = int(seg.u64[_OFF_CHUNK_OFF // 8])
                ln = int(seg.u64[_OFF_CHUNK_LEN // 8])
                frame_crc = int(seg.u32[_OFF_CRC // 4])
                if int(seg.u32[_OFF_HCRC // 4]) != _header_crc(
                        want, peer_round, total, off, ln, frame_crc):
                    self._t_crc.inc()
                    raise WireCorruption(
                        f"shm wire frame header from rank {r} failed "
                        f"its CRC32 (round {rnd}, chunk seq {want})")
                if st[0] is None:
                    st[0] = bytearray(total)
                    st[1] = total
                    # LATCH the frame CRC before any ack: once the
                    # final chunk is acked the writer may overwrite the
                    # header with the NEXT round's values — a post-ack
                    # header read would compare against the wrong CRC
                    st[4] = frame_crc
                if total != st[1] or off + ln > st[1]:
                    self._t_crc.inc()
                    raise WireCorruption(
                        f"shm wire frame from rank {r} truncated/"
                        f"inconsistent: total {total} vs {st[1]}, "
                        f"chunk [{off}:{off + ln}]")
                if ln:
                    # one copy, straight from the segment (bytearray
                    # slice assignment takes the buffer protocol), and
                    # the CRC runs over the COPIED bytes — cache-warm,
                    # and immune to any post-ack overwrite
                    st[0][off:off + ln] = seg.data[:ln].data
                    if self.payload_crc:
                        st[5] = fast_crc(
                            memoryview(st[0])[off:off + ln], st[5])
                st[2] += 1
                self._rseq[(channel, r)] = want
                # ack AFTER the copy: the writer may now overwrite
                seg.consumed[self.rank] = want
                expect_chunks = max(1, -(-st[1] // self.cap))
                if st[2] >= expect_chunks:
                    if self.payload_crc and (st[5] & 0xFFFFFFFF) != st[4]:
                        self._t_crc.inc()
                        raise WireCorruption(
                            f"shm wire frame from rank {r} failed its "
                            f"CRC32 (round {rnd}, {st[1]} bytes)")
                    st[3] = True
                progressed = True
            if wi >= len(plan) and all(st[3] for st in rstate.values()):
                break
            if progressed:
                spins = 0
                continue
            spins += 1
            if spins > _HOT_SPINS:
                time.sleep(_SLEEP_S)
                if wi < len(plan):
                    # chunks left to publish and every sleep here means
                    # a reader has not acked the previous one: ring
                    # BACKPRESSURE (the watchdog's shm_backpressure
                    # rule reads the counter's slope). Reader-side
                    # waits (wi done, peers not published) stay out —
                    # they are the PEER's problem, named by critpath.
                    wstall_s += _SLEEP_S
                now = time.perf_counter()
                if now - last_probe > _PROBE_PERIOD_S:
                    last_probe = now
                    dead = _peer_loss_probe(
                        f"shm wire exchange (channel {channel}, "
                        f"round {rnd}): peer silent")
                    if dead is not None:
                        raise dead
                if deadline is not None and now - t0 > deadline:
                    fdeadline.raise_deadline(
                        f"shm wire exchange (channel {channel}, round "
                        f"{rnd}): a peer never published/consumed its "
                        f"frame", fatal=True)
        self._wseq[channel] += len(plan)
        self._t_rounds.inc()
        self._t_bytes.inc(len(blob))
        if wstall_s > 0.0:
            self.writer_stall_s += wstall_s
            self._t_wstall.inc(wstall_s)
        out: List[bytes] = []
        for r in range(self.nprocs):
            out.append(blob if r == self.rank
                       else bytes(rstate[r][0]))
        return out

    # -- diagnostics ---------------------------------------------------------

    def stats(self) -> dict:
        return {"token": self.token, "rank": self.rank,
                "nprocs": self.nprocs, "channels": self.channels,
                "cap_bytes": self.cap,
                "rounds": [int(r) for r in self._round],
                "writer_stall_s": round(self.writer_stall_s, 6),
                "frame_hw_bytes": self.frame_hw_bytes}

    def mem_bytes(self) -> dict:
        """Ledger probe (telemetry/accounting.py): this process's shm
        footprint — the segments it OWNS (created, counted once
        process-wide) vs the peer segments it merely maps (shared
        pages, owned elsewhere), plus the frame high-watermark the
        occupancy gauge tracks."""
        return {"segment_bytes": len(self._own) * self._size,
                "peer_mapped_bytes": len(self._peer) * self._size,
                "frame_hw_bytes": self.frame_hw_bytes}
