"""Jax-free flat value codec: header + raw array segments.

The CORE of the window wire's codec (:mod:`multiverso_tpu.parallel.wire`),
factored out in round 19 so the replica plane's jax-free reader
processes can speak the same zero-copy framing without importing the
verb codec (``wire.py`` pulls ``updaters.base`` → jax for its
Add/GetOption tags — a read-tier process must stay numpy-only). This is
the round-17 seal factoring applied to the VALUE grammar: one encoder,
one cursor, one set of tags, with ``wire.py`` layering its option tags
on top through the extension hook.

Why flat instead of pickle: the serve/lookup payloads are almost
entirely contiguous ndarrays. Pickle walks the object graph, copies
every buffer into its stream and walks it again on the far side; this
codec writes a small header (dtype/shape tags) followed by the raw
array bytes and decodes arrays ZERO-COPY with ``np.frombuffer`` against
the received blob (decoded arrays are read-only views — consumers copy
before mutating). The ROADMAP named the pickled-frames replica lookup
protocol the read tier's "next 10x"; :func:`encode_frame` /
:func:`decode_frame` are that flat lookup framing, sealed with the
versioned trailer (parallel/seal.py — hardware CRC32C) like every
other byte that crosses a process boundary.

Value tags (same grammar as the window wire — wire.py documents the
full table)::

    n  None
    a  ndarray   u8 dtype-str len, dtype str, u8 ndim, i64 dims, raw
    v  DEFERRED ndarray — same header as 'a', NO raw bytes
    d  nested dict: u8 count + entries
    l  list: u32 count + values (tuples pickle — identity must survive)
    t  bool (u8)    i  int (i64)    f  float (f64)
    s  str / b  bytes: i64 length + raw
    q  COMPRESSED ndarray — i64 envelope length + a parallel/compress.py
       tagged codec envelope; decode is EAGER (the consumer gets the
       reconstructed ndarray, and an unknown codec tag fails loudly
       inside the envelope — the seal's "newer writer" posture)
    p  pickle fallback (exotic tail; extensions run BEFORE this)
"""

from __future__ import annotations

import pickle
import struct
from typing import Optional, Tuple

import numpy as np

from multiverso_tpu.parallel import seal
from multiverso_tpu.parallel.compress import CompressedArray, decode_array

_U8 = struct.Struct("<B")
_U32 = struct.Struct("<I")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")

#: leading byte of a flat FRAME (the serve/lookup protocol unit) —
#: distinct from wire.py's window/barrier kinds so a misrouted blob
#: fails loudly at the first byte
KIND_FLAT = 0x46        # 'F'

#: OPTIONAL trace-context entry in a flat request dict (round 22):
#: ``[trace_id, span_id]`` of the caller's open span, present ONLY when
#: ``-trace`` is armed on the sending side. Same negotiation posture as
#: the seal and codec tags — an old receiver sees an unknown dict key
#: it never reads (dict entries are self-delimiting), a new receiver of
#: an old sender sees it absent, and when absent the encoded frame is
#: BYTE-IDENTICAL to a pre-round-22 one (the dict is one entry shorter;
#: nothing else moves), so tracing-off leaves the wire untouched.
TRACE_KEY = "_tctx"


class Extension:
    """Hook for domain tags layered over the core grammar (wire.py's
    Add/GetOption records). ``encode`` appends parts and returns True
    when it owns ``v``; ``decode`` returns ``(True, value)`` when it
    owns ``tag``. The core consults extensions BEFORE its pickle
    fallback, so extension tags always win over 'p'."""

    def encode(self, parts: list, v) -> bool:
        return False

    def decode(self, tag: bytes, cur: "_Cursor"):
        return False, None


class DeferredArray:
    """Placeholder for an ndarray whose BYTES did not ride the host
    wire: the encoder wrote only its dtype/shape header, and the owning
    rank keeps the real array in ``local`` (None on every other rank
    after decode). The windowed engine substitutes these for large Add
    values when the device transport is selected — every rank still
    sees the full shape metadata (needed for lockstep bucket math), and
    the values move through the table's device-parts collectives
    instead of the host staging wire."""

    __slots__ = ("dtype", "shape", "local")

    def __init__(self, dtype, shape, local=None):
        self.dtype = np.dtype(dtype)
        self.shape = tuple(int(s) for s in shape)
        self.local = local

    @classmethod
    def of(cls, arr: np.ndarray) -> "DeferredArray":
        arr = np.asarray(arr)
        return cls(arr.dtype, arr.shape, local=arr)

    @property
    def size(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n

    @property
    def nbytes(self) -> int:
        return self.size * self.dtype.itemsize

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        tag = "local" if self.local is not None else "remote"
        return f"DeferredArray({self.dtype.str}, {self.shape}, {tag})"


def dtype_wire_safe(dt) -> bool:
    """True when ``dt`` survives the flat wire: its ``.str`` tag decodes
    back to the SAME dtype. Extension dtypes (e.g. ml_dtypes.bfloat16,
    which jax registers) stringify as opaque void tags like ``<V2`` —
    encoding those flat would decode as void (silent corruption), and
    ``memoryview`` refuses their buffers anyway, so their arrays ride
    the pickle fallback instead (correct, just slower) and the engine
    never defers them to the device wire."""
    dt = np.dtype(dt)
    try:
        return not dt.hasobject and np.dtype(dt.str) == dt
    except TypeError:
        return False


def _norm_array(v: np.ndarray) -> np.ndarray:
    """Contiguous, little-endian view/copy of ``v`` for the wire."""
    v = np.ascontiguousarray(v)
    if v.dtype.byteorder == ">":
        v = v.astype(v.dtype.newbyteorder("<"))
    return v


def _encode_array_header(parts: list, tag: bytes, dtype: np.dtype,
                         shape: Tuple[int, ...]) -> None:
    ds = dtype.str.encode("ascii")
    parts.append(tag)
    parts.append(_U8.pack(len(ds)))
    parts.append(ds)
    parts.append(_U8.pack(len(shape)))
    for dim in shape:
        parts.append(_I64.pack(dim))


def encode_value(parts: list, v, ext: Optional[Extension] = None) -> None:
    if v is None:
        parts.append(b"n")
    elif isinstance(v, np.ndarray) and dtype_wire_safe(v.dtype):
        v = _norm_array(v)
        _encode_array_header(parts, b"a", v.dtype, v.shape)
        if v.size == 0:
            pass                       # no payload bytes
        elif v.ndim == 0:
            parts.append(v.tobytes())  # memoryview can't cast 0-d
        else:
            parts.append(memoryview(v).cast("B"))
    elif isinstance(v, DeferredArray):
        _encode_array_header(parts, b"v", v.dtype, v.shape)
    elif isinstance(v, CompressedArray):
        parts.append(b"q")
        parts.append(_I64.pack(len(v.blob)))
        parts.append(v.blob)
    elif ext is not None and ext.encode(parts, v):
        pass
    elif isinstance(v, dict):
        if len(v) > 255:
            raise ValueError("wire dict too wide")
        parts.append(b"d")
        parts.append(_U8.pack(len(v)))
        for key in sorted(v):
            kb = str(key).encode("utf-8")
            parts.append(_U8.pack(len(kb)))
            parts.append(kb)
            encode_value(parts, v[key], ext)
    elif isinstance(v, bool):          # before int: bool is an int subtype
        parts.append(b"t")
        parts.append(_U8.pack(1 if v else 0))
    elif isinstance(v, int) and -(2 ** 63) <= v < 2 ** 63:
        parts.append(b"i")
        parts.append(_I64.pack(v))
    elif isinstance(v, float):
        parts.append(b"f")
        parts.append(_F64.pack(v))
    elif isinstance(v, str):
        sb = v.encode("utf-8")
        parts.append(b"s")
        parts.append(_I64.pack(len(sb)))
        parts.append(sb)
    elif isinstance(v, bytes):
        parts.append(b"b")
        parts.append(_I64.pack(len(v)))
        parts.append(v)
    elif type(v) is list:
        # lists only — a tuple must come back a tuple (pickle keeps
        # container identity; the flat tag would flatten it to a list)
        parts.append(b"l")
        parts.append(_U32.pack(len(v)))
        for item in v:
            encode_value(parts, item, ext)
    else:
        # option subclasses, huge ints, user table payloads: correctness
        # over speed for the exotic tail
        pb = pickle.dumps(v)
        parts.append(b"p")
        parts.append(_I64.pack(len(pb)))
        parts.append(pb)


class _Cursor:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def unpack(self, st: struct.Struct):
        vals = st.unpack_from(self.buf, self.pos)
        # mv-lint: ok(cross-domain-state): a _Cursor is constructed, walked and dropped inside ONE decode call — instance-local state; the class-level write aggregation is instance-blind here
        self.pos += st.size
        return vals

    def take(self, n: int):
        out = self.buf[self.pos: self.pos + n]
        if len(out) != n:
            raise ValueError("wire blob truncated")
        self.pos += n
        return out


def decode_value(cur: _Cursor, ext: Optional[Extension] = None):
    tag = cur.take(1)
    if tag == b"n":
        return None
    if tag in (b"a", b"v"):
        (dlen,) = cur.unpack(_U8)
        dtype = np.dtype(bytes(cur.take(dlen)).decode("ascii"))
        (ndim,) = cur.unpack(_U8)
        shape = tuple(cur.unpack(_I64)[0] for _ in range(ndim))
        if tag == b"v":
            return DeferredArray(dtype, shape)
        count = 1
        for dim in shape:
            count *= dim
        arr = np.frombuffer(cur.buf, dtype, count=count, offset=cur.pos)
        cur.pos += count * dtype.itemsize
        return arr.reshape(shape)
    if tag == b"d":
        (n,) = cur.unpack(_U8)
        out = {}
        for _ in range(n):
            (klen,) = cur.unpack(_U8)
            key = bytes(cur.take(klen)).decode("utf-8")
            out[key] = decode_value(cur, ext)
        return out
    if tag == b"t":
        return bool(cur.unpack(_U8)[0])
    if tag == b"i":
        return cur.unpack(_I64)[0]
    if tag == b"f":
        return cur.unpack(_F64)[0]
    if tag == b"s":
        (n,) = cur.unpack(_I64)
        return bytes(cur.take(n)).decode("utf-8")
    if tag == b"b":
        (n,) = cur.unpack(_I64)
        return bytes(cur.take(n))
    if tag == b"q":
        (n,) = cur.unpack(_I64)
        return decode_array(cur.take(n))
    if tag == b"l":
        (n,) = cur.unpack(_U32)
        return [decode_value(cur, ext) for _ in range(n)]
    if tag == b"p":
        (n,) = cur.unpack(_I64)
        return pickle.loads(bytes(cur.take(n)))
    if ext is not None:
        ok, val = ext.decode(tag, cur)
        if ok:
            return val
    raise ValueError(f"unknown wire tag {tag!r}")


# -- flat FRAMES (the serve/lookup protocol unit) ---------------------------

def encode_frame(obj) -> bytes:
    """One flat protocol frame: kind byte + the value grammar + the
    versioned seal trailer. Replaces a pickled dict one-for-one — any
    value the grammar speaks rides flat (arrays as raw segments), the
    exotic tail still pickles per value."""
    parts: list = [_U8.pack(KIND_FLAT)]
    encode_value(parts, obj)
    return seal.seal_frame(b"".join(parts))


def decode_frame(blob: bytes):
    """Verify the seal, check the kind byte, decode the value. Array
    entries are zero-copy READ-ONLY views into ``blob`` (callers copy
    before mutating). Raises ``WireCorruption`` on a torn/flipped frame
    BEFORE any parsing. The cursor walks the original blob (check_crc,
    not open_frame — slicing the trailer off would copy the whole
    payload and forfeit the zero-copy decode); the value grammar is
    self-delimiting, so the unread trailer bytes are never parsed."""
    seal.check_crc(blob)
    cur = _Cursor(blob)
    (kind,) = cur.unpack(_U8)
    if kind != KIND_FLAT:
        raise ValueError(f"not a flat frame (leading byte {kind:#x})")
    return decode_value(cur)
