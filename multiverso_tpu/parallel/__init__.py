"""Mesh + transport layer (reference L2 replacement).

Where the reference moves bytes with MPI/ZMQ point-to-point messages
(reference src/net*, include/multiverso/net/), the TPU build places table
shards on a ``jax.sharding.Mesh`` and lets XLA turn sharding mismatches into
ICI/DCN collectives. The hand-rolled Bruck / recursive-halving allreduce
engine (reference src/net/allreduce_engine.cpp) is replaced by ``psum`` —
XLA picks the wire algorithm per size/topology, which is exactly the
size-adaptive choice AllreduceEngine made by hand
(reference allreduce_engine.cpp:31-55).
"""

from multiverso_tpu.parallel.mesh import (  # noqa: F401
    MeshContext,
    build_mesh,
    partition_offsets,
)
from multiverso_tpu.parallel.allreduce import (  # noqa: F401
    RendezvousAllreduce,
    device_allreduce,
)
