"""Mesh + transport layer (reference L2 replacement).

Where the reference moves bytes with MPI/ZMQ point-to-point messages
(reference src/net*, include/multiverso/net/), the TPU build places table
shards on a ``jax.sharding.Mesh`` and lets XLA turn sharding mismatches into
ICI/DCN collectives. The hand-rolled Bruck / recursive-halving allreduce
engine (reference src/net/allreduce_engine.cpp) is replaced by ``psum`` —
XLA picks the wire algorithm per size/topology, which is exactly the
size-adaptive choice AllreduceEngine made by hand
(reference allreduce_engine.cpp:31-55).

The mesh/allreduce re-exports are LAZY (PEP 562): ``mesh`` and
``allreduce`` import jax at module level, but this package also hosts
the jax-free transport tier (``multihost``, ``shm_wire``, ``seal``) the
replica plane's reader processes ride — importing those submodules must
not pull jax through this ``__init__``.
"""

_LAZY = {
    "MeshContext": "multiverso_tpu.parallel.mesh",
    "build_mesh": "multiverso_tpu.parallel.mesh",
    "partition_offsets": "multiverso_tpu.parallel.mesh",
    "RendezvousAllreduce": "multiverso_tpu.parallel.allreduce",
    "device_allreduce": "multiverso_tpu.parallel.allreduce",
}

__all__ = sorted(_LAZY)


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is not None:
        import importlib
        value = getattr(importlib.import_module(mod), name)
        globals()[name] = value
        return value
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
