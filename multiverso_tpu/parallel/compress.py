"""Tagged blob-compression codecs for the hot byte paths, jax-free.

PR 14's hardware-CRC32C seal took the checksum off the wire's critical
path (~7 GB/s); bytes SHIPPED are now the dominant cost on the fan-out
and cross-proc paths. This module is the reproduction of the reference's
compression layer (include/multiverso/util/quantization_util.h — per-blob
filters applied before the wire) recast in the repo's negotiation idiom:
every compressed array rides an ENVELOPE whose first byte is a codec
tag, exactly like the seal's algorithm trailer byte
(:mod:`multiverso_tpu.parallel.seal`), so mixed fleets roll forward
safely — readers upgrade first, and a reader that meets a tag from the
reserved range it does not know fails LOUDLY as "written by a newer
writer" instead of decoding garbage.

Codecs (tag space ``0xD0..0xDF``, disjoint from the seal's
``0xC0..0xCF`` so a misrouted blob can never verify):

* **raw** (``0xD0``) — identity: dtype/shape header + raw bytes. The
  lossless fallback every other codec's encoder may pick when it would
  not win.
* **int8 rows** (``0xD1``) — per-row scale quantization, LOSSY: each
  row stores one f32 scale (``max|row| / 127``) plus int8 codes; decode
  is ``q * scale``. ~4x smaller than f32 with max-abs error bounded by
  ``scale/2 <= max|row|/254`` per element. For gradient-shaped delta
  traffic (window Add values, replica delta rows).
* **bf16** (``0xD2``) — round-to-nearest-even truncation of f32 to the
  upper 16 bits, LOSSY: 2x smaller, relative error <= 2**-8. For value
  rows (base payloads, serve frames) where int8's shared row scale is
  too coarse.
* **bitmap-RLE** (``0xD3``) — LOSSLESS run-length coding of a sorted-
  unique non-negative int64 id set (the "rows dirtied since
  prev_version" descriptors in replica/delta.py): the conceptual dirty
  BITMAP's alternating gap/run lengths, varint-coded. Churn-local id
  sets cost ~2 bytes/id instead of 8; a dense "all rows" set collapses
  to a few bytes.

Everything is behind ``-mv_compress`` (default OFF — the wire stays
byte-identical to an uncompressed build), and the LOSSY codecs
additionally require a per-table opt-in via ``-mv_compress_lossy``
(comma-separated table ids, or ``all``), so KV/sparse tables stay
lossless by default. Telemetry: ``compress.pre_bytes.<path>`` /
``compress.post_bytes.<path>`` counters per hot path (``replica`` /
``window`` / ``serve``) feed bench.py's bytes-ceiling ratchets.

Lossy determinism contract: decode(encode(x)) is a pure function of the
envelope BYTES — no host state, no float environment dependence beyond
IEEE numpy ops — so every rank/reader that decodes the same blob
reconstructs bit-identical values. The windowed engine leans on this:
the sending rank applies its OWN verbs through the same decode
(sync/server.py materializes its local window), so SPMD replicas never
diverge under quantization.

This module is numpy-only (no jax, no seal import) — it sits on the
replica reader's import path, which must stay jax-free.
"""

from __future__ import annotations

import struct
from typing import Optional, Tuple

import numpy as np

from multiverso_tpu.failsafe.errors import WireCorruption
from multiverso_tpu.utils.configure import (MV_DEFINE_bool,
                                            MV_DEFINE_string,
                                            cached_bool_flag, cached_flag,
                                            cached_str_flag)

MV_DEFINE_bool("mv_compress", False,
               "compress hot-path wire blobs (replica fan-out bundles, "
               "cross-proc delta windows, replica serve frames) with the "
               "tagged codecs in parallel/compress.py; off = identity, "
               "byte-identical wire")
MV_DEFINE_string("mv_compress_lossy", "",
                 "comma-separated table ids (or 'all') whose float "
                 "payloads may ride the LOSSY int8/bf16 codecs; every "
                 "other table stays lossless regardless of -mv_compress")

_U8 = struct.Struct("<B")
_U32 = struct.Struct("<I")
_I64 = struct.Struct("<q")

#: reserved codec-tag space — the seal idiom (seal.py TAG_BASE 0xC0)
#: one nibble up, so the two reserved ranges can never be confused
TAG_BASE = 0xD0
TAG_RAW = 0xD0
TAG_INT8_ROWS = 0xD1
TAG_BF16 = 0xD2
TAG_RLE_IDS = 0xD3

#: telemetry counter names per hot byte path (pre = array bytes offered
#: to a codec, post = envelope bytes that actually shipped)
PATHS = ("replica", "window", "serve")

_enabled_flag = cached_bool_flag("mv_compress", False)
_lossy_raw_flag = cached_str_flag("mv_compress_lossy", "")


def _parse_lossy(raw) -> object:
    s = str(raw).strip().lower()
    if not s:
        return frozenset()
    if s in ("all", "*"):
        return "all"
    return frozenset(p.strip() for p in s.split(",") if p.strip())


#: parsed (cached) form of -mv_compress_lossy — per-payload membership
#: checks must not re-split a string on the fan-out/window hot paths
_lossy_set_flag = cached_flag("mv_compress_lossy", frozenset(),
                              _parse_lossy)


def enabled() -> bool:
    """True when ``-mv_compress`` is on (listener-cached read)."""
    return _enabled_flag()


def lossy_opted(table_id) -> bool:
    """True when ``table_id`` opted into the lossy codecs via
    ``-mv_compress_lossy`` (per-table contract: lossless by default)."""
    spec = _lossy_set_flag()
    return spec == "all" or str(table_id) in spec


def config_token() -> Tuple[bool, str]:
    """Hashable stamp of the live codec configuration — cache keys that
    must invalidate when an operator flips a flag mid-run (the
    publisher's content-addressed encode cache)."""
    return (_enabled_flag(), _lossy_raw_flag())


def _note(path: str, pre: int, post: int) -> None:
    """Per-path byte accounting (wire.py's per-blob registry-lookup
    idiom — one dict probe per blob, not per element; NULL instrument
    when telemetry is off)."""
    from multiverso_tpu.telemetry import metrics as _tmetrics
    _tmetrics.counter("compress.pre_bytes." + path).inc(pre)
    _tmetrics.counter("compress.post_bytes." + path).inc(post)


# -- envelope array header ---------------------------------------------------
#
# Same layout as the flat value grammar's array header (flat.py) —
# u8 dtype-str length, dtype str, u8 ndim, i64 dims — duplicated here
# (~15 lines) so this module stays import-free of the codec layers that
# import IT (flat.py speaks CompressedArray via its 'q' tag).


def _pack_header(parts: list, dtype: np.dtype, shape) -> None:
    ds = dtype.str.encode("ascii")
    parts.append(_U8.pack(len(ds)))
    parts.append(ds)
    parts.append(_U8.pack(len(shape)))
    for dim in shape:
        parts.append(_I64.pack(int(dim)))


def _unpack_header(blob, pos: int):
    (dlen,) = _U8.unpack_from(blob, pos)
    pos += 1
    dtype = np.dtype(bytes(blob[pos:pos + dlen]).decode("ascii"))
    pos += dlen
    (ndim,) = _U8.unpack_from(blob, pos)
    pos += 1
    shape = []
    for _ in range(ndim):
        shape.append(_I64.unpack_from(blob, pos)[0])
        pos += 8
    return dtype, tuple(shape), pos


def _wire_contig(arr: np.ndarray) -> np.ndarray:
    """Contiguous little-endian form for the envelope (flat.py's
    ``_norm_array`` rule)."""
    arr = np.asarray(arr)
    if arr.ndim:                # ascontiguousarray promotes 0-d to 1-d
        arr = np.ascontiguousarray(arr)
    if arr.dtype.byteorder == ">":
        arr = arr.astype(arr.dtype.newbyteorder("<"))
    return arr


# -- codecs ------------------------------------------------------------------


def encode_raw(arr: np.ndarray) -> bytes:
    """Identity envelope (lossless): header + raw bytes."""
    arr = _wire_contig(np.asarray(arr))
    parts: list = [_U8.pack(TAG_RAW)]
    _pack_header(parts, arr.dtype, arr.shape)
    if arr.size:
        parts.append(arr.tobytes())
    return b"".join(parts)


def _rows2d(arr: np.ndarray) -> np.ndarray:
    return arr.reshape(1, -1) if arr.ndim == 1 else arr


def encode_int8_rows(arr: np.ndarray) -> bytes:
    """Per-row-scale int8 quantization (LOSSY). ``arr`` is 1-D or 2-D
    float32/float64; a 1-D array quantizes as one row. Per element the
    reconstruction error is bounded by ``scale/2`` where ``scale =
    max|row|/127`` — an all-zero (or empty) row stores scale 0 and
    decodes exactly."""
    arr = _wire_contig(np.asarray(arr))
    if arr.ndim not in (1, 2) or arr.dtype.kind != "f":
        raise ValueError(
            f"int8 row codec wants a 1-D/2-D float array, got "
            f"{arr.dtype} ndim={arr.ndim}")
    rows = _rows2d(arr)
    if rows.size:
        maxabs = np.max(np.abs(rows), axis=1)
    else:
        maxabs = np.zeros(rows.shape[0], rows.dtype)
    scale = (maxabs / 127.0).astype(np.float32)
    safe = np.where(scale > 0, scale, np.float32(1.0)).astype(rows.dtype)
    q = np.clip(np.rint(rows / safe[:, None]), -127, 127).astype(np.int8)
    parts: list = [_U8.pack(TAG_INT8_ROWS)]
    _pack_header(parts, arr.dtype, arr.shape)
    parts.append(scale.tobytes())
    parts.append(q.tobytes())
    return b"".join(parts)


def encode_bf16(arr: np.ndarray) -> bytes:
    """bfloat16 truncation of a float32 array (LOSSY, round-to-nearest-
    even): keeps the f32 exponent, drops 16 mantissa bits — relative
    error <= 2**-8. NaN/Inf survive (a NaN's payload is forced non-zero
    so rounding can never turn it into Inf). No ml_dtypes dependency:
    the wire stores raw u16 upper halves."""
    arr = _wire_contig(np.asarray(arr))
    if arr.dtype != np.float32:
        raise ValueError(f"bf16 codec wants float32, got {arr.dtype}")
    bits = arr.view(np.uint32)
    rounded = bits + np.uint32(0x7FFF) + ((bits >> np.uint32(16))
                                          & np.uint32(1))
    special = (bits & np.uint32(0x7F800000)) == np.uint32(0x7F800000)
    hi = np.where(special, bits >> np.uint32(16),
                  rounded >> np.uint32(16)).astype(np.uint16)
    is_nan = special & ((bits & np.uint32(0x007FFFFF)) != 0)
    hi = np.where(is_nan, hi | np.uint16(1), hi)
    parts: list = [_U8.pack(TAG_BF16)]
    _pack_header(parts, arr.dtype, arr.shape)
    parts.append(np.ascontiguousarray(hi).tobytes())
    return b"".join(parts)


def _varint(out: bytearray, v: int) -> None:
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)


def _read_varint(blob, pos: int):
    shift = 0
    v = 0
    while True:
        b = blob[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        if b < 0x80:
            return v, pos
        shift += 7


def rle_encodable(ids: np.ndarray) -> bool:
    """True when ``ids`` meets the bitmap-RLE contract: 1-D int64,
    sorted strictly increasing, non-negative (what TableJournal.drain /
    merge_descriptors emit by construction — np.nonzero/np.unique)."""
    if not isinstance(ids, np.ndarray) or ids.dtype != np.int64 \
            or ids.ndim != 1:
        return False
    if ids.size == 0:
        return True
    if int(ids[0]) < 0:
        return False
    return bool(np.all(np.diff(ids) > 0))


def encode_rle_ids(ids: np.ndarray) -> bytes:
    """Bitmap-RLE envelope (LOSSLESS) of a sorted-unique non-negative
    int64 id set: the runs of the conceptual dirty bitmap, coded as
    alternating varint (gap, run-length) pairs. Callers gate on
    :func:`rle_encodable`."""
    ids = np.asarray(ids)
    out = bytearray(_U8.pack(TAG_RLE_IDS))
    _varint(out, int(ids.size))
    if ids.size:
        brk = np.flatnonzero(np.diff(ids) != 1)
        starts = np.concatenate(([int(ids[0])],
                                 ids[brk + 1])).astype(np.int64)
        ends = np.concatenate((ids[brk],
                               [int(ids[-1])])).astype(np.int64)
        prev_end = -1
        for s, e in zip(starts.tolist(), ends.tolist()):
            _varint(out, s - prev_end - 1)      # zeros gap
            _varint(out, e - s + 1)             # ones run
            prev_end = e
    return bytes(out)


def decode_array(blob) -> np.ndarray:
    """Decode one codec envelope back to its array. Deterministic pure
    function of the bytes (the SPMD lossy-consistency contract). A tag
    from the reserved range this build does not know raises the typed
    loud error — the seal's "newer writer" posture."""
    if not len(blob):
        raise WireCorruption("empty compression envelope")
    tag = blob[0]
    if tag == TAG_RAW:
        dtype, shape, pos = _unpack_header(blob, 1)
        count = 1
        for dim in shape:
            count *= dim
        arr = np.frombuffer(blob, dtype, count=count, offset=pos)
        return arr.reshape(shape)
    if tag == TAG_INT8_ROWS:
        dtype, shape, pos = _unpack_header(blob, 1)
        nrows = shape[0] if len(shape) == 2 else 1
        scale = np.frombuffer(blob, np.float32, count=nrows, offset=pos)
        pos += nrows * 4
        count = 1
        for dim in shape:
            count *= dim
        q = np.frombuffer(blob, np.int8, count=count, offset=pos)
        if count == 0:      # reshape(-1) can't infer a dim of size 0
            return np.zeros(shape, dtype)
        out = (q.reshape(nrows, -1).astype(dtype)
               * scale[:, None].astype(dtype))
        return out.reshape(shape)
    if tag == TAG_BF16:
        dtype, shape, pos = _unpack_header(blob, 1)
        count = 1
        for dim in shape:
            count *= dim
        hi = np.frombuffer(blob, np.uint16, count=count, offset=pos)
        out = (hi.astype(np.uint32) << np.uint32(16)).view(np.float32)
        return out.reshape(shape)
    if tag == TAG_RLE_IDS:
        n, pos = _read_varint(blob, 1)
        out = np.empty(n, np.int64)
        filled = 0
        at = 0
        while filled < n:
            gap, pos = _read_varint(blob, pos)
            run, pos = _read_varint(blob, pos)
            start = at + gap
            out[filled:filled + run] = np.arange(start, start + run,
                                                 dtype=np.int64)
            filled += run
            at = start + run
        return out
    if TAG_BASE <= tag <= TAG_BASE + 0x0F:
        raise WireCorruption(
            f"compressed blob carries unknown codec tag {tag:#x} — "
            f"written by a newer writer (upgrade readers before "
            f"writers), or corrupted in the envelope; refusing to parse")
    raise WireCorruption(
        f"not a compression envelope (leading byte {tag:#x})")


class CompressedArray:
    """An ndarray in its tagged-envelope form. Travels through pickle
    (replica fan-out bundles) and through the flat value grammar's
    ``q`` tag (window wire, serve frames); consumers materialize with
    :meth:`decode` — or the flat decoder does it eagerly for them."""

    __slots__ = ("blob",)

    def __init__(self, blob: bytes):
        self.blob = bytes(blob)

    @property
    def nbytes(self) -> int:
        return len(self.blob)

    def decode(self) -> np.ndarray:
        return decode_array(self.blob)

    def __getstate__(self):
        return self.blob

    def __setstate__(self, state):
        self.blob = state

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"CompressedArray({len(self.blob)}B, tag=" \
               f"{self.blob[0]:#x})" if self.blob else "CompressedArray()"


# -- hot-path packers --------------------------------------------------------


def _pack_float(arr: np.ndarray, codec: str) -> Optional[bytes]:
    """Envelope for a float payload array under ``codec`` ('int8' or
    'bf16'); None when the array does not fit the codec or the envelope
    would not win."""
    if not isinstance(arr, np.ndarray) or arr.size == 0:
        return None
    if codec == "int8":
        if arr.ndim not in (1, 2) or arr.dtype.kind != "f":
            return None
        blob = encode_int8_rows(arr)
    else:
        if arr.dtype != np.float32:
            return None
        blob = encode_bf16(arr)
    return blob if len(blob) < arr.nbytes else None


def pack_payload(table_id, payload: dict, path: str = "replica") -> dict:
    """Compress one replica bundle payload's arrays (delta.py grammar):
    ``ids``/``keys`` descriptors ride bitmap-RLE (lossless, whenever it
    wins); ``rows``/``values`` float arrays ride int8 (delta-shaped —
    the payload carries an id/key vector) or bf16 (whole-state value
    rows) ONLY when ``table_id`` opted into lossy. Returns ``payload``
    itself when compression is off or nothing won."""
    if not enabled():
        return payload
    out = None
    pre = post = 0
    for key in ("ids", "keys"):
        v = payload.get(key)
        if isinstance(v, np.ndarray) and v.size and rle_encodable(v):
            blob = encode_rle_ids(v)
            if len(blob) < v.nbytes:
                out = out if out is not None else dict(payload)
                out[key] = CompressedArray(blob)
                pre += v.nbytes
                post += len(blob)
    if lossy_opted(table_id):
        delta_shaped = "ids" in payload or \
            (payload.get("fam") == "kv" and "keys" in payload)
        for key in ("rows", "values"):
            v = payload.get(key)
            blob = _pack_float(v, "int8" if delta_shaped and key != "values"
                               else "bf16")
            if blob is not None:
                out = out if out is not None else dict(payload)
                out[key] = CompressedArray(blob)
                pre += v.nbytes
                post += len(blob)
    if out is None:
        return payload
    _note(path, pre, post)
    return out


def unpack_payload(payload: dict) -> dict:
    """Materialize every CompressedArray in a bundle payload IN PLACE
    (the dict is freshly unpickled — nobody else holds it)."""
    for key, v in payload.items():
        if isinstance(v, CompressedArray):
            payload[key] = v.decode()
    return payload


def pack_window_values(table_id: int, payload: dict) -> dict:
    """Window-path Add compression: quantize a lossy-opted table's
    ``values`` deltas to int8. Returns a NEW payload dict holding a
    CompressedArray (callers persist it on the message, the
    DeferredArray idiom) or ``payload`` unchanged. The sending rank
    must apply its own verbs through :func:`materialize_window` so
    every rank reconstructs the identical dequantized delta."""
    if not enabled() or not lossy_opted(table_id):
        return payload
    blob = _pack_float(payload.get("values"), "int8")
    if blob is None:
        return payload
    v = payload["values"]
    out = dict(payload)
    out["values"] = CompressedArray(blob)
    _note("window", v.nbytes, len(blob))
    return out


def materialize_window(verbs: list) -> list:
    """Replace CompressedArray payload values with their decoded arrays
    across one window's verb records — the sending rank's twin of the
    peers' eager flat decode, sharing :func:`decode_array` so the
    reconstruction is bit-identical on every rank. Payload dicts are
    copied before substitution (the originals stay compressed on their
    messages for a possible re-pack)."""
    out = []
    for rec in verbs:
        kind, tid, payload = rec
        hit = None
        for key, v in payload.items():
            if isinstance(v, CompressedArray):
                hit = hit if hit is not None else dict(payload)
                hit[key] = v.decode()
        out.append((kind, tid, hit) if hit is not None else rec)
    return out


def pack_serve_rows(table_id: int, rows, path: str = "serve"):
    """Serve-frame compression (replica lookup responses): bf16 for a
    lossy-opted table's f32 result rows; anything else ships as-is."""
    if not enabled() or not lossy_opted(table_id):
        return rows
    blob = _pack_float(rows if isinstance(rows, np.ndarray) else None,
                       "bf16")
    if blob is None:
        return rows
    _note(path, rows.nbytes, len(blob))
    return CompressedArray(blob)
