"""Allreduce for model-average (``-ma``) mode.

Two faces, replacing the reference's two paths:

* ``device_allreduce`` — mesh-wide sum via ``psum`` under ``shard_map``.
  Replaces both ``MPI_Allreduce`` (reference mpi_net.h:148-152) and the
  hand-rolled Bruck / recursive-halving ``AllreduceEngine``
  (reference src/net/allreduce_engine.cpp:31-55): XLA picks the wire
  algorithm per message size and ICI topology, which is the same
  size-adaptive decision the engine made by hand.

* ``RendezvousAllreduce`` — in-process allreduce across worker *threads*
  (our stand-in for MPI ranks in the 1-host world, matching the semantics of
  ``MV_Aggregate`` in Test/test_allreduce.cpp:11-20: every participant
  contributes its buffer and receives the elementwise sum in place).
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from multiverso_tpu.parallel import mesh as mesh_lib
from multiverso_tpu.parallel.mesh import SERVER_AXIS


def device_allreduce(x: jax.Array, mesh: Mesh, axis_name: str = SERVER_AXIS) -> jax.Array:
    """Sum ``x`` (sharded or replicated along ``axis_name``) across the mesh.

    The idiomatic form: annotate the desired output sharding and let XLA
    insert the all-reduce over ICI.
    """
    @partial(mesh_lib.shard_map, mesh=mesh, in_specs=P(axis_name),
             out_specs=P())
    def _psum(shard):
        return jax.lax.psum(shard, axis_name)

    return _psum(x)


class RendezvousAllreduce:
    """N-participant elementwise-sum rendezvous.

    Each participant thread calls ``allreduce(arr)``; all block until every
    contribution arrived, then all receive the sum. Reusable across rounds
    (generation counter), mirroring repeated ``MV_Aggregate`` calls.

    ``cross_reduce`` (optional) extends the sum beyond this process: the
    last-arriving thread applies it to the thread-summed buffer exactly once
    per round — the multihost leg of MV_Aggregate (every process's last
    thread issues the same collective; reference MPI_Allreduce,
    mpi_net.h:148-152).
    """

    def __init__(self, num_participants: int, cross_reduce=None):
        if num_participants <= 0:
            raise ValueError("num_participants must be positive")
        self.n = num_participants
        self._cross = cross_reduce
        self._lock = threading.Condition()
        self._accum: Optional[np.ndarray] = None
        self._arrived = 0
        self._generation = 0
        self._result: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None
        #: set when a participant's deadline expired mid-round: the
        #: round can never complete correctly (its contribution is in
        #: _accum but its caller has moved on), so the rendezvous
        #: BREAKS for everyone — threading.Barrier.abort semantics,
        #: fail-fast over silently skewed sums
        self._broken = False

    def allreduce(self, arr: np.ndarray) -> np.ndarray:
        arr = np.asarray(arr)
        from multiverso_tpu.failsafe import deadline as fdeadline
        with self._lock:
            if self._broken:
                fdeadline.raise_deadline(
                    "allreduce rendezvous (broken by an earlier "
                    "participant deadline)")
            gen = self._generation
            if self._accum is None:
                self._accum = arr.astype(np.float64, copy=True)
            else:
                self._accum += arr
            self._arrived += 1
            if self._arrived == self.n:
                # the round ENDS no matter what cross_reduce does — a raise
                # here must not strand the n-1 waiters or wedge future
                # rounds, so state reset + notify happen unconditionally
                result = self._accum
                error = None
                if self._cross is not None:
                    try:
                        result = np.asarray(self._cross(result))
                    except BaseException as exc:
                        error = exc
                self._result = result
                self._error = error
                self._accum = None
                self._arrived = 0
                self._generation += 1
                self._lock.notify_all()
            else:
                if not self._lock.wait_for(
                        lambda: self._generation > gen or self._broken,
                        fdeadline.timeout_or_none()):
                    # a participant never arrived: bounded by
                    # -mv_deadline_s (None = block as before). Our
                    # contribution is already in _accum and cannot be
                    # handed back, so the whole rendezvous breaks —
                    # a retry re-adding it would double-count
                    self._broken = True
                    self._lock.notify_all()
                    fdeadline.raise_deadline(
                        "allreduce rendezvous (missing participants)")
                if self._broken and self._generation <= gen:
                    fdeadline.raise_deadline(
                        "allreduce rendezvous (broken by a peer "
                        "participant deadline)")
            if self._error is not None:
                raise RuntimeError(
                    "cross-host allreduce failed") from self._error
            return self._result.astype(arr.dtype)


def jit_mean_across(params: jax.Array, mesh: Mesh, axis_name: str = SERVER_AXIS) -> jax.Array:
    """Model-average helper: mean of per-device replicas along the mesh axis
    (the `model average` training mode, reference -ma flag zoo.cpp:24,49)."""
    @partial(mesh_lib.shard_map, mesh=mesh, in_specs=P(axis_name),
             out_specs=P())
    def _pmean(shard):
        return jax.lax.pmean(shard, axis_name)

    return _pmean(params)
