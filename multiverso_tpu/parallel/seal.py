"""CRC32 frame sealing — the one corruption posture, jax-free.

Factored out of :mod:`multiverso_tpu.parallel.wire` (round 17) so the
replica plane's jax-free reader processes can seal/verify fan-out blobs
without importing the verb codec (``wire.py`` pulls
``updaters.base`` → jax for its Add/GetOption tags — a read-tier
process must stay numpy-only). ``wire.py`` re-exports everything here,
so every existing call site keeps working and the posture stays ONE
implementation: a little-endian CRC32 trailer over the body, verified
BEFORE any parsing, raising the typed ``WireCorruption`` (and counting
``wire.crc_failures``) on mismatch or truncation.
"""

from __future__ import annotations

import struct
import zlib

from multiverso_tpu.failsafe.errors import WireCorruption

#: every sealed blob carries a little-endian CRC32 trailer over all
#: preceding bytes: a flipped bit or truncated frame raises
#: WireCorruption at open instead of materializing garbage
CRC_TRAILER_BYTES = 4

_U32 = struct.Struct("<I")


def _seal(body: bytes) -> bytes:
    """Append the CRC32 trailer (little-endian u32 over ``body``)."""
    return body + _U32.pack(zlib.crc32(body) & 0xFFFFFFFF)


def seal_frame(body: bytes) -> bytes:
    """Public sealing for satellite planes (elastic shard moves,
    replica fan-out blobs): the same CRC32 trailer every window blob
    carries, so one corruption posture covers every byte that crosses
    a process boundary."""
    return _seal(body)


def open_frame(blob: bytes) -> bytes:
    """Verify + strip a :func:`seal_frame` trailer; raises
    ``WireCorruption`` (counting ``wire.crc_failures``) on mismatch."""
    check_crc(blob)
    return blob[:-CRC_TRAILER_BYTES]


def check_crc(blob: bytes) -> None:
    """Verify a sealed blob's CRC32 trailer; raises ``WireCorruption``
    (counting ``wire.crc_failures``) on mismatch or truncation. Runs
    BEFORE any parsing so corrupt bytes never reach the decoders."""
    ok = len(blob) > CRC_TRAILER_BYTES and (
        zlib.crc32(blob[:-CRC_TRAILER_BYTES]) & 0xFFFFFFFF
        == _U32.unpack_from(blob, len(blob) - CRC_TRAILER_BYTES)[0])
    if not ok:
        from multiverso_tpu.telemetry import metrics as _tmetrics
        _tmetrics.counter("wire.crc_failures").inc()
        raise WireCorruption(
            f"wire blob failed CRC32 check ({len(blob)} bytes) — "
            f"corrupted or truncated frame")
