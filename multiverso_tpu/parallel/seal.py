"""Versioned frame sealing — the one corruption posture, jax-free.

Factored out of :mod:`multiverso_tpu.parallel.wire` (round 17) so the
replica plane's jax-free reader processes can seal/verify fan-out blobs
without importing the verb codec (``wire.py`` pulls
``updaters.base`` → jax for its Add/GetOption tags — a read-tier
process must stay numpy-only). ``wire.py`` re-exports everything here,
so every existing call site keeps working and the posture stays ONE
implementation: a trailer over the body, verified BEFORE any parsing,
raising the typed ``WireCorruption`` (and counting
``wire.crc_failures``) on mismatch or truncation.

Round 19 — the VERSIONED trailer. The PR 8/9 critpath measured
``zlib.crc32`` at ~0.8 GB/s on this host class: ~80% of the window
codec's ~6ms encode + ~4ms decode per 3MiB window, and the same
trailer seals shm frames, replica fan-out bundles and serving frames —
the checksum WAS the wire's dominant local cost. The seal now carries
an algorithm tag byte:

* **legacy** (no tag) — ``body | u32 crc32`` (little-endian zlib
  CRC32): every blob sealed before round 19. Still verifies, so a new
  reader opens old checkpoint-era blobs. The compatibility is
  ONE-directional — an OLD reader cannot verify a tagged blob — so a
  rolling upgrade must upgrade READERS (replicas, clients) before
  writers, or move the fleet together; the tag byte exists so the
  next algorithm bump inherits two-way verify for free.
* **crc32c** (tag ``0xC2``) — ``body | u32 crc32c | u8 tag``:
  hardware CRC32C through the native module's SSE4.2 path
  (``native/src/crc32c.cc``, jax-free ctypes binding — the replica
  reader verifies without jax), measured ~8x zlib.crc32 here. Sealing
  picks it whenever the native library is loadable; without it sealing
  falls back to the legacy chunked pure-zlib trailer and verification
  of crc32c-tagged blobs falls back to a (slow, correctness-only)
  table-driven python CRC32C.

Tag bytes live in the reserved ``0xC0..0xCF`` range; a blob whose last
byte names a RESERVED-BUT-UNKNOWN tag (and which fails the legacy
check — a legacy blob's crc high byte may land in the range by chance)
fails loudly as "sealed by a newer writer" instead of decoding
garbage.
"""

from __future__ import annotations

import struct
import zlib

import numpy as np

from multiverso_tpu.failsafe.errors import WireCorruption

#: legacy trailer: little-endian u32 CRC32 over all preceding bytes
CRC_TRAILER_BYTES = 4
#: versioned trailer: u32 checksum + the algorithm tag byte
TAGGED_TRAILER_BYTES = 5

#: reserved algorithm-tag space (low nibble = algorithm id); a legacy
#: blob has no tag at all — discrimination is verify-first (see module
#: docstring for the collision math: a legacy blob whose crc byte lands
#: in the range still verifies through the legacy check)
TAG_BASE = 0xC0
TAG_CRC32C = 0xC2

#: chunk size of the pure-zlib fallback seal: zlib.crc32 releases the
#: GIL per call, so chunking keeps a multi-MB seal from pinning other
#: threads behind one monolithic C call
_ZLIB_CHUNK = 1 << 20

_U32 = struct.Struct("<I")

# -- checksum engines -------------------------------------------------------

#: native CRC32C entry points, resolved ONCE (None = unavailable).
#: Sentinel False = not probed yet; the probe is deferred off import so
#: `import seal` never pays a dlopen. Two bindings of the same symbol:
#: the c_char_p one marshals a ``bytes`` argument in ~2.7us vs ~6.5us
#: through the ndpointer conversion (measured) — at serving-frame sizes
#: that delta is bigger than the checksum itself, so the hot sealed-
#: frame paths (bytes in, bytes out) ride char_p and only genuine
#: buffer views (shm streaming chunks) pay the generic binding.
_crc32c_native = False
_crc32c_charp = False

#: software CRC32C table (lazy): correctness-only fallback for
#: VERIFYING crc32c-tagged blobs on a host without the native library
_sw_table = None


def _native():
    global _crc32c_native, _crc32c_charp
    if _crc32c_native is False:
        try:
            from multiverso_tpu import native as _native_mod
            fn = _native_mod.crc32c_fn()
            fastfn = (_native_mod.crc32c_charp_fn()
                      if fn is not None else None)
        except Exception:
            fn = fastfn = None
        # mv-lint: ok(cross-domain-state): idempotent lazy init — every racing thread resolves the same callables (or None) and a double-store of an identical reference is benign; a per-call lock would tax every sealed frame
        _crc32c_charp = fastfn
        # mv-lint: ok(cross-domain-state): same idempotent lazy init (the sentinel store happens LAST so a racing reader never sees the probed flag without the charp binding)
        _crc32c_native = fn
    return _crc32c_native


def _sw_crc32c(data, value: int = 0) -> int:
    """Table-driven CRC32C — the degraded-verify path only (a few MB/s;
    sealing never picks crc32c without the native engine)."""
    global _sw_table
    if _sw_table is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (0x82F63B78 ^ (c >> 1)) if c & 1 else c >> 1
            table.append(c)
        # mv-lint: ok(cross-domain-state): idempotent lazy init — racing threads build identical tables; last store wins harmlessly
        _sw_table = table
    crc = (value & 0xFFFFFFFF) ^ 0xFFFFFFFF
    table = _sw_table
    for b in memoryview(data).cast("B"):
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def crc32c(data, value: int = 0) -> int:
    """CRC32C of ``data`` chained from ``value`` — the zlib.crc32 call
    shape (``crc32c(b, crc32c(a)) == crc32c(a+b)``). Native SSE4.2 when
    the library is loadable, table-driven python otherwise."""
    fn = _native()
    if fn is not None:
        if type(data) is bytes and _crc32c_charp is not None:
            return int(_crc32c_charp(data, len(data),
                                     value & 0xFFFFFFFF))
        arr = np.frombuffer(data, np.uint8)    # zero-copy for bytes/views
        return int(fn(arr, arr.size, value & 0xFFFFFFFF))
    return _sw_crc32c(data, value)


def _crc32c_prefix(blob: bytes, n: int) -> int:
    """CRC32C of ``blob[:n]`` WITHOUT materializing the slice — the
    verify hot path (length rides the C call, so a bytes blob needs no
    memoryview and takes the fast char_p binding)."""
    fn = _native()
    if fn is not None:
        if type(blob) is bytes and _crc32c_charp is not None:
            return int(_crc32c_charp(blob, n, 0))
        arr = np.frombuffer(blob, np.uint8)
        return int(fn(arr[:n], n, 0))
    return _sw_crc32c(memoryview(blob)[:n])


def fast_crc(data, value: int = 0) -> int:
    """The fastest checksum BOTH ends of a same-version wire agree on:
    native CRC32C when loadable, zlib.crc32 otherwise. For transports
    whose two ends run the same build on the same host (the shm wire's
    frame headers + optional payload CRC) — NOT for sealed blobs that
    cross version boundaries; those carry the algorithm in the trailer
    tag instead."""
    fn = _native()
    if fn is not None:
        if type(data) is bytes and _crc32c_charp is not None:
            return int(_crc32c_charp(data, len(data),
                                     value & 0xFFFFFFFF))
        arr = np.frombuffer(data, np.uint8)
        return int(fn(arr, arr.size, value & 0xFFFFFFFF))
    return zlib.crc32(data, value) & 0xFFFFFFFF


def _zlib_crc_chunked(body: bytes) -> int:
    """Legacy-seal CRC32, computed over bounded chunks (GIL release per
    chunk — the pure-zlib fallback the module docstring names)."""
    view = memoryview(body)
    crc = 0
    for off in range(0, len(view), _ZLIB_CHUNK):
        crc = zlib.crc32(view[off:off + _ZLIB_CHUNK], crc)
    return crc & 0xFFFFFFFF


# -- sealing ----------------------------------------------------------------

def _seal(body: bytes) -> bytes:
    """Append the versioned trailer: hardware-CRC32C tagged when the
    native engine is loadable, the legacy chunked-zlib CRC32 otherwise
    (old readers keep verifying what a degraded host seals)."""
    if _native() is not None:
        return b"".join((body, _U32.pack(crc32c(body)),
                         bytes((TAG_CRC32C,))))
    return body + _U32.pack(_zlib_crc_chunked(body))


def seal_frame(body: bytes) -> bytes:
    """Public sealing for satellite planes (elastic shard moves,
    replica fan-out blobs, serving lookup frames): the same versioned
    trailer every window blob carries, so one corruption posture covers
    every byte that crosses a process boundary."""
    return _seal(body)


def seal_trailer(parts) -> bytes:
    """The :func:`seal_frame` trailer for a body given as a SEQUENCE of
    buffers, computed by streaming — ``seal_frame(b"".join(parts)) ==
    b"".join(parts) + seal_trailer(parts)``, without ever concatenating
    the parts. For single-copy frame builders (the tcp wire writes
    header and chunk straight into its wire buffer and appends this
    trailer; a 4 MiB chunk never exists as a third intermediate copy)."""
    if _native() is not None:
        crc = 0
        for p in parts:
            crc = crc32c(p, crc)
        return _U32.pack(crc & 0xFFFFFFFF) + bytes((TAG_CRC32C,))
    crc = 0
    for p in parts:
        view = memoryview(p)
        for off in range(0, len(view), _ZLIB_CHUNK):
            crc = zlib.crc32(view[off:off + _ZLIB_CHUNK], crc)
    return _U32.pack(crc & 0xFFFFFFFF)


def seal_frame_legacy(body: bytes) -> bytes:
    """The pre-round-19 CRC32 seal — kept for the cross-version
    round-trip drills (a new reader must open old blobs); runtime
    sealing always goes through :func:`seal_frame`."""
    return body + _U32.pack(_zlib_crc_chunked(body))


# -- verification -----------------------------------------------------------

def _count_failure() -> None:
    from multiverso_tpu.telemetry import metrics as _tmetrics
    _tmetrics.counter("wire.crc_failures").inc()


def _verify(blob: bytes) -> int:
    """Verify ``blob``'s trailer; returns the BODY length (the trailer
    length differs per algorithm tag). Raises ``WireCorruption``
    (counting ``wire.crc_failures``) on mismatch, truncation or an
    unknown reserved tag. Runs BEFORE any parsing so corrupt bytes
    never reach the decoders."""
    n = len(blob)
    view = memoryview(blob)
    tag = blob[-1] if n else -1
    # legacy checks ride the same chunked loop as legacy sealing (one
    # monolithic zlib.crc32 over a multi-MB body would pin the GIL for
    # ~ms — exactly what _ZLIB_CHUNK exists to avoid)
    if tag == TAG_CRC32C and n > TAGGED_TRAILER_BYTES:
        body = n - TAGGED_TRAILER_BYTES
        if _crc32c_prefix(blob, body) == _U32.unpack_from(blob, body)[0]:
            return body
        # a LEGACY blob whose crc32 high byte happens to be the tag
        # value: fall through to the legacy check before failing
        if (_zlib_crc_chunked(view[:n - CRC_TRAILER_BYTES])
                == _U32.unpack_from(blob, n - CRC_TRAILER_BYTES)[0]):
            return n - CRC_TRAILER_BYTES
        _count_failure()
        raise WireCorruption(
            f"wire blob failed its CRC32C seal ({n} bytes) — corrupted "
            f"or truncated frame")
    if n > CRC_TRAILER_BYTES and (
            _zlib_crc_chunked(view[:n - CRC_TRAILER_BYTES])
            == _U32.unpack_from(blob, n - CRC_TRAILER_BYTES)[0]):
        return n - CRC_TRAILER_BYTES
    if TAG_BASE <= tag <= TAG_BASE + 0x0F and n > TAGGED_TRAILER_BYTES:
        _count_failure()
        raise WireCorruption(
            f"wire blob carries unknown seal trailer tag {tag:#x} "
            f"({n} bytes) — sealed by a newer writer, or corrupted in "
            f"the trailer; refusing to parse")
    _count_failure()
    raise WireCorruption(
        f"wire blob failed CRC check ({n} bytes) — corrupted or "
        f"truncated frame")


def open_frame(blob: bytes) -> bytes:
    """Verify + strip a :func:`seal_frame` trailer (either algorithm);
    raises ``WireCorruption`` (counting ``wire.crc_failures``) on
    mismatch."""
    return blob[:_verify(blob)]


def check_crc(blob: bytes) -> None:
    """Verify a sealed blob's trailer; raises ``WireCorruption``
    (counting ``wire.crc_failures``) on mismatch or truncation. Runs
    BEFORE any parsing so corrupt bytes never reach the decoders.
    Front-anchored decoders (the window codec walks a cursor from byte
    0 and never reads the trailer) can call this without caring which
    trailer length the blob carries."""
    _verify(blob)
