"""Flat binary codec for the windowed engine's wire (sync/server.py).

The window exchange moves lists of ``(kind, table_id, payload)`` verb
records whose payloads are almost entirely numpy arrays — ``(row_ids,
deltas)`` batches, ``(keys, values)`` pairs, compressed-wire dicts.
Pickle walks that object graph, copies every buffer into its output
stream, and walks it again on the far side; for payloads that are
already contiguous ndarrays that is pure overhead. This codec writes a
small header (verb kinds, table ids, entry keys, dtype/shape tags)
followed by the raw array bytes, and decodes arrays ZERO-COPY with
``np.frombuffer`` against the received blob (decoded arrays are
read-only views — every consumer in the parts protocol copies before
mutating, e.g. ``np.concatenate`` / ``np.asarray`` merges).

The flat layout is also what lets the same bytes ride either wire: a
pickled object graph can only live on the host, but a header +
contiguous-segments blob is indistinguishable from device memory, so
the transport decision (host staging allgather vs device collectives —
the reference's payload-size-adaptive wire pick,
allreduce_engine.cpp:31-55) needs no re-serialization.

Wire format (all explicitly little-endian; dtype tags carry their own
byte order, e.g. ``<f4``, so a big-endian array is normalized at encode
and decodes correctly anywhere):

* blob[0] — blob kind: ``KIND_WINDOW`` for a verb window, versioned;
  ``KIND_HEAD_BARRIER`` marks a non-verb head marker blob
  (sync/server.py exchanges those so a cross-rank verb-vs-barrier head
  mismatch fails the loud SPMD CHECK instead of deadlocking).
* u32 exchange sequence number (failsafe): each rank stamps its
  position in the window-exchange stream; the engine CHECKs that every
  received frame carries ITS sequence, so a rank that re-entered the
  exchange alone (asymmetric corruption retry) pairs with its peers'
  NEXT round as a loud desync error, never a silent mismatched merge.
* u32 verb count, then per verb: u8 kind char, u32 table id, u8 entry
  count, then per entry: u8 key length + key utf8, u8 value tag + the
  tag's body.
* trailing u32 — CRC32 over everything before it (failsafe subsystem):
  decode verifies it BEFORE parsing, so a flipped bit or truncated
  frame raises ``WireCorruption`` instead of decoding garbage.

Value tags::

    n  None
    a  ndarray   u8 dtype-str len, dtype str, u8 ndim, i64 dims, raw
    v  DEFERRED ndarray — same header as 'a', NO raw bytes (the owner
       keeps the array locally; it rides the device wire instead)
    o  AddOption  (i64 worker_id, f64 momentum/learning_rate/rho/lambda_)
    g  GetOption  (i64 worker_id)
    d  nested dict (compressed payloads): u8 count + entries
    t  bool (u8)    i  int (i64)    f  float (f64)
    s  str / b  bytes: i64 length + raw
    p  pickle fallback (anything else — exotic options, user payloads,
       extension-dtype arrays whose dtype the flat header cannot
       represent, see dtype_wire_safe): i64 length + pickle bytes
"""

from __future__ import annotations

import pickle
import struct
from typing import List, Tuple

import numpy as np

from multiverso_tpu.failsafe.errors import WireCorruption
# sealing lives in parallel/seal.py (jax-free — the replica plane's
# reader processes verify fan-out blobs without importing this codec's
# updater-option tags); re-exported here so every call site keeps one
# import home and one corruption posture
from multiverso_tpu.parallel.seal import (  # noqa: F401
    CRC_TRAILER_BYTES, _seal, check_crc, open_frame, seal_frame)
from multiverso_tpu.updaters.base import AddOption, GetOption

#: first byte of every exchanged blob — lets the far side tell a verb
#: window from a non-verb head marker (and catch format drift loudly)
KIND_WINDOW = 0x57      # 'W'
KIND_HEAD_BARRIER = 0x42  # 'B'

_U8 = struct.Struct("<B")
_U32 = struct.Struct("<I")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")
_VERB = struct.Struct("<BIB")      # kind char, table id, entry count
_ADD_OPT = struct.Struct("<qdddd")


class DeferredArray:
    """Placeholder for an ndarray whose BYTES did not ride the host
    wire: the encoder wrote only its dtype/shape header, and the owning
    rank keeps the real array in ``local`` (None on every other rank
    after decode). The windowed engine substitutes these for large Add
    values when the device transport is selected — every rank still
    sees the full shape metadata (needed for lockstep bucket math), and
    the values move through the table's device-parts collectives
    instead of the host staging wire."""

    __slots__ = ("dtype", "shape", "local")

    def __init__(self, dtype, shape, local=None):
        self.dtype = np.dtype(dtype)
        self.shape = tuple(int(s) for s in shape)
        self.local = local

    @classmethod
    def of(cls, arr: np.ndarray) -> "DeferredArray":
        arr = np.asarray(arr)
        return cls(arr.dtype, arr.shape, local=arr)

    @property
    def size(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n

    @property
    def nbytes(self) -> int:
        return self.size * self.dtype.itemsize

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        tag = "local" if self.local is not None else "remote"
        return f"DeferredArray({self.dtype.str}, {self.shape}, {tag})"


def payload_nbytes(payload: dict) -> int:
    """Array bytes a verb payload carries — the ONE byte-accounting
    rule shared by the worker-side telemetry counters (tables/base.py)
    and the engine's window byte budget (sync/server.py), so the two
    sides can never drift. DeferredArray placeholders count zero here:
    their bytes ride the device wire, not this payload."""
    total = 0
    for v in payload.values():
        if isinstance(v, np.ndarray):
            total += v.nbytes
        elif isinstance(v, dict):       # compressed-wire payloads
            total += sum(a.nbytes for a in v.values()
                         if isinstance(a, np.ndarray))
    return total


def payload_has_deferred(payload: dict) -> bool:
    """True when any value of a decoded verb payload is a DeferredArray
    placeholder — its bytes ride the DEVICE wire, so applying the verb
    is a collective device program. The pipelined engine's overlap gate
    (sync/server.py _mh_fence_cause) fences such windows: a device
    collective on the apply thread must never run concurrently with the
    exchange thread's host allgather (rank-divergent interleavings
    deadlock the world). Deferral only ever replaces a payload's
    top-level ``values`` entry, but checking every value is as cheap."""
    for v in payload.values():
        if isinstance(v, DeferredArray):
            return True
    return False


def dtype_wire_safe(dt) -> bool:
    """True when ``dt`` survives the flat wire: its ``.str`` tag decodes
    back to the SAME dtype. Extension dtypes (e.g. ml_dtypes.bfloat16,
    which jax registers) stringify as opaque void tags like ``<V2`` —
    encoding those flat would decode as void (silent corruption), and
    ``memoryview`` refuses their buffers anyway, so their arrays ride
    the pickle fallback instead (correct, just slower) and the engine
    never defers them to the device wire."""
    dt = np.dtype(dt)
    try:
        return not dt.hasobject and np.dtype(dt.str) == dt
    except TypeError:
        return False


def _norm_array(v: np.ndarray) -> np.ndarray:
    """Contiguous, little-endian view/copy of ``v`` for the wire."""
    v = np.ascontiguousarray(v)
    if v.dtype.byteorder == ">":
        v = v.astype(v.dtype.newbyteorder("<"))
    return v


def _encode_array_header(parts: list, tag: bytes, dtype: np.dtype,
                         shape: Tuple[int, ...]) -> None:
    ds = dtype.str.encode("ascii")
    parts.append(tag)
    parts.append(_U8.pack(len(ds)))
    parts.append(ds)
    parts.append(_U8.pack(len(shape)))
    for dim in shape:
        parts.append(_I64.pack(dim))


def _encode_value(parts: list, v) -> None:
    if v is None:
        parts.append(b"n")
    elif isinstance(v, np.ndarray) and dtype_wire_safe(v.dtype):
        v = _norm_array(v)
        _encode_array_header(parts, b"a", v.dtype, v.shape)
        if v.size == 0:
            pass                       # no payload bytes
        elif v.ndim == 0:
            parts.append(v.tobytes())  # memoryview can't cast 0-d
        else:
            parts.append(memoryview(v).cast("B"))
    elif isinstance(v, DeferredArray):
        _encode_array_header(parts, b"v", v.dtype, v.shape)
    elif type(v) is AddOption:
        parts.append(b"o")
        parts.append(_ADD_OPT.pack(int(v.worker_id), float(v.momentum),
                                   float(v.learning_rate), float(v.rho),
                                   float(v.lambda_)))
    elif type(v) is GetOption:
        parts.append(b"g")
        parts.append(_I64.pack(int(v.worker_id)))
    elif isinstance(v, dict):
        if len(v) > 255:
            raise ValueError("wire dict too wide")
        parts.append(b"d")
        parts.append(_U8.pack(len(v)))
        for key in sorted(v):
            kb = str(key).encode("utf-8")
            parts.append(_U8.pack(len(kb)))
            parts.append(kb)
            _encode_value(parts, v[key])
    elif isinstance(v, bool):          # before int: bool is an int subtype
        parts.append(b"t")
        parts.append(_U8.pack(1 if v else 0))
    elif isinstance(v, int) and -(2 ** 63) <= v < 2 ** 63:
        parts.append(b"i")
        parts.append(_I64.pack(v))
    elif isinstance(v, float):
        parts.append(b"f")
        parts.append(_F64.pack(v))
    elif isinstance(v, str):
        sb = v.encode("utf-8")
        parts.append(b"s")
        parts.append(_I64.pack(len(sb)))
        parts.append(sb)
    elif isinstance(v, bytes):
        parts.append(b"b")
        parts.append(_I64.pack(len(v)))
        parts.append(v)
    else:
        # option subclasses, huge ints, user table payloads: correctness
        # over speed for the exotic tail
        pb = pickle.dumps(v)
        parts.append(b"p")
        parts.append(_I64.pack(len(pb)))
        parts.append(pb)


def encode_window(verbs: List[Tuple[str, int, dict]],
                  seq: int = 0) -> bytes:
    """``[(kind, table_id, payload), ...]`` -> wire bytes. ``kind`` is a
    single ascii char ('A'/'G'); payload is the verb's payload dict;
    ``seq`` stamps the sender's window-exchange position (see module
    docstring — the engine's lockstep-desync tripwire)."""
    parts: list = [_U8.pack(KIND_WINDOW), _U32.pack(seq & 0xFFFFFFFF),
                   _U32.pack(len(verbs))]
    for kind, table_id, payload in verbs:
        if len(payload) > 255:
            raise ValueError("wire payload too wide")
        parts.append(_VERB.pack(ord(kind), table_id, len(payload)))
        for key in sorted(payload):
            kb = key.encode("utf-8")
            if len(kb) > 255:
                raise ValueError("wire payload key too long")
            parts.append(_U8.pack(len(kb)))
            parts.append(kb)
            _encode_value(parts, payload[key])
    blob = _seal(b"".join(parts))
    # telemetry byte accounting (per window — not per element, so the
    # registry lookup is off the hot loop); NULL instrument when off
    from multiverso_tpu.telemetry import metrics as _tmetrics
    _tmetrics.counter("wire.encode_bytes").inc(len(blob))
    return blob


class _Cursor:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def unpack(self, st: struct.Struct):
        vals = st.unpack_from(self.buf, self.pos)
        # mv-lint: ok(cross-domain-state): a _Cursor is constructed, walked and dropped inside ONE decode call — instance-local state; the class-level write aggregation is instance-blind here
        self.pos += st.size
        return vals

    def take(self, n: int):
        out = self.buf[self.pos: self.pos + n]
        if len(out) != n:
            raise ValueError("wire blob truncated")
        self.pos += n
        return out


def _decode_value(cur: _Cursor):
    tag = cur.take(1)
    if tag == b"n":
        return None
    if tag in (b"a", b"v"):
        (dlen,) = cur.unpack(_U8)
        dtype = np.dtype(bytes(cur.take(dlen)).decode("ascii"))
        (ndim,) = cur.unpack(_U8)
        shape = tuple(cur.unpack(_I64)[0] for _ in range(ndim))
        if tag == b"v":
            return DeferredArray(dtype, shape)
        count = 1
        for dim in shape:
            count *= dim
        arr = np.frombuffer(cur.buf, dtype, count=count, offset=cur.pos)
        cur.pos += count * dtype.itemsize
        return arr.reshape(shape)
    if tag == b"o":
        wid, mom, lr, rho, lam = cur.unpack(_ADD_OPT)
        return AddOption(worker_id=wid, momentum=mom, learning_rate=lr,
                         rho=rho, lambda_=lam)
    if tag == b"g":
        return GetOption(worker_id=cur.unpack(_I64)[0])
    if tag == b"d":
        (n,) = cur.unpack(_U8)
        out = {}
        for _ in range(n):
            (klen,) = cur.unpack(_U8)
            key = bytes(cur.take(klen)).decode("utf-8")
            out[key] = _decode_value(cur)
        return out
    if tag == b"t":
        return bool(cur.unpack(_U8)[0])
    if tag == b"i":
        return cur.unpack(_I64)[0]
    if tag == b"f":
        return cur.unpack(_F64)[0]
    if tag == b"s":
        (n,) = cur.unpack(_I64)
        return bytes(cur.take(n)).decode("utf-8")
    if tag == b"b":
        (n,) = cur.unpack(_I64)
        return bytes(cur.take(n))
    if tag == b"p":
        (n,) = cur.unpack(_I64)
        return pickle.loads(bytes(cur.take(n)))
    raise ValueError(f"unknown wire tag {tag!r}")


def decode_window_seq(blob: bytes):
    """Wire bytes -> ``(seq, [(kind, table_id, payload), ...])``. Array
    entries are zero-copy READ-ONLY views into ``blob``. The CRC32
    trailer is verified FIRST: corruption raises ``WireCorruption``
    before any byte is parsed."""
    check_crc(blob)
    from multiverso_tpu.telemetry import metrics as _tmetrics
    _tmetrics.counter("wire.decode_bytes").inc(len(blob))
    cur = _Cursor(blob)
    (magic,) = cur.unpack(_U8)
    if magic != KIND_WINDOW:
        raise ValueError(f"not a window blob (leading byte {magic:#x})")
    (seq,) = cur.unpack(_U32)
    (count,) = cur.unpack(_U32)
    out = []
    for _ in range(count):
        kind, table_id, n_entries = cur.unpack(_VERB)
        payload = {}
        for _ in range(n_entries):
            (klen,) = cur.unpack(_U8)
            key = bytes(cur.take(klen)).decode("utf-8")
            payload[key] = _decode_value(cur)
        out.append((chr(kind), table_id, payload))
    return seq, out


def decode_window(blob: bytes) -> List[Tuple[str, int, dict]]:
    """``decode_window_seq`` without the sequence number."""
    return decode_window_seq(blob)[1]


def encode_head_barrier(msg_type: int) -> bytes:
    """Marker blob a rank exchanges when its window HEAD is a non-verb
    message (StoreLoad / barrier ping / FinishTrain): the peer ranks
    must be at the same head kind, and the loud mismatch CHECK needs the
    kinds on the wire to compare (sync/server.py _mh_windows)."""
    return _seal(_U8.pack(KIND_HEAD_BARRIER) + _I64.pack(int(msg_type)))


def decode_head_kind(blob: bytes):
    """First-byte dispatch: ('window', None) or ('barrier', msg_type) —
    raises on anything else (format drift is a loud error). Barrier
    markers are fully consumed here, so their CRC is verified here;
    window blobs defer to decode_window's check."""
    if not blob:
        raise ValueError("empty wire blob")
    lead = blob[0]
    if lead == KIND_WINDOW:
        return "window", None
    if lead == KIND_HEAD_BARRIER:
        check_crc(blob)
        return "barrier", _I64.unpack_from(blob, 1)[0]
    raise ValueError(f"unknown wire blob kind {lead:#x}")
