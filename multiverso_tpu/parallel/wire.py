"""Flat binary codec for the windowed engine's wire (sync/server.py).

The window exchange moves lists of ``(kind, table_id, payload)`` verb
records whose payloads are almost entirely numpy arrays — ``(row_ids,
deltas)`` batches, ``(keys, values)`` pairs, compressed-wire dicts.
Pickle walks that object graph, copies every buffer into its output
stream, and walks it again on the far side; for payloads that are
already contiguous ndarrays that is pure overhead. This codec writes a
small header (verb kinds, table ids, entry keys, dtype/shape tags)
followed by the raw array bytes, and decodes arrays ZERO-COPY with
``np.frombuffer`` against the received blob (decoded arrays are
read-only views — every consumer in the parts protocol copies before
mutating, e.g. ``np.concatenate`` / ``np.asarray`` merges).

The flat layout is also what lets the same bytes ride either wire: a
pickled object graph can only live on the host, but a header +
contiguous-segments blob is indistinguishable from device memory, so
the transport decision (host staging allgather vs device collectives —
the reference's payload-size-adaptive wire pick,
allreduce_engine.cpp:31-55) needs no re-serialization.

Round 19 — the VALUE grammar (tags, cursor, array headers,
DeferredArray) lives jax-free in :mod:`multiverso_tpu.parallel.flat`
(this module pulls jax via ``updaters.base`` for its option tags; the
replica serve protocol speaks the same grammar without that import).
This module layers the engine-specific pieces on top: the window/
barrier frame kinds, the exchange SEQ stamp, and the Add/GetOption
record tags via the flat codec's extension hook.

Wire format (all explicitly little-endian; dtype tags carry their own
byte order, e.g. ``<f4``, so a big-endian array is normalized at encode
and decodes correctly anywhere):

* blob[0] — blob kind: ``KIND_WINDOW`` for a verb window, versioned;
  ``KIND_HEAD_BARRIER`` marks a non-verb head marker blob
  (sync/server.py exchanges those so a cross-rank verb-vs-barrier head
  mismatch fails the loud SPMD CHECK instead of deadlocking).
* u32 exchange sequence number (failsafe): each rank stamps its
  position in the window-exchange stream; the engine CHECKs that every
  received frame carries ITS sequence, so a rank that re-entered the
  exchange alone (asymmetric corruption retry) pairs with its peers'
  NEXT round as a loud desync error, never a silent mismatched merge.
* u32 verb count, then per verb: u8 kind char, u32 table id, u8 entry
  count, then per entry: u8 key length + key utf8, u8 value tag + the
  tag's body.
* trailing seal (parallel/seal.py, round 19: versioned — hardware
  CRC32C tagged, legacy CRC32 still verifies): decode verifies it
  BEFORE parsing, so a flipped bit or truncated frame raises
  ``WireCorruption`` instead of decoding garbage.

Value tags (core grammar in flat.py, options added here)::

    n  None
    a  ndarray   u8 dtype-str len, dtype str, u8 ndim, i64 dims, raw
    v  DEFERRED ndarray — same header as 'a', NO raw bytes (the owner
       keeps the array locally; it rides the device wire instead)
    o  AddOption  (i64 worker_id, f64 momentum/learning_rate/rho/lambda_)
    g  GetOption  (i64 worker_id)
    d  nested dict (compressed payloads): u8 count + entries
    l  list: u32 count + values
    t  bool (u8)    i  int (i64)    f  float (f64)
    s  str / b  bytes: i64 length + raw
    q  COMPRESSED ndarray (parallel/compress.py tagged envelope —
       int8 row quantization on lossy-opted tables' Add deltas);
       decode is eager, and the SENDING rank materializes its own
       window through the same envelope decode so SPMD replicas stay
       bit-identical under quantization (sync/server.py)
    p  pickle fallback (anything else — exotic options, user payloads,
       extension-dtype arrays whose dtype the flat header cannot
       represent, see dtype_wire_safe): i64 length + pickle bytes
"""

from __future__ import annotations

import struct
from typing import List, Tuple

import numpy as np

from multiverso_tpu.failsafe.errors import WireCorruption  # noqa: F401
# tagged codec envelopes (round 21): the window byte budget must count
# a compressed value at its envelope size, not zero
from multiverso_tpu.parallel.compress import CompressedArray
# the jax-free codec core (round 19): tags, cursor, array framing —
# shared with the replica serve protocol's flat frames
from multiverso_tpu.parallel.flat import (  # noqa: F401
    DeferredArray, Extension, _Cursor, _norm_array, decode_value,
    dtype_wire_safe, encode_value)
# sealing lives in parallel/seal.py (jax-free — the replica plane's
# reader processes verify fan-out blobs without importing this codec's
# updater-option tags); re-exported here so every call site keeps one
# import home and one corruption posture
from multiverso_tpu.parallel.seal import (  # noqa: F401
    CRC_TRAILER_BYTES, _seal, check_crc, open_frame, seal_frame)
from multiverso_tpu.updaters.base import AddOption, GetOption

#: first byte of every exchanged blob — lets the far side tell a verb
#: window from a non-verb head marker (and catch format drift loudly)
KIND_WINDOW = 0x57      # 'W'
KIND_HEAD_BARRIER = 0x42  # 'B'

_U8 = struct.Struct("<B")
_U32 = struct.Struct("<I")
_I64 = struct.Struct("<q")
_VERB = struct.Struct("<BIB")      # kind char, table id, entry count
_ADD_OPT = struct.Struct("<qdddd")


class _OptionExt(Extension):
    """The engine's updater-option record tags, layered over the flat
    core (the one jax-coupled piece of the grammar: the option classes
    live beside the updaters)."""

    def encode(self, parts: list, v) -> bool:
        if type(v) is AddOption:
            parts.append(b"o")
            parts.append(_ADD_OPT.pack(
                int(v.worker_id), float(v.momentum),
                float(v.learning_rate), float(v.rho), float(v.lambda_)))
            return True
        if type(v) is GetOption:
            parts.append(b"g")
            parts.append(_I64.pack(int(v.worker_id)))
            return True
        return False

    def decode(self, tag: bytes, cur: _Cursor):
        if tag == b"o":
            wid, mom, lr, rho, lam = cur.unpack(_ADD_OPT)
            return True, AddOption(worker_id=wid, momentum=mom,
                                   learning_rate=lr, rho=rho, lambda_=lam)
        if tag == b"g":
            return True, GetOption(worker_id=cur.unpack(_I64)[0])
        return False, None


_EXT = _OptionExt()


def _encode_value(parts: list, v) -> None:
    encode_value(parts, v, _EXT)


def _decode_value(cur: _Cursor):
    return decode_value(cur, _EXT)


def payload_nbytes(payload: dict) -> int:
    """Array bytes a verb payload carries — the ONE byte-accounting
    rule shared by the worker-side telemetry counters (tables/base.py)
    and the engine's window byte budget (sync/server.py), so the two
    sides can never drift. DeferredArray placeholders count zero here:
    their bytes ride the device wire, not this payload."""
    total = 0
    for v in payload.values():
        if isinstance(v, np.ndarray):
            total += v.nbytes
        elif isinstance(v, CompressedArray):
            total += v.nbytes           # the envelope IS the wire cost
        elif isinstance(v, dict):       # compressed-wire payloads
            total += sum(a.nbytes for a in v.values()
                         if isinstance(a, np.ndarray))
    return total


def payload_has_deferred(payload: dict) -> bool:
    """True when any value of a decoded verb payload is a DeferredArray
    placeholder — its bytes ride the DEVICE wire, so applying the verb
    is a collective device program. The pipelined engine's overlap gate
    (sync/server.py _mh_fence_cause) fences such windows: a device
    collective on the apply thread must never run concurrently with the
    exchange thread's host allgather (rank-divergent interleavings
    deadlock the world). Deferral only ever replaces a payload's
    top-level ``values`` entry, but checking every value is as cheap."""
    for v in payload.values():
        if isinstance(v, DeferredArray):
            return True
    return False


def encode_window(verbs: List[Tuple[str, int, dict]],
                  seq: int = 0) -> bytes:
    """``[(kind, table_id, payload), ...]`` -> wire bytes. ``kind`` is a
    single ascii char ('A'/'G'); payload is the verb's payload dict;
    ``seq`` stamps the sender's window-exchange position (see module
    docstring — the engine's lockstep-desync tripwire)."""
    parts: list = [_U8.pack(KIND_WINDOW), _U32.pack(seq & 0xFFFFFFFF),
                   _U32.pack(len(verbs))]
    for kind, table_id, payload in verbs:
        if len(payload) > 255:
            raise ValueError("wire payload too wide")
        parts.append(_VERB.pack(ord(kind), table_id, len(payload)))
        for key in sorted(payload):
            kb = key.encode("utf-8")
            if len(kb) > 255:
                raise ValueError("wire payload key too long")
            parts.append(_U8.pack(len(kb)))
            parts.append(kb)
            _encode_value(parts, payload[key])
    blob = _seal(b"".join(parts))
    # telemetry byte accounting (per window — not per element, so the
    # registry lookup is off the hot loop); NULL instrument when off
    from multiverso_tpu.telemetry import metrics as _tmetrics
    _tmetrics.counter("wire.encode_bytes").inc(len(blob))
    return blob


def decode_window_seq(blob: bytes):
    """Wire bytes -> ``(seq, [(kind, table_id, payload), ...])``. Array
    entries are zero-copy READ-ONLY views into ``blob``. The seal
    trailer is verified FIRST: corruption raises ``WireCorruption``
    before any byte is parsed."""
    check_crc(blob)
    from multiverso_tpu.telemetry import metrics as _tmetrics
    _tmetrics.counter("wire.decode_bytes").inc(len(blob))
    cur = _Cursor(blob)
    (magic,) = cur.unpack(_U8)
    if magic != KIND_WINDOW:
        raise ValueError(f"not a window blob (leading byte {magic:#x})")
    (seq,) = cur.unpack(_U32)
    (count,) = cur.unpack(_U32)
    out = []
    for _ in range(count):
        kind, table_id, n_entries = cur.unpack(_VERB)
        payload = {}
        for _ in range(n_entries):
            (klen,) = cur.unpack(_U8)
            key = bytes(cur.take(klen)).decode("utf-8")
            payload[key] = _decode_value(cur)
        out.append((chr(kind), table_id, payload))
    return seq, out


def decode_window(blob: bytes) -> List[Tuple[str, int, dict]]:
    """``decode_window_seq`` without the sequence number."""
    return decode_window_seq(blob)[1]


def encode_head_barrier(msg_type: int) -> bytes:
    """Marker blob a rank exchanges when its window HEAD is a non-verb
    message (StoreLoad / barrier ping / FinishTrain): the peer ranks
    must be at the same head kind, and the loud mismatch CHECK needs the
    kinds on the wire to compare (sync/server.py _mh_windows)."""
    return _seal(_U8.pack(KIND_HEAD_BARRIER) + _I64.pack(int(msg_type)))


def decode_head_kind(blob: bytes):
    """First-byte dispatch: ('window', None) or ('barrier', msg_type) —
    raises on anything else (format drift is a loud error). Barrier
    markers are fully consumed here, so their CRC is verified here;
    window blobs defer to decode_window's check."""
    if not blob:
        raise ValueError("empty wire blob")
    lead = blob[0]
    if lead == KIND_WINDOW:
        return "window", None
    if lead == KIND_HEAD_BARRIER:
        check_crc(blob)
        return "barrier", _I64.unpack_from(blob, 1)[0]
    raise ValueError(f"unknown wire blob kind {lead:#x}")
