"""Cross-host TCP wire for the windowed engine's exchange.

The reference treats transports as swappable deployment choices behind
one ``NetInterface`` (MPI vs ZMQ, PAPER.md L2); the TPU build grew the
same split one layer at a time — gloo (the boot allgather) is the loud
fallback, the shm wire (parallel/shm_wire.py) is the same-host fast
path, and THIS module is the cross-host member: one framed TCP stream
per (channel, peer), so engine shards and replica subscribers get the
independent exchange channels gloo's single ordered collective stream
cannot offer, across machine boundaries.

Frame grammar (per stream — a stream carries exactly one (channel,
peer) direction pair, so frames never interleave across channels):

* ``[u32 sealed_len][sealed]`` where ``sealed`` is
  ``seal.seal_frame(header | chunk)`` — the versioned CRC32C seal
  (parallel/seal.py) is the integrity layer, so a flipped bit anywhere
  (length prefix, header, body, even the seal's own tag byte) surfaces
  as a typed ``WireCorruption`` BEFORE any field is trusted, never as
  a hang or a garbage array. A corrupted length prefix is bounded
  structurally: ``sealed_len`` may never exceed the chunk cap, so the
  reader refuses it instead of waiting for gigabytes that never come.
* ``header`` packs ``(magic, sender, round, total, off, len, channel,
  blob_crc)``. ``round`` counts exchanges per channel and both sides
  advance it in lockstep (the exchange IS collective): a rank
  re-entering an exchange alone surfaces as a loud round mismatch —
  the same SEQ-stamp posture as the shm wire and the engine's window
  blobs. ``blob_crc`` covers the WHOLE blob (seal.fast_crc), verified
  after reassembly when ``payload_crc`` is on; the engine install
  turns it off because its blobs arrive pre-sealed.
* Blobs larger than the chunk cap ride multiple frames; an empty blob
  still publishes one zero-length frame so readers always have a
  header to consume.

Liveness contract (the shm wire's lesson, restated for sockets):

* a KILLED peer resets/closes its streams — EOF or ECONNRESET mid-
  frame converts to a typed ``ActorDied`` immediately, long before any
  collective deadline;
* a SILENTLY dead host (no RST ever arrives) is caught by the elastic
  lease probe: a stalled exchange consults the membership authority
  ~4x/second and raises the typed ``MembershipChanged`` the lease
  produces;
* everything else is bounded by ``-mv_deadline_s`` (or the caller's
  explicit ``timeout_s``) — expiry raises ``DeadlineExceeded`` with
  the diagnostic bundle, marked fatal (the stream position is unsound;
  the caller must scrap the wire, never retry the round).

Mesh bring-up: each rank binds one listener per channel at
construction; ``listen_endpoints()`` is what the install rendezvous
allgathers (one gloo round), and ``connect()`` dials every HIGHER
rank's listeners while a short-lived accept thread collects the
inbound dials from LOWER ranks (rank 0 dials everyone; the highest
rank only accepts — the fixed direction is what lets a replica reader
bind first and wait for its publisher's dial). Every accepted stream
must open with a sealed hello naming (channel, rank, session token);
foreign dialers are rejected without poisoning the mesh. The accept
thread exits once the mesh is up — steady-state exchanges run entirely
on the caller's thread (a selectors loop interleaving sends and recvs
across all peers, so multi-chunk frames cannot flow-control deadlock
without any receiver threads).

Selection lives in ``multihost.maybe_install_wire``: ``-mv_wire=tcp``
forces this wire; ``auto`` picks shm when every rank shares a host and
tcp when hosts differ AND the engine/replica asked for more than one
channel; gloo stays the loud fallback. This module imports no jax —
the replica reader's scale-out premise extends to the transport.
"""

from __future__ import annotations

import selectors
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from multiverso_tpu.failsafe import deadline as fdeadline
from multiverso_tpu.failsafe.errors import ActorDied, WireCorruption
from multiverso_tpu.parallel import seal
from multiverso_tpu.telemetry import metrics as tmetrics
from multiverso_tpu.utils.log import CHECK, Log

#: frame header: magic u32 | sender u32 | round u64 | total u64 |
#: off u64 | len u32 | channel u32 | blob_crc u32
_HDR_FMT = "<IIQQQIII"
_HDR_LEN = struct.calcsize(_HDR_FMT)

_MAGIC = 0x4D565443        # "MVTC"
_HELLO_MAGIC = 0x4D564849  # "MVHI"

#: how often a stalled exchange consults the elastic membership lease
#: (shm_wire._PROBE_PERIOD_S rationale: detection latency far under
#: any -mv_deadline_s worth arming)
_PROBE_PERIOD_S = 0.25

#: mesh bring-up bound when neither timeout_s nor -mv_deadline_s is
#: set — connect() is bounded BY CONSTRUCTION (a half-up mesh must
#: never hang the install)
_CONNECT_TIMEOUT_S = 30.0

_SEND_SLICE = 1 << 18
_RECV_SLICE = 1 << 20

#: hello frames are tiny (header + token); anything bigger is foreign
_HELLO_CAP = 4096


def _dial_host() -> str:
    """The address this host advertises in listen_endpoints(). The
    -mv_wire_hostname flag deliberately does NOT redirect this —
    identity labels may be overridden for the loopback cross-host
    drills, but dialing always rides a reachable address."""
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"


def _peer_loss_probe(what: str):
    """A stalled exchange asks the elastic authority whether a peer is
    DEAD (lease expired). TCP catches killed processes for free (the
    kernel sends RST/FIN), but a powered-off HOST sends nothing — the
    probe converts that silence into a typed MembershipChanged before
    the collective deadline. Returns the error to raise, or None."""
    try:
        from multiverso_tpu import elastic
        if not elastic.enabled():
            return None
        return elastic.peer_loss(what)
    except Exception:       # the deadline still bounds the wait
        return None


def _chaos():
    """The active chaos injector (failsafe/chaos.py), or None. Lazy:
    the wire must stay importable (and jax-free) without the failsafe
    flag machinery fully configured."""
    try:
        from multiverso_tpu.failsafe import chaos
        return chaos.get()
    except Exception:
        return None


class TcpWire:
    """Cross-host allgather-bytes transport over framed TCP streams.

    One instance per process per world; ``exchange(blob, channel)`` is
    collective per channel — every rank of the world must call it for
    the same channel in the same per-channel order (the engine's SPMD
    window contract guarantees exactly that, per shard). Construction
    binds the listeners; ``connect()`` (after the endpoint rendezvous)
    establishes the full mesh."""

    #: transport label (multihost.wire_name reads this off the
    #: installed instance)
    name = "tcp"

    def __init__(self, token: str, rank: int, nprocs: int,
                 channels: int, data_bytes: int,
                 payload_crc: bool = True):
        CHECK(nprocs >= 2, "TcpWire needs a multi-process world")
        CHECK(channels >= 1, "TcpWire needs at least one channel")
        self.token = token
        self.rank = rank
        self.nprocs = nprocs
        self.channels = channels
        #: chunk cap per frame — large blobs ride multiple frames so a
        #: corrupted length prefix can never demand an unbounded read
        self.chunk = max(4096, min(int(data_bytes), 4 << 20))
        self._max_frame = _HDR_LEN + self.chunk + 64
        self.payload_crc = bool(payload_crc)
        #: established streams: (channel, peer_rank) -> socket
        self._conn: Dict[Tuple[int, int], socket.socket] = {}
        #: persistent per-stream inbound buffers — one recv may pull
        #: the tail of this round together with the head of the peer's
        #: NEXT round; leftover bytes must survive across exchanges
        self._inbuf: Dict[Tuple[int, int], bytearray] = {}
        self._round = [0] * channels
        #: reusable recv landing pads, ONE PER CHANNEL — recv()
        #: allocating a fresh 1 MiB bytes per wakeup costs real
        #: page-fault time at wire speed, so recv_into a persistent
        #: scratch keeps the pages hot. Per channel, not per wire:
        #: each channel's exchange is single-threaded, but different
        #: channels run from different shard threads concurrently
        #: (the sharded engine's model) and a shared pad would let one
        #: channel's recv overwrite another's bytes mid-append
        self._scratch = [bytearray(_RECV_SLICE) for _ in range(channels)]
        self._closed = False
        self._lock = threading.Lock()
        self._accept_exc: Optional[BaseException] = None
        self._accept_thread: Optional[threading.Thread] = None
        self.frame_hw_bytes = 0
        self.stall_s = 0.0
        self._t_crc = tmetrics.counter("tcp_wire.crc_failures")
        self._t_rounds = tmetrics.counter("tcp_wire.exchanges")
        self._t_bytes = tmetrics.counter("tcp_wire.bytes_out")
        self._t_stall = tmetrics.counter("tcp_wire.stall_s")
        self._t_connects = tmetrics.counter("tcp_wire.connects")
        self._t_hw = tmetrics.gauge("tcp_wire.frame_hw_bytes")
        self._listeners: List[socket.socket] = []
        self._endpoints: List[Tuple[str, int]] = []
        host = _dial_host()
        try:
            for _ch in range(channels):
                ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                ls.bind(("0.0.0.0", 0))
                ls.listen(max(8, nprocs))
                self._listeners.append(ls)
                self._endpoints.append((host, ls.getsockname()[1]))
        except OSError:
            for ls in self._listeners:
                ls.close()
            raise

    # -- wiring --------------------------------------------------------------

    def listen_endpoints(self) -> List[Tuple[str, int]]:
        """This rank's (host, port) per channel — what the install
        rendezvous allgathers so every rank can dial every listener."""
        return list(self._endpoints)

    def connect(self, world_endpoints,
                timeout_s: Optional[float] = None) -> None:
        """Establish the full mesh: dial every HIGHER rank's listeners
        (one stream per channel, opened with a sealed hello naming
        (channel, rank, token)) while the accept thread collects the
        LOWER ranks' inbound dials. ``world_endpoints`` maps rank ->
        [(host, port) per channel]; ``None`` means wait for inbound
        only (legal only for the highest rank — the replica reader's
        bind-then-wait posture). Bounded by ``timeout_s`` /
        ``-mv_deadline_s`` / a 30s floor; an incomplete mesh raises
        instead of hanging, and the wire must then be scrapped."""
        CHECK(not self._closed, "tcp wire used after close")
        CHECK(world_endpoints is not None or self.rank == self.nprocs - 1,
              f"tcp wire rank {self.rank} must dial ranks "
              f"{self.rank + 1}..{self.nprocs - 1} but got no endpoints")
        deadline = (timeout_s if timeout_s is not None
                    else (fdeadline.timeout_or_none()
                          or _CONNECT_TIMEOUT_S))
        t_end = time.monotonic() + deadline
        expected = self.rank * self.channels     # lower ranks dial us
        self._accept_exc = None
        self._accept_thread = threading.Thread(
            target=self._accept_loop, args=(expected, t_end),
            name=f"mv-tcpwire-accept-r{self.rank}", daemon=True)
        self._accept_thread.start()
        try:
            for r in range(self.rank + 1, self.nprocs):
                eps = world_endpoints[r]
                CHECK(len(eps) >= self.channels,
                      f"tcp wire rank {r} advertised {len(eps)} "
                      f"endpoints for {self.channels} channels")
                for ch in range(self.channels):
                    host, port = eps[ch]
                    remaining = t_end - time.monotonic()
                    if remaining <= 0:
                        fdeadline.raise_deadline(
                            f"tcp wire mesh connect (dial rank {r} "
                            f"channel {ch})", deadline, fatal=True)
                    try:
                        s = socket.create_connection(
                            (host, int(port)),
                            timeout=max(0.1, remaining))
                    except OSError as e:
                        raise ActorDied(
                            f"tcp wire peer rank {r} (dial "
                            f"{host}:{port}, channel {ch})", e)
                    s.setsockopt(socket.IPPROTO_TCP,
                                 socket.TCP_NODELAY, 1)
                    hello = struct.pack(
                        "<III", _HELLO_MAGIC, ch, self.rank
                    ) + self.token.encode("utf-8")
                    sealed = seal.seal_frame(hello)
                    s.sendall(struct.pack("<I", len(sealed)) + sealed)
                    with self._lock:
                        self._conn[(ch, r)] = s
        except BaseException:
            self.close()
            raise
        self._accept_thread.join(max(0.0, t_end - time.monotonic()) + 1.0)
        total = (self.nprocs - 1) * self.channels
        if self._accept_exc is not None or len(self._conn) != total:
            exc = self._accept_exc
            self.close()
            if isinstance(exc, (WireCorruption, ActorDied)):
                raise exc
            fdeadline.raise_deadline(
                f"tcp wire mesh connect: {len(self._conn)}/{total} "
                f"streams up before the bound"
                + (f" ({exc!r})" if exc else ""), deadline, fatal=True)
        for (ch, r), s in self._conn.items():
            s.setblocking(False)
            self._inbuf.setdefault((ch, r), bytearray())
        self._t_connects.inc(len(self._conn))
        Log.Debug("tcp wire rank %d: mesh up — %d streams across %d "
                  "channels", self.rank, len(self._conn), self.channels)

    def _accept_loop(self, expected: int, t_end: float) -> None:
        """Install-time only: accept ``expected`` inbound dials, map
        each stream by its sealed hello, then close the listeners and
        EXIT — no thread survives into steady state."""
        sel = selectors.DefaultSelector()
        try:
            for ls in self._listeners:
                ls.setblocking(False)
                sel.register(ls, selectors.EVENT_READ)
            got = 0
            while got < expected:
                remaining = t_end - time.monotonic()
                if remaining <= 0:
                    raise socket.timeout(
                        f"tcp wire accept: {got}/{expected} inbound "
                        f"streams before the connect bound")
                for key, _ in sel.select(timeout=min(0.25, remaining)):
                    try:
                        conn, _addr = key.fileobj.accept()
                    except OSError:
                        continue
                    conn.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                    ch, r = self._read_hello(conn, t_end)
                    if ch is None:
                        continue        # foreign dialer, rejected
                    with self._lock:
                        self._conn[(ch, r)] = conn
                    got += 1
        except BaseException as exc:
            self._accept_exc = exc
        finally:
            sel.close()
            for ls in self._listeners:
                try:
                    ls.close()
                except OSError:
                    pass
            self._listeners = []

    def _read_hello(self, conn: socket.socket, t_end: float):
        """Validate one inbound stream's sealed hello. A garbled or
        foreign hello (wrong token, wrong magic, corrupt seal) closes
        THAT stream and returns (None, None) — one stray dialer must
        never poison the mesh."""
        try:
            (ln,) = struct.unpack("<I", self._recv_exact(conn, 4, t_end))
            if ln > _HELLO_CAP:
                raise WireCorruption(
                    f"tcp wire hello claims {ln} bytes (cap "
                    f"{_HELLO_CAP}) — refused unread")
            body = seal.open_frame(self._recv_exact(conn, ln, t_end))
            magic, ch, r = struct.unpack_from("<III", body, 0)
            token = bytes(body[12:]).decode("utf-8", "replace")
            if (magic != _HELLO_MAGIC or token != self.token
                    or not 0 <= ch < self.channels
                    or not 0 <= r < self.nprocs or r == self.rank):
                raise WireCorruption(
                    f"tcp wire hello is foreign: magic {magic:#x}, "
                    f"channel {ch}, rank {r}, token match "
                    f"{token == self.token}")
            return ch, r
        except (OSError, ValueError, struct.error) as exc:
            Log.Error("tcp wire rank %d: rejected inbound dialer: %r",
                      self.rank, exc)
            try:
                conn.close()
            except OSError:
                pass
            return None, None

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int, t_end: float) -> bytes:
        """Blocking bounded read of exactly ``n`` bytes (hello path
        only — steady-state reads are non-blocking)."""
        out = bytearray()
        while len(out) < n:
            remaining = t_end - time.monotonic()
            if remaining <= 0:
                raise socket.timeout("tcp wire hello read timed out")
            conn.settimeout(min(1.0, remaining))
            data = conn.recv(n - len(out))
            if not data:
                raise ConnectionResetError(
                    "tcp wire stream closed during hello")
            out += data
        return bytes(out)

    def close(self) -> None:
        """Close every stream and listener. Idempotent."""
        if self._closed:
            return
        self._closed = True
        with self._lock:
            conns = list(self._conn.values())
            self._conn.clear()
        for s in conns:
            try:
                s.close()
            except OSError:
                pass
        for ls in self._listeners:
            try:
                ls.close()
            except OSError:
                pass
        self._listeners = []
        t = self._accept_thread
        if t is not None and t.is_alive():
            t.join(1.0)
        self._inbuf.clear()

    # -- the exchange --------------------------------------------------------

    def _frames(self, blob: bytes, rnd: int, channel: int,
                crc: int) -> "Tuple[bytearray, List[int]]":
        """The outbound frame train — identical toward every peer —
        built in ONE pass: header, chunk and streamed seal trailer are
        appended straight into the wire buffer (seal.seal_trailer), so
        the blob is copied exactly once regardless of chunk count.
        Returns (wire buffer, per-frame byte sizes — chaos tcp.drop
        trims the final frame off a peer's send limit)."""
        mv = memoryview(blob)
        plan = ([(0, 0)] if not blob else
                [(off, min(self.chunk, len(blob) - off))
                 for off in range(0, len(blob), self.chunk)])
        out = bytearray()
        sizes = []
        for off, ln in plan:
            hdr = struct.pack(_HDR_FMT, _MAGIC, self.rank, rnd,
                              len(blob), off, ln, channel, crc)
            chunk = mv[off:off + ln]
            trailer = seal.seal_trailer((hdr, chunk))
            flen = _HDR_LEN + ln + len(trailer)
            out += struct.pack("<I", flen)
            out += hdr
            out += chunk
            out += trailer
            sizes.append(4 + flen)
        return out, sizes

    def exchange(self, blob: bytes, channel: int,
                 timeout_s: Optional[float] = None) -> List[bytes]:
        """Every rank's blob for this channel's next round, rank order.
        Collective per channel; bounded by ``-mv_deadline_s`` or
        ``timeout_s``. NOTE a failed exchange leaves the channel's
        round counter advanced: the caller must scrap the wire, never
        retry the round (the shm wire's contract, verbatim)."""
        CHECK(not self._closed, "tcp wire used after close")
        CHECK(0 <= channel < self.channels,
              f"tcp wire channel {channel} out of range "
              f"(wire has {self.channels})")
        rnd = self._round[channel]
        self._round[channel] += 1
        if len(blob) > self.frame_hw_bytes:
            self.frame_hw_bytes = len(blob)
            self._t_hw.set(float(len(blob)))
        crc = ((seal.fast_crc(blob) & 0xFFFFFFFF)
               if self.payload_crc else 0)
        peers = [r for r in range(self.nprocs) if r != self.rank]
        inj = _chaos()
        if inj is not None:
            d = inj.tcp_delay()
            if d > 0:
                time.sleep(d)
            if inj.tcp_partition():
                self._partition(channel)
        out, frame_sizes = self._frames(blob, rnd, channel, crc)
        out_view = memoryview(out)
        out_limit = {r: len(out) for r in peers}
        if inj is not None and inj.tcp_drop():
            # swallow the final frame toward the lowest peer: that
            # peer stalls on bytes that never arrive and its lease
            # probe / deadline converts the stall — the drill's point
            out_limit[peers[0]] = len(out) - frame_sizes[-1]
        st = {r: {"buf": self._inbuf.setdefault((channel, r),
                                                bytearray()),
                  "out_pos": 0, "asm": None, "total": None,
                  "chunks": 0, "crc": 0, "crc_latch": 0,
                  "done_r": False}
              for r in peers}
        deadline = (timeout_s if timeout_s is not None
                    else fdeadline.timeout_or_none())
        t0 = time.perf_counter()
        last_probe = t0
        stall_s = 0.0
        sel = selectors.DefaultSelector()
        try:
            for r in peers:
                s = st[r]
                # pre-buffered bytes from the previous round's recv may
                # already complete this peer's frame train
                self._drain_frames(r, channel, rnd, s)
                sock = self._conn.get((channel, r))
                if sock is None:
                    raise ActorDied(
                        f"tcp wire peer rank {r} (channel {channel}, "
                        f"round {rnd})",
                        ConnectionResetError("stream severed"))
                events = 0
                if not s["done_r"]:
                    events |= selectors.EVENT_READ
                if s["out_pos"] < out_limit[r]:
                    events |= selectors.EVENT_WRITE
                if events:
                    try:
                        sel.register(sock, events, r)
                    except (ValueError, OSError) as e:
                        raise ActorDied(
                            f"tcp wire peer rank {r} (channel "
                            f"{channel}, round {rnd})", e)
            while True:
                if all(s["done_r"] and s["out_pos"] >= out_limit[r]
                       for r, s in st.items()):
                    break
                iter_t0 = time.perf_counter()
                progressed = False
                for key, mask in sel.select(timeout=0.05):
                    r = key.data
                    s = st[r]
                    sock = key.fileobj
                    if mask & selectors.EVENT_WRITE:
                        progressed |= self._pump_send(
                            sock, s, out_view, out_limit[r], r,
                            channel, rnd, sel)
                    if mask & selectors.EVENT_READ and not s["done_r"]:
                        progressed |= self._pump_recv(
                            sock, s, r, channel, rnd, sel,
                            out_limit[r])
                now = time.perf_counter()
                if progressed:
                    continue
                stall_s += now - iter_t0
                if now - last_probe > _PROBE_PERIOD_S:
                    last_probe = now
                    dead = _peer_loss_probe(
                        f"tcp wire exchange (channel {channel}, "
                        f"round {rnd}): peer silent")
                    if dead is not None:
                        raise dead
                if deadline is not None and now - t0 > deadline:
                    fdeadline.raise_deadline(
                        f"tcp wire exchange (channel {channel}, round "
                        f"{rnd}): a peer never sent/consumed its "
                        f"frame train", fatal=True)
        finally:
            sel.close()
        self._t_rounds.inc()
        self._t_bytes.inc(len(blob) * len(peers))
        if stall_s > 0.0:
            self.stall_s += stall_s
            self._t_stall.inc(stall_s)
        return [blob if r == self.rank else bytes(st[r]["asm"])
                for r in range(self.nprocs)]

    def _pump_send(self, sock, s, out_view, limit, r, channel, rnd,
                   sel) -> bool:
        if s["out_pos"] >= limit:
            self._downgrade(sel, sock, s, r, limit)
            return False
        try:
            n = sock.send(out_view[s["out_pos"]:
                                   min(s["out_pos"] + _SEND_SLICE,
                                       limit)])
        except (BlockingIOError, InterruptedError):
            return False
        except OSError as e:
            raise ActorDied(
                f"tcp wire peer rank {r} (channel {channel}, round "
                f"{rnd}, send)", e)
        s["out_pos"] += n
        if s["out_pos"] >= limit:
            self._downgrade(sel, sock, s, r, limit)
        return n > 0

    def _pump_recv(self, sock, s, r, channel, rnd, sel, limit) -> bool:
        scratch = self._scratch[channel]
        try:
            n = sock.recv_into(scratch)
        except (BlockingIOError, InterruptedError):
            return False
        except OSError as e:
            raise ActorDied(
                f"tcp wire peer rank {r} (channel {channel}, round "
                f"{rnd}, recv)", e)
        if not n:
            raise ActorDied(
                f"tcp wire peer rank {r} (channel {channel}, round "
                f"{rnd})",
                ConnectionResetError(
                    "stream closed mid-exchange (peer died or was "
                    "killed)"))
        s["buf"] += memoryview(scratch)[:n]
        self._drain_frames(r, channel, rnd, s)
        if s["done_r"]:
            self._downgrade(sel, sock, s, r, limit)
        return True

    @staticmethod
    def _downgrade(sel, sock, s, r, limit) -> None:
        """Shrink a stream's selector interest to what's still
        pending; unregister when both directions are done (a done
        stream must not be read — the peer's NEXT round may already be
        arriving and belongs to the next exchange call)."""
        events = 0
        if not s["done_r"]:
            events |= selectors.EVENT_READ
        if s["out_pos"] < limit:
            events |= selectors.EVENT_WRITE
        try:
            if events:
                sel.modify(sock, events, r)
            else:
                sel.unregister(sock)
        except (KeyError, ValueError):
            pass

    def _drain_frames(self, r: int, channel: int, rnd: int,
                      s: dict) -> None:
        """Parse complete frames out of the stream buffer. Stops as
        soon as this round's blob is assembled — bytes beyond it
        belong to the peer's next round and stay buffered.

        Parsing rides memoryviews end to end (verify, header decode,
        assembly memcpy) — the only copy a chunk pays is its landing in
        ``asm``. The views live inside :meth:`_parse_frames` so the
        buffer compaction here never trips the bytearray export
        guard."""
        buf = s["buf"]
        consumed = self._parse_frames(r, channel, rnd, s,
                                      memoryview(buf), len(buf))
        if consumed:
            del buf[:consumed]

    def _parse_frames(self, r: int, channel: int, rnd: int, s: dict,
                      view, size: int) -> int:
        pos = 0
        while not s["done_r"]:
            if size - pos < 4:
                return pos
            (flen,) = struct.unpack_from("<I", view, pos)
            if flen > self._max_frame or flen < _HDR_LEN:
                self._t_crc.inc()
                raise WireCorruption(
                    f"tcp wire frame from rank {r} claims {flen} "
                    f"bytes (cap {self._max_frame}) — a corrupted "
                    f"length prefix is refused, never awaited")
            if size - pos < 4 + flen:
                return pos
            sealed = view[pos + 4:pos + 4 + flen]
            pos += 4 + flen
            try:
                body = seal.open_frame(sealed)
            except WireCorruption:
                self._t_crc.inc()
                raise
            magic, sender, frnd, total, off, ln, fch, fcrc = \
                struct.unpack_from(_HDR_FMT, body, 0)
            if magic != _MAGIC or sender != r or fch != channel:
                self._t_crc.inc()
                raise WireCorruption(
                    f"tcp wire frame header is foreign: magic "
                    f"{magic:#x}, sender {sender}, channel {fch} on "
                    f"the (channel {channel}, peer {r}) stream")
            if frnd != rnd:
                raise WireCorruption(
                    f"tcp wire desync on channel {channel}: rank {r} "
                    f"is at exchange round {frnd}, rank {self.rank} "
                    f"at {rnd} — a rank re-entered the exchange "
                    f"alone; the stream cannot be trusted")
            chunk = body[_HDR_LEN:]
            if s["asm"] is None:
                s["asm"] = bytearray(total)
                s["total"] = total
                s["crc_latch"] = fcrc
            if (total != s["total"] or off + ln > s["total"]
                    or len(chunk) != ln):
                self._t_crc.inc()
                raise WireCorruption(
                    f"tcp wire frame from rank {r} truncated/"
                    f"inconsistent: total {total} vs {s['total']}, "
                    f"chunk [{off}:{off + ln}] carrying "
                    f"{len(chunk)} bytes")
            if ln:
                s["asm"][off:off + ln] = chunk
                if self.payload_crc:
                    s["crc"] = seal.fast_crc(chunk, s["crc"])
            s["chunks"] += 1
            expect = max(1, -(-s["total"] // self.chunk))
            if s["chunks"] >= expect:
                if self.payload_crc and \
                        (s["crc"] & 0xFFFFFFFF) != s["crc_latch"]:
                    self._t_crc.inc()
                    raise WireCorruption(
                        f"tcp wire frame from rank {r} failed its "
                        f"whole-blob CRC (round {rnd}, {s['total']} "
                        f"bytes)")
                s["done_r"] = True
        return pos

    def _partition(self, channel: int) -> None:
        """Chaos tcp.partition: sever every stream of this channel.
        Peers see EOF (typed ActorDied); our own next socket op fails
        the same way."""
        with self._lock:
            severed = [(k, s) for k, s in self._conn.items()
                       if k[0] == channel]
        for k, s in severed:
            try:
                s.close()
            except OSError:
                pass
        Log.Error("tcp wire rank %d: chaos tcp.partition severed %d "
                  "streams on channel %d", self.rank, len(severed),
                  channel)

    # -- diagnostics ---------------------------------------------------------

    def stats(self) -> dict:
        return {"token": self.token, "rank": self.rank,
                "nprocs": self.nprocs, "channels": self.channels,
                "chunk_bytes": self.chunk,
                "rounds": [int(r) for r in self._round],
                "streams": len(self._conn),
                "endpoints": list(self._endpoints),
                "stall_s": round(self.stall_s, 6),
                "frame_hw_bytes": self.frame_hw_bytes}

    def mem_bytes(self) -> dict:
        """Ledger probe (telemetry/accounting.py): inbound stream
        buffers currently held plus the frame high-watermark."""
        return {"inbuf_bytes": sum(len(b)
                                   for b in self._inbuf.values()),
                "stream_count": len(self._conn),
                "frame_hw_bytes": self.frame_hw_bytes}
