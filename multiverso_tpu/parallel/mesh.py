"""Device mesh construction and shard placement.

The TPU-native replacement for the reference's node/rank fabric: instead of
N MPI processes each hosting a parameter shard in its heap
(reference src/zoo.cpp, src/net/mpi_net.h), a ``jax.sharding.Mesh`` with a
``server`` axis hosts every table shard in HBM. ``num_servers`` is the mesh
size along that axis; worker identity is a host-side concept (threads in one
process, processes across hosts via ``jax.distributed``).

``partition_offsets`` preserves the reference's contiguous-shard math —
each server takes ``size // num_servers`` elements and the last takes the
remainder (reference src/table/array_table.cpp:10-19, 101-105) — used by
host-side partition logic and by parity unit tests
(reference Test/unittests/test_array.cpp:47-66 tests Partition as a pure
function).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SERVER_AXIS = "server"

# ``jax.shard_map`` graduated from jax.experimental across jax releases
# (and renamed its replication-check kwarg ``check_rep`` -> ``check_vma``
# on the way); resolve whichever this jax ships so the table/allreduce
# programs run on both. Callers use the new spellings.
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _exp_shard_map(*args, **kwargs)


def partition_offsets(size: int, num_servers: int) -> List[Tuple[int, int]]:
    """[(offset, count)] per server; last server takes the remainder.

    Mirrors reference array_table.cpp:101-105 (server_offsets_ construction).
    """
    if num_servers <= 0:
        raise ValueError("num_servers must be positive")
    base = size // num_servers
    out = []
    for s in range(num_servers):
        offset = base * s
        count = base if s < num_servers - 1 else size - base * (num_servers - 1)
        out.append((offset, count))
    return out


def row_partition_server(row: int, num_rows: int, num_servers: int) -> int:
    """Reference-parity row→server math: ``row / (num_row / num_server)``
    with the tail clamped to the last server (reference
    matrix_table.cpp:24-46). Kept as the parity-tested pure function; the
    actual TPU storage ownership is ``storage_partition_server`` (equal-size
    shards — jax shards must be uniform, so the remainder spreads by ceil
    blocks instead of piling on the last server). The two agree whenever
    ``num_servers`` divides ``num_rows``."""
    base = num_rows // num_servers
    if base == 0:
        return 0
    return min(row // base, num_servers - 1)


def ceil_block_rows(num_rows: int, num_servers: int) -> int:
    """Rows per server shard in the interleaved TPU layout — the ONE place
    the ceil-block ownership law lives (matrix_table.py storage and its
    shard-local id math both derive from this)."""
    return -(-num_rows // num_servers)


def storage_partition_server(row: int, num_rows: int, num_servers: int) -> int:
    """Which server shard actually owns a row in the interleaved TPU layout
    (matrix_table.py): ceil-based equal blocks."""
    block = ceil_block_rows(num_rows, num_servers)
    return min(row // block, num_servers - 1)


def pad_to_multiple(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def next_bucket(n: int, min_bucket: int = 8) -> int:
    """Smallest bucket size >= n (and >= min_bucket). The table layer pads
    dynamic id batches to these buckets so XLA compiles a handful of shapes
    instead of one per batch size.

    Ladder: powers of two up to 256, then quarter-octave steps (b/2 x
    {1.25, 1.5, 1.75, 2}) — pad waste drops from <=100% to <=25% of the
    batch (wasted lanes are real DMAs on the row hot path) for ~4x the
    shape count, and every rung above 256 stays a multiple of 64, the
    Pallas row-kernel chunk."""
    b = min_bucket
    while b < n:
        b <<= 1
    if b <= 256:
        return b
    half = b >> 1
    for num in (5, 6, 7):          # half * 1.25 / 1.5 / 1.75
        cand = (half * num) // 4   # half >= 256 -> exact and 64-aligned
        if cand >= n:
            return cand
    return b


def local_device_count(mesh: Mesh) -> int:
    """Devices of ``mesh`` owned by THIS process (>=1)."""
    import jax as _jax
    pid = _jax.process_index()
    return max(1, sum(1 for d in mesh.devices.flat
                      if d.process_index == pid))


def parts_bucket(n: int, local_dev: int) -> int:
    """Per-process bucket for a batch-sharded parts array: the next_bucket
    rung rounded up to a multiple of this process's device count, so the
    global (nproc * bucket) batch always shards evenly over the mesh."""
    return pad_to_multiple(next_bucket(n), local_dev)


def place_parts(mesh: Mesh, local, nproc: int) -> jax.Array:
    """THIS process's local block -> a batch-sharded GLOBAL array whose
    axis 0 stacks every process's block in process order (global shape
    ``(nproc * local.shape[0], ...)``, sharded P(SERVER_AXIS) on axis 0).

    The one placement primitive behind every table's multi-process
    device-plane verbs. Host arrays ride
    ``make_array_from_process_local_data``; device-resident arrays stay
    in HBM — the block is split across this process's mesh devices with
    on-device slices (no host round-trip), falling back to the host path
    only if the sharding's device-to-index map doesn't line up with
    process-contiguous blocks (it does for the process-grouped meshes
    build_mesh constructs)."""
    import jax as _jax
    spec = P(SERVER_AXIS, *([None] * (local.ndim - 1)))
    sharding = NamedSharding(mesh, spec)
    global_shape = (nproc * local.shape[0],) + tuple(local.shape[1:])
    if isinstance(local, jax.Array) and local.is_fully_addressable:
        pid = _jax.process_index()
        offset = pid * local.shape[0]
        pieces, ok = [], True
        for dev, idx in sharding.devices_indices_map(global_shape).items():
            if dev.process_index != pid:
                continue
            lo = (idx[0].start or 0) - offset
            hi = (idx[0].stop if idx[0].stop is not None
                  else global_shape[0]) - offset
            if lo < 0 or hi > local.shape[0]:
                ok = False   # non-contiguous process blocks: host fallback
                break
            pieces.append((lo, hi, dev))
        if ok:
            arrs = [_jax.device_put(local[lo:hi], dev)
                    for lo, hi, dev in pieces]
            return _jax.make_array_from_single_device_arrays(
                global_shape, sharding, arrs)
        local = np.asarray(local)
    return _jax.make_array_from_process_local_data(
        sharding, np.asarray(local), global_shape)


def build_mesh(devices: Optional[Sequence[jax.Device]] = None,
               axis_name: str = SERVER_AXIS) -> Mesh:
    """1-D mesh over all (or given) devices along the server axis."""
    if devices is None:
        devices = jax.devices()
    dev_array = np.asarray(devices)
    return Mesh(dev_array, (axis_name,))


@dataclass
class MeshContext:
    """Owns the mesh and canonical shardings for the table layer."""

    mesh: Mesh

    @classmethod
    def create(cls, devices: Optional[Sequence[jax.Device]] = None) -> "MeshContext":
        return cls(mesh=build_mesh(devices))

    @property
    def num_servers(self) -> int:
        return self.mesh.shape[SERVER_AXIS]

    def sharding_1d(self) -> NamedSharding:
        """Contiguous range shards of a 1-D array (ArrayTable layout)."""
        return NamedSharding(self.mesh, P(SERVER_AXIS))

    def sharding_rows(self) -> NamedSharding:
        """Row shards of a 2-D array (MatrixTable layout)."""
        return NamedSharding(self.mesh, P(SERVER_AXIS, None))

    def sharding_worker_rows(self) -> NamedSharding:
        """(num_workers, rows, ...) state sharded on the row axis — used for
        per-worker server state such as AdaGrad accumulators
        (reference adagrad_updater.h:19,26) and SparseMatrixTable dirty bits
        (reference sparse_matrix_table.h:67-69)."""
        return NamedSharding(self.mesh, P(None, SERVER_AXIS))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def place(self, array, sharding: NamedSharding):
        """Host -> HBM placement with an explicit layout."""
        return jax.device_put(array, sharding)

    def fetch(self, arr) -> np.ndarray:
        """Device -> host of a possibly globally-sharded array.

        Single-process (and fully-replicated) arrays fetch directly. In a
        multi-process job a shard-spanning array lives partly on
        non-addressable devices — reassemble the global value by
        allgathering every process's local shards. That makes this a
        COLLECTIVE in multihost mode, which the table layer's collective
        contract already guarantees (parallel/multihost.py docstring)."""
        if not isinstance(arr, jax.Array):
            return np.asarray(arr)
        if arr.is_fully_addressable or arr.is_fully_replicated:
            return np.asarray(arr)
        from jax.experimental import multihost_utils

        from multiverso_tpu.parallel import multihost
        multihost.note_collective()
        return np.asarray(multihost_utils.process_allgather(arr, tiled=True))
