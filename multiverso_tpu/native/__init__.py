"""Native runtime loader (ctypes over native/libmultiverso_tpu.so).

The C++ runtime mirrors the reference's native core (actors, store,
updaters, BSP sync, c_api — see native/) and additionally exports fast
text parsers used by the python data pipelines. The library is built on
demand with ``make`` and loaded via ctypes; everything degrades gracefully
to pure python when no toolchain is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
# installed wheels carry the library as package data right here (built by
# setup.py); source checkouts build it in the repo's native/ dir
_PKG_LIB_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "libmultiverso_tpu.so")
_REPO_LIB_PATH = os.path.join(_NATIVE_DIR, "libmultiverso_tpu.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        result = subprocess.run(["make", "-C", _NATIVE_DIR, "-j4",
                                 "libmultiverso_tpu.so"],
                                capture_output=True, text=True, timeout=300)
        return result.returncode == 0
    except Exception:
        return False


def _try_load(path: str) -> Optional[ctypes.CDLL]:
    """Load + signature-check one candidate; None on any failure
    (AttributeError = stale .so missing a newer symbol)."""
    try:
        handle = ctypes.CDLL(path)
        _configure_signatures(handle)
        return handle
    except (OSError, AttributeError):
        return None


def lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, or None when unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        # wheel package-data first, source-tree build second
        for path in (_PKG_LIB_PATH, _REPO_LIB_PATH):
            if os.path.exists(path):
                _lib = _try_load(path)
                if _lib is not None:
                    return _lib
        # missing everywhere, or every existing candidate was stale:
        # rebuild the SOURCE-TREE library (the package-data .so is an
        # immutable wheel artifact — recovery must not retry it) and load
        # that; otherwise degrade to pure python (module contract)
        if _build():
            _lib = _try_load(_REPO_LIB_PATH)
        return _lib


def _configure_signatures(h: ctypes.CDLL) -> None:
    i64 = ctypes.c_int64
    h.MV_CountLibsvm.restype = i64
    h.MV_CountLibsvm.argtypes = [ctypes.c_char_p, i64,
                                 ctypes.POINTER(i64), ctypes.POINTER(i64)]
    h.MV_ParseLibsvm.restype = i64
    h.MV_ParseLibsvm.argtypes = [
        ctypes.c_char_p, i64, ctypes.c_int,
        np.ctypeslib.ndpointer(np.int32), np.ctypeslib.ndpointer(np.float32),
        np.ctypeslib.ndpointer(np.int64), np.ctypeslib.ndpointer(np.int64),
        np.ctypeslib.ndpointer(np.float32)]
    h.MV_BuildVocabHash.restype = i64
    h.MV_BuildVocabHash.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int32,
        np.ctypeslib.ndpointer(np.int64), i64]
    h.MV_TokenizeToIds.restype = i64
    h.MV_TokenizeToIds.argtypes = [
        ctypes.c_char_p, i64, ctypes.POINTER(ctypes.c_char_p),
        ctypes.c_int32, np.ctypeslib.ndpointer(np.int64), i64,
        np.ctypeslib.ndpointer(np.int32), i64]
    h.MV_TokenizeLinesToIds.restype = i64
    h.MV_TokenizeLinesToIds.argtypes = h.MV_TokenizeToIds.argtypes
    f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    h.MV_HostStoreNew.restype = ctypes.c_void_p
    h.MV_HostStoreNew.argtypes = [i64, i64, ctypes.c_float]
    h.MV_HostStoreFree.argtypes = [ctypes.c_void_p]
    h.MV_HostStoreLoad.argtypes = [ctypes.c_void_p, f32p]
    h.MV_HostStoreGetAll.argtypes = [ctypes.c_void_p, f32p]
    h.MV_HostStoreAddAll.argtypes = [ctypes.c_void_p, f32p]
    h.MV_HostStoreAddRows.argtypes = [ctypes.c_void_p, i32p, i64, f32p]
    h.MV_HostStoreGetRows.argtypes = [ctypes.c_void_p, i32p, i64, f32p]
    h.MV_HostStorePoolStats.argtypes = [
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")]
    # round 19 — the versioned seal's hardware CRC32C (crc32c.cc);
    # hasattr-guarded like MV_KvIndexCapacity so a stale prebuilt .so
    # degrades to the pure-python seal paths instead of failing load
    if hasattr(h, "MV_Crc32c"):
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        h.MV_Crc32c.restype = ctypes.c_uint32
        h.MV_Crc32c.argtypes = [u8p, i64, ctypes.c_uint32]
        h.MV_Crc32cHw.restype = ctypes.c_int
        h.MV_Crc32cHw.argtypes = []
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    h.MV_KvIndexNew.restype = ctypes.c_void_p
    h.MV_KvIndexNew.argtypes = [i64]
    h.MV_KvIndexFree.argtypes = [ctypes.c_void_p]
    h.MV_KvIndexSize.restype = i64
    h.MV_KvIndexSize.argtypes = [ctypes.c_void_p]
    if hasattr(h, "MV_KvIndexCapacity"):    # older prebuilt .so
        h.MV_KvIndexCapacity.restype = i64
        h.MV_KvIndexCapacity.argtypes = [ctypes.c_void_p]
    h.MV_KvIndexLookup.argtypes = [ctypes.c_void_p, i64p, i64, i32p]
    h.MV_KvIndexInsert.argtypes = [ctypes.c_void_p, i64p, i64, i32p]
    h.MV_KvIndexItems.argtypes = [ctypes.c_void_p, i64p, i32p]
    h.MV_KvIndexSetItems.argtypes = [ctypes.c_void_p, i64p, i32p, i64]


def parse_libsvm(text: bytes, weighted: bool = False
                 ) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray,
                                     np.ndarray, np.ndarray]]:
    """Fast parse of a libsvm text chunk.

    -> (labels i32, weights f32, offsets i64[n+1], keys i64, values f32)
    or None when the native lib is unavailable.
    """
    h = lib()
    if h is None:
        return None
    n_samples = ctypes.c_int64()
    n_entries = ctypes.c_int64()
    h.MV_CountLibsvm(text, len(text), ctypes.byref(n_samples),
                     ctypes.byref(n_entries))
    ns, ne = n_samples.value, n_entries.value
    labels = np.empty(max(ns, 1), np.int32)
    weights = np.empty(max(ns, 1), np.float32)
    offsets = np.zeros(ns + 1, np.int64)
    keys = np.empty(max(ne, 1), np.int64)
    values = np.empty(max(ne, 1), np.float32)
    parsed = h.MV_ParseLibsvm(text, len(text), int(weighted), labels, weights,
                              offsets, keys, values)
    if parsed < 0:
        raise ValueError("native libsvm parser: malformed input")
    if parsed != ns:
        return None
    return labels[:ns], weights[:ns], offsets, keys[:ne], values[:ne]


class VocabTokenizer:
    """Native tokenize + vocab lookup (native/src/reader.cc
    MV_BuildVocabHash / MV_TokenizeToIds): builds an open-addressing word
    hash once, then maps whitespace-tokenized text to word ids in C++ —
    the reference WordEmbedding reader's hot loop (reader.cpp tokenize +
    Dictionary::GetWordIdx per token) off the python interpreter.
    Out-of-vocab tokens come back as -1 (caller filters)."""

    def __init__(self, handle: ctypes.CDLL, words):
        self._h = handle
        self._word_bytes = [w.encode("utf-8") for w in words]  # keep alive
        self._words = (ctypes.c_char_p * len(words))(*self._word_bytes)
        self._n = len(words)
        cap = 8
        while cap < 2 * self._n + 1:
            cap <<= 1
        self._table = np.empty(cap, np.int64)
        self._cap = cap
        handle.MV_BuildVocabHash(self._words, self._n, self._table, cap)

    @classmethod
    def create(cls, words) -> Optional["VocabTokenizer"]:
        handle = lib()
        if handle is None or not len(words):
            return None
        return cls(handle, list(words))

    def tokenize(self, text: bytes, max_ids: int) -> np.ndarray:
        """Word ids of ``text`` in order, -1 for out-of-vocab tokens."""
        out = np.empty(max(max_ids, 1), np.int32)
        n = self._h.MV_TokenizeToIds(text, len(text), self._words, self._n,
                                     self._table, self._cap, out,
                                     len(out))
        return out[:n]

    def tokenize_lines(self, text: bytes) -> np.ndarray:
        """Word ids of a multi-line chunk with -2 sentinels at newlines —
        one foreign call per chunk (per-line calls cost more than the
        tokenizing). -1 still marks out-of-vocab."""
        out = np.empty(len(text) + 2, np.int32)
        n = self._h.MV_TokenizeLinesToIds(text, len(text), self._words,
                                          self._n, self._table, self._cap,
                                          out, len(out))
        return out[:n]


class NativeHostStore:
    """Threaded f32 LOGICAL row store (native/src/host_store.cc): the
    CPU-backend matrix host plane's apply/gather substrate for linear
    aux-free updaters (data += sign*delta). Single-writer (the engine
    thread); the parallelism is inside one call — the reference's
    OpenMP-parallel server loop (updater.cpp:21-29), GIL-free via
    ctypes."""

    def __init__(self, handle: ctypes.CDLL, rows: int, cols: int,
                 sign: float):
        self._h = handle
        self.rows, self.cols = rows, cols
        self._ptr = handle.MV_HostStoreNew(rows, cols, ctypes.c_float(sign))
        if not self._ptr:
            raise MemoryError("MV_HostStoreNew failed")

    @classmethod
    def create(cls, rows: int, cols: int,
               sign: float) -> Optional["NativeHostStore"]:
        handle = lib()
        if handle is None:
            return None
        return cls(handle, rows, cols, sign)

    def __del__(self):
        ptr, self._ptr = getattr(self, "_ptr", None), None
        if ptr:
            self._h.MV_HostStoreFree(ptr)

    def _check_full(self, arr: np.ndarray) -> np.ndarray:
        # the C++ side memcpys/applies rows*cols floats blindly — an
        # undersized buffer would be an out-of-bounds heap read
        arr = np.ascontiguousarray(arr, np.float32)
        if arr.size != self.rows * self.cols:
            raise ValueError(f"expected {self.rows}x{self.cols} floats, "
                             f"got shape {arr.shape}")
        return arr

    def load(self, full: np.ndarray) -> None:
        self._h.MV_HostStoreLoad(self._ptr, self._check_full(full))

    def get_all(self) -> np.ndarray:
        out = np.empty((self.rows, self.cols), np.float32)
        self._h.MV_HostStoreGetAll(self._ptr, out)
        return out

    def add_all(self, delta: np.ndarray) -> None:
        self._h.MV_HostStoreAddAll(self._ptr, self._check_full(delta))

    def _check_ids(self, ids: np.ndarray) -> np.ndarray:
        # the C++ side indexes data + id*cols blindly — an out-of-range
        # id would be silent heap corruption, not an exception
        ids = np.ascontiguousarray(ids, np.int32)
        if len(ids) and (int(ids.min()) < 0 or int(ids.max()) >= self.rows):
            raise ValueError(f"row id out of range [0, {self.rows})")
        return ids

    def add_rows(self, ids: np.ndarray, deltas: np.ndarray) -> None:
        """ids must be UNIQUE and in-range (caller pre-combines)."""
        ids = self._check_ids(ids)
        deltas = np.ascontiguousarray(deltas, np.float32)
        if deltas.size != len(ids) * self.cols:
            raise ValueError(f"expected {len(ids)}x{self.cols} delta "
                             f"floats, got shape {deltas.shape}")
        self._h.MV_HostStoreAddRows(self._ptr, ids, len(ids), deltas)

    def get_rows(self, ids: np.ndarray) -> np.ndarray:
        ids = self._check_ids(ids)
        out = np.empty((len(ids), self.cols), np.float32)
        self._h.MV_HostStoreGetRows(self._ptr, ids, len(ids), out)
        return out


class KvIndex:
    """Native int64 -> int32 slot index (native/src/kv_index.cc): linear
    probing with the splitmix64 finalizer. Batch insert assigns slots in
    BATCH ORDER (the KV multihost contract: identical key streams produce
    identical indices on every host). Single-writer."""

    def __init__(self, handle: ctypes.CDLL, cap_hint: int):
        self._h = handle
        self._ptr = handle.MV_KvIndexNew(cap_hint)
        if not self._ptr:
            raise MemoryError("MV_KvIndexNew failed")

    @classmethod
    def create(cls, cap_hint: int = 1024) -> Optional["KvIndex"]:
        handle = lib()
        if handle is None:
            return None
        return cls(handle, cap_hint)

    def __del__(self):
        ptr, self._ptr = getattr(self, "_ptr", None), None
        if ptr:
            self._h.MV_KvIndexFree(ptr)

    def __len__(self) -> int:
        return int(self._h.MV_KvIndexSize(self._ptr))

    def capacity(self) -> int:
        """Allocated probing-table slots (>= len; the load-factor
        headroom the accounting ledger must count). Falls back to len
        on an older .so without the export."""
        fn = getattr(self._h, "MV_KvIndexCapacity", None)
        if fn is None:
            return len(self)
        return int(fn(self._ptr))

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        keys = np.ascontiguousarray(keys, np.int64)
        out = np.empty(len(keys), np.int32)
        self._h.MV_KvIndexLookup(self._ptr, keys, len(keys), out)
        return out

    def insert(self, keys: np.ndarray) -> np.ndarray:
        """Missing keys get size++ in batch order; returns all slots."""
        keys = np.ascontiguousarray(keys, np.int64)
        out = np.empty(len(keys), np.int32)
        self._h.MV_KvIndexInsert(self._ptr, keys, len(keys), out)
        return out

    def items(self):
        """-> (keys i64[n], slots i32[n]), arbitrary order."""
        n = len(self)
        keys = np.empty(max(n, 1), np.int64)
        slots = np.empty(max(n, 1), np.int32)
        self._h.MV_KvIndexItems(self._ptr, keys, slots)
        return keys[:n], slots[:n]

    def set_items(self, keys: np.ndarray, slots: np.ndarray) -> None:
        """Replace contents (keys must be unique; slots must be a
        permutation of 0..n-1 — the native side tracks one next-slot
        counter, so gapped slot sets would make items() return
        uninitialized tail entries)."""
        keys = np.ascontiguousarray(keys, np.int64)
        slots = np.ascontiguousarray(slots, np.int32)
        if len(keys) != len(slots):
            raise ValueError("keys/slots length mismatch")
        if len(slots) and not np.array_equal(
                np.sort(slots), np.arange(len(slots), dtype=np.int32)):
            raise ValueError("set_items slots must be a permutation of "
                             "0..n-1 (native used counter is next-slot)")
        self._h.MV_KvIndexSetItems(self._ptr, keys, slots, len(keys))


def crc32c_fn():
    """The native CRC32C entry point (``MV_Crc32c(data_u8, n, seed)``
    -> u32, zlib.crc32-style chaining), or None when the native lib is
    unavailable or predates the export. Returned as the raw callable so
    the seal's hot loop (parallel/seal.py) pays the capability probe
    ONCE, not per frame. This module stays jax-free — the replica
    plane's reader processes verify fan-out seals through it."""
    h = lib()
    if h is None or not hasattr(h, "MV_Crc32c"):
        return None
    return h.MV_Crc32c


_charp_fn = None


def crc32c_charp_fn():
    """MV_Crc32c bound with a ``c_char_p`` first argument — the FAST
    binding for ``bytes`` inputs (the sealed-frame hot path): ctypes
    passes a bytes object as char* for ~2.7us/call vs ~6.5us through
    the ndpointer conversion (measured; the delta is pure argument
    marshalling). Lives on a second CDLL handle of the same library so
    the generic ndpointer binding (memoryviews, numpy views — the shm
    wire's streaming chunks) keeps working. None when unavailable."""
    global _charp_fn
    if _charp_fn is None:
        if lib() is None or not hasattr(lib(), "MV_Crc32c"):
            return None
        for path in (_PKG_LIB_PATH, _REPO_LIB_PATH):
            if os.path.exists(path):
                try:
                    h2 = ctypes.CDLL(path)
                    fn = h2.MV_Crc32c
                    fn.restype = ctypes.c_uint32
                    fn.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                   ctypes.c_uint32]
                    # mv-lint: ok(cross-domain-state): idempotent lazy init — every racing thread binds the same symbol of the same library; a double-store of an equivalent callable is benign
                    _charp_fn = fn
                    break
                except (OSError, AttributeError):
                    continue
    return _charp_fn


def crc32c(data, value: int = 0) -> Optional[int]:
    """CRC32C of ``data`` chained from ``value`` (the zlib.crc32 call
    shape), or None when the native runtime is unavailable."""
    fn = crc32c_fn()
    if fn is None:
        return None
    arr = np.frombuffer(data, np.uint8)    # zero-copy for bytes/views
    return int(fn(arr, arr.size, value & 0xFFFFFFFF))


def pool_stats() -> Optional[dict]:
    """The native host-store pool's dispatch tallies (round 13
    watchdog plane): {parallel_runs, inline_busy, inline_small,
    pool_threads}. ``inline_busy`` counts applies that found the pool
    owned by another engine shard and ran their slices inline — the
    saturation signal the apply-pool watchdog rule alerts on. None
    when the native runtime is unavailable."""
    handle = lib()
    if handle is None:
        return None
    out = np.zeros(4, np.int64)
    handle.MV_HostStorePoolStats(out)
    return {"parallel_runs": int(out[0]), "inline_busy": int(out[1]),
            "inline_small": int(out[2]), "pool_threads": int(out[3])}
