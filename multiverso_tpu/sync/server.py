"""Server engines: async (default) and BSP sync.

Behavioral equivalent of reference src/server.cpp:

* ``Server`` — async ASGD mode: applies every Get/Add as it arrives and
  always replies (server.cpp:23-58). Workers never wait for each other;
  the shard application itself is a jit'd XLA op dispatched asynchronously,
  so the actor thread stays ahead of the device.

* ``SyncServer`` — BSP mode (``-sync=true``): the exact vector-clock
  protocol of server.cpp:60-222, re-implemented: Adds from workers whose Get
  clock ran ahead of the global Get round are cached; Gets from workers with
  outstanding/uncounted Adds are cached; completing an Add round drains
  cached Gets and vice versa; ``Server_Finish_Train`` forces a worker's
  clocks to infinity and drains (server.cpp:188-211). Guarantee preserved
  (comment at server.cpp:60-67): all workers' i-th Get returns identical
  parameters, assuming all workers issue the same number of Gets/Adds.

Selection by the ``sync`` flag mirrors ``Server::GetServer``
(server.cpp:224-232).
"""

from __future__ import annotations

import collections
import threading
import time as _time
from typing import Deque, Dict, List, Optional

import numpy as np

from multiverso_tpu.actor import Actor, actor_names
from multiverso_tpu.failsafe import chaos
from multiverso_tpu.failsafe import deadline as fdeadline
from multiverso_tpu.failsafe.dedup import DedupWindow
from multiverso_tpu.failsafe.errors import (DeadlineExceeded,
                                            MembershipChanged,
                                            TransientError, WireCorruption)
from multiverso_tpu.message import Message, MsgType, copy_result
from multiverso_tpu.parallel import compress
from multiverso_tpu.parallel import multihost
from multiverso_tpu.parallel import wire
from multiverso_tpu.telemetry import flight as tflight
from multiverso_tpu.telemetry import metrics as tmetrics
from multiverso_tpu.telemetry import trace as ttrace
from multiverso_tpu.updaters.base import AddOption, GetOption
from multiverso_tpu.utils.configure import (GetFlag, MV_DEFINE_bool,
                                            MV_DEFINE_int, MV_DEFINE_string,
                                            cached_bool_flag,
                                            cached_int_flag,
                                            cached_str_flag)
from multiverso_tpu.utils.dashboard import monitor_region
from multiverso_tpu.utils.log import CHECK, Log
from multiverso_tpu.utils.mt_queue import MtQueue


MV_DEFINE_bool("sync", False, "sync or async")
# Declared-but-dead in the reference (server.cpp:21); kept for flag parity.
MV_DEFINE_int("backup_worker_ratio", 0, "ratio% of backup workers (dead flag, parity)")
# Windowed-engine transport selection (the reference picks its allreduce
# wire adaptively by payload size, allreduce_engine.cpp:31-55). "host":
# every window payload rides the staging allgather (capped_exchange).
# "device": eligible Add values never cross the host wire — only their
# dtype/shape metadata does — and the data moves through the table's
# device-parts collectives (place_parts + one traced program; on a pod
# that is ICI at fabric bandwidth). "auto": per-verb by payload size
# against -window_device_min_bytes. The default threshold sits just
# above this repo's MEASURED single-host crossover (bench.py transport
# profile: one host window round costs ~1.6 ms latency + bytes at
# ~350-410 MB/s, while one device-parts round costs a FIXED ~14-15 ms
# floor on the CPU backend — per-call jit dispatch + gloo collectives
# over padded parts buffers — so the device wire only wins past ~4-6 MB
# per window, which a 4 MB-budget window barely reaches). A POD
# deployment, where the device wire moves 100+ GB/s with ~us dispatch,
# should run -window_transport=device (or drop the threshold to ~1 MB)
# — see docs/BENCHMARK.md "transport selection".
# each constant feeds both the flag registration and the cached
# accessor's fallback, so the two defaults cannot drift apart
_WINDOW_TRANSPORT_DEFAULT = "auto"
_WINDOW_DEVICE_MIN_BYTES_DEFAULT = 6 << 20
MV_DEFINE_string("window_transport", _WINDOW_TRANSPORT_DEFAULT,
                 "windowed-engine Add-value transport: auto / host / device")
MV_DEFINE_int("window_device_min_bytes", _WINDOW_DEVICE_MIN_BYTES_DEFAULT,
              "auto transport: defer Add values >= this many bytes to "
              "the device wire (default just above this host's measured "
              "crossover)")
# both are read per window on the pack path — listener-cached reads,
# not a registry RLock walk per window (hot-path-flag-cache law)
_window_transport_flag = cached_str_flag("window_transport",
                                         _WINDOW_TRANSPORT_DEFAULT)
_window_device_min_bytes_flag = cached_int_flag(
    "window_device_min_bytes", _WINDOW_DEVICE_MIN_BYTES_DEFAULT)
# Round 7 — PIPELINED window engine. The serial engine ran drain ->
# encode -> exchange -> apply strictly in sequence on the actor thread,
# parking every worker behind the whole chain. With the pipeline a
# dedicated EXCHANGE thread owns the host-wire collective stream
# (encode + capped_exchange + decode, strictly in SEQ order — the
# collective sequence every rank issues is unchanged) while the engine
# actor stays the APPLY stage: window N applies while window N+1
# exchanges, but ONLY when window N's apply is host-local on every rank
# (no device-wire positions and every touched table's
# mh_apply_is_local() — both decided from EXCHANGED bytes, so all ranks
# gate identically and an apply-side device collective can never race
# the exchange thread's allgather into a rank-divergent order).
# -mv_pipeline=false restores the serial engine exactly.
MV_DEFINE_bool("mv_pipeline", True,
               "pipelined windowed engine: overlap window N's apply "
               "with window N+1's host exchange (false = serial engine)")
_pipeline_flag = cached_bool_flag("mv_pipeline", True)
# Round 12 — the three measured walls (PR 8 critpath: binding phase
# `apply` 22/47 windows, every fence `depth`, host_scaling flat because
# ONE actor serializes every table) attacked through one refactor:
# engine SHARDS (per-table-group actors, each with its own window
# stream / exchange stage / SEQ counter), a tunable pipeline DEPTH,
# and a parallel APPLY pool for different tables of one window.
MV_DEFINE_int("mv_engine_shards", 0,
              "engine shards: per-table-group engine actors, each "
              "owning its own window stream, exchange stage and SEQ "
              "counter; tables route by table_id %% shards (rank-"
              "agreed, no negotiation). 0 = auto: single-process "
              "worlds use min(tables, cores/4) via lazy shard spawn, "
              "multi-process worlds stay at 1 unless set explicitly "
              "(>1 there needs a multi-channel wire's per-shard "
              "channels — -mv_wire=shm same-host, tcp cross-host — "
              "because gloo is one globally-ordered "
              "collective stream). 1 = today's single engine byte-for-"
              "byte. Clamped to 1 under -sync (the BSP vector clocks "
              "count verbs across ALL tables) and -mv_elastic (the "
              "epoch relay is single-channel).")
MV_DEFINE_int("mv_pipeline_depth", 2,
              "pipelined engine depth cap: max exchanged-but-unapplied "
              "windows before the exchange stage fences (PR 6/8 "
              "measured every burst fence as `depth` — a transiently "
              "slow apply stops fencing the exchange at higher "
              "depths, at the cost of pinning more decoded windows)")
_pipeline_depth_flag = cached_int_flag("mv_pipeline_depth", 2)
MV_DEFINE_int("mv_apply_workers", 4,
              "apply-stage worker pool: apply DIFFERENT tables' "
              "segments of one exchanged window concurrently (per-"
              "table apply order stays serial, so determinism is "
              "untouched; only host-local windows parallelize — a "
              "collective apply keeps the strict position order). "
              "<=1 = serial apply, today's engine")
_apply_workers_flag = cached_int_flag("mv_apply_workers", 4)
# Worker-side fast paths (tables/base.py reads these through listener
# caches; they are DEFINED here so zoo's eager `import
# multiverso_tpu.sync.server` registers them before MV_Init's
# ParseCMDFlags — a flag defined in a lazily-imported module would
# silently drop its first-call CLI setting).
MV_DEFINE_int("mv_write_combine", 8,
              "worker-side write combining: coalesce up to N "
              "consecutive fire-and-forget Adds to one table into ONE "
              "request before the mailbox hop (0 = off, byte-identical "
              "message stream). A COUNT cap, deliberately not bytes: "
              "fire-and-forget call sequences are program-structural "
              "and therefore lockstep across SPMD ranks, while payload "
              "bytes can skew per rank — a byte cap would flush ranks "
              "at different call positions and diverge the multi-"
              "process verb streams.")
MV_DEFINE_int("mv_get_staleness", 0,
              "worker-side Get cache: serve a repeated identical Get "
              "from the last fetched result while the engine has "
              "applied at most N windows since the fill and this "
              "worker process wrote nothing to the table (SSP-style "
              "bounded staleness; 0 = off, every Get exact). "
              "Single-process worlds only — a cache hit removes a verb "
              "from the stream, which the multi-process SPMD collective "
              "contract cannot tolerate.")

# Round 11 — performance forensics. Every window's lifecycle is
# stamped per rank as compact flight events keyed by (mepoch, SEQ):
# form (verbs waiting for the stage to pick them up), pack, encode,
# exchange (with the time BLOCKED IN THE COLLECTIVE split out from
# local staging via multihost.last_exchange_stats — the exchange-done
# wall stamp is also the cross-rank clock-alignment rendezvous), decode
# and apply, with apply time additionally attributed per table family
# and verb kind. telemetry/critpath.py merges per-rank dumps into a
# cross-rank timeline and names the binding rank + phase per window.
# Rides the flight recorder's listener-cached gate; the tier-1 overhead
# guard (tests/test_critpath.py) holds the stamping to the same <=2%
# blocking-round budget as the recorder itself.
MV_DEFINE_bool("mv_phase_stamps", True,
               "per-window lifecycle phase stamping (form/pack/encode/"
               "exchange/decode/apply flight events + engine.phase.* "
               "histograms; false = window events only). No-op while "
               "-mv_flight_events=0 gates the recorder off. "
               "Multi-process windows stamp EVERY window (the "
               "cross-rank critical path needs every (mepoch, SEQ) "
               "position, and those windows cost a collective each); "
               "single-process windows observe the apply histogram "
               "every window but sample the flight events + per-table "
               "attribution 1-in-32 — those windows run in ~250us and "
               "per-window stamping would blow the 2% blocking-round "
               "budget the tier-1 guard enforces.")
_phase_stamps_flag = cached_bool_flag("mv_phase_stamps", True)

#: single-process sampling period for the full stamp (power of two;
#: window 1, 33, 65, ... stamp — the FIRST window always does, so
#: short tests and short jobs still leave phase records)
_PH_SP_SAMPLE = 32

#: the window lifecycle phase taxonomy (order = the gauge encoding of
#: engine.binding_phase: index into this tuple, -1 = none yet).
#: ``exchange_wait`` is the slice of ``exchange`` blocked inside the
#: collective op itself — the part a straggling peer inflates.
ENGINE_PHASES = ("form", "pack", "encode", "exchange", "exchange_wait",
                 "decode", "apply")

#: table families the per-family apply-seconds histograms are
#: registered for eagerly (visible at zero from the first scrape);
#: custom table classes get a lazy family from their class name
_TABLE_FAMILIES = ("matrix", "sparse", "array", "kv")


def _table_family(table) -> str:
    """Short family label of a server table for the apply attribution
    (``SparseMatrixServerTable`` -> ``sparse``, ``KVServerTable`` ->
    ``kv``; unknown classes degrade to their lowercased class name)."""
    name = type(table).__name__.lower()
    for fam in ("sparse", "kv", "array", "matrix"):
        if fam in name:
            return fam
    return name.replace("servertable", "").replace("table", "") or "table"


#: apply-stage poll granularity while an exchange is in flight: the
#: actor keeps draining the mailbox (feeding the NEXT window) between
#: polls instead of blocking inside the collective like the serial
#: engine did. One exchange costs >= the ~1.6ms allgather latency, so
#: 2ms polls add at most one spin per window.
_PL_POLL_S = 0.002

_INF = float("inf")

#: fence-cause taxonomy (round 9 — the observability plane's answer to
#: "overlap_pct sits at ~36%: WHAT fences?"). Every stall of the
#: pipelined exchange stage is classified into exactly one cause and
#: counted in ``engine.fence.<cause>``, with the stall seconds observed
#: into the ``engine.fence.stall_s`` histogram:
#:
#: * ``barrier``        — a non-verb window head (StoreLoad / Publish /
#:                        barrier ping / FinishTrain): its dispatch may
#:                        itself run collectives, so the stage fences
#:                        until the actor reports it done;
#: * ``nonlocal_table`` — a touched table's apply is not host-local
#:                        (mh_apply_is_local() False): the apply runs
#:                        device collectives that must not race the
#:                        exchange thread's allgather;
#: * ``device_wire``    — a window position's values rode the device
#:                        wire (DeferredArray): same collective-apply
#:                        reasoning;
#: * ``depth``          — the DEPTH cap: the apply stage simply hasn't
#:                        kept up (the only cause raising the cap or
#:                        speeding the apply would remove).
FENCE_CAUSES = ("barrier", "nonlocal_table", "device_wire", "depth")


class _ApplyPool:
    """Daemon-thread worker pool for the parallel apply
    (-mv_apply_workers). Deliberately NOT concurrent.futures: its
    worker threads are non-daemon and joined at interpreter exit, so
    one apply job wedged in a native call would turn a clean fatal
    shutdown into a process that never exits. These workers are
    daemons draining an MtQueue; jobs signal completion through a
    per-job box + event, and shutdown just closes the queue."""

    def __init__(self, workers: int, name: str):
        self._q: MtQueue = MtQueue()
        #: thread count this pool was built with — the adaptive-tuning
        #: path (round 20 policy plane) compares it against the live
        #: -mv_apply_workers value and rebuilds the pool between
        #: windows when they differ
        self.workers = max(1, workers)
        for i in range(self.workers):
            threading.Thread(target=self._loop, daemon=True,
                             name=f"mv-apply-{name}-{i}").start()

    def submit(self, fn) -> dict:
        box = {"done": threading.Event()}
        self._q.Push((fn, box))
        return box

    def _loop(self) -> None:
        while True:
            ok, item = self._q.Pop()
            if not ok:
                return
            fn, box = item
            try:
                box["result"] = fn()
            except BaseException as exc:    # re-raised by the waiter
                box["error"] = exc
            box["done"].set()

    def shutdown(self) -> None:
        self._q.Exit()


class _StageKilled(Exception):
    """Internal: the apply stage killed the exchange stage after a
    fatal engine error — exit quietly, the actor already failed every
    in-pipeline waiter."""


class VectorClock:
    """Per-worker progress clock (reference server.cpp:81-137).

    ``Update(i)`` ticks worker i; returns True when the tick completes a
    round (global clock catches up to the max local clock).
    """

    def __init__(self, n: int):
        self._local: List[float] = [0] * n
        self._global = 0

    def Update(self, i: int) -> bool:
        self._local[i] += 1
        if self._global < min(self._local):
            self._global += 1
            if self._global == self._max_element():
                return True
        return False

    def FinishTrain(self, i: int) -> bool:
        self._local[i] = _INF
        m = min(self._local)
        if self._global < m:
            self._global = m
            if self._global == self._max_element():
                return True
        return False

    def _max_element(self) -> float:
        finite = [v for v in self._local if v != _INF]
        return max([self._global] + finite)

    def local_clock(self, i: int) -> float:
        return self._local[i]

    def global_clock(self) -> float:
        return self._global

    def staleness(self) -> float:
        """How far the fastest still-training worker runs ahead of the
        global round — the BSP skew the telemetry gauge tracks (0 when
        every worker is caught up or finished)."""
        finite = [v for v in self._local if v != _INF]
        return max(max(finite) - self._global, 0.0) if finite else 0.0

    def DebugString(self) -> str:
        local = " ".join("-1" if v == _INF else str(int(v)) for v in self._local)
        return f"global {self._global} local: {local}"


class _ExchangeStage:
    """EXCHANGE stage of the pipelined windowed engine (round 7).

    One daemon thread owns the host-wire collective stream: every window
    exchange and barrier head-marker exchange runs here, strictly in
    stream order, so the collective sequence each rank issues is
    identical to the serial engine's however the apply stage is
    scheduled. Items flow actor -> ``_in`` -> this thread -> ``out`` ->
    actor:

    * ``("verbs", [msgs])`` — admitted Get/Add messages, appended to the
      stage's pending deque. The thread packs pending into windows
      (byte budget + transport deferral), exchanges each, agrees on the
      cross-rank prefix, and emits ``("window", mine, windows, prefix,
      descs0, t0)``; verbs beyond the agreed prefix stay pending and
      lead the next exchange (the serial engine's re-led-window rule).
    * ``("barrier", msg)`` — a non-verb window head: the thread flushes
      every pending verb first (stream order), runs the head-marker
      exchange, and emits ``("barrier", msg)`` for the actor to
      dispatch in order.
    * ``("stop", None)`` — thread exit (engine shutdown).

    OVERLAP GATE: after emitting a window whose apply is NOT host-local
    (any device-wire position, or a table without mh_apply_is_local())
    — and after every barrier, whose dispatch may itself run
    collectives — the thread FENCES: no further collective until the
    actor reports that item applied. The gate decision derives only
    from exchanged bytes and rank-agreed table state, so every rank
    fences at the same windows and apply-side device collectives never
    interleave with exchange-thread allgathers in rank-divergent order.

    Failsafe: the collective itself stays deadline-bounded
    (fdeadline.bounded inside _mh_exchange_decode); a fence that never
    lifts (apply stage wedged) raises DeadlineExceeded under
    -mv_deadline_s. ANY escape parks the stage (``dead``) and emits
    ``("error", exc)`` — the actor fails every in-pipeline waiter and
    poisons itself, exactly the serial engine's fatal contract.
    """

    def __init__(self, srv: "Server"):
        self._srv = srv
        #: max exchanged-but-not-yet-applied items (-mv_pipeline_depth,
        #: default 2): bounds how far the exchange runs ahead (decoded
        #: windows pin their blobs in memory). Round 20: read through
        #: the listener cache at EVERY gate, not once per stage life —
        #: the policy plane tunes the flag live, and the cap is pacing
        #: only (window CONTENT stays the exchanged/agreed prefix), so
        #: ranks reading different values for a window or two cannot
        #: diverge the stream; they just fence at different depths.
        self.depth_cap = max(1, _pipeline_depth_flag())
        self._in: MtQueue = MtQueue()
        self.out: MtQueue = MtQueue()
        self._pending: Deque[Message] = collections.deque()
        self._emitted = 0
        self._applied = 0
        self._fence_at = 0
        #: why _fence_at was last raised (fence-cause profiling); the
        #: depth-cap stall is classified separately in _gate
        self._fence_cause = "barrier"
        self._cv = threading.Condition()
        self._killed = False
        self.dead: Optional[BaseException] = None
        #: overlap telemetry: wall-clock start of the in-flight exchange
        #: (0.0 = idle) + total busy seconds; the apply stage intersects
        #: its intervals against these (see Server._note_overlap)
        self.busy_since = 0.0
        self.busy_s = 0.0
        #: perf forensics: when the CURRENT pending run started filling
        #: (0.0 = empty) — the window's "form" phase is the stretch its
        #: verbs waited for the stage to pick them up
        self._pending_since = 0.0
        # the WORLD rank (elastic membership view), not the boot rank:
        # exchanged windows index by position in the current member
        # order. A stage never survives an epoch transition (the rebase
        # retires it), so binding at construction is sound.
        self._my_rank = multihost.world_rank()
        self._thread = threading.Thread(target=self._main,
                                        name="mv-engine-exchange",
                                        daemon=True)
        self._thread.start()

    # -- actor-side API -----------------------------------------------------

    def feed_verbs(self, msgs: List[Message]) -> None:
        self._in.Push(("verbs", msgs))

    def feed_barrier(self, msg: Message) -> None:
        self._in.Push(("barrier", msg))

    def note_applied(self) -> None:
        """The actor finished processing one emitted item — lifts the
        depth bound and any fence waiting on it."""
        with self._cv:
            self._applied += 1
            self._cv.notify_all()

    def stop(self) -> None:
        self._in.Push(("stop", None))
        self._in.Exit()

    def poison(self) -> None:
        """Apply-stage kill switch after a fatal engine error: a stage
        left with pending verbs must issue NO further collectives (the
        stream is desynced) and must not block shutdown on a fence the
        dead actor will never lift."""
        self._killed = True
        with self._cv:
            self._cv.notify_all()
        self._in.Exit()

    def depth(self) -> int:
        """Exchanged-but-unapplied items (diagnostics)."""
        return self._emitted - self._applied

    def pending_verbs(self) -> int:
        return len(self._pending)

    # -- stage thread -------------------------------------------------------

    def _wait_applied(self, upto: int, what: str) -> None:
        timeout = fdeadline.timeout_or_none()
        with self._cv:
            ok = self._cv.wait_for(
                lambda: self._applied >= upto or self._killed, timeout)
        if self._killed:
            raise _StageKilled()
        if not ok:
            fdeadline.raise_deadline(what, fatal=True)

    _GATE_WHAT = "pipelined engine apply fence (apply stage did not drain)"

    def _gate(self) -> None:
        """Before ANY new collective: honour the fence (a non-local
        apply or barrier dispatch may be running device collectives on
        the actor thread) and the pipeline depth bound.

        Fence-cause profiling (round 9): when the gate actually stalls,
        the stall is classified (the explicit fence's recorded cause,
        or ``depth`` when only the DEPTH cap holds it) and its seconds
        observed — this is the dataset behind raising overlap_pct."""
        # live depth (round 20): one cached-dict read per gate, so a
        # policy-plane -mv_pipeline_depth update takes effect at the
        # NEXT window instead of never
        self.depth_cap = max(1, _pipeline_depth_flag())
        depth_target = self._emitted - self.depth_cap + 1
        target = max(self._fence_at, depth_target)
        # advisory read (GIL-atomic int): only classifies; correctness
        # stays with the cv wait below
        if self._applied >= target:
            self._wait_applied(target, self._GATE_WHAT)
            return
        cause = (self._fence_cause if self._fence_at >= depth_target
                 else "depth")
        t0 = _time.perf_counter()
        self._wait_applied(target, self._GATE_WHAT)
        self._srv._note_fence(cause, _time.perf_counter() - t0)

    def _main(self) -> None:
        try:
            self._loop()
        except _StageKilled as exc:
            # actor-side kill: every waiter was already failed there —
            # park dead WITHOUT emitting an error item
            self.dead = self.dead or exc
        except BaseException as exc:  # delivered to the apply stage
            self.dead = exc
            self.out.Push(("error", exc))

    def _loop(self) -> None:
        items: Deque = collections.deque()
        while not self._killed:
            # absorb everything already queued (larger windows, and a
            # barrier behind queued verbs is seen before we block)
            while True:
                ok, it = self._in.TryPop()
                if not ok:
                    break
                items.append(it)
            if not items and not self._pending:
                ok, it = self._in.Pop()     # idle: block for work
                if not ok:
                    return
                items.append(it)
                continue
            # input order is admission order: only LEADING verb items
            # may join pending ahead of a queued barrier
            while items and items[0][0] == "verbs":
                if not self._pending:
                    self._pending_since = _time.perf_counter()
                self._pending.extend(items.popleft()[1])
            if self._pending:
                self._exchange_one()
                continue
            kind, payload = items.popleft()
            if kind == "stop":
                return
            # barrier head: marker exchange at this stream position;
            # its dispatch (actor side) may run collectives, so fence
            # until the actor reports it done
            self._gate()
            self._srv._mh_check_barrier_head(payload)
            self._emitted += 1
            self._fence_at = self._emitted
            self._fence_cause = "barrier"
            self.out.Push(("barrier", payload))

    def _exchange_one(self) -> None:
        srv = self._srv
        self._gate()
        verbs = list(self._pending)
        t0 = _time.perf_counter()
        self.busy_since = t0
        # perf forensics: the window's phase record, threaded through
        # the exchange (this thread) into the apply stage (the actor),
        # emitted as ONE compact flight event at apply-done
        ph = None
        if srv._phases_on():
            ph = {}
            if self._pending_since:
                ph["form"] = max(0.0, t0 - self._pending_since)
        try:
            # the "server.window" span opens HERE (parented to the head
            # verb, exactly like the serial engine) so the nested
            # exchange span stays its child and the apply stage parents
            # its apply span to it — one tree per window across both
            # stage threads
            with ttrace.span("server.window", cat="server",
                             parent=verbs[0].trace_ctx,
                             args={"pending": len(verbs)}) as win_ctx:
                _tp = _time.perf_counter()
                local, used = srv._mh_pack_window(verbs)
                if ph is not None:
                    ph["pack"] = _time.perf_counter() - _tp
                windows = srv._mh_exchange_decode(local, self._my_rank,
                                                  ph)
        finally:
            now = _time.perf_counter()
            self.busy_since = 0.0
            self.busy_s += now - t0
            a0 = srv._apply_since
            if a0:
                # this exchange ended while an apply was running: the
                # overlapped stretch is ours to record (the apply-side
                # intersection only sees exchanges still in flight)
                srv._note_overlap(max(0.0, now - max(a0, t0)))
        prefix = min(len(w) for w in windows)
        descs = [[(k, t) for k, t, _ in w[:prefix]] for w in windows]
        srv._flight_exchanged(descs, self._my_rank)
        CHECK(all(d == descs[0] for d in descs),
              f"multi-process verb streams diverge inside a window: "
              f"{descs} — every process must issue the same table-verb "
              f"sequence (the SPMD collective contract)")
        for _ in range(prefix):
            self._pending.popleft()
        self._emitted += 1
        # re-led verbs' form clock restarts HERE: form measures how
        # long the next window's head waited since the stage could
        # have started it (the previous window's cut), not since the
        # verb's original arrival — a stalled run would otherwise read
        # cumulative, unbounded form times
        self._pending_since = (_time.perf_counter() if self._pending
                               else 0.0)
        fence_cause = srv._mh_fence_cause(descs[0], windows, prefix)
        if fence_cause is not None:
            self._fence_at = self._emitted
            self._fence_cause = fence_cause
        if ph is not None:
            ph["seq"] = srv._mh_seq - 1
            ph["mepoch"] = multihost.membership_epoch()
        self.out.Push(("window", used[:prefix], windows, prefix, descs[0],
                       t0, win_ctx, ph, fence_cause))


class Server(Actor):
    """Async server engine (reference server.cpp:23-58)."""

    def __init__(self, name: str = actor_names.kServer):
        super().__init__(name)
        self.store_: List = []  # ServerTable list (reference server.h:24)
        #: round 12 — sharded engine: which wire channel this engine's
        #: window stream exchanges on, and the matching flight-event
        #: stream id ((mepoch, stream, SEQ) keying). 0 for the
        #: unsharded engine and shard 0; sub-shards override both.
        self.mh_channel = 0
        self.mh_stream = 0
        #: lazy apply-stage worker pool (-mv_apply_workers)
        self._apply_pool = None
        #: True while this engine is the ONLY window stream issuing
        #: collectives in a multi-process world (a ShardedServer with
        #: live sub-shards sets False on every shard: collective
        #: applies then CHECK-fail loudly — see _mh_fence_cause)
        self.mh_single_collective_stream = True
        #: windows split by a non-Get/Add barrier message (observability +
        #: lets tests assert the barrier path actually engaged)
        self.window_barrier_splits = 0
        #: multi-process windowed protocol observability: verbs processed
        #: through collective windows / window exchanges issued
        self.mh_window_verbs = 0
        self.mh_window_exchanges = 0
        #: ... and the Add-application economics the burst tests assert:
        #: dispatches actually issued (merged run = 1), runs that merged
        #: across positions AND ranks, and positions whose values rode
        #: the DEVICE wire (transport selection; see -window_transport)
        self.mh_add_dispatches = 0
        self.mh_add_run_merged = 0
        self.mh_device_wire_adds = 0
        #: standing exchange capacities per window-head descriptor
        #: (multihost.capped_exchange) — evolves identically on every
        #: rank, keeping steady exchanges to ONE collective round
        self._mh_caps: Dict = {}
        #: failsafe: window-exchange sequence stamp. Incremented only on
        #: a SUCCESSFUL exchange, so every rank's counter marches in
        #: lockstep; a rank that re-enters the exchange alone (after an
        #: asymmetric CRC failure) pairs with its peers' NEXT round and
        #: the seq mismatch CHECK fires loudly on every rank instead of
        #: silently merging different windows
        self._mh_seq = 0
        # telemetry (telemetry/metrics.py; NULL instruments when off).
        # The mh_* int attributes above stay — tests assert them — and
        # the typed instruments mirror them into snapshots/exports.
        self._t_window_s = tmetrics.histogram("server.window.latency_s")
        self._t_encode_s = tmetrics.histogram("server.wire.encode_s")
        self._t_decode_s = tmetrics.histogram("server.wire.decode_s")
        self._t_exchanges = tmetrics.counter("server.window.exchanges")
        self._t_verbs = tmetrics.counter("server.window.verbs")
        self._t_splits = tmetrics.counter("server.window.barrier_splits")
        self._t_dispatch = tmetrics.counter("server.add.dispatches")
        self._t_merged = tmetrics.counter("server.add.run_merged")
        self._t_defer = tmetrics.counter("server.add.device_deferrals")
        #: host-vs-device transport byte accounting: what this rank
        #: actually shipped on the host staging wire vs what it kept
        #: local for the device-parts collectives (DeferredArray)
        self._t_host_bytes = tmetrics.counter("server.wire.host_bytes")
        self._t_dev_bytes = tmetrics.counter("server.wire.device_bytes")
        self._t_budget = tmetrics.gauge("server.window.host_budget_bytes")
        #: failsafe: (src, msg_id) at-most-once window for Adds + its
        #: hit counter (worker retries / duplicate deliveries answered
        #: from the record instead of re-applying)
        try:
            dedup_cap = int(GetFlag("mv_dedup_window"))
        except Exception:
            dedup_cap = 4096
        self._dedup = DedupWindow(dedup_cap)
        self._t_dedup_hits = tmetrics.counter("failsafe.dedup_hits")
        # registered eagerly (not on first increment) so a healthy run's
        # MV_MetricsSnapshot() shows the failsafe machinery at ZERO —
        # dashboards can alert on these without probing for existence
        tmetrics.counter("failsafe.deadline_exceeded")
        tmetrics.counter("failsafe.retries")
        tmetrics.counter("wire.crc_failures")
        # round 7 — pipelined engine + worker-side fast paths:
        #: windows applied by THIS engine (every topology) — the
        #: worker-side staleness-bounded Get cache's epoch source
        #: (tables/base.py; a plain int: GIL-atomic reads from workers)
        self.window_epoch = 0
        #: exchange/apply overlap telemetry: percentage of exchange-
        #: stage busy seconds that ran concurrently with an apply
        self._t_overlap_pct = tmetrics.gauge("engine.overlap_pct")
        tmetrics.counter("worker.write_combine_hits")   # eager (see above)
        tmetrics.counter("worker.get_cache_hits")
        # round 9 — fence-cause profiling: every pipelined-stage stall
        # classified (FENCE_CAUSES above) + its seconds. Registered
        # eagerly so the -stats_interval_s reporter and /metrics show
        # the whole breakdown at zero from the first scrape — the
        # dataset the ROADMAP's overlap attack reads.
        for _cause in FENCE_CAUSES:
            tmetrics.counter(f"engine.fence.{_cause}")
        self._t_fence_stall_s = tmetrics.histogram("engine.fence.stall_s")
        #: last classified fence cause (dashboard [Ops] line probe)
        self.last_fence_cause = ""
        # round 11 — perf forensics: phase histograms + per-family
        # apply seconds + the local binding-phase gauge, all registered
        # EAGERLY so /metrics and the -stats_interval_s reporter show
        # the whole taxonomy at zero from the first scrape
        # handles CACHED on the engine (not looked up per window: the
        # registry get takes a lock + an f-string — measurable against
        # the <=2% phase-stamp budget on the blocking round)
        self._t_phase = {p: tmetrics.histogram(f"engine.phase.{p}_s")
                         for p in ENGINE_PHASES}
        #: round 22 fleet digest: whole-window seconds (phase totals),
        #: merged across ranks via the heartbeat rollups so /fleet can
        #: quote a fleet-wide window p99. Handle cached like _t_phase —
        #: a per-window registry get would bill the 2% budget.
        self._d_window = tmetrics.digest("digest.engine.window_s")
        self._t_apply_fam = {
            fam: tmetrics.histogram(f"engine.apply.table_s.{fam}")
            for fam in _TABLE_FAMILIES}
        #: tid -> (family, histogram) cache for the apply attribution
        self._fam_cache: Dict[int, tuple] = {}
        #: locally-dominant lifecycle phase of the last stamped window,
        #: encoded as its ENGINE_PHASES index (-1 = none yet). A LOCAL
        #: proxy only — the cross-rank binding verdict needs every
        #: rank's dump (telemetry/critpath.py); the handler serving
        #: this stays never-collective.
        self._t_binding = tmetrics.gauge("engine.binding_phase")
        self._t_binding.set(-1.0)
        self.last_binding_phase = ""
        #: round 13 — watchdog plane saturation surfaces. apply_busy_s
        #: accumulates this STREAM's total apply seconds as a plain
        #: float (one add per window — the watchdog/ops refresh mirrors
        #: it into the engine.shard<k>.* gauges off the hot path; a
        #: per-window gauge.set would bill its lock against the 2%
        #: blocking-round budget). xw_busy_s accumulates seconds
        #: blocked inside the window-exchange collective the same way.
        #: Both are UNCONDITIONAL — the watchdog's straggler rule reads
        #: them so it keeps working with ``-mv_phase_stamps=0`` or the
        #: flight recorder off. The per-stream binding gauge is
        #: resolved lazily: sub-shards learn their stream id AFTER
        #: construction.
        self.apply_busy_s = 0.0
        self.xw_busy_s = 0.0
        #: round 20 — policy-plane routing inputs, accumulated
        #: UNCONDITIONALLY on the actor thread (plain dict int/float
        #: adds; apply-pool jobs return private dicts that merge here,
        #: so only the engine-shard domain ever writes these):
        #: per-table verbs this stream processed, and per-table apply
        #: seconds (multi-process windows). The shard_imbalance ->
        #: routing-map decider picks the hottest table of the hottest
        #: stream from exactly these tallies (rebalance.plan_routing).
        self.table_verbs: Dict[int, int] = {}
        self.table_apply_s: Dict[int, float] = {}
        self._t_binding_st = None
        self._t_pool_jobs = tmetrics.counter("engine.apply_pool.jobs")
        self._t_pool_inline = tmetrics.counter(
            "engine.apply_pool.inline_jobs")
        #: single-process window counter for the 1-in-N full-stamp
        #: sampling + the current window's stamp decision (read by
        #: _local_window for the per-table attribution gating)
        self._ph_tick = 0
        self._ph_stamp_this = False
        self._ex_stage: Optional[_ExchangeStage] = None
        self._apply_since = 0.0   # apply interval start (overlap calc)
        self._overlap_s = 0.0
        self._overlap_lock = threading.Lock()
        self.RegisterHandler(MsgType.Request_Get, self._get_entry)
        self.RegisterHandler(MsgType.Request_Add, self._add_entry)
        # round 19 — batched verb envelopes flatten into the window at
        # drain time (_expand_multi), so the window entry handles them;
        # counters registered eagerly (the PR 6 scrape-at-zero rule)
        self.RegisterHandler(MsgType.Request_MultiVerb, self._get_entry)
        self._t_multi = tmetrics.counter("engine.multi_verb_batches")
        self._t_multi_size = tmetrics.histogram("engine.multi_verb_size")
        self.RegisterHandler(MsgType.Server_Finish_Train, self.ProcessFinishTrain)
        # barrier ping: replies once the mailbox drained up to this point —
        # must NOT touch the BSP clocks, unlike FinishTrain (native
        # ServerC registers the same handler, native/src/store.cc)
        self.RegisterHandler(MsgType.Request_Barrier, lambda m: m.reply(None))
        # table persistence on the engine thread: the snapshot/restore in
        # payload["fn"] cannot race applied Adds (native kStoreTable/
        # kLoadTable parity, native/src/store.cc HandleStoreLoad)
        self.RegisterHandler(MsgType.Request_StoreLoad, self._store_load_entry)
        # serving-plane snapshot publish (round 8, serving/snapshot.py):
        # a non-verb message, so the window machinery above makes it a
        # BARRIER — windows split around it and the multi-process
        # head-marker exchange proves every rank dispatches it at the
        # same stream position. payload["fn"] captures every table at
        # that position: the consistent cut costs nothing beyond the
        # ordering the engine already enforces. SAME handler as
        # StoreLoad on purpose: checkpoint saves and publishes are one
        # cut mechanism (Zoo.CallOnEngine), so they cannot drift.
        self.RegisterHandler(MsgType.Request_Publish,
                             self._store_load_entry)

    #: worker-side fast paths gate on the engine's consistency mode:
    #: the async engine's contract (a Get may observe more progress,
    #: never less) admits both; the BSP SyncServer counts Get/Add
    #: MESSAGES into its vector clocks, so combining N Adds into one
    #: message (or serving a Get without a message) would desync the
    #: round accounting — SyncServer overrides both to False.
    GET_CACHE_OK = True
    WRITE_COMBINE_OK = True
    #: round 19 — whether this engine flattens Request_MultiVerb
    #: envelopes. The async window engine does (members become ordinary
    #: window verbs); the BSP SyncServer processes messages strictly
    #: one at a time, so Zoo.SendToServerMulti falls back to delivering
    #: the members individually there (same stream order, unbatched).
    MULTI_VERB_OK = True

    def receive_multi(self, members) -> None:
        """Accept one batched verb submission: wrap the pre-built
        member messages in a Request_MultiVerb envelope and push it —
        ONE mailbox hop for the whole batch. The envelope's on_reply
        forwards a failure reply (actor death sweep / handler error on
        the envelope itself) to every member, so batch waiters raise
        typed instead of hanging when the engine dies mid-flight."""
        env = Message(msg_type=MsgType.Request_MultiVerb,
                      payload={"members": list(members)},
                      on_reply=_fail_multi_members)
        # straight to the mailbox (poison check + push): routing
        # already happened — ShardedServer.receive_multi split the
        # batch per shard before delegating here, and going back
        # through its Receive override would re-split forever
        Actor.Receive(self, env)

    def _expand_multi(self, batch: list) -> list:
        """Flatten Request_MultiVerb envelopes into their member verbs
        IN PLACE of the envelope's drain position — the members enter
        the window in submission order, ahead of anything drained after
        the envelope, which is exactly the serial-stream order N single
        submits would have produced. Members carry no mailbox enqueue
        stamp, so note_dequeue skips them (the envelope's one stamp
        already accounted the hop)."""
        out: list = []
        for m in batch:
            if m.msg_type is MsgType.Request_MultiVerb:
                self.note_dequeue(m)
                members = m.payload["members"]
                self._t_multi.inc()
                self._t_multi_size.observe(len(members))
                out.extend(members)
            else:
                out.append(m)
        return out

    def RegisterTable(self, server_table) -> int:
        table_id = len(self.store_)
        self.store_.append(server_table)
        # the id on the table itself: the perf-forensics surfaces
        # (apply attribution, row-skew sketch metrics) name tables by
        # family+id without walking the store
        server_table.table_id = table_id
        # replica plane (round 17): attach the publish dirty journal at
        # registration so the first post-publish interval is covered
        # from the table's birth (a late-attached journal costs one
        # full-payload fan-out). One cached-flag read when off.
        from multiverso_tpu import replica as _replica
        _replica.maybe_attach_journal(server_table)
        return table_id

    def Stop(self) -> None:
        if self._ex_stage is not None:
            self._ex_stage.stop()
        pool, self._apply_pool = self._apply_pool, None
        if pool is not None:
            # no join: the actor drain above already applied every
            # window, and the workers are daemons — a wedged job can
            # never hold the interpreter's exit hostage
            pool.shutdown()
        super().Stop()

    # -- round 12: sharded-engine facade points (the unsharded engine
    # IS shard 0 of a 1-shard world; ShardedServer overrides these) ----------

    def epoch_for_table(self, table_id: int) -> int:
        """Window epoch of the stream applying ``table_id``'s verbs —
        the worker-side Get cache's staleness clock (tables/base.py).
        Per-shard in a sharded engine: a busy NEIGHBOR shard must not
        age another table's cache entries."""
        return self.window_epoch

    def cut_epoch(self) -> int:
        """Total windows applied across every stream — the stream
        position a cross-stream cut (snapshot/checkpoint) is taken at
        (serving/snapshot.py stamps it into the published version)."""
        return self.window_epoch

    def shard_states(self) -> List[dict]:
        """Per-shard live state for /healthz and the dashboard
        [Engine] line (LOCAL, never collective)."""
        st = self._ex_stage
        return [{
            "shard": self.mh_stream,
            "actor": self.name,
            "poisoned": repr(self._poison) if self._poison is not None
            else None,
            "mailbox_depth": self.mailbox.Size(),
            "window_epoch": self.window_epoch,
            "window_exchanges": self.mh_window_exchanges,
            "apply_busy_s": round(self.apply_busy_s, 6),
            "xw_busy_s": round(self.xw_busy_s, 6),
            "window_verbs": self.mh_window_verbs,
            # snapshot copies: the watchdog/policy samplers hold these
            # across ticks while the actor keeps mutating the originals
            "table_verbs": dict(self.table_verbs),
            "table_apply_s": {t: round(v, 6)
                              for t, v in self.table_apply_s.items()},
            "stage": None if st is None else {
                "depth": st.depth(),
                "pending_verbs": st.pending_verbs(),
                "mid_exchange": bool(st.busy_since),
                "dead": repr(st.dead) if st.dead is not None else None,
            },
        }]

    def _flight_exchanged(self, descs, my_rank: int) -> None:
        """Flight event for one completed exchange: THIS rank's verbs
        over the AGREED prefix, recorded BEFORE the cross-rank
        divergence CHECK — so a diverging window is in the ring when
        the CHECK aborts it, which is what forensics.correlate aligns.
        The prefix (not the full local pack) is deliberate: ragged
        drains legally pack different window LENGTHS per rank, and a
        full-pack descriptor would read as a false divergence on a
        healthy stream."""
        if tflight.enabled():
            tflight.record("window.exchanged", seq=self._mh_seq - 1,
                           epoch=self.window_epoch,
                           mepoch=multihost.membership_epoch(),
                           stream=self.mh_stream,
                           detail=",".join(f"{k}{t}"
                                           for k, t in descs[my_rank]))

    def _note_fence(self, cause: str, stall_s: float) -> None:
        """Account one pipelined-stage stall: ``engine.fence.<cause>``
        counter + the stall-seconds histogram + a flight event. Called
        from the exchange stage thread only."""
        tmetrics.counter(f"engine.fence.{cause}").inc()
        self._t_fence_stall_s.observe(stall_s)
        self.last_fence_cause = cause
        tflight.record("fence", seq=self._mh_seq,
                       epoch=self.window_epoch,
                       mepoch=multihost.membership_epoch(),
                       stream=self.mh_stream, detail=cause)

    def _note_overlap(self, s: float) -> None:
        """Record ``s`` seconds of exchange/apply concurrency (called by
        whichever stage's interval closed while the other was active)
        and refresh the engine.overlap_pct gauge."""
        if s <= 0:
            return
        st = self._ex_stage
        with self._overlap_lock:
            self._overlap_s += s
            busy = st.busy_s if st is not None else 0.0
            if busy > 0:
                self._t_overlap_pct.set(
                    min(100.0, 100.0 * self._overlap_s / busy))

    # -- perf forensics: phase stamping (round 11) --------------------------

    def _phases_on(self) -> bool:
        """The phase-stamping gate: two cached flag reads (the flight
        recorder's listener-cached capacity + -mv_phase_stamps)."""
        return _phase_stamps_flag() and tflight.enabled()

    def _binding_stream_gauge(self):
        """The PER-STREAM binding-phase gauge (round 13 — the global
        ``engine.binding_phase`` is one name, so N shard streams would
        overwrite each other's verdicts). Lazy: a sub-shard's stream id
        is assigned after construction. Only touched when the binding
        phase CHANGES, so the lookup amortizes to nothing."""
        g = self._t_binding_st
        if g is None:
            g = self._t_binding_st = tmetrics.gauge(
                f"engine.stream{self.mh_stream}.binding_phase")
        return g

    def _ph_emit(self, ph: dict, nverbs: int) -> None:
        """Emit one window's phase record: the ``window.phases`` flight
        event (keyed by (mepoch, SEQ); durations in integer
        microseconds) + the engine.phase.*_s histograms + the local
        binding-phase gauge. Offsets in the detail re-anchor the
        window's monotonic landmarks to the event's OWN ``tm`` stamp:

        * ``xd`` — microseconds from exchange-done back to the event's
          ``tm`` (so exchange-done's wall time = the event's ``t`` -
          xd/1e6, which is the cross-rank rendezvous critpath aligns
          clocks on);
        * ``ax`` — microseconds from exchange-done to apply-start (the
          decode + depth-queue gap).

        Single-process windows carry only ``a`` (there is no exchange);
        their seq stays -1, which keeps them out of the cross-rank
        stream alignment by construction — and they take the fast path
        below, because they ARE the blocking hot loop the tier-1
        overhead guard times."""
        apply_s = ph.get("apply", 0.0)
        if "x" not in ph:
            # apply-only window: one observe + one flight record (the
            # gauge only moves when the binding phase CHANGES)
            if apply_s > 0.0:
                self._t_phase["apply"].observe(apply_s)
                self._d_window.observe(apply_s)
                if self.last_binding_phase != "apply":
                    self.last_binding_phase = "apply"
                    self._t_binding.set(
                        float(ENGINE_PHASES.index("apply")))
                    self._binding_stream_gauge().set(
                        float(ENGINE_PHASES.index("apply")))
            tflight.record("window.phases", seq=ph.get("seq", -1),
                           epoch=self.window_epoch,
                           mepoch=ph.get("mepoch", 0),
                           stream=self.mh_stream,
                           detail=f"v={nverbs};a={int(apply_s * 1e6)}")
            return
        durs = {"form": ph.get("form", 0.0), "pack": ph.get("pack", 0.0),
                "encode": ph.get("encode", 0.0),
                "exchange": ph.get("x", 0.0),
                "exchange_wait": ph.get("xw", 0.0),
                "decode": ph.get("dec", 0.0),
                "apply": ph.get("apply", 0.0)}
        for name, secs in durs.items():
            if secs > 0.0:
                self._t_phase[name].observe(secs)
        # window total for the fleet digest: exchange already contains
        # its wait portion, so the wait is not added again
        self._d_window.observe(sum(durs.values()) - durs["exchange_wait"])
        # local binding proxy: the phase that dominated this window's
        # wall locally (exchange_wait stands in for "a peer bound us")
        cand = {k: v for k, v in durs.items() if k != "exchange"}
        binding = max(cand, key=cand.get) if any(cand.values()) else ""
        if binding and binding != self.last_binding_phase:
            self.last_binding_phase = binding
            self._t_binding.set(float(ENGINE_PHASES.index(binding)))
            self._binding_stream_gauge().set(
                float(ENGINE_PHASES.index(binding)))
        parts = [f"v={nverbs}"]
        for tag, key in (("f", "form"), ("p", "pack"), ("e", "encode"),
                         ("x", "exchange"), ("xw", "exchange_wait"),
                         ("d", "decode"), ("a", "apply")):
            if durs[key] > 0.0:
                parts.append(f"{tag}={int(durs[key] * 1e6)}")
        x_done_m = ph.get("x_done_m", 0.0)
        if x_done_m:
            # anchor offsets vs a mono stamp taken JUST before record()
            # samples its own (the gap is the record call itself, ~us —
            # inside the documented alignment error bound)
            now_m = _time.perf_counter()
            parts.append(f"xd={int((now_m - x_done_m) * 1e6)}")
            a_start = ph.get("a_start_m", 0.0)
            if a_start:
                parts.append(f"ax={int((a_start - x_done_m) * 1e6)}")
        tflight.record("window.phases", seq=ph.get("seq", -1),
                       epoch=self.window_epoch,
                       mepoch=ph.get("mepoch", 0),
                       stream=self.mh_stream,
                       detail=";".join(parts))

    def _ph_tables(self, tbl: dict, seq: int, mepoch: int) -> None:
        """Apply-time attribution per (table, verb): one
        ``window.tables`` flight event (``<family><tid>:<A|G>=<us>``)
        + the per-family engine.apply.table_s.* histograms — the
        dataset that names WHICH table's ProcessAddRun is the
        depth-fence culprit."""
        parts = []
        items = (tbl.items() if len(tbl) == 1 else sorted(tbl.items()))
        for (tid, verb), secs in items:
            cached = self._fam_cache.get(tid)
            if cached is None:
                try:
                    fam = _table_family(self.store_[tid])
                except Exception:
                    fam = "table"
                hist = self._t_apply_fam.get(
                    fam) or tmetrics.histogram(
                        f"engine.apply.table_s.{fam}")
                cached = self._fam_cache[tid] = (fam, hist)
            fam, hist = cached
            hist.observe(secs)
            parts.append(f"{fam}{tid}:{verb}={int(secs * 1e6)}")
        if parts:
            tflight.record("window.tables", seq=seq,
                           epoch=self.window_epoch, mepoch=mepoch,
                           stream=self.mh_stream,
                           detail=";".join(parts))

    # -- elastic plane hooks (round 10, elastic/) ---------------------------

    def _elastic_rebase(self, mepoch: int, cause: str) -> None:
        """Epoch transition, ON the engine thread with the stream
        fenced: re-base the exchange stream for the new world — SEQ
        back to 0 (every surviving member re-bases at the same cut, so
        the counters stay lockstep), standing caps dropped (the world
        size changed, so per-key exchanged buffer shapes changed), and
        the exchange stage retired (the next window builds a fresh one
        bound to the new world rank)."""
        st = self._ex_stage
        if st is not None:
            st.poison()
            st.dead = st.dead or _StageKilled()
            self._ex_stage = None
        self._mh_seq = 0
        self._mh_caps.clear()
        tflight.record("membership.epoch", seq=0,
                       epoch=self.window_epoch, mepoch=mepoch,
                       detail=f"cause={cause}")
        Log.Info("engine: exchange stream re-based for membership "
                 "epoch %d (%s)", mepoch, cause)

    def _elastic_post_transition(self, pending) -> bool:
        """After a barrier dispatch that performed an epoch transition:
        when the new world is single-member the collective protocol is
        gone — drain the remaining pipeline/batch contents through the
        local window path and report True."""
        if multihost.world_size() > 1:
            return False
        batch = list(pending)
        pending.clear()
        if batch:
            self._local_window(batch)
            self.window_epoch += 1
            tflight.record("window.applied", epoch=self.window_epoch,
                           mepoch=multihost.membership_epoch(),
                           stream=self.mh_stream,
                           detail=f"{len(batch)}v")
        return True

    @staticmethod
    def _bounded_collective(fn, what: str):
        """fdeadline.bounded + membership-lease consult: a deadline on
        a collective asks the elastic authority whether a peer's lease
        expired BEFORE going fatal — a dead peer converts the deadline
        into the typed MembershipChanged the transition path handles
        (heartbeat leases riding the failsafe deadline machinery). No
        elastic plane (or every lease fresh): the DeadlineExceeded
        propagates exactly as before."""
        try:
            return fdeadline.bounded(fn, what)
        except MembershipChanged:
            raise
        except BaseException as exc:
            # a dead peer surfaces either as the deadline OR as a
            # transport error from the abandoned collective — both
            # consult the lease. Fresh leases: the original error
            # re-raises untouched (genuine divergence stays fatal).
            from multiverso_tpu import elastic
            repl = elastic.peer_loss(what) if elastic.enabled() else None
            if repl is not None:
                raise repl from exc
            raise

    #: how many queued messages one Get/Add drains into its window.
    #: Each pipelined Get hides one device->host copy RTT, queued Adds to
    #: one table coalesce into one merged dispatch, and identical queued
    #:  Gets share one gather; the window stays modest so other messages
    #: are not starved for long.
    GET_PIPELINE_WINDOW = 16

    def _admit(self, msg: Message) -> bool:
        """Failsafe admission gate, applied to every drained message
        BEFORE it can enter a window's verb stream.

        (1) At-most-once Adds: the (src, msg_id) dedup window answers a
        duplicate — a mailbox dup or a worker retry after a failed ack —
        from the recorded outcome instead of re-applying, and keeps it
        OUT of the SPMD verb stream, where an extra verb on one rank
        would trip the cross-rank divergence CHECK.

        (2) Chaos rehearsal: the armed injector may reject a tracked
        verb with TransientError before applying (driving the worker
        retry path) or mark an Add to apply-then-fail-its-ack (driving
        the retry INTO the dedup window). Decisions are consulted for
        every verb in admission order, so two SPMD ranks with the same
        seed fault the same lockstep positions."""
        if (msg.msg_type in (MsgType.Request_Add, MsgType.Request_Get)
                and getattr(msg, "_fs_admitted", False)):
            # duplicate delivery of the SAME object (a mailbox dup):
            # the admitted copy owns the reply — drop silently. Object
            # identity needs no window slot, so this holds for
            # fire-and-forget Adds too — and it covers Gets, whose
            # duplicate would double-tick the BSP get clock and desync
            # the SyncServer's round accounting.
            self._t_dedup_hits.inc()
            tflight.record("dedup.hit", epoch=self.window_epoch,
                           detail=f"obj src{msg.src}")
            return False
        if msg.msg_type is MsgType.Request_Add and msg.msg_id:
            key = (msg.src, msg.msg_id)
            tracked = msg.waiter is not None
            if tracked and self._dedup.seen(key):
                self._t_dedup_hits.inc()
                tflight.record("dedup.hit", epoch=self.window_epoch,
                               detail=f"retry src{msg.src}")
                ready, outcome = self._dedup.outcome(key)
                msg.reply(outcome if ready else TransientError(
                    "duplicate Add while the original is in flight"))
                return False
            failack = False
            cz = chaos.get()
            if cz is not None:
                action = cz.verb_action(tracked=tracked)
                if action == "transient":
                    msg.reply(TransientError("chaos: transient verb "
                                             "fault (pre-apply)"))
                    return False
                failack = action == "failack"
            msg._fs_admitted = True
            if tracked:
                # only TRACKED Adds occupy dedup slots: they are the
                # only ones a worker can retry, and a high-rate
                # fire-and-forget burst must not evict a pending retry
                # record (that eviction would break at-most-once)
                self._dedup.record(key)
                self._fs_wrap_reply(msg, key, failack)
            return True
        if msg.msg_type is MsgType.Request_Get:
            cz = chaos.get()
            if (cz is not None
                    and cz.verb_action(tracked=msg.waiter is not None)
                    == "transient"):
                # Gets only take the pre-serve transient fault — they
                # are idempotent (retry re-serves), so failack has
                # nothing to rehearse (the draw still advances, keeping
                # schedules lockstep across ranks)
                msg.reply(TransientError("chaos: transient verb fault"))
                return False
            msg._fs_admitted = True
        return True

    def _fs_wrap_reply(self, msg: Message, key, failack: bool) -> None:
        """Shadow ``msg.reply`` so the apply outcome lands in the dedup
        window the moment it is known (whichever engine path replies),
        and — chaos failack — the ACK delivered to the worker is
        corrupted into a TransientError while the recorded outcome stays
        truthful: the retry must be answered from the record, not
        re-applied."""
        orig = msg.reply
        dedup = self._dedup

        def _reply(result=None):
            dedup.set_outcome(key, result)
            if failack and not isinstance(result, Exception):
                orig(TransientError("chaos: ack failed after apply"))
            else:
                orig(result)

        msg.reply = _reply

    def _get_entry(self, msg: Message) -> None:
        """Window handler for Request_Get AND Request_Add, async engine.

        Drains a window of already-queued messages, then:

        * ADD COALESCING — all Adds to one table inside the window apply
          as ONE merged dispatch (table.ProcessAddRun) at the position of
          the table's FIRST Add. Later Adds of the run thereby land
          before any Get queued between them — legal under the async
          contract (a Get may observe MORE progress, never less: every
          coalesced Add was already enqueued when the Get was). Falls
          back to per-message ProcessAdd when the table declines the
          merge (aux updaters, multihost, validation doubts). Any
          OTHER message type (StoreLoad, flag sets, ...) is a window
          BARRIER: runs split at it, so an Add acknowledged before a
          Load is never re-applied after the restore.
        * GET DEDUP — identical queued Gets (same table, payload,
          option) share one device gather; extra repliers get copies.
        * GET PIPELINING — distinct Gets overlap their device->host
          copies (dispatch all, finalize after), as before.

        SyncServer overrides both entries with its unbatched clocked
        path: the BSP defer/drain protocol must see messages strictly
        one at a time."""
        batch = [msg]
        while len(batch) < self.GET_PIPELINE_WINDOW:
            ok, nxt = self.mailbox.TryPop()
            if not ok:
                break
            batch.append(nxt)
        # round 19 — batched verb envelopes flatten here, BEFORE
        # admission/windowing: each member is an ordinary stream verb
        # from this point on (dedup slots, chaos draws, window
        # positions, replies), so one envelope = one admission but N
        # lockstep stream positions
        batch = self._expand_multi(batch)
        for m in batch:
            # drained members bypass _dispatch — observe their queue
            # wait here (idempotent; the head was noted there already,
            # and multi members carry no enqueue stamp)
            self.note_dequeue(m)
        # failsafe admission (dedup + chaos) BEFORE windowing: a
        # duplicate or chaos-rejected verb must never become a stream
        # position (divergent descriptors across ranks otherwise)
        batch = [m for m in batch if self._admit(m)]
        if not batch:
            return
        if multihost.world_size() > 1:
            # multi-process WINDOWED protocol (round 5): one host
            # collective exchanges the whole window; verbs then apply
            # from the exchanged parts with cross-rank coalescing/dedup.
            self._mh_windows(batch)
            return
        _t0 = _time.perf_counter()
        phases = self._phases_on()
        if phases:
            self._ph_tick += 1
            self._ph_stamp_this = (self._ph_tick
                                   & (_PH_SP_SAMPLE - 1)) == 1
        else:
            self._ph_stamp_this = False
        with ttrace.span("server.window", cat="server",
                         args={"verbs": len(batch)}):
            self._local_window(batch)
        self.window_epoch += 1     # worker get-cache staleness clock
        tflight.record("window.applied", epoch=self.window_epoch,
                       stream=self.mh_stream,
                       detail=f"{len(batch)}v")
        _win_s = _time.perf_counter() - _t0
        self._t_window_s.observe(_win_s)
        # a single-process window's whole body IS apply — the per-shard
        # load number the watchdog's imbalance rule compares (one plain
        # float add: within the blocking-round overhead budget)
        self.apply_busy_s += _win_s
        if phases:
            # single-process window: the whole body is apply (there is
            # no exchange); seq stays -1 so these never enter the
            # cross-rank stream alignment. The apply histogram sees
            # EVERY window; the flight record rides the 1-in-N sample
            # (see the -mv_phase_stamps help text)
            if self._ph_stamp_this:
                self._ph_emit({"apply": _win_s}, len(batch))
            else:
                self._t_phase["apply"].observe(_win_s)
        # count Add/Get verbs only, like the mh path's prefix count —
        # the counter must mean the same thing in every topology
        self._t_verbs.inc(sum(1 for m in batch if m.msg_type in
                              (MsgType.Request_Add, MsgType.Request_Get)))

    def _local_window(self, batch) -> None:
        """Apply one drained single-process window (see _get_entry)."""
        # Any non-Get/Add message (e.g. Request_StoreLoad's Load) mutates
        # table state outside the Add/Get algebra: it BARRIERS the window.
        # Adds must not coalesce across it (a Load between two Adds would
        # apply the later Add before the restore and silently wipe it),
        # and a Get queued after it must not join a gather dispatched
        # before it.
        segments: list = [[]]
        for m in batch:
            if m.msg_type in (MsgType.Request_Add, MsgType.Request_Get):
                segments[-1].append(m)
                # round 20 — policy routing input (actor thread only)
                if m.table_id >= 0:
                    self.table_verbs[m.table_id] = (
                        self.table_verbs.get(m.table_id, 0) + 1)
            else:
                segments.append(m)       # barrier marker
                segments.append([])
        pending = []   # (finalize, [msgs]) in dispatch order
        seen: Dict[tuple, int] = {}
        # perf forensics: per-(table, verb) apply seconds — only on the
        # 1-in-N sampled windows (_get_entry decides; the elastic
        # post-transition drain path leaves the flag wherever the last
        # window set it, which is fine for a sampled surface)
        tbl = {} if self._ph_stamp_this else None
        for seg in segments:
            if not isinstance(seg, list):
                # barrier: runs its normal handler in order, with
                # standard error routing; no dedup survives it
                self.window_barrier_splits += 1
                self._t_splits.inc()
                tflight.record("barrier", epoch=self.window_epoch,
                               stream=self.mh_stream,
                               detail=MsgType(seg.msg_type).name)
                self._dispatch(seg)
                seen.clear()
                continue
            add_runs: Dict[int, list] = {}
            n_gets = 0
            for m in seg:
                if m.msg_type is MsgType.Request_Add:
                    add_runs.setdefault(m.table_id, []).append(m)
                else:
                    n_gets += 1
            applied = set()
            for m in seg:
                if m.msg_type is MsgType.Request_Add:
                    if m.table_id not in applied:
                        applied.add(m.table_id)
                        _tt = (_time.perf_counter() if tbl is not None
                               else 0.0)
                        self._process_add_run(add_runs[m.table_id])
                        if tbl is not None:
                            k = (m.table_id, "A")
                            tbl[k] = (tbl.get(k, 0.0)
                                      + _time.perf_counter() - _tt)
                        # a Get queued after this Add must not join a
                        # gather dispatched before it (it would observe
                        # LESS progress than was enqueued ahead of it) —
                        # drop the table's dedup entries
                        seen = {k: v for k, v in seen.items()
                                if k[0] != m.table_id}
                else:
                    # key cost (tobytes of the payload arrays) only when
                    # the window could actually contain a duplicate
                    key = self._get_dedup_key(m) if n_gets > 1 else None
                    if key is not None and key in seen:
                        pending[seen[key]][1].append(m)
                        continue
                    _tt = (_time.perf_counter() if tbl is not None
                           else 0.0)
                    with monitor_region("SERVER_PROCESS_GET"):
                        try:
                            table = self.store_[m.table_id]
                            finalize = table.ProcessGetAsync(**m.payload)
                            if finalize is None:
                                self.ProcessGet(m)
                            else:
                                if key is not None:
                                    seen[key] = len(pending)
                                pending.append((finalize, [m]))
                        except Exception as exc:
                            # failures (bad table id included) reply to
                            # THIS message only — an escape here would
                            # abandon every pending finalize and hang
                            # their waiters
                            Log.Error("table ProcessGet dispatch failed: "
                                      "%r", exc)
                            m.reply(exc)
                    if tbl is not None:
                        k = (m.table_id, "G")
                        tbl[k] = (tbl.get(k, 0.0)
                                  + _time.perf_counter() - _tt)
        for finalize, msgs in pending:
            _tt = _time.perf_counter() if tbl is not None else 0.0
            err = None
            try:
                result = finalize()
            except Exception as exc:
                Log.Error("table %d Get finalize failed: %r",
                          msgs[0].table_id, exc)
                err = exc
            if tbl is not None:
                k = (msgs[0].table_id, "G")
                tbl[k] = tbl.get(k, 0.0) + _time.perf_counter() - _tt
            if err is not None:
                for m in msgs:
                    m.reply(err)
                continue
            msgs[0].reply(result)
            for m in msgs[1:]:
                # each deduped caller owns its result arrays
                m.reply(copy_result(result))
        if tbl:
            self._ph_tables(tbl, -1, 0)

    # -- multi-process WINDOWED protocol (round 5) --------------------------
    # The r4 design took the strict path: every table verb ran its own
    # host collective (allgather merge), forfeiting windows, coalescing
    # and dedup in any nproc > 1 world (~2 host collectives per verb).
    # Now the engine exchanges a whole WINDOW of verbs in ONE allgather:
    # each rank packs its drained (kind, table, payload) prefix, the
    # ranks agree on the longest common verb prefix, and every rank then
    # holds EVERY rank's payloads for those verbs — so the merged
    # applies/gathers run from local data with no further host rounds,
    # and the single-process window optimizations return across ranks
    # (cross-rank add-coalescing via ProcessAddRunParts, union-gather
    # get-dedup via ProcessGetWindowParts). This restores the
    # reference's per-rank independence economics (worker.cpp:30-52,
    # server.cpp:23-58: requests fan out and apply as they arrive)
    # under the SPMD collective contract: every process still issues
    # the same verb sequence, but now pays ~2 host rounds per WINDOW
    # instead of ~2 per verb (multihost.STATS counts them; bench
    # two_proc_collectives_per_op is the metric).
    #
    # Ordering semantics match the single-process window: a table's
    # window Adds apply at its FIRST Add position (a Get queued after
    # that observes more progress — legal, every coalesced Add was
    # already enqueued when the Get was); Gets group per (table,
    # before/after-the-add-run segment) so no Get ever observes LESS
    # than strict order would show it. Non-verb messages (StoreLoad,
    # barriers, FinishTrain) split the window exactly as before and
    # dispatch in strict global order — their position in the verb
    # stream is lockstep because prefix processing is.
    #
    # Round 6 — adaptive transport: the window rides the FLAT BINARY
    # codec (parallel/wire.py) instead of pickle, and per Add verb the
    # engine picks the wire the reference's allreduce engine would
    # (size-adaptive, allreduce_engine.cpp:31-55): small payloads stay
    # on the host staging allgather; large eligible payloads ship only
    # their dtype/shape metadata and the VALUES ride the table's
    # device-parts collectives (-window_transport /
    # -window_device_min_bytes; bench.py measures the crossover).

    def _mh_windows(self, batch) -> None:
        """Process drained messages through collective windows until
        nothing remains (blocking in the exchange while peers catch up
        is the protocol's flow control, exactly as the r4 per-verb
        collectives blocked). Verbs beyond an exchange's agreed prefix
        stay in the local deque and lead the NEXT exchange — the loop
        always drains fully before returning.

        Round 7: with ``-mv_pipeline`` (default) the exchange half runs
        on the dedicated stage thread and THIS thread becomes the apply
        stage — window N applies while window N+1 exchanges whenever
        the overlap gate allows (see _ExchangeStage). The serial path
        below is byte-identical to the round-5/6 engine.

        A DeadlineExceeded from the exchange (peer gone / diverged,
        -mv_deadline_s set) fails EVERY drained message — their waiters
        raise instead of hanging — and then propagates with its fatal
        mark so the actor poisons itself: after an abandoned collective
        this rank's collective stream is unsound.

        ELASTIC EXCEPTION (round 10): a MembershipChanged — a peer's
        heartbeat lease expired, confirmed by the coordinator when the
        exchange deadline consulted it — is NOT fatal when the elastic
        plane can transition: the engine rolls every table back to the
        retained snapshot cut on the shrunk world's mesh, re-bases the
        exchange stream (SEQ 0, caps dropped, stage retired) and stays
        healthy; the drained messages fail with the TYPED error (their
        effects were rolled back with everything after the cut) so the
        worker re-runs from its last elastic sync point — continuity,
        not a full-world restart."""
        pending: Deque[Message] = collections.deque(batch)
        try:
            try:
                if _pipeline_flag():
                    self._mh_pipelined(pending)
                else:
                    self._mh_windows_inner(pending)
            except MembershipChanged as exc:
                from multiverso_tpu import elastic
                if self._ex_stage is not None:
                    st = self._ex_stage
                    st.poison()
                    st.dead = st.dead or exc
                    self._ex_stage = None
                if not elastic.engine_transition(self, exc):
                    raise       # no plane / no cut: the fatal path below
                for m in pending:
                    m.reply(exc)
                return
        except Exception as exc:
            # ANY escape aborts the stream mid-window — an abandoned
            # exchange (DeadlineExceeded), an exhausted frame retry or
            # corrupted barrier marker (WireCorruption), a desync/
            # divergence CHECK (FatalError) — and all of them leave
            # this rank's collective position unsound: fail every
            # drained waiter (per-position errors never escape; they
            # reply locally), then poison the actor so no further
            # collectives are issued from a desynced stream. The
            # pipelined path keeps ``pending`` holding every message
            # currently owned by EITHER stage, so both drain here —
            # and the stage is killed so it issues no further
            # collectives from the desynced stream.
            if self._ex_stage is not None:
                self._ex_stage.poison()
            # forensics: the abort itself becomes a ring event, then
            # the whole ring hits disk (when -mv_diag_dir is set) so a
            # diverged 2-proc world leaves per-rank dumps that
            # telemetry/forensics.py can align — BEFORE waiters are
            # failed, so a fast-exiting worker can't beat the dump
            tflight.record("engine.fatal", seq=self._mh_seq,
                           epoch=self.window_epoch,
                           mepoch=multihost.membership_epoch(),
                           stream=self.mh_stream,
                           detail=f"{type(exc).__name__}: "
                                  f"{exc}"[:200])
            tflight.dump_failure(
                f"engine window stream abort ({type(exc).__name__})")
            for m in pending:
                m.reply(exc)
            exc.mv_fatal = True
            raise

    # -- round 7: PIPELINED window engine (apply stage) ---------------------

    def _mh_pipelined(self, fed: "Deque[Message]") -> None:
        """Apply stage + scheduler: feed admitted messages to the
        exchange stage in admission order, keep draining the mailbox
        while exchanges are in flight (the NEXT window forms while the
        current one is still on the wire — this is where the overlap
        comes from), and apply completed windows strictly in emission
        (= SEQ) order. ``fed`` always holds every message owned by the
        pipeline, oldest first — the caller's error path fails exactly
        those."""
        stage = self._ex_stage
        if stage is None or stage.dead is not None:
            stage = self._ex_stage = _ExchangeStage(self)
        for m in fed:
            self._pl_feed(stage, m)
        deadline = fdeadline.timeout_or_none()
        stall_s = 0.0
        while fed:
            # opportunistic drain: verbs arriving during an exchange
            # join the stage's pending deque and form the next window
            # (bounded per spin so applies are never starved). Batched
            # envelopes flatten HERE too — without the expansion an
            # envelope would feed the stage as a barrier, a per-rank
            # timing artifact that diverges the SPMD streams (review
            # catch, round 19)
            for _ in range(64):
                ok, m = self.mailbox.TryPop()
                if not ok:
                    break
                if m.msg_type is MsgType.Request_MultiVerb:
                    for mm in self._expand_multi([m]):
                        if self._admit(mm):
                            fed.append(mm)
                            self._pl_feed(stage, mm)
                    continue
                self.note_dequeue(m)
                if self._admit(m):
                    fed.append(m)
                    self._pl_feed(stage, m)
            ok, item = stage.out.TryPop()
            if not ok:
                ok, item = stage.out.Pop(timeout=_PL_POLL_S)
            if not ok:
                # exchange still in flight (or waiting for peers). The
                # stage bounds its own collective; this guard catches a
                # stage that died without emitting (interpreter
                # teardown) — grace past the stage's own deadline so
                # its richer error wins the race when both fire.
                stall_s += _PL_POLL_S
                if deadline is not None and stall_s > deadline + 1.0:
                    fdeadline.raise_deadline(
                        "pipelined window flush (exchange stage stalled)",
                        fatal=True)
                continue
            stall_s = 0.0
            kind = item[0]
            if kind == "error":
                raise item[1]
            try:
                if kind == "barrier":
                    head = item[1]
                    CHECK(fed.popleft() is head,
                          "pipeline completion order desync (engine bug)")
                    self.window_barrier_splits += 1
                    self._t_splits.inc()
                    self._dispatch(head)
                else:
                    (_, mine, windows, prefix, descs0, t0, win_ctx,
                     ph, fcause) = item
                    # a fence-free window is host-local on EVERY rank
                    # (the same rank-agreed decision that allowed the
                    # overlap) — exactly the windows whose tables may
                    # apply concurrently without reordering collectives
                    self._pl_apply(mine, windows, prefix, descs0,
                                   win_ctx, ph,
                                   parallel_ok=fcause is None)
                    for m in mine:
                        CHECK(fed.popleft() is m,
                              "pipeline completion order desync "
                              "(engine bug)")
                    self._t_window_s.observe(_time.perf_counter() - t0)
            finally:
                # ALWAYS lift the stage's fence/depth gate — even when a
                # fatal apply error is about to poison the actor, the
                # stage must not hang inside _wait_applied
                stage.note_applied()
            if self._ex_stage is not stage:
                # an elastic rebase retired the stage inside that
                # barrier dispatch (epoch transition): the pipeline's
                # remaining contents re-anchor to the NEW world —
                # single-member worlds drain through the local window
                # path, otherwise a fresh stage (bound to the new
                # world rank, SEQ 0) takes over and the verbs re-lead
                # the new epoch's stream
                if self._elastic_post_transition(fed):
                    return
                stage = self._ex_stage = _ExchangeStage(self)
                for m in fed:
                    self._pl_feed(stage, m)

    def _pl_feed(self, stage: _ExchangeStage, m: Message) -> None:
        # envelopes must have been flattened by every feeding path —
        # one reaching the stage would become a bogus cross-rank
        # barrier position
        CHECK(m.msg_type is not MsgType.Request_MultiVerb,
              "unexpanded multi-verb envelope fed to the exchange "
              "stage (engine bug)")
        if m.msg_type in (MsgType.Request_Add, MsgType.Request_Get):
            stage.feed_verbs([m])
        else:
            stage.feed_barrier(m)

    def _pl_apply(self, verbs, windows, prefix, descs0, win_ctx,
                  ph=None, parallel_ok: bool = False) -> None:
        """Apply one exchanged window on the actor thread, recording
        the apply interval for the overlap telemetry (and closing the
        window's phase record — ``ph`` rode the stage's out queue from
        the exchange thread)."""
        t0 = _time.perf_counter()
        self._apply_since = t0
        if ph is not None:
            ph["a_start_m"] = t0
        try:
            with ttrace.span("server.window.apply", cat="server",
                             parent=win_ctx, args={"verbs": prefix}):
                self._mh_apply_window(verbs, windows, prefix, descs0,
                                      seq=(ph or {}).get("seq", -1),
                                      parallel_ok=parallel_ok)
        finally:
            now = _time.perf_counter()
            self._apply_since = 0.0
            self.apply_busy_s += now - t0
            st = self._ex_stage
            b0 = st.busy_since if st is not None else 0.0
            if b0:
                # an exchange is STILL in flight as this apply ends:
                # record the stretch both were busy (the stage records
                # the symmetric case when its exchange ends first)
                self._note_overlap(max(0.0, now - max(b0, t0)))
            self.window_epoch += 1
            if ph is not None:
                ph["apply"] = now - t0
                self._ph_emit(ph, prefix)
            tflight.record("window.applied", seq=self._mh_seq,
                           epoch=self.window_epoch,
                           mepoch=multihost.membership_epoch(),
                           stream=self.mh_stream,
                           detail=f"{prefix}v")

    def _mh_windows_inner(self, pending: "Deque[Message]") -> None:
        while pending:
            head = pending[0]
            if head.msg_type not in (MsgType.Request_Add,
                                     MsgType.Request_Get):
                # window barrier: strict-order dispatch (may itself run
                # collectives — matched, every rank hits it at the same
                # global verb position). The marker exchange makes a
                # cross-rank head MISMATCH (this rank at a barrier, a
                # peer exchanging verbs) fail the loud SPMD CHECK
                # instead of deadlocking in mismatched collectives.
                self._mh_check_barrier_head(head)
                pending.popleft()
                self.window_barrier_splits += 1
                self._t_splits.inc()
                self._dispatch(head)
                if self._elastic_post_transition(pending):
                    return
                continue
            verbs = []
            for m in pending:
                if m.msg_type in (MsgType.Request_Add, MsgType.Request_Get):
                    verbs.append(m)
                else:
                    break
            done = self._mh_collective_window(verbs)
            for _ in range(done):
                pending.popleft()

    #: byte budget for one exchange's packed payloads: verbs beyond it
    #: wait for the next exchange. Bounds the re-ship cost when ranks
    #: drain raggedly (a short peer prefix would otherwise make every
    #: retry re-pickle + re-transmit the whole pending run — O(W^2)
    #: bytes for a W-verb burst of large payloads).
    MH_WINDOW_BYTES = 4 << 20

    #: one shared byte-accounting rule with the worker-side telemetry
    #: counters (wire.payload_nbytes) — the budget and the counters
    #: must never drift
    _payload_bytes = staticmethod(wire.payload_nbytes)

    def _mh_check_barrier_head(self, head: Message) -> None:
        """Exchange a head-kind marker for a non-verb window head. Every
        rank reaches the same barrier at the same stream position in a
        legal SPMD program, so the markers agree; a divergent program
        (one rank at a StoreLoad while a peer exchanges verbs) trips the
        loud CHECK on every rank instead of stranding the verb rank in
        an unmatched collective. Best-effort when standing caps have
        already diverged across mismatched keys: the exchange itself
        then fails at the runtime layer (mismatched buffer shapes) —
        still an error, not a silent hang."""
        marker = wire.encode_head_barrier(int(head.msg_type))
        blobs = self._bounded_collective(
            lambda: multihost.capped_exchange(marker, self._mh_caps,
                                              "HEAD_B",
                                              channel=self.mh_channel),
            "window head-marker exchange")
        # seq of the NEXT exchange: barriers do not advance the SEQ
        # counter, so forensics aligns a barrier against the verbs a
        # diverged peer exchanged at that same seq
        tflight.record("barrier", seq=self._mh_seq,
                       epoch=self.window_epoch,
                       mepoch=multihost.membership_epoch(),
                       stream=self.mh_stream,
                       detail=MsgType(head.msg_type).name)
        kinds = [wire.decode_head_kind(b) for b in blobs]
        CHECK(all(k == kinds[0] for k in kinds),
              f"multi-process window heads diverge: {kinds} — every "
              f"process must reach the same barrier/verb at the same "
              f"stream position (the SPMD collective contract)")

    def _mh_transport(self) -> str:
        mode = _window_transport_flag()
        CHECK(mode in ("auto", "host", "device"),
              f"-window_transport must be auto/host/device, got {mode!r}")
        return mode

    def _mh_maybe_defer(self, tid: int, payload: dict, mode: str,
                        min_bytes: int) -> dict:
        """Transport selection, per Add verb at pack time (the
        reference's payload-size-adaptive wire pick): when the device
        wire is selected and the table can apply this payload through
        its device-parts collectives, replace the ``values`` array with
        a wire.DeferredArray — the exchange then ships only dtype/shape
        metadata and the bytes ride the device. The decision is
        rank-local (peers may differ); the APPLY decision is taken from
        the exchanged metadata (any rank deferred -> device path), so
        every rank still runs the identical program. ``mode`` and
        ``min_bytes`` are parsed ONCE per window by the caller (flags
        cannot change mid-window)."""
        if mode == "host":
            return payload
        v = payload.get("values")
        if isinstance(v, wire.DeferredArray):   # re-led window leftover
            return payload
        if not isinstance(v, np.ndarray):
            return payload
        if not wire.dtype_wire_safe(v.dtype):
            # extension dtypes (bfloat16 &c) have no flat wire header;
            # their payloads stay whole on the host pickle fallback
            return payload
        if mode == "auto" and v.nbytes < min_bytes:
            return payload
        try:
            table = self.store_[tid]
        except Exception:
            return payload      # bad table id: the apply path reports it
        if not table.device_wire_add_ok(payload):
            return payload
        out = dict(payload)
        out["values"] = wire.DeferredArray.of(v)
        self._t_defer.inc()
        self._t_dev_bytes.inc(v.nbytes)
        return out

    def _mh_collective_window(self, verbs) -> int:
        """One collective window: exchange, agree on the common prefix,
        execute it from the exchanged parts. Returns how many of this
        rank's ``verbs`` were processed (>= 1)."""
        _t_start = _time.perf_counter()
        with ttrace.span("server.window", cat="server",
                         parent=verbs[0].trace_ctx,
                         args={"verbs": len(verbs)}):
            done = self._mh_collective_window_inner(verbs)
        self._t_window_s.observe(_time.perf_counter() - _t_start)
        return done

    #: collective re-exchange attempts after a CRC-detected corrupt
    #: frame. Recovery relies on SYMMETRIC detection — every rank sees
    #: the same round corrupted, which holds for fabric-level faults of
    #: the shared round and (by construction) for the seeded chaos
    #: schedule — so each rank re-enters the exchange in lockstep. An
    #: ASYMMETRIC corruption leaves the detecting rank raising
    #: WireCorruption after its retries while peers move on: a loud
    #: error, bounded on the peers by -mv_deadline_s — never silently
    #: decoded garbage.
    MH_WIRE_RETRIES = 2

    def _mh_exchange_decode(self, local, my_rank: int,
                            ph: Optional[dict] = None) -> list:
        """Encode + exchange + decode one window, deadline-bounded,
        retrying the full (collective) exchange when a received frame
        fails its CRC32 trailer. Returns every rank's verb list.

        ``ph`` (perf forensics, round 11): accumulates this window's
        encode/exchange/decode phase seconds — exchange split into
        total wall vs time BLOCKED IN THE COLLECTIVE
        (multihost.last_exchange_stats), whose done-stamps anchor the
        cross-rank clock alignment. CRC retries accumulate into the
        same phases (the retry cost is real window cost); the stamps
        kept are the SUCCESSFUL exchange's."""
        last_exc = None
        for attempt in range(1 + self.MH_WIRE_RETRIES):
            # flat binary codec (parallel/wire.py): pickle's object-
            # graph walk + buffer copies were pure overhead for payloads
            # that are already contiguous arrays; decode below is
            # zero-copy. server.wire.encode_s times the CODEC only
            # (bench compares it against the pickled baseline)
            _t0 = _time.perf_counter()
            blob = wire.encode_window(local, seq=self._mh_seq)
            _enc_s = _time.perf_counter() - _t0
            self._t_encode_s.observe(_enc_s)
            if ph is not None:
                ph["encode"] = ph.get("encode", 0.0) + _enc_s
            cz = chaos.get()
            if cz is not None:
                bad = cz.corrupt_blob(blob)
                if bad is not None:
                    blob = bad
            self._t_host_bytes.inc(len(blob))
            # standing-cap exchange keyed by the window HEAD verb: the
            # head is the same global verb on every rank (FIFO + common-
            # prefix processing), and per-head payload sizes are stable
            # in steady loops — so the exchange stays on the 1-round path
            _tx = _time.perf_counter()
            with ttrace.span("server.window.exchange", cat="server",
                             args={"bytes": len(blob)}):
                blobs = self._bounded_collective(
                    lambda: multihost.capped_exchange(
                        blob, self._mh_caps, (local[0][0], local[0][1]),
                        channel=self.mh_channel),
                    "window exchange")
            xs = multihost.last_exchange_stats()
            # plain-attr accumulation (one float add, no stamps needed):
            # the watchdog straggler rule's collective-wait input
            self.xw_busy_s += xs.get("coll_s", 0.0)
            if ph is not None:
                ph["x"] = ph.get("x", 0.0) + _time.perf_counter() - _tx
                ph["xw"] = ph.get("xw", 0.0) + xs["coll_s"]
                # rendezvous anchor: every rank leaves this allgather
                # at ~the same instant (critpath's clock-offset source)
                ph["x_done_m"] = xs["done_m"]
                ph["x_done_w"] = xs["done_w"]
            _t0 = _time.perf_counter()
            try:
                windows: list = []
                for i, b in enumerate(blobs):
                    if i == my_rank:
                        # our own verbs verbatim — no decode round-trip,
                        # and deferred values keep their .local arrays.
                        # COMPRESSED values are the one exception: every
                        # rank must apply the identical dequantized
                        # reconstruction (the peers decode eagerly in
                        # the flat codec; we run the same envelope
                        # decode here), else lossy codecs would diverge
                        # the SPMD replicas
                        windows.append(compress.materialize_window(local))
                        continue
                    head_kind, head_mt = wire.decode_head_kind(b)
                    CHECK(head_kind == "window",
                          f"multi-process window heads diverge: rank {i} "
                          f"is at a non-verb barrier (msg_type {head_mt}) "
                          f"while rank {my_rank} exchanges verbs — every "
                          f"process must reach the same stream position "
                          f"(the SPMD collective contract)")
                    peer_seq, decoded = wire.decode_window_seq(b)
                    CHECK(peer_seq == (self._mh_seq & 0xFFFFFFFF),
                          f"window exchange desynchronized: rank {i} is "
                          f"at exchange {peer_seq}, rank {my_rank} at "
                          f"{self._mh_seq} — a rank re-entered the "
                          f"exchange alone (asymmetric frame corruption "
                          f"retry?); the stream cannot be trusted")
                    windows.append(decoded)
            except WireCorruption as exc:
                last_exc = exc
                tflight.record("wire.crc_retry", seq=self._mh_seq,
                               epoch=self.window_epoch,
                               mepoch=multihost.membership_epoch(),
                               stream=self.mh_stream,
                               detail=f"attempt{attempt + 1}")
                Log.Error("window exchange frame corrupt (attempt "
                          "%d/%d): %r — re-exchanging", attempt + 1,
                          1 + self.MH_WIRE_RETRIES, exc)
                continue
            _dec_s = _time.perf_counter() - _t0
            self._t_decode_s.observe(_dec_s)
            if ph is not None:
                ph["dec"] = ph.get("dec", 0.0) + _dec_s
            self._mh_seq += 1
            self.mh_window_exchanges += 1
            self._t_exchanges.inc()
            return windows
        # retries exhausted: this rank cannot re-enter the exchange
        # again without desyncing from peers — fatal for the actor
        last_exc.mv_fatal = True
        raise last_exc

    def _mh_pack_window(self, verbs):
        """Pack a window from ``verbs`` under the byte budget; returns
        ``(local, used)`` — the packed (kind, table, payload) records
        and the messages they came from (always >= 1). The budget
        counts what rides the HOST wire, so values deferred to the
        device wire (DeferredArray — dtype/shape header only) cost
        ~nothing here and a device-transport burst of large Adds still
        coalesces into one exchange."""
        mode = self._mh_transport()
        min_bytes = _window_device_min_bytes_flag()
        local = []
        used = []
        packed = 0
        for i, m in enumerate(verbs):
            kind = "A" if m.msg_type is MsgType.Request_Add else "G"
            payload = m.payload
            if kind == "A":
                payload = self._mh_maybe_defer(m.table_id, payload,
                                               mode, min_bytes)
                # -mv_compress: int8-quantize a lossy-opted table's Add
                # values for the host wire (parallel/compress.py tagged
                # envelope; a no-op for deferred/already-compressed
                # values). The apply side reconstructs through ONE
                # decode on every rank, our own included — see the
                # materialize step in _mh_exchange_decode
                payload = compress.pack_window_values(m.table_id,
                                                      payload)
                if payload is not m.payload:
                    # keep the deferred/compressed form on the message:
                    # a verb re-led after a short peer prefix / budget
                    # cut must not re-defer, re-compress (or re-count)
                    # on the next pack pass
                    m.payload = payload
            nbytes = self._payload_bytes(payload)
            if packed + nbytes > self.MH_WINDOW_BYTES and i > 0:
                # over-budget verb waits for the next exchange — its
                # bytes stay OUT of this window's budget accounting
                break
            packed += nbytes
            local.append((kind, m.table_id, payload))
            used.append(m)
        self._t_budget.set(packed)
        tflight.record("window.admitted", seq=self._mh_seq,
                       epoch=self.window_epoch,
                       mepoch=multihost.membership_epoch(),
                       stream=self.mh_stream,
                       detail=f"{len(used)}v/{packed}B")
        return local, used

    def _mh_fence_cause(self, descs0, windows, prefix) -> Optional[str]:
        """None when THIS window's apply runs entirely on the host —
        the pipelined engine's overlap gate — else the FENCE_CAUSES
        entry naming why it must fence (fence-cause profiling). Decided
        from EXCHANGED data (every rank holds identical windows) plus
        table state that evolves at lockstep verb positions
        (tables/base.py mh_apply_is_local contract), so every rank
        gates identically: overlap never pairs an apply-side device
        collective on one rank with an exchange-thread allgather on
        another."""
        tables_ok: Dict[int, bool] = {}
        cause = None
        for kind, tid in descs0:
            ok = tables_ok.get(tid)
            if ok is None:
                try:
                    ok = bool(self.store_[tid].mh_apply_is_local())
                except Exception:
                    ok = False   # bad table id: per-position error path
                tables_ok[tid] = ok
            if not ok:
                cause = "nonlocal_table"
                break
        if cause is None:
            for w in windows:
                for _, _, payload in w[:prefix]:
                    if wire.payload_has_deferred(payload):
                        cause = "device_wire"  # device values: collective
                        break
                if cause is not None:
                    break
        # round 12 — sharded multi-process worlds: a COLLECTIVE apply
        # (device program / gloo round inside the apply) is only sound
        # when ONE stream exists to order it. With N shard streams
        # live, shard A's collective apply could interleave with shard
        # B's in a different order on different ranks — loud CHECK
        # (with advice) instead of a silent rank-divergent deadlock.
        # (Cross-stream CUT payloads are exempt by construction: every
        # stream is fenced while they run.)
        if cause is not None:
            CHECK(self.mh_single_collective_stream,
                  f"window requires a collective apply ({cause}) but "
                  f"the engine runs {getattr(self, '_shard_cap', '>1')}"
                  f" shard streams in a multi-process world — "
                  f"collective applies need ONE ordered stream: run "
                  f"-mv_engine_shards=1, or keep every table's apply "
                  f"host-local (-window_transport=host + host-backed "
                  f"tables)")
        return cause

    def _mh_collective_window_inner(self, verbs) -> int:
        my_rank = multihost.world_rank()
        ph = {} if self._phases_on() else None
        _tp = _time.perf_counter()
        local, used = self._mh_pack_window(verbs)
        if ph is not None:
            ph["pack"] = _time.perf_counter() - _tp
        windows = self._mh_exchange_decode(local, my_rank, ph)
        prefix = min(len(w) for w in windows)
        descs = [[(k, t) for k, t, _ in w[:prefix]] for w in windows]
        self._flight_exchanged(descs, my_rank)
        CHECK(all(d == descs[0] for d in descs),
              f"multi-process verb streams diverge inside a window: "
              f"{descs} — every process must issue the same table-verb "
              f"sequence (the SPMD collective contract)")
        seq = self._mh_seq - 1
        if ph is not None:
            ph["seq"] = seq
            ph["mepoch"] = multihost.membership_epoch()
            ph["a_start_m"] = _time.perf_counter()
        _ta = _time.perf_counter()
        self._mh_apply_window(used[:prefix], windows, prefix, descs[0],
                              seq=seq)
        self.apply_busy_s += _time.perf_counter() - _ta
        self.window_epoch += 1
        if ph is not None:
            ph["apply"] = _time.perf_counter() - ph["a_start_m"]
            self._ph_emit(ph, prefix)
        tflight.record("window.applied", seq=self._mh_seq,
                       epoch=self.window_epoch,
                       mepoch=multihost.membership_epoch(),
                       stream=self.mh_stream,
                       detail=f"{prefix}v")
        return prefix

    def _mh_apply_window(self, verbs, windows, prefix, descs0,
                         seq: int = -1,
                         parallel_ok: bool = False) -> None:
        """Apply one exchanged window's agreed prefix: cross-rank
        coalesced add runs + deduped get groups, replies to this rank's
        own messages. Shared by the serial engine and the pipelined
        apply stage — the semantics (ordering, grouping, error routing)
        are identical in both. ``seq`` is this window's exchange SEQ
        (perf forensics: keys the per-table apply attribution; -1 when
        phases are off).

        ``parallel_ok`` (round 12): DIFFERENT tables' segments of this
        window apply concurrently on the -mv_apply_workers pool. Only
        set for windows whose apply is host-local on every rank (the
        pipelined overlap gate's rank-agreed decision): per-table op
        order stays serial — determinism untouched — while a window
        that fenced (collective applies) keeps the strict interleaved
        position order below, because collective device/host programs
        must issue in one agreed order."""
        my_rank = multihost.world_rank()
        self.mh_window_verbs += prefix
        self._t_verbs.inc(prefix)
        # chaos rehearsal: a per-site APPLY delay on this rank only — a
        # perf fault, not a correctness one (the stream stays lockstep;
        # the delay models a slow apply stage, the straggler the
        # critpath drill must attribute). Consulted once per window.
        cz = chaos.get()
        if cz is not None:
            _delay = cz.apply_delay()
            if _delay > 0.0:
                _time.sleep(_delay)
        # round 20 — policy routing inputs: always-on per-table tallies
        # (one dict add per agreed position + two perf_counter calls
        # per window op — inside the 2% blocking-round budget)
        for _k, _tid in descs0:
            self.table_verbs[_tid] = self.table_verbs.get(_tid, 0) + 1
        tbl = {}
        # group per table: Add positions, and Get positions split into
        # the before/after segment around the table's one add-run
        add_pos: Dict[int, list] = {}
        for i, (kind, tid) in enumerate(descs0):
            if kind == "A":
                add_pos.setdefault(tid, []).append(i)
        get_groups: Dict[tuple, list] = {}   # (tid, segment) -> positions
        for i, (kind, tid) in enumerate(descs0):
            if kind == "G":
                seg = 0 if (tid not in add_pos or i < add_pos[tid][0]) else 1
                get_groups.setdefault((tid, seg), []).append(i)
        parts_at = [[w[i][2] for w in windows] for i in range(prefix)]
        # ONE ordered op list (first-position order, per-table dedup +
        # before/after-add get segmentation) feeds BOTH branches, so
        # the serial and parallel engines cannot drift on the window
        # grammar. The serial branch executes it in strict position
        # order — collective applies (fenced windows) must issue in
        # one agreed order; the parallel branch regroups per table.
        ops = self._mh_window_ops(descs0, add_pos, get_groups)
        n_tables = len({tid for _, tid, _ in ops})
        if (parallel_ok and n_tables > 1 and _apply_workers_flag() > 1):
            self._mh_apply_parallel(ops, parts_at, verbs, my_rank, tbl)
        else:
            self._mh_run_ops(ops, parts_at, verbs, my_rank, tbl)
        for (_tid, _k), _v in tbl.items():
            self.table_apply_s[_tid] = (self.table_apply_s.get(_tid, 0.0)
                                        + _v)
        if tbl and self._phases_on():
            self._ph_tables(tbl, seq, multihost.membership_epoch())

    @staticmethod
    def _mh_window_ops(descs0, add_pos, get_groups) -> list:
        """The window's op list in first-position order:
        ``("A", tid, positions)`` once per table's merged add run,
        ``("G", tid, positions)`` once per (table, before/after-add
        segment) get group. Within a table the order is its serial
        apply order (seg-0 gets precede the add run precede seg-1
        gets, because their first positions do)."""
        ops = []
        applied: set = set()
        served: set = set()
        for i, (kind, tid) in enumerate(descs0):
            if kind == "A":
                if tid in applied:
                    continue
                applied.add(tid)
                ops.append(("A", tid, add_pos[tid]))
            else:
                seg = (0 if (tid not in add_pos
                             or i < add_pos[tid][0]) else 1)
                if (tid, seg) in served:
                    continue
                served.add((tid, seg))
                ops.append(("G", tid, get_groups[(tid, seg)]))
        return ops

    def _mh_run_ops(self, ops, parts_at, verbs, my_rank: int,
                    tbl) -> dict:
        """Execute window ops in the given order (the shared worker
        body of the serial branch and each parallel job); accumulates
        per-(table, verb) apply seconds into ``tbl`` when given and
        also returns them (parallel jobs pass a private dict)."""
        for kind, tid, positions in ops:
            _tt = _time.perf_counter() if tbl is not None else 0.0
            if kind == "A":
                with ttrace.span("server.window.add_run", cat="server",
                                 args={"table_id": tid,
                                       "positions": len(positions)}):
                    self._mh_add_run(tid, positions, parts_at, verbs,
                                     my_rank)
            else:
                with ttrace.span("server.window.get_group",
                                 cat="server",
                                 args={"table_id": tid}):
                    self._mh_get_group(tid, positions, parts_at,
                                       verbs, my_rank)
            if tbl is not None:
                k = (tid, kind)
                tbl[k] = tbl.get(k, 0.0) + _time.perf_counter() - _tt
        return tbl

    def _ensure_apply_pool(self) -> "_ApplyPool":
        """The apply-stage worker pool at the LIVE ``-mv_apply_workers``
        size (round 20): the policy plane tunes the flag at a fenced
        cut, and the next parallel window rebuilds the pool when the
        size changed. Safe between windows on the actor thread — every
        prior window's jobs were waited for, so the retired pool's
        queue is empty when it closes; its daemon workers just exit."""
        want = max(2, min(_apply_workers_flag(), 16))
        pool = self._apply_pool
        if pool is None or pool.workers != want:
            if pool is not None:
                pool.shutdown()
            pool = self._apply_pool = _ApplyPool(want, self.name)
        return pool

    def _mh_apply_parallel(self, ops, parts_at, verbs, my_rank: int,
                           tbl) -> None:
        """Round 12 — the parallel apply: the shared op list regrouped
        into per-table ordered jobs (a table's serial order is kept)
        run concurrently across tables on the worker pool. Only
        reached for host-local windows (see _mh_apply_window), where
        different tables share no state and issue no collectives, so
        the cross-table interleaving the serial branch produces was
        never observable."""
        jobs: Dict[int, list] = {}
        for op in ops:
            jobs.setdefault(op[1], []).append(op)
        pool = self._ensure_apply_pool()
        job_lists = list(jobs.values())
        # the LAST job runs inline on the actor thread: one fewer
        # handoff, and the pool only ever carries n_tables - 1 jobs
        boxes = [pool.submit(lambda j=j: self._mh_run_ops(
            j, parts_at, verbs, my_rank,
            {} if tbl is not None else None))
            for j in job_lists[:-1]]
        # pool-utilization accounting (watchdog plane): jobs handed to
        # the worker pool vs the one job that always runs inline here
        self._t_pool_jobs.inc(len(boxes))
        self._t_pool_inline.inc()
        results = [self._mh_run_ops(job_lists[-1], parts_at, verbs,
                                    my_rank,
                                    {} if tbl is not None else None)]
        deadline = fdeadline.timeout_or_none()
        t0 = _time.perf_counter()
        for box in boxes:
            left = (None if deadline is None
                    else max(0.0, deadline - (_time.perf_counter() - t0)))
            if not box["done"].wait(left):
                fdeadline.raise_deadline(
                    "parallel window apply (a table's apply job never "
                    "finished)", fatal=True)
            if "error" in box:
                raise box["error"]
            results.append(box.get("result"))
        if tbl is not None:
            for local in results:
                for k, v in (local or {}).items():
                    tbl[k] = tbl.get(k, 0.0) + v

    def _mh_add_run(self, tid: int, positions, parts_at, verbs,
                    my_rank: int) -> None:
        """A table's window-worth of collective Adds: merged across
        positions AND ranks when the table accepts, per-position
        otherwise. Positions whose values rode the DEVICE wire (any
        rank's part holds a DeferredArray — visible identically on
        every rank from the exchanged metadata) apply through the
        table's device-parts collectives and never join a host merge —
        as ONE merged device round when the table offers
        ProcessAddRunPartsDevice, per position otherwise.
        Failures reply to this rank's own messages only — every rank
        reaches identical decisions from identical parts."""
        try:
            table = self.store_[tid]
        except Exception as exc:
            for p in positions:
                verbs[p].reply(exc)
            return
        deferred = {p for p in positions
                    if any(isinstance(q.get("values"), wire.DeferredArray)
                           for q in parts_at[p])}
        # the HOST-wire subset still merges when device-wire positions
        # share the run — one large deferred Add must not demote the
        # small-burst positions back to per-position dispatches
        host_pos = [p for p in positions if p not in deferred]
        pending = list(positions)
        if len(host_pos) > 1:
            try:
                merged = bool(table.ProcessAddRunParts(
                    [parts_at[p] for p in host_pos], my_rank))
            except Exception as exc:
                Log.Error("table %d merged parts Add failed: %r", tid, exc)
                for p in pending:
                    verbs[p].reply(exc)
                return
            if merged:
                self.mh_add_dispatches += 1
                self.mh_add_run_merged += 1
                self._t_dispatch.inc()
                self._t_merged.inc()
                for p in host_pos:
                    verbs[p].reply(None)
                pending = [p for p in pending if p in deferred]
        # ...and the DEVICE-wire subset merges too: one collective parts
        # round for the run's deferred positions when the table offers
        # ProcessAddRunPartsDevice (decisions from exchanged metadata,
        # so every rank merges or declines identically)
        dev_pos = [p for p in pending if p in deferred]
        if len(dev_pos) > 1:
            try:
                dev_merged = bool(table.ProcessAddRunPartsDevice(
                    [parts_at[p] for p in dev_pos], my_rank))
            except Exception as exc:
                Log.Error("table %d merged device Add failed: %r", tid, exc)
                for p in pending:
                    verbs[p].reply(exc)
                return
            if dev_merged:
                self.mh_add_dispatches += 1
                self.mh_add_run_merged += 1
                self.mh_device_wire_adds += len(dev_pos)
                self._t_dispatch.inc()
                self._t_merged.inc()
                for p in dev_pos:
                    verbs[p].reply(None)
                pending = [p for p in pending if p not in deferred]
        for p in pending:
            with monitor_region("SERVER_PROCESS_ADD"):
                try:
                    if p in deferred:
                        table.ProcessAddPartsDevice(parts_at[p], my_rank)
                        self.mh_device_wire_adds += 1
                    else:
                        table.ProcessAddParts(parts_at[p], my_rank)
                    self.mh_add_dispatches += 1
                    self._t_dispatch.inc()
                except Exception as exc:
                    Log.Error("table %d parts Add failed: %r", tid, exc)
                    verbs[p].reply(exc)
                    continue
            verbs[p].reply(None)

    def _mh_get_group(self, tid: int, positions, parts_at, verbs,
                      my_rank: int) -> None:
        """A (table, segment)'s collective Gets: one shared union gather
        when the table offers it, per-position otherwise."""
        try:
            table = self.store_[tid]
        except Exception as exc:
            for p in positions:
                verbs[p].reply(exc)
            return
        results = None
        if len(positions) > 1:
            try:
                results = table.ProcessGetWindowParts(
                    [parts_at[p] for p in positions], my_rank)
            except Exception as exc:
                Log.Error("table %d window parts Get failed: %r", tid, exc)
                for p in positions:
                    verbs[p].reply(exc)
                return
        if results is not None:
            CHECK(len(results) == len(positions),
                  "ProcessGetWindowParts result count mismatch")
            for p, res in zip(positions, results):
                verbs[p].reply(res)
            return
        for p in positions:
            with monitor_region("SERVER_PROCESS_GET"):
                try:
                    result = table.ProcessGetParts(parts_at[p], my_rank)
                except Exception as exc:
                    Log.Error("table %d parts Get failed: %r", tid, exc)
                    verbs[p].reply(exc)
                    continue
            verbs[p].reply(result)

    def _process_add_run(self, msgs) -> None:
        """Apply a table's window-worth of Adds: merged when the table
        accepts (ProcessAddRun validates BEFORE mutating and returns
        False to decline), per-message otherwise."""
        if len(msgs) > 1:
            try:
                table = self.store_[msgs[0].table_id]
                merged = table.ProcessAddRun([m.payload for m in msgs])
            except Exception as exc:
                # the run contract: state mutates only after validation,
                # so a raise here means the whole merged Add failed
                Log.Error("table %d merged Add failed: %r",
                          msgs[0].table_id, exc)
                for m in msgs:
                    m.reply(exc)
                return
            if merged:
                self._t_dispatch.inc()
                self._t_merged.inc()
                for m in msgs:
                    m.reply(None)
                return
        for m in msgs:
            self.ProcessAdd(m)

    @staticmethod
    def _get_dedup_key(m: Message):
        """Hashable identity of a Get's request, or None when any payload
        part can't be keyed (those never dedup)."""
        parts = [m.table_id]
        for k in sorted(m.payload):
            v = m.payload[k]
            if isinstance(v, np.ndarray):
                parts.append((k, v.dtype.str, v.shape, v.tobytes()))
            elif v is None or isinstance(v, (bool, int, float, str, bytes)):
                parts.append((k, v))
            elif isinstance(v, (GetOption, AddOption)):
                parts.append((k, repr(v)))
            else:
                return None
        return tuple(parts)

    def ProcessGet(self, msg: Message) -> None:
        with monitor_region("SERVER_PROCESS_GET"):
            try:
                # store_ lookup inside the try: a bad table id must reply
                # to THIS message, not escape and abandon the window
                result = self.store_[msg.table_id].ProcessGet(**msg.payload)
            except Exception as exc:
                # Deliver the failure to THIS request — critical when this
                # message is a drained cached message processed inside
                # another worker's request (SyncServer drain loops): the
                # actor-level fallback would mis-attribute the error to the
                # outer message and leave this one's waiter hung.
                Log.Error("table %d ProcessGet failed: %r", msg.table_id, exc)
                msg.reply(exc)
                return
            msg.reply(result)

    def _add_entry(self, msg: Message) -> None:
        """Request_Add enters the same window as Gets (coalescing — see
        _get_entry). SyncServer re-binds this to its strict ProcessAdd."""
        self._get_entry(msg)

    def ProcessAdd(self, msg: Message) -> None:
        with monitor_region("SERVER_PROCESS_ADD"):
            try:
                # store_ lookup inside the try (see ProcessGet)
                self.store_[msg.table_id].ProcessAdd(**msg.payload)
            except Exception as exc:
                Log.Error("table %d ProcessAdd failed: %r", msg.table_id, exc)
                msg.reply(exc)
                return
            self._t_dispatch.inc()
            msg.reply(None)

    def ProcessFinishTrain(self, msg: Message) -> None:
        msg.reply(None)

    def _store_load_entry(self, msg: Message) -> None:
        """Engine-cut payload runner (StoreLoad AND Publish): run the
        message's fn at this stream position, reply its result."""
        try:
            msg.reply(msg.payload["fn"]())
        except Exception as exc:
            Log.Error("engine-cut payload fn (%s) failed: %r",
                      msg.msg_type.name, exc)
            msg.reply(exc)

    @staticmethod
    def GetServer(num_workers: int) -> "Server":
        """Factory mirroring reference server.cpp:224-232 — extended
        (round 12) with the sharded engine: ``-mv_engine_shards``
        resolves through :func:`engine_shard_cap`, and a cap > 1
        builds the router-fronted ShardedServer (1 = today's single
        engine byte-for-byte)."""
        if GetFlag("sync"):
            Log.Debug("Create a sync server")
            return SyncServer(num_workers)
        cap = engine_shard_cap()
        if cap > 1:
            Log.Debug("Create a sharded async server (%d shard slots)",
                      cap)
            return ShardedServer(cap)
        Log.Debug("Create an async server")
        return Server()


def requested_engine_channels() -> int:
    """How many independent wire channels the engine WANTS for this
    world — consulted by Zoo.Start BEFORE transport selection (the shm
    wire pre-creates its channel segments). The explicit
    ``-mv_engine_shards`` value; clamping modes (sync/elastic) and the
    multi-process auto default want one."""
    try:
        flag = int(GetFlag("mv_engine_shards"))
    except Exception:
        flag = 0
    if flag <= 1 or bool(GetFlag("sync")):
        return 1
    try:
        if bool(GetFlag("mv_elastic")):
            return 1
    except Exception:
        pass
    return flag


def engine_shard_cap() -> int:
    """Resolved engine shard-slot count for a NEW engine (see the
    ``-mv_engine_shards`` help text). The reference's actor runtime
    gives EVERY actor its own thread + mailbox (PAPER.md L1 — nothing
    forces one server actor); the clamps below are where this build's
    collective protocols genuinely do:

    * BSP (-sync): the vector clocks count verbs across all tables;
    * elastic epochs: the coordinator relay is one ordered channel;
    * multi-process on gloo: ONE globally-ordered collective stream —
      per-shard streams need a multi-channel wire's channels
      (-mv_wire: shm same-host, tcp cross-host)."""
    try:
        flag = int(GetFlag("mv_engine_shards"))
    except Exception:
        flag = 0
    if bool(GetFlag("sync")):
        return 1
    try:
        if bool(GetFlag("mv_elastic")):
            if flag > 1:
                Log.Info("engine: -mv_engine_shards=%d clamped to 1 "
                         "under -mv_elastic (the epoch relay is a "
                         "single ordered channel)", flag)
            return 1
    except Exception:
        pass
    if multihost.world_size() > 1:
        if flag <= 1:
            return 1        # auto: multi-process worlds opt in explicitly
        channels = multihost.wire_channels()
        if channels < flag:
            Log.Error("engine: -mv_engine_shards=%d needs %d "
                      "independent exchange channels but the active "
                      "wire offers %d (gloo is one ordered collective "
                      "stream — same-host worlds take -mv_wire=auto/"
                      "shm, cross-host worlds -mv_wire=tcp) — clamped "
                      "to 1", flag, flag, channels)
            return 1
        return flag
    if flag >= 1:
        return flag
    # auto, single-process: min(tables, cores/4) — the table bound
    # falls out of LAZY shard spawn (ShardedServer.RegisterTable)
    import os
    return max(1, min(8, (os.cpu_count() or 4) // 4))


#: non-verb message types the sharded router turns into CROSS-STREAM
#: CUTS (every shard fences at one agreed stream position, the payload
#: runs once, every shard releases): checkpoint/StoreLoad, serving
#: publish, the barrier drain ping, and FinishTrain. Any OTHER
#: non-verb type dispatches on shard 0 only (unknown types have no
#: cross-shard ordering to preserve).
def _fail_multi_members(env: Message) -> None:
    """on_reply of a Request_MultiVerb envelope: the ONLY reply an
    envelope ever takes is a failure sweep (actor poison via
    _fail_pending, or _dispatch's error routing when expansion itself
    raised) — forward it to every member so batch waiters raise typed
    instead of hanging on a dead engine. First-reply-wins on each
    member makes the forward idempotent against normal replies."""
    if isinstance(env.result, Exception):
        for m in env.payload.get("members", ()):
            m.reply(env.result)


_CUT_TYPES = (MsgType.Request_StoreLoad, MsgType.Request_Publish,
              MsgType.Request_Barrier, MsgType.Server_Finish_Train)


class _CutFence:
    """One cross-stream cut rendezvous (round 12).

    Every sub-shard's stream carries a fence message at the cut's
    position; its dispatch parks the shard here (``hold``). The head
    shard (the router, = shard 0) waits for every sub to arrive
    (``arrive_head``), runs the cut payload with ALL streams fenced —
    every verb admitted before the cut applied, none after, on every
    shard — then ``release``s the subs. All waits are poll-sliced and
    honour ``-mv_deadline_s``; a poisoned shard converts the wait into
    the typed ActorDied instead of a hang."""

    _POLL_S = 0.05

    def __init__(self, head: "Server", n_subs: int):
        self._head = head
        self._need = n_subs
        self._cv = threading.Condition()
        self._arrived = 0
        self._released = False
        self._abort: Optional[BaseException] = None

    def hold(self) -> None:
        """Sub-shard side: arrive, then block until the head releases
        the cut (or aborts / dies / the deadline expires)."""
        deadline = fdeadline.timeout_or_none()
        t0 = _time.perf_counter()
        with self._cv:
            self._arrived += 1
            self._cv.notify_all()
            while not self._released and self._abort is None:
                head_poison = getattr(self._head, "_poison", None)
                if head_poison is not None:
                    from multiverso_tpu.failsafe.errors import ActorDied
                    raise ActorDied(self._head.name, head_poison)
                self._cv.wait(self._POLL_S)
                if (deadline is not None
                        and _time.perf_counter() - t0 > deadline):
                    fdeadline.raise_deadline(
                        "cross-stream cut (the head shard never ran "
                        "the cut payload)", fatal=True)
            if self._abort is not None:
                raise self._abort

    def arrive_head(self, subs) -> None:
        """Head side: block until every sub-shard fenced. A dead sub
        (or an expired deadline) aborts the cut on every waiter."""
        deadline = fdeadline.timeout_or_none()
        t0 = _time.perf_counter()
        with self._cv:
            while self._arrived < self._need:
                for sub in subs:
                    if sub._poison is not None:
                        from multiverso_tpu.failsafe.errors import \
                            ActorDied
                        exc = ActorDied(sub.name, sub._poison)
                        self._abort = exc
                        self._cv.notify_all()
                        raise exc
                self._cv.wait(self._POLL_S)
                if (deadline is not None
                        and _time.perf_counter() - t0 > deadline):
                    try:
                        fdeadline.raise_deadline(
                            "cross-stream cut (a shard never fenced)",
                            fatal=True)
                    except BaseException as exc:
                        self._abort = exc
                        self._cv.notify_all()
                        raise

    def release(self) -> None:
        with self._cv:
            self._released = True
            self._cv.notify_all()


class _EngineShard(Server):
    """Sub-shard k of a :class:`ShardedServer`: a full engine actor —
    own thread, mailbox, window stream, exchange stage, SEQ counter,
    dedup window — whose ``store_`` is the SHARED table list and whose
    exchanges ride wire channel k (flight events stamped stream k).
    Non-verb messages only ever reach it as cut fences from the
    router."""

    def __init__(self, parent: "ShardedServer", slot: int):
        super().__init__(name=f"{actor_names.kServer}_shard{slot}")
        self.store_ = parent.store_     # ONE table list, router-owned
        self.mh_channel = slot
        self.mh_stream = slot
        for mt in _CUT_TYPES:
            self.RegisterHandler(mt, self._fence_entry)

    def _fence_entry(self, msg: Message) -> None:
        """Cut-fence dispatch: park this shard's stream until the head
        releases the cut. Failures reply typed (never hang the cut
        caller); a fatal abort (head death / deadline) re-raises so
        this shard poisons like any other desynced stream."""
        fence = (msg.payload or {}).get("_mv_fence")
        if fence is None:       # defensive: not a router fence
            msg.reply(None)
            return
        try:
            fence.hold()
        except Exception as exc:
            msg.reply(exc)
            if getattr(exc, "mv_fatal", False):
                raise
            return
        except BaseException as exc:
            # SystemExit & friends keep base-actor semantics: reply,
            # then let the escape kill + poison this shard's loop
            msg.reply(exc)
            raise
        msg.reply(None)


class ShardedServer(Server):
    """Round 12 — the sharded engine: this actor IS shard 0 and the
    router. Verbs route to a shard by ``table_id % shard_slots`` (rank-
    agreed arithmetic, so SPMD ranks agree on routing without
    negotiation) unless a ROUTING-MAP override is installed (round 20:
    the policy plane re-routes hot tables live via
    :meth:`install_routing`, at a fenced cross-stream cut so the change
    lands at one agreed position on every rank); each shard owns an
    independent window stream with
    its own exchange stage, SEQ counter and wire channel, so different
    tables' windows form, exchange and apply CONCURRENTLY — the fix
    for the flat ``host_scaling_Melem_s`` wall (ONE actor serialized
    every table). Sub-shards spawn LAZILY at table registration, so
    the effective shard count is min(tables, slots).

    Non-verb messages (checkpoint StoreLoad, serving Publish, barrier
    pings, FinishTrain) become CROSS-STREAM CUTS: every shard fences
    at the cut's position in ITS stream (in a multi-process world each
    fence is a barrier head-marker exchange on the shard's own
    channel, lockstep per shard by the SPMD contract), the payload
    runs ONCE with all streams fenced, then every shard releases. Every
    verb admitted before the cut is applied before the payload runs
    and none after — on every shard — which is exactly the PR 5
    publish-barrier soundness argument lifted to N streams (DESIGN.md
    §14)."""

    def __init__(self, shard_cap: int):
        super().__init__()
        CHECK(shard_cap >= 2,
              f"ShardedServer needs >= 2 shard slots, got {shard_cap}")
        self._shard_cap = shard_cap
        self._subs: Dict[int, _EngineShard] = {}
        #: round 20 — the table->shard ROUTING MAP: overrides on top of
        #: the ``table_id % shard_cap`` default. Installed ONLY inside
        #: a cross-stream cut payload (policy plane install_routing:
        #: every stream fenced, every pre-cut verb applied), so routing
        #: for a table changes at ONE agreed multi-stream position; in
        #: SPMD worlds the installing cut is issued at the same
        #: lockstep app position on every rank (the MV_PolicySync
        #: discipline), keeping the per-shard verb streams rank-agreed.
        self._routing: Dict[int, int] = {}
        #: routing-map installs applied (the /actions + drill probe)
        self.routing_installs = 0
        #: the ROUTING FREEZE (round 20 review fix): route-decision +
        #: mailbox-push must be atomic against cut-fence enqueue, or a
        #: verb that computed its slot under the OLD map could land
        #: BEHIND the fence in the old stream while the cut swaps the
        #: map — splitting one table's verbs across two concurrently
        #: draining streams (per-table serial order broken). Cuts
        #: close the gate (under _route_lock) before enqueueing their
        #: fences and reopen it when the LAST in-flight cut releases;
        #: verb pushes spin on the gate (bounded waits) and route
        #: under the same lock. The open-gate fast path costs one
        #: Event check + one uncontended lock per push.
        self._route_lock = threading.Lock()
        self._route_open = threading.Event()
        self._route_open.set()
        self._cuts_inflight = 0
        #: cross-stream cuts processed (the sharded sibling of
        #: window_barrier_splits, which counts shard 0's stream only)
        self.cut_count = 0
        for mt in _CUT_TYPES:
            self.RegisterHandler(mt, self._wrap_cut(self._handlers[mt]))

    def _slot_for(self, table_id: int) -> int:
        """Effective shard slot of ``table_id``: the routing-map
        override when one is installed, else the rank-agreed modulo
        default. One dict get on the verb path."""
        if table_id < 0:
            return 0
        slot = self._routing.get(table_id)
        return (table_id % self._shard_cap) if slot is None else slot

    def install_routing(self, mapping: Dict[int, int]) -> list:
        """Install table->shard overrides. MUST run as a cross-stream
        cut payload (Zoo.CallOnEngine): with every stream fenced, every
        verb admitted before the cut has applied under the OLD map and
        none after, so a table's window stream migrates between shard
        channels at one consistent position. Targets are restricted to
        LIVE slots (0 or a spawned sub-shard) and known tables; the
        returned ``[(table_id, prev_slot, new_slot), ...]`` names what
        actually changed (the policy plane's revert input). Idempotent:
        re-installing the current slot is a no-op entry."""
        live = {0} | set(self._subs)
        applied = []
        for tid, slot in sorted(mapping.items()):
            tid, slot = int(tid), int(slot)
            CHECK(0 <= tid < len(self.store_),
                  f"install_routing: unknown table {tid}")
            CHECK(slot in live,
                  f"install_routing: slot {slot} not live (live slots "
                  f"{sorted(live)})")
            prev = self._slot_for(tid)
            if prev == slot:
                continue
            self._routing[tid] = slot
            applied.append((tid, prev, slot))
        if applied:
            self.routing_installs += 1
        return applied

    def routing_report(self) -> dict:
        """Effective routing of every registered table + live slots
        (LOCAL probe — the policy decider's and /actions' input)."""
        return {"shard_cap": self._shard_cap,
                "live_slots": sorted({0} | set(self._subs)),
                "installs": self.routing_installs,
                "overrides": dict(self._routing),
                "routing": {tid: self._slot_for(tid)
                            for tid in range(len(self.store_))}}

    def _wrap_cut(self, base):
        def entry(msg: Message) -> None:
            fence = getattr(msg, "_mv_cut", None)
            if fence is None:       # no subs were live at routing time
                return base(msg)
            try:
                fence.arrive_head(list(self._subs.values()))
                base(msg)
            finally:
                # release + reopen even when the rendezvous aborted (a
                # dead sub / expired deadline): a stuck freeze would
                # park every verb push forever
                fence.release()
                self._cut_done()
        return entry

    def _cut_done(self) -> None:
        """One in-flight cut finished: reopen the routing gate when it
        was the last (cuts may overlap — publish racing a policy
        install — and the gate must stay closed until ALL fences are
        resolved)."""
        with self._route_lock:
            self._cuts_inflight -= 1
            if self._cuts_inflight <= 0:
                self._cuts_inflight = 0
                self._route_open.set()

    def _route_push(self, msg: Message) -> None:
        """Route one verb and push it to its stream, atomically
        against cut-fence enqueue (see the routing-freeze note in
        __init__). The open-gate path is one Event check + one
        uncontended lock."""
        while True:
            opened = self._route_open.wait(0.5)
            if not opened and self._poison is not None:
                # router died mid-cut and the gate will never reopen:
                # fall through — the push surfaces the typed ActorDied
                # instead of spinning forever
                pass
            elif not opened:
                continue
            with self._route_lock:
                if (self._route_open.is_set()
                        or self._poison is not None):
                    sub = self._subs.get(self._slot_for(msg.table_id))
                    if sub is not None:
                        # mv-lint: ok(lock-order): sub is an _EngineShard whose Receive IS Actor.Receive (mailbox push, no _route_lock) — the by-name edge to ShardedServer.Receive cannot execute (a sub is never the router)
                        sub.Receive(msg)    # chaos/poison apply there
                    else:
                        super().Receive(msg)
                    return

    def RegisterTable(self, server_table) -> int:
        table_id = super().RegisterTable(server_table)
        if multihost.world_size() > 1:
            # pre-warm the table's host mirror at THIS lockstep
            # position: a multi-stream engine cannot order collective
            # applies, so the mirror bootstrap the single engine did
            # in the first fenced window must happen here instead
            # (tables/base.py mh_prepare_local_apply contract)
            try:
                server_table.mh_prepare_local_apply()
            except Exception as exc:
                Log.Error("engine: table %d local-apply pre-warm "
                          "failed (%r) — its first window will need a "
                          "collective apply", table_id, exc)
        slot = table_id % self._shard_cap
        if slot and slot not in self._subs:
            sub = _EngineShard(self, slot)
            self._subs[slot] = sub
            if multihost.world_size() > 1:
                # N live streams in a multi-process world: no shard may
                # issue collective APPLIES any more (loud CHECK in
                # _mh_fence_cause; cut payloads stay exempt — every
                # stream is fenced while they run)
                self.mh_single_collective_stream = False
                sub.mh_single_collective_stream = False
                for other in self._subs.values():
                    other.mh_single_collective_stream = False
            sub.Start()
            Log.Debug("engine: shard %d spawned (table %d; %d/%d "
                      "slots live)", slot, table_id,
                      1 + len(self._subs), self._shard_cap)
        return table_id

    def receive_multi(self, members) -> None:
        """Split one batch per shard stream (round 19): routing is by
        table (``table_id % slots``), so splitting the member list by
        slot preserves every TABLE's submission order — the guarantee
        the batched-verb contract makes — while each shard still takes
        its sub-batch as one envelope. Worst case the batch costs
        min(len, live shards) pushes instead of one; per-shard verb
        positions stay lockstep across SPMD ranks because the split is
        the same rank-agreed arithmetic the router uses."""
        if not self._subs:
            return super().receive_multi(members)
        # route + push under the routing-freeze gate, like every other
        # verb path (the slot decisions and the pushes must be one
        # atomic step against a cut's fence enqueue)
        while True:
            opened = self._route_open.wait(0.5)
            if not opened and self._poison is None:
                continue
            with self._route_lock:
                if (not self._route_open.is_set()
                        and self._poison is None):
                    continue
                groups: Dict[int, list] = {}
                for m in members:
                    groups.setdefault(self._slot_for(m.table_id),
                                      []).append(m)
                for slot, ms in groups.items():
                    sub = self._subs.get(slot)
                    if sub is not None:
                        sub.receive_multi(ms)
                    else:
                        Server.receive_multi(self, ms)
                return

    def Receive(self, msg: Message) -> None:
        if msg.msg_type is MsgType.Request_MultiVerb:
            # a pre-wrapped envelope (tests / direct callers): re-split
            # it per shard — letting shard 0 expand it would put other
            # shards' tables into the wrong window stream
            self.receive_multi(msg.payload["members"])
            return
        if msg.msg_type in (MsgType.Request_Get, MsgType.Request_Add):
            self._route_push(msg)
            return
        subs = list(self._subs.values())
        if not subs or msg.msg_type not in _CUT_TYPES:
            super().Receive(msg)
            return
        # CROSS-STREAM CUT: fence every sub-shard's stream, then send
        # the head message to shard 0. Per-shard mailbox order is the
        # caller's program order restricted to that shard, so SPMD
        # ranks place every fence at the same per-shard stream
        # position — the cut is one agreed multi-stream position. The
        # fences enqueue with the ROUTING GATE closed: a concurrent
        # verb either pushed before them (ahead of the fence — applied
        # under the pre-cut routing before any payload runs) or routes
        # after the cut fully releases (under whatever map the payload
        # installed) — never with an old decision behind the fence.
        self.cut_count += 1  # mv-lint: ok(cross-domain-state): diagnostics-only tally; worker cuts and the policy thread's installs may race the GIL int add and at worst under-count a probe nothing gates on
        fence = _CutFence(self, len(subs))
        with self._route_lock:
            self._cuts_inflight += 1
            self._route_open.clear()
            for sub in subs:
                sub.Receive(Message(msg_type=msg.msg_type,
                                    payload={"_mv_fence": fence}))
            msg._mv_cut = fence
            super().Receive(msg)

    # -- facade points -------------------------------------------------------

    def epoch_for_table(self, table_id: int) -> int:
        sub = self._subs.get(self._slot_for(table_id))
        return (sub or self).window_epoch

    def cut_epoch(self) -> int:
        return self.window_epoch + sum(s.window_epoch
                                       for s in self._subs.values())

    def shard_states(self) -> List[dict]:
        out = super().shard_states()
        for slot in sorted(self._subs):
            out.extend(self._subs[slot].shard_states())
        return out

    def Stop(self) -> None:
        # shard 0 (the router) first: its drain may still dispatch a
        # queued cut, which needs the subs alive to fence; the subs'
        # own drains then flush any released fences
        super().Stop()
        for sub in self._subs.values():
            sub.Stop()


class SyncServer(Server):
    """BSP server (reference server.cpp:60-222). See module docstring."""

    #: the vector-clock protocol counts Get/Add MESSAGES per worker:
    #: worker-side write combining / get caching would break the round
    #: accounting ("all workers issue the same number of Gets/Adds")
    GET_CACHE_OK = False
    WRITE_COMBINE_OK = False
    #: ...and batched envelopes would hide N clock ticks inside one
    #: message — Zoo.SendToServerMulti delivers members individually
    MULTI_VERB_OK = False

    def __init__(self, num_workers: int):
        super().__init__()
        # Zoo.SendToServerMulti honors MULTI_VERB_OK and delivers
        # members individually, but direct callers (Server.receive_multi
        # is inherited; ShardedServer.Receive documents pre-wrapped
        # envelopes) could still land one — the inherited registration
        # points at _get_entry, whose BSP override would feed the
        # envelope to ProcessGet (table_id -1 → a bogus store_[-1]
        # dispatch AND a spurious get-clock tick). Re-register a
        # handler that flattens members strictly one at a time through
        # the clocked entries instead (review catch, round 19).
        self.RegisterHandler(MsgType.Request_MultiVerb,
                             self._multi_entry_bsp)
        self._num_workers = num_workers
        self._get_clocks = VectorClock(num_workers)
        self._add_clocks = VectorClock(num_workers)
        self._num_waited_add = [0] * num_workers
        self._add_cache: Deque[Message] = collections.deque()
        self._get_cache: Deque[Message] = collections.deque()
        #: telemetry: worst clock skew across both vector clocks — how
        #: stale the slowest worker's view is vs the fastest's. A
        #: MAX-merge gauge: the job-wide number is the worst rank's
        #: skew, not a sum over ranks
        self._t_staleness = tmetrics.max_gauge("server.bsp.staleness")

    def _note_staleness(self) -> None:
        self._t_staleness.set(max(self._get_clocks.staleness(),
                                  self._add_clocks.staleness()))

    def ProcessAdd(self, msg: Message) -> None:
        worker = msg.src
        # 1. Before add: cache faster worker (server.cpp:141-147)
        if self._get_clocks.local_clock(worker) > self._get_clocks.global_clock():
            self._add_cache.append(msg)
            self._num_waited_add[worker] += 1
            self._note_staleness()
            return
        # 2. Process add
        super().ProcessAdd(msg)
        # 3. After add: drain cached gets when the add round completes
        if self._add_clocks.Update(worker):
            CHECK(not self._add_cache, "add cache must be empty at round end")
            while self._get_cache:
                get_msg = self._get_cache.popleft()
                super().ProcessGet(get_msg)
                CHECK(not self._get_clocks.Update(get_msg.src),
                      "drained Get must not complete a round")
        self._note_staleness()

    def _multi_entry_bsp(self, msg: Message) -> None:
        """A batched envelope on the BSP engine: process the members
        inline, strictly one at a time, through the clocked entries —
        at the envelope's mailbox position, so member order (and the
        round accounting, which counts individual messages) is exactly
        what member-by-member delivery would have produced."""
        for m in msg.payload["members"]:
            if m.msg_type is MsgType.Request_Add:
                self._add_entry(m)
            else:
                self._get_entry(m)

    def _get_entry(self, msg: Message) -> None:
        # no pipelining window under BSP: the vector-clock protocol's
        # defer/drain decisions depend on strict one-at-a-time
        # processing. The failsafe admission gate (dedup + chaos) still
        # applies BEFORE the clocks see the verb — a duplicate Add must
        # not tick a vector clock twice.
        if not self._admit(msg):
            return
        self.ProcessGet(msg)

    def _add_entry(self, msg: Message) -> None:
        # no add-coalescing under BSP either (same strictness)
        if not self._admit(msg):
            return
        self.ProcessAdd(msg)

    def ProcessGet(self, msg: Message) -> None:
        worker = msg.src
        # 1. Before get: wait for other workers' adds (server.cpp:164-171)
        if (self._add_clocks.local_clock(worker) > self._add_clocks.global_clock()
                or self._num_waited_add[worker] > 0):
            self._get_cache.append(msg)
            self._note_staleness()
            return
        # 2. Process get
        super().ProcessGet(msg)
        # 3. After get: drain cached adds when the get round completes
        if self._get_clocks.Update(worker):
            while self._add_cache:
                add_msg = self._add_cache.popleft()
                super().ProcessAdd(add_msg)
                CHECK(not self._add_clocks.Update(add_msg.src),
                      "drained Add must not complete a round")
                self._num_waited_add[add_msg.src] -= 1
        self._note_staleness()

    def ProcessFinishTrain(self, msg: Message) -> None:
        """server.cpp:188-211: force worker clocks to infinity, drain caches."""
        worker = msg.src
        if self._add_clocks.FinishTrain(worker):
            CHECK(not self._add_cache, "add cache must be empty")
            while self._get_cache:
                get_msg = self._get_cache.popleft()
                super().ProcessGet(get_msg)
                CHECK(not self._get_clocks.Update(get_msg.src), "")
        if self._get_clocks.FinishTrain(worker):
            CHECK(not self._get_cache, "get cache must be empty")
            while self._add_cache:
                add_msg = self._add_cache.popleft()
                super().ProcessAdd(add_msg)
                CHECK(not self._add_clocks.Update(add_msg.src), "")
                self._num_waited_add[add_msg.src] -= 1
        msg.reply(None)
