"""Consistency modes (reference L4 server actors)."""

from multiverso_tpu.sync.server import Server, SyncServer, VectorClock  # noqa: F401
