"""Shared (membership epoch, stream, exchange SEQ) stream alignment.

Two offline tools read per-rank flight dumps and line their events up
by stream position: ``telemetry/forensics.py`` (divergence hunting)
and ``telemetry/critpath.py`` (cross-rank critical-path
reconstruction). Both must apply IDENTICAL rules for

* the alignment key — the ``(mepoch, stream, seq)`` triple: the
  elastic plane re-bases the exchange SEQ to 0 at every membership
  epoch transition, and the SHARDED engine (round 12) runs one
  independent window stream per shard, each with its own SEQ counter —
  two healthy ranks legally record seq 0 once per (epoch, stream). A
  dump from an older world carries neither field and reads as epoch 0,
  stream 0 throughout;
* ragged tails — a dump whose ``(mepoch, stream)`` sub-stream merely
  ENDS earlier than its peers' (the rank died or dumped first) covers
  a shorter range and is NOT a hole at the uncovered positions; the
  rule is applied PER sub-stream, because shards drain independently
  (shard 1 legally runs far ahead of shard 0);
* evicted heads — a dump that STARTS later because the bounded ring
  aged out its oldest events (``dropped > 0`` in the header) is NOT a
  hole at the front either; a front-missing position on a rank that
  dropped NOTHING cannot be eviction and IS one.

This module is that single rule set — factored out in round 11 so the
two tools cannot drift on epoch re-basing, shard-stream keying or
ragged-tail handling.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

#: an alignment key: (membership epoch, engine shard stream, SEQ)
Pos = Tuple[int, int, int]


def expand_paths(paths: List[str]) -> List[str]:
    """CLI argument expansion shared by the forensics and critpath
    mains (round 13): a DIRECTORY argument globs its own
    ``flight_rank*.jsonl`` dumps — the exact layout ``-mv_diag_dir``
    writes — so ``python -m ...forensics <diag_dir>`` works without
    hand-listing every rank. File arguments pass through untouched; a
    directory holding no dumps raises loudly (a typo'd path must not
    silently correlate the remaining ranks)."""
    import glob
    import os
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            found = sorted(glob.glob(os.path.join(p,
                                                  "flight_rank*.jsonl")))
            if not found:
                raise FileNotFoundError(
                    f"directory {p!r} holds no flight_rank*.jsonl "
                    f"dumps (is it the -mv_diag_dir of a run that "
                    f"dumped?)")
            out.extend(found)
        else:
            out.append(p)
    return out


def load(path: str) -> dict:
    """Read one flight JSONL dump -> ``{"rank": r, "header": {...},
    "events": [...], "path": path}`` (events oldest first)."""
    header: dict = {}
    events: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("flight_header"):
                header = rec
            else:
                events.append(rec)
    return {"rank": int(header.get("rank", -1)), "header": header,
            "events": events, "path": path}


def stream(events: List[dict], kinds) -> Dict[Pos, List[dict]]:
    """``(mepoch, stream, seq) -> ordered events of ``kinds`` at that
    stream position`` (ring order preserved within a position). Events
    with a negative seq — e.g. single-process ``window.phases``
    records — are not stream positions and are skipped."""
    out: Dict[Pos, List[dict]] = {}
    for e in events:
        if e.get("kind") in kinds and e.get("seq", -1) >= 0:
            key = (int(e.get("mepoch", 0) or 0),
                   int(e.get("stream", 0) or 0), int(e["seq"]))
            out.setdefault(key, []).append(e)
    return out


def by_rank(dumps: List[dict], kinds) -> Tuple[Dict[int, Dict[Pos, List[dict]]],
                                               Dict[int, int]]:
    """Per-rank keyed streams + per-rank header drop counts from loaded
    dumps (see :func:`load`). A dump without a rank in its header gets
    a synthetic one so degenerate inputs still align."""
    streams: Dict[int, Dict[Pos, List[dict]]] = {}
    dropped: Dict[int, int] = {}
    for d in dumps:
        rank = d["rank"] if d["rank"] >= 0 else len(streams)
        streams[rank] = stream(d["events"], kinds)
        dropped[rank] = int(d["header"].get("dropped", 0))
    return streams, dropped


def all_positions(streams: Dict[int, Dict[Pos, List[dict]]]) -> List[Pos]:
    """Sorted union of every rank's stream positions."""
    if not streams:
        return []
    return sorted(set().union(*[set(s) for s in streams.values()]))


def common_positions(streams: Dict[int, Dict[Pos, List[dict]]]) -> List[Pos]:
    """Sorted positions present on EVERY rank — the covered overlap the
    ragged-tail/evicted-head rules leave usable for cross-rank math."""
    if not streams:
        return []
    covered = None
    for s in streams.values():
        covered = set(s) if covered is None else covered & set(s)
    return sorted(covered or ())


def stream_bounds(rank_stream: Dict[Pos, List[dict]]) -> Dict[tuple,
                                                              Tuple[Pos,
                                                                    Pos]]:
    """Per-``(mepoch, stream)`` (min, max) covered positions of one
    rank's keyed stream — computed in ONE pass so repeated
    :func:`is_hole` calls over a large dump stay linear (callers
    checking many positions pass this in)."""
    out: Dict[tuple, Tuple[Pos, Pos]] = {}
    for p in rank_stream:
        sub = p[:2]
        b = out.get(sub)
        out[sub] = ((p, p) if b is None
                    else (min(b[0], p), max(b[1], p)))
    return out


def is_hole(rank_stream: Dict[Pos, List[dict]], pos: Pos,
            dropped: int, bounds=None) -> bool:
    """True when ``pos`` missing from ``rank_stream`` is a HOLE — a
    genuine stream gap — rather than a legal shorter covered range.

    Evaluated WITHIN ``pos``'s own ``(mepoch, stream)`` sub-stream:
    shard streams drain independently, so shard 1 being far ahead of
    shard 0 must not turn shard 0's ragged tail into a "gap". A rank
    that never recorded the sub-stream at all covers none of it —
    shorter coverage, not a hole. Within the sub-stream, a missing
    position only counts as a hole when the rank recorded activity on
    BOTH sides of it, or ahead of it while its header says it dropped
    nothing (a front-missing position then cannot be ring eviction).
    ``bounds`` (optional): this rank's precomputed
    :func:`stream_bounds`, for callers probing many positions."""
    if not rank_stream or pos in rank_stream:
        return False
    b = (bounds if bounds is not None
         else stream_bounds(rank_stream)).get(pos[:2])
    if b is None:
        return False            # this (mepoch, stream) never recorded
    if pos >= b[1]:
        return False            # ragged tail: the sub-stream ends here
    if pos > b[0]:
        return True             # activity on both sides: a real gap
    return dropped == 0         # front-missing without eviction


def coverage_note(streams: Dict[int, Dict[Pos, List[dict]]],
                  dropped: Dict[int, int]) -> Optional[str]:
    """Human-readable summary of ragged coverage across ranks (None
    when every rank covers the same positions)."""
    allp = all_positions(streams)
    common = set(common_positions(streams))
    if not allp or len(common) == len(allp):
        return None
    parts = []
    for r in sorted(streams):
        s = streams[r]
        missing = len(allp) - len(s)
        if missing:
            why = ("ring evicted its head" if dropped.get(r, 0)
                   else "shorter covered range")
            parts.append(f"rank {r} misses {missing} position(s) "
                         f"({why})")
    return ("; ".join(parts) + f" — {len(common)}/{len(allp)} "
            f"positions covered by every rank")
