"""Cross-rank divergence forensics over flight-recorder dumps.

A multi-process world's correctness rests on the SPMD collective
contract: every rank issues the same table-verb sequence at the same
stream positions. When that breaks, the engine's divergence CHECK (or
SEQ-mismatch CHECK) fires — loud, but the message only shows the
mismatched window, not WHERE the streams first came apart. With
``-mv_diag_dir`` set, every rank dumps its flight ring on those
failures (telemetry/flight.py); :func:`correlate` aligns the dumps by
**exchange SEQ** and reports the first diverging stream position with
each rank's verbs at it.

Alignment algorithm:

* every successful window exchange records a ``window.exchanged`` event
  stamped with the engine's exchange SEQ and a compact descriptor of
  the recording rank's verbs over the AGREED prefix (``"A0,G1"`` = Add
  table 0, Get table 1; the prefix rather than the full local pack —
  ragged drains legally pack different window lengths per rank) —
  recorded BEFORE the cross-rank descriptor CHECK, so the diverging
  window is in the ring even though the CHECK aborted it;
* barrier head-markers record a ``barrier`` event stamped with the seq
  of the NEXT exchange (barriers do not advance the SEQ counter), so a
  rank at a barrier while a peer exchanges verbs shows up as a kind
  mismatch at that seq;
* per rank, events sharing a seq keep their ring order. Ranks are
  compared seq by seq over the union: the first seq whose per-rank
  event lists differ (kind or verbs) — or that some rank never reached
  while a peer with later activity did — is the divergence point.

Events *applied* (``window.applied``) carry the window epoch instead;
they corroborate how far each rank's APPLY stage got but alignment
rides the exchange SEQ, which is the collective clock.

Elastic worlds (round 10): the engine re-bases the exchange SEQ to 0
at every MEMBERSHIP epoch transition, and every stream event carries
its membership epoch (``mepoch``). Sharded engines (round 12) run one
independent window stream per shard, each with its own SEQ counter,
stamped as ``stream``. Alignment therefore keys on the ``(mepoch,
stream, seq)`` triple (telemetry/align.py, shared with critpath), so
a legal re-base or an independent shard stream never reads as a
divergence while a real divergence *within* one stream still does.

CLI::

    python -m multiverso_tpu.telemetry.forensics diag/flight_rank*.jsonl
    python -m multiverso_tpu.telemetry.forensics diag/

(a directory argument globs its own ``flight_rank*.jsonl`` — the
layout ``-mv_diag_dir`` writes) prints the report and exits 1 when a
divergence was found (0 when the streams agree — useful in drills).
"""

from __future__ import annotations

from typing import List, Optional

from multiverso_tpu.telemetry import align

#: event kinds that are stream positions (collective-clock events)
_STREAM_KINDS = ("window.exchanged", "barrier")

#: one flight JSONL dump -> {"rank", "header", "events", "path"} —
#: shared with telemetry/critpath.py (telemetry/align.py owns the
#: loader AND the (mepoch, seq) keying + ragged-tail rules, so the two
#: tools cannot drift on epoch re-basing or hole classification)
load = align.load


def _desc(evs: Optional[List[dict]]) -> Optional[str]:
    if not evs:
        return None
    return ";".join(f"{e['kind']}:{e.get('detail', '')}" for e in evs)


def correlate(paths: List[str]) -> dict:
    """Align the rings in ``paths`` by (membership epoch, exchange SEQ);
    return a report:

    ``{"diverged": bool, "seq": first diverging seq or None, "mepoch":
    its membership epoch (0 = boot world), "per_rank": {rank:
    verbs-at-that-position or None}, "ranks": [...],
    "agreed_through": last seq every rank agreed at (or None),
    "agreed_mepoch": that position's membership epoch, "note": str}``

    A rank whose dump merely covers a SHORTER seq range than its
    peers' does not count as diverged at the uncovered seqs: a dump
    can end earlier (the rank died or dumped first) and it can START
    later (the bounded ring evicted the oldest events — a long-running
    rank with extra serving/snapshot events ages out early exchanges
    its peers still hold). Divergence needs either differing events at
    a seq, or a HOLE: a seq missing on a rank that recorded activity
    on both sides of it — or ahead of it while its header says it
    dropped nothing (a front-missing seq then cannot be eviction).
    """
    dumps = [load(p) for p in paths]
    streams, dropped = align.by_rank(dumps, _STREAM_KINDS)
    ranks = sorted(streams)
    all_pos = align.all_positions(streams)
    # per-rank sub-stream bounds ONCE: is_hole over every missing
    # position stays linear on large multi-shard dumps
    bounds = {r: align.stream_bounds(streams[r]) for r in ranks}
    agreed: Optional[tuple] = None
    for pos in all_pos:
        mepoch, stream_id, seq = pos
        descs = {r: _desc(streams[r].get(pos)) for r in ranks}
        present = {r: d for r, d in descs.items() if d is not None}
        missing = [r for r, d in descs.items() if d is None]
        # the hole-vs-shorter-covered-range rule lives in align.is_hole
        # (shared with critpath): a dump may legally end earlier (rank
        # died / dumped first) or start later (bounded ring evicted its
        # oldest events, dropped > 0) — only a genuine gap diverges
        holes = [r for r in missing
                 if align.is_hole(streams[r], pos, dropped.get(r, 0),
                                  bounds=bounds[r])]
        vals = set(present.values())
        if len(vals) > 1 or holes:
            per_rank = {r: descs[r] for r in ranks}
            detail = ", ".join(
                f"rank {r}: {descs[r] if descs[r] is not None else '<missing>'}"
                for r in ranks)
            ep = f" (membership epoch {mepoch})" if mepoch else ""
            st = f" (engine stream {stream_id})" if stream_id else ""
            return {"diverged": True, "seq": seq, "mepoch": mepoch,
                    "stream": stream_id,
                    "ranks": ranks, "per_rank": per_rank,
                    "agreed_through": (agreed[2] if agreed else None),
                    "agreed_mepoch": (agreed[0] if agreed else None),
                    "agreed_stream": (agreed[1] if agreed else None),
                    "note": (f"first diverging exchange SEQ {seq}"
                             f"{ep}{st}: {detail}")}
        if len(present) == len(ranks):
            agreed = pos
    return {"diverged": False, "seq": None, "mepoch": None,
            "stream": None,
            "ranks": ranks, "per_rank": {},
            "agreed_through": (agreed[2] if agreed else None),
            "agreed_mepoch": (agreed[0] if agreed else None),
            "agreed_stream": (agreed[1] if agreed else None),
            "note": (f"streams agree through exchange SEQ {agreed[2]}"
                     + (f" of membership epoch {agreed[0]}"
                        if agreed[0] else "")
                     + (f" on engine stream {agreed[1]}"
                        if agreed[1] else "")
                     if agreed is not None
                     else "no common stream events")}


def report_text(report: dict) -> str:
    """Human-readable rendering of a :func:`correlate` report."""
    lines = [f"== flight forensics: ranks {report['ranks']} =="]
    if report["diverged"]:
        ep = (f" of membership epoch {report['mepoch']}"
              if report.get("mepoch") else "")
        st = (f" on engine stream {report['stream']}"
              if report.get("stream") else "")
        lines.append(f"DIVERGED at exchange SEQ {report['seq']}{ep}{st} "
                     f"(streams agreed through "
                     f"{report['agreed_through']})")
        for r in report["ranks"]:
            d = report["per_rank"].get(r)
            lines.append(f"  rank {r}: "
                         f"{d if d is not None else '<no event>'}")
    else:
        lines.append(report["note"])
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    from multiverso_tpu.utils.log import Log
    parser = argparse.ArgumentParser(
        prog="python -m multiverso_tpu.telemetry.forensics",
        description="align per-rank flight-recorder dumps by exchange "
                    "SEQ and report the first diverging stream position")
    parser.add_argument("paths", nargs="+",
                        help="per-rank flight_rank<R>.jsonl dumps, or "
                             "a directory (e.g. the -mv_diag_dir) "
                             "whose flight_rank*.jsonl are globbed")
    args = parser.parse_args(argv)
    report = correlate(align.expand_paths(args.paths))
    Log.Info("%s", report_text(report))
    return 1 if report["diverged"] else 0


if __name__ == "__main__":      # pragma: no cover - CLI shim
    raise SystemExit(main())
