"""Always-on flight recorder: a bounded ring of structured events.

The reference ships a Logger and a Dashboard; debugging a desynced SPMD
verb stream from those means reading log text after the fact. The
flight recorder is the blackbox complement: every rank keeps the last
``-mv_flight_events`` structured events — window admitted / exchanged /
applied (with the exchange SEQ), fence entered (with its cause),
barriers, CRC retries, dedup hits, snapshot publish/evict, serving
dispatch/shed, actor poison — ALWAYS ON, cheap enough to leave enabled
in production (one lock + tuple append per event; the 2% tier-1
overhead guard in tests/test_opsplane.py holds it to that).

Recording is allocation-cheap by construction: an event is one small
tuple ``(t_wall, t_mono, kind, seq, epoch, detail, mepoch)`` appended
to a ``deque(maxlen=N)`` — no dicts, no formatting, no I/O on the hot
path. Formatting happens only at dump/inspection time.

Every event is DUAL-STAMPED (round 11): ``time.time()`` (wall) for
cross-rank alignment and ``time.perf_counter()`` (monotonic) for
interval math — wall-clock alone corrupted phase durations whenever an
NTP step landed mid-window. The dump header carries BOTH clocks
sampled back to back (``dumped_at`` / ``dumped_at_mono``), so offline
tools can convert any event's monotonic stamp into that rank's wall
timeline: ``wall(tm) = dumped_at - (dumped_at_mono - tm)``.

``-mv_flight_events=0`` disables recording through the same
listener-cached no-op gate pattern as the ``-telemetry``/``-trace``
flags (the off path is one cached int read and a return).

Dumps are JSONL (one event object per line, after a header line naming
rank/pid/recorded/dropped) via :func:`dump` / ``MV_DumpFlightRecorder``;
``telemetry/forensics.py`` aligns dumps from several ranks by exchange
SEQ to pinpoint the first diverging stream position. Failure paths
(the engine's divergence/SEQ CHECKs, DeadlineExceeded escapes) call
:func:`dump_failure`, which writes ``flight_rank<R>.jsonl`` under
``-mv_diag_dir`` when that flag is set — so a crashed 2-proc world
leaves per-rank rings on disk ready for ``python -m
multiverso_tpu.telemetry.forensics``.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import List, Optional, Tuple

from multiverso_tpu.utils.configure import (MV_DEFINE_int, MV_DEFINE_string,
                                            cached_int_flag)
from multiverso_tpu.utils.log import Log

MV_DEFINE_int("mv_flight_events", 4096,
              "flight recorder ring capacity (events kept per rank, "
              "always on; 0 disables recording entirely — the gate is "
              "one cached int read per event)")
MV_DEFINE_string("mv_diag_dir", "",
                 "postmortem artifact directory: failure paths dump "
                 "per-rank flight rings here (flight_rank<R>.jsonl), "
                 "and MV_DumpDiagnostics/Zoo.Stop add the telemetry "
                 "snapshot sidecar + span trace dump — ONE flag "
                 "captures a complete postmortem (empty = off)")

#: the -mv_flight_events gate, CACHED behind a flag listener (the
#: record() call sits on per-window engine paths)
_cap = cached_int_flag("mv_flight_events", 4096)

#: default ring capacity when the flag registry is torn down mid-dump
_DEFAULT_CAP = 4096


class FlightRecorder:
    """One process-wide bounded event ring. Thread-safe: every mutation
    is one short critical section (workers, the engine actor, the
    exchange stage and serving threads all record concurrently)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ring: "collections.deque[Tuple]" = collections.deque(
            maxlen=_DEFAULT_CAP)
        self._recorded = 0

    def record(self, cap: int, kind: str, seq: int, epoch: int,
               detail: str, mepoch: int = 0, stream: int = 0) -> None:
        # dual stamp OUTSIDE the lock (back-to-back, so the pair is
        # coherent): wall for cross-rank alignment, monotonic for
        # NTP-step-proof interval math (telemetry/critpath.py)
        t_wall = time.time()
        t_mono = time.perf_counter()
        with self._lock:
            ring = self._ring
            if ring.maxlen != cap:
                # capacity flag changed: keep the newest events that fit
                ring = collections.deque(ring, maxlen=cap)
                self._ring = ring
            ring.append((t_wall, t_mono, kind, seq, epoch, detail,
                         mepoch, stream))
            self._recorded += 1

    def stats(self) -> Tuple[int, int]:
        """(recorded_total, dropped_total) — dropped = aged out of the
        ring bound (the blackbox keeps the newest events)."""
        with self._lock:
            return self._recorded, self._recorded - len(self._ring)

    def approx_bytes(self, per_event_overhead: int) -> Tuple[int, int]:
        """(event_count, byte_estimate) for the accounting ledger:
        ``events * overhead + total detail chars``, summed from the RAW
        ring tuples — the ledger probes this every watchdog tick, so it
        must not materialize len(ring) dicts per tick the way
        :meth:`events` does. One snapshot-copy under the lock (same as
        every other reader), then plain arithmetic."""
        with self._lock:
            raw = list(self._ring)
        return (len(raw),
                sum(per_event_overhead + len(ev[5]) for ev in raw))

    def last_detail(self, kind: str) -> Optional[str]:
        """detail of the most recent event of ``kind`` (dashboard [Ops]
        line probe), or None."""
        with self._lock:
            events = list(self._ring)
        for ev in reversed(events):
            if ev[2] == kind:
                return ev[5]
        return None

    def events(self, n: Optional[int] = None) -> List[dict]:
        """The newest ``n`` events (all when None) as dicts, oldest
        first — the /flight endpoint + bundle tail shape. ``t`` is the
        wall clock, ``tm`` the monotonic stamp taken with it (interval
        math rides ``tm``; cross-rank alignment rides ``t``).
        ``mepoch`` is the membership epoch the event was recorded under
        (0 = boot world; the elastic plane re-bases the exchange SEQ
        per membership epoch). ``stream`` (round 12) is the engine
        shard's window stream the event belongs to (0 = the unsharded
        engine / shard 0): each shard owns an independent exchange
        stream with its own SEQ counter, so the offline tools align by
        (mepoch, stream, seq) — telemetry/align.py is the one rule
        set."""
        with self._lock:
            raw = list(self._ring)
        if n is not None and n > 0:
            raw = raw[-n:]
        return [{"t": ev[0], "tm": ev[1], "kind": ev[2], "seq": ev[3],
                 "epoch": ev[4], "detail": ev[5],
                 "mepoch": ev[6] if len(ev) > 6 else 0,
                 "stream": ev[7] if len(ev) > 7 else 0}
                for ev in raw]

    def tail_text(self, n: int = 40) -> str:
        """Compact textual tail for the failsafe diagnostic bundle."""
        lines = []
        for e in self.events(n):
            me = f" mepoch={e['mepoch']}" if e.get("mepoch") else ""
            st = f" stream={e['stream']}" if e.get("stream") else ""
            lines.append(f"{e['t']:.6f} {e['kind']} seq={e['seq']} "
                         f"epoch={e['epoch']}{me}{st} {e['detail']}")
        return "\n".join(lines) or "<flight ring empty>"

    def _reset_for_tests(self) -> None:
        with self._lock:
            self._ring.clear()
            self._recorded = 0


RECORDER = FlightRecorder()


def record(kind: str, seq: int = -1, epoch: int = -1,
           detail: str = "", mepoch: int = 0, stream: int = 0) -> None:
    """Record one event. The disabled path (``-mv_flight_events=0``)
    is one cached int read and a return — the no-op gate pattern.
    ``mepoch`` stamps the membership epoch (elastic plane; 0 = boot
    world) and ``stream`` the engine shard's window stream (round 12):
    stream events align by (mepoch, stream, seq)."""
    cap = _cap()
    if cap <= 0:
        return
    RECORDER.record(cap, kind, seq, epoch, detail, mepoch, stream)


def enabled() -> bool:
    return _cap() > 0


def stats() -> Tuple[int, int]:
    return RECORDER.stats()


def last_detail(kind: str) -> Optional[str]:
    return RECORDER.last_detail(kind)


def events(n: Optional[int] = None) -> List[dict]:
    return RECORDER.events(n)


def tail_text(n: int = 40) -> str:
    return RECORDER.tail_text(n)


def _rank() -> int:
    try:
        from multiverso_tpu.parallel import multihost
        return multihost.process_index()
    except Exception:       # pragma: no cover - early interpreter state
        return 0


def _host() -> str:
    try:
        from multiverso_tpu.parallel import multihost
        return multihost.host_label()
    except Exception:       # pragma: no cover - early interpreter state
        return ""


def dump(path: str) -> str:
    """Write the ring as JSONL: a header object (rank, host, pid,
    recorded, dropped), then one event object per line, oldest first. Returns
    ``path``. Local-only — never collective (each rank dumps its own
    ring; forensics.correlate aligns them offline)."""
    recorded, dropped = RECORDER.stats()
    # BOTH clocks, sampled back to back: offline tools re-anchor any
    # event's monotonic stamp onto this rank's wall timeline with
    # wall(tm) = dumped_at - (dumped_at_mono - tm)
    header = {"flight_header": 1, "rank": _rank(), "pid": os.getpid(),
              "host": _host(),
              "recorded": recorded, "dropped": dropped,
              "dumped_at": time.time(),
              "dumped_at_mono": time.perf_counter()}
    with open(path, "w") as f:
        f.write(json.dumps(header) + "\n")
        for e in RECORDER.events():
            f.write(json.dumps(e) + "\n")
    return path


def diag_dir() -> str:
    """The -mv_diag_dir flag value ('' = off), registry-safe."""
    from multiverso_tpu.utils.configure import GetFlag
    try:
        return str(GetFlag("mv_diag_dir"))
    except Exception:       # registry torn down
        return ""


def dump_failure(what: str) -> Optional[str]:
    """Failure-path dump: write this rank's ring to
    ``<mv_diag_dir>/flight_rank<R>.jsonl`` (best-effort, never turns
    one failure into two). No-op (None) when ``-mv_diag_dir`` is unset
    or recording is off. Later failures overwrite earlier ones — the
    ring still holds the earlier events, so the newest dump is the most
    complete."""
    d = diag_dir()
    if not d or not enabled():
        return None
    try:
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"flight_rank{_rank()}.jsonl")
        dump(path)
        Log.Error("flight recorder dumped to %s (%s)", path, what)
        return path
    except Exception as exc:    # never turn one failure into two
        Log.Error("flight recorder dump failed: %r", exc)
        return None


def _reset_for_tests() -> None:
    RECORDER._reset_for_tests()
