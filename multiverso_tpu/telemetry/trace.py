"""Span-based structured tracing across the actor runtime.

Dapper-style: a *span* is a named, timed region on one thread; spans
nest through a thread-local stack, and a span's context ``(trace_id,
span_id)`` rides on ``Message.trace_ctx`` so the tree continues on the
thread that dequeues the message — one tree follows a verb from the
worker's ``GetAsync/AddAsync`` through the engine mailbox into the
server's window lifecycle (sync/server.py).

Export is Chrome trace-event JSON (`MV_DumpTrace`), loadable in
Perfetto / chrome://tracing:

* complete events (``ph: "X"``) — one per finished span, with
  ``trace_id/span_id/parent_id`` in ``args`` (the tree is explicit even
  across threads);
* flow events (``ph: "s"`` at message enqueue, ``ph: "f"`` at dequeue)
  — Perfetto draws the worker->server mailbox hop as an arrow.

Device correlation: when ``MV_StartProfiler`` has an xplane trace
active (api.py flips :func:`set_xplane`), every span also enters a
``jax.profiler.TraceAnnotation`` of the same name, so host spans appear
on the device timeline next to the XLA ops they dispatched.

Gated by ``-trace`` (default off). The ring buffer is bounded
(:data:`MAX_EVENTS`): a forgotten long-running trace degrades to
keeping the most recent events instead of eating the heap.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time
from typing import NamedTuple, Optional

from multiverso_tpu.utils.configure import MV_DEFINE_bool, cached_bool_flag
from multiverso_tpu.utils.log import Log

MV_DEFINE_bool("trace", False,
               "span tracing on/off (export with MV_DumpTrace)")

#: the -trace gate, CACHED behind a flag listener (hot-path span entry
#: must not pay a registry-lock GetFlag per message)
enabled = cached_bool_flag("trace", False)

#: completed-event ring bound — oldest events drop first
MAX_EVENTS = 200_000

_events = collections.deque(maxlen=MAX_EVENTS)
_events_lock = threading.Lock()
_tls = threading.local()
_id_counter = itertools.count(1)
_id_lock = threading.Lock()
#: set by api.MV_StartProfiler/MV_StopProfiler: bridge spans into
#: jax.profiler.TraceAnnotation while an xplane trace runs
_xplane_active = False


class SpanContext(NamedTuple):
    trace_id: int
    span_id: int




def set_xplane(active: bool) -> None:
    global _xplane_active
    _xplane_active = bool(active)


def _next_id() -> int:
    # pid-prefixed so ids from different ranks' dumps never collide
    with _id_lock:
        return (os.getpid() << 24) | (next(_id_counter) & 0xFFFFFF)


def _now_us() -> float:
    return time.perf_counter() * 1e6


def current_ctx() -> Optional[SpanContext]:
    """The calling thread's innermost open span, or None (used to stamp
    ``Message.trace_ctx`` at enqueue)."""
    return getattr(_tls, "ctx", None)


def _record(event: dict) -> None:
    with _events_lock:
        _events.append(event)


class _NullSpan:
    """Shared no-op context manager: the tracing-off fast path must not
    allocate per call (span() sits on per-message hot paths)."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("name", "cat", "args", "_parent", "_prev", "_ctx",
                 "_ann", "_t0")

    def __init__(self, name, parent, cat, args):
        self.name = name
        self.cat = cat
        self.args = args
        self._parent = parent

    def __enter__(self):
        self._prev = getattr(_tls, "ctx", None)
        parent_ctx = self._parent if self._parent is not None else self._prev
        self._parent = parent_ctx
        sid = _next_id()
        self._ctx = SpanContext(
            parent_ctx.trace_id if parent_ctx else sid, sid)
        _tls.ctx = self._ctx
        self._ann = None
        if _xplane_active:
            try:
                import jax
                self._ann = jax.profiler.TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self._t0 = _now_us()
        return self._ctx

    def __exit__(self, *exc):
        dur = _now_us() - self._t0
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
        _tls.ctx = self._prev
        ev_args = {"trace_id": self._ctx.trace_id,
                   "span_id": self._ctx.span_id,
                   "parent_id": self._parent.span_id if self._parent else 0}
        if self.args:
            ev_args.update(self.args)
        _record({"name": self.name, "cat": self.cat, "ph": "X",
                 "ts": self._t0, "dur": dur, "pid": os.getpid(),
                 "tid": threading.get_ident(), "args": ev_args})
        return False


def span(name: str, parent: Optional[SpanContext] = None, cat: str = "mv",
         args: Optional[dict] = None):
    """Context manager opening a span for the ``with`` block. ``parent``
    overrides the thread-local nesting (pass a message's ``trace_ctx``
    when picking work up from a mailbox). ``with`` yields the span's
    context (None when tracing is off)."""
    if not enabled():
        return _NULL_SPAN
    return _Span(name, parent, cat, args)


def flow_start(ctx: Optional[SpanContext], name: str = "mv.msg") -> None:
    """Flow-arrow origin (message enqueue). No-op when ``ctx`` is None
    or tracing is off."""
    if ctx is None or not enabled():
        return
    _record({"name": name, "cat": "msg", "ph": "s", "id": ctx.span_id,
             "ts": _now_us(), "pid": os.getpid(),
             "tid": threading.get_ident()})


def flow_end(ctx: Optional[SpanContext], name: str = "mv.msg") -> None:
    """Flow-arrow target (message dequeue on the actor thread)."""
    if ctx is None or not enabled():
        return
    _record({"name": name, "cat": "msg", "ph": "f", "bp": "e",
             "id": ctx.span_id, "ts": _now_us(), "pid": os.getpid(),
             "tid": threading.get_ident()})


def chrome_trace(events: list, process_names: Optional[dict] = None,
                 thread_names: Optional[dict] = None) -> dict:
    """Wrap prepared trace events as a Chrome trace-event object
    (Perfetto / chrome://tracing loadable) — THE one writer both the
    live span dump below and offline reconstructions
    (telemetry/critpath.py's merged cross-rank timeline) ride, so the
    export schema cannot fork. ``process_names``: {pid: label};
    ``thread_names``: {(pid, tid): label}."""
    meta = []
    for pid, name in sorted((process_names or {}).items()):
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"name": name}})
    for (pid, tid), name in sorted((thread_names or {}).items()):
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid, "args": {"name": name}})
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def to_chrome_trace() -> dict:
    """The buffered events as a Chrome trace-event object (JSON-ready)."""
    with _events_lock:
        events = list(_events)
    out = chrome_trace(events,
                       process_names={os.getpid(): _process_label()})
    # round 22: a (wall, mono) anchor pair sampled at export time. Span
    # timestamps are perf_counter-based (each process its own zero);
    # the fleet trace-merge CLI (telemetry/fleet.py --trace) uses this
    # pair to map every dump onto one wall timeline before refining the
    # residual offset from matched client/server span pairs.
    out["clock"] = {"wall_s": time.time(), "mono_us": _now_us(),
                    "pid": os.getpid()}
    return out


#: process label for dumps/merges — stamped by set_process_label()
#: from contexts that KNOW their identity (MV_Init on trainer ranks,
#: Replica.start on readers). A lazy multihost.process_index() here
#: would put device work on every dump caller's thread (the replica
#: serve loop exports dumps — device-work-domain law).
_PROC_LABEL = "multiverso"


def set_process_label(label: str) -> None:
    global _PROC_LABEL
    _PROC_LABEL = str(label)


def _process_label() -> str:
    return _PROC_LABEL


def dump(path: str) -> str:
    """Write the buffered span tree as Chrome trace JSON to ``path``
    (per-rank file in multihost jobs — each rank holds its own spans)
    and return the path."""
    data = to_chrome_trace()
    with open(path, "w") as f:
        json.dump(data, f)
    Log.Info("telemetry: wrote %d trace events to %s",
             len(data["traceEvents"]), path)
    return path


def clear() -> None:
    with _events_lock:
        _events.clear()


def _reset_for_tests() -> None:
    clear()
    set_xplane(False)
