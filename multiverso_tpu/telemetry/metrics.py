"""Typed metrics registry: Counter / Gauge / log-bucketed Histogram /
mergeable Digest.

Prometheus-style instruments for the runtime's hot paths, designed
around the two constraints the Dashboard already solved partially:

* **threads** — worker threads and the engine actor update instruments
  concurrently; every mutation is a short critical section.
* **hosts** — a multi-process job wants job-wide totals, but collective
  reduces require every rank to agree on buffer shape. Instrument
  *names* are exchanged first and the reduce runs over the union
  (the ``Dashboard.AggregateAcrossHosts`` trick), and every instrument
  encodes to a FIXED-width float vector — counters/gauges to one slot,
  histograms to ``N_BUCKETS + 2`` (count, sum, buckets) — so the one
  allreduce always agrees on shape even when rank A observed a
  histogram rank B never touched.

Histogram buckets are a fixed geometric ladder (powers of two from
``2**_MIN_EXP``): bucket ``i`` holds values in ``(2**(_MIN_EXP+i-1),
2**(_MIN_EXP+i)]``. One ladder serves seconds (~1us resolution) and
bytes alike, and because the ladder is a compile-time constant, bucket
vectors from different hosts add elementwise — which is exactly what
the cross-host merge does. Percentiles interpolate linearly inside the
winning bucket, so p50/p90/p99 are estimates with <= one-octave error,
the standard log-bucket tradeoff.

The ``-telemetry`` flag gates the whole layer: when false, instrument
lookups return one shared no-op ``NULL`` instrument and the registry
stays empty (the off fast path allocates nothing; tests assert this).
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List

from multiverso_tpu.utils.configure import MV_DEFINE_bool, cached_bool_flag
from multiverso_tpu.utils.log import CHECK

MV_DEFINE_bool("telemetry", True,
               "typed metrics registry (counters/gauges/histograms) on/off")

#: the -telemetry gate, CACHED behind a flag listener: GetFlag walks
#: the typed registries under their lock — too costly per message
enabled = cached_bool_flag("telemetry", True)

#: fixed histogram ladder: bucket i's upper bound is 2**(_MIN_EXP + i).
#: 64 octaves from ~1e-6 (1us / 1 byte-ish) to ~8.8e12 cover every
#: latency and byte quantity the runtime observes.
N_BUCKETS = 64
_MIN_EXP = -20
#: fixed vector widths per instrument kind — the cross-host merge
#: contract (every rank derives the same layout from (name, kind))
_WIDTHS = {"c": 1, "g": 1, "m": 1, "h": N_BUCKETS + 2,
           "d": N_BUCKETS + 4}




def bucket_index(v: float) -> int:
    """Ladder bucket for ``v``: smallest i with v <= 2**(_MIN_EXP+i),
    clamped to [0, N_BUCKETS). Non-positive values land in bucket 0."""
    if v <= 0:
        return 0
    m, e = math.frexp(v)          # v = m * 2**e, 0.5 <= m < 1 — exact
    ce = e - 1 if m == 0.5 else e  # ceil(log2(v)) without float log
    return min(max(ce - _MIN_EXP, 0), N_BUCKETS - 1)


def bucket_bounds(i: int):
    """(lower, upper] value bounds of bucket ``i`` (lower of bucket 0
    is 0 — it also absorbs non-positive observations)."""
    lo = 0.0 if i == 0 else 2.0 ** (_MIN_EXP + i - 1)
    return lo, 2.0 ** (_MIN_EXP + i)


class _Null:
    """Shared no-op instrument handed out when telemetry is off; every
    mutator is a pass so cached handles stay valid either way."""

    def inc(self, n: float = 1.0) -> None:
        pass

    def dec(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass

    @property
    def value(self) -> float:
        return 0.0


NULL = _Null()


class Counter:
    """Monotonic total (counts, bytes). Cross-host merge: sum."""

    kind = "c"
    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def _vector(self) -> List[float]:
        return [self._value]

    @staticmethod
    def _snapshot(vec) -> dict:
        return {"type": "counter", "value": float(vec[0])}


class Gauge:
    """Point-in-time level (mailbox depth, staleness). Cross-host
    merge: sum — a job-wide depth/budget is the sum of per-rank levels;
    per-rank values live in the local snapshot."""

    kind = "g"
    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> float:
        return self._value

    def _vector(self) -> List[float]:
        return [self._value]

    @staticmethod
    def _snapshot(vec) -> dict:
        return {"type": "gauge", "value": float(vec[0])}


class MaxGauge(Gauge):
    """Gauge whose cross-host merge takes the MAX instead of the sum —
    for levels where job-wide means worst-rank, not total (BSP
    staleness: two ranks each 3 stale is a skew of 3, not 6)."""

    kind = "m"
    __slots__ = ()

    @staticmethod
    def _snapshot(vec) -> dict:
        return {"type": "gauge", "value": float(vec[0])}


class Histogram:
    """Log-bucketed distribution (latencies, sizes): totals + fixed
    bucket vector, p50/p90/p99 estimated by in-bucket interpolation.
    Cross-host merge: elementwise sum of (count, sum, buckets)."""

    kind = "h"
    __slots__ = ("name", "_lock", "_count", "_sum", "_buckets")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._count = 0
        self._sum = 0.0
        self._buckets = [0] * N_BUCKETS

    def observe(self, v: float) -> None:
        i = bucket_index(v)
        with self._lock:
            self._count += 1
            self._sum += v
            self._buckets[i] += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def _vector(self) -> List[float]:
        with self._lock:
            return [float(self._count), self._sum] + [
                float(b) for b in self._buckets]

    @staticmethod
    def percentile(buckets, count: float, q: float) -> float:
        """Estimate the q-quantile (0<q<1) from a bucket vector by
        linear interpolation inside the winning bucket."""
        if count <= 0:
            return 0.0
        target = q * count
        cum = 0.0
        for i, b in enumerate(buckets):
            if b <= 0:
                continue
            if cum + b >= target:
                lo, hi = bucket_bounds(i)
                frac = (target - cum) / b
                return lo + frac * (hi - lo)
            cum += b
        lo, hi = bucket_bounds(N_BUCKETS - 1)
        return hi

    @staticmethod
    def _snapshot(vec) -> dict:
        count = float(vec[0])
        total = float(vec[1])
        buckets = [float(b) for b in vec[2:2 + N_BUCKETS]]
        out = {
            "type": "histogram",
            "count": int(count),
            "sum": total,
            "mean": total / count if count else 0.0,
            "p50": Histogram.percentile(buckets, count, 0.50),
            "p90": Histogram.percentile(buckets, count, 0.90),
            "p99": Histogram.percentile(buckets, count, 0.99),
            # sparse bucket map (index -> count): full 64-wide vectors
            # would drown the snapshot; tests re-derive merges from this
            "buckets": {str(i): int(b) for i, b in enumerate(buckets)
                        if b > 0},
        }
        return out


class Digest:
    """Mergeable latency/size digest (round 22): a Histogram's bucket
    ladder plus exact min/max, built so two digests from DIFFERENT
    processes combine into the digest of the combined stream without
    any loss beyond the ladder itself.

    Vector layout (width ``N_BUCKETS + 4``): ``[count, sum, min, max,
    b0..b63]``. The merge is elementwise — count/sum/buckets add,
    min takes the min, max the max — which makes it exact (the merged
    vector equals the vector a single digest would have built from the
    concatenated stream), hence associative and commutative; the fleet
    accumulator relies on that to fold rollups in arrival order.

    Quantiles interpolate inside the winning ladder bucket (<= one
    octave of relative error, same bound as Histogram) and are then
    CLAMPED to the exact ``[min, max]`` — so single-sample and
    narrow-range digests report true values, not bucket upper bounds.
    Empty digests use ``+inf/-inf`` sentinels for min/max (the merge
    identity); they render as 0 in snapshots."""

    kind = "d"
    __slots__ = ("name", "_lock", "_count", "_sum", "_min", "_max",
                 "_buckets")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._buckets = [0] * N_BUCKETS

    def observe(self, v: float) -> None:
        v = float(v)
        i = bucket_index(v)
        with self._lock:
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            self._buckets[i] += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def _vector(self) -> List[float]:
        with self._lock:
            return [float(self._count), self._sum, self._min,
                    self._max] + [float(b) for b in self._buckets]

    @staticmethod
    def empty_vector() -> List[float]:
        """The merge identity — what an untouched digest encodes to
        (and what absent ranks contribute in the cross-host merge)."""
        return [0.0, 0.0, math.inf, -math.inf] + [0.0] * N_BUCKETS

    @staticmethod
    def merge_vec(a, b) -> List[float]:
        """Exact elementwise merge of two digest vectors -> new list."""
        CHECK(len(a) == len(b) == N_BUCKETS + 4,
              f"digest vector width mismatch: {len(a)} vs {len(b)}")
        out = [float(a[0]) + float(b[0]), float(a[1]) + float(b[1]),
               min(float(a[2]), float(b[2])),
               max(float(a[3]), float(b[3]))]
        out.extend(float(a[i]) + float(b[i])
                   for i in range(4, N_BUCKETS + 4))
        return out

    def merge(self, other: "Digest") -> "Digest":
        """Pure combine: a NEW digest holding both streams."""
        merged = Digest(self.name)
        vec = Digest.merge_vec(self._vector(), other._vector())
        merged._count = int(vec[0])
        merged._sum = vec[1]
        merged._min = vec[2]
        merged._max = vec[3]
        merged._buckets = [int(b) for b in vec[4:]]
        return merged

    @staticmethod
    def quantile(vec, q: float) -> float:
        """Bounded-error q-quantile from a digest VECTOR: ladder
        interpolation clamped to the exact [min, max]."""
        count = float(vec[0])
        if count <= 0:
            return 0.0
        lo, hi = float(vec[2]), float(vec[3])
        est = Histogram.percentile(vec[4:4 + N_BUCKETS], count, q)
        return min(max(est, lo), hi)

    @staticmethod
    def _snapshot(vec) -> dict:
        count = float(vec[0])
        total = float(vec[1])
        return {
            "type": "digest",
            "count": int(count),
            "sum": total,
            "mean": total / count if count else 0.0,
            "min": float(vec[2]) if count else 0.0,
            "max": float(vec[3]) if count else 0.0,
            "p50": Digest.quantile(vec, 0.50),
            "p95": Digest.quantile(vec, 0.95),
            "p99": Digest.quantile(vec, 0.99),
            "buckets": {str(i): int(b)
                        for i, b in enumerate(vec[4:4 + N_BUCKETS])
                        if b > 0},
        }


_SNAPSHOTTERS = {"c": Counter._snapshot, "g": Gauge._snapshot,
                 "m": MaxGauge._snapshot, "h": Histogram._snapshot,
                 "d": Digest._snapshot}
_CLASSES = {"c": Counter, "g": Gauge, "m": MaxGauge, "h": Histogram,
            "d": Digest}


def _merge_cols(kind: str, cols):
    """Reduce a (ranks, width) column block to one merged vector per
    the kind's law: max-gauges take the rank max; digests merge
    columnwise (count/sum/buckets add, min-col min, max-col max);
    everything else sums elementwise."""
    if kind == "m":
        return cols.max(axis=0)
    if kind == "d":
        merged = cols.sum(axis=0)
        merged[2] = cols[:, 2].min()
        merged[3] = cols[:, 3].max()
        return merged
    return cols.sum(axis=0)


class MetricsRegistry:
    """Process-wide named instrument registry (lazy get-or-create, the
    Dashboard.Get idiom, typed)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[str, object] = {}

    def _get(self, name: str, cls):
        if not enabled():
            return NULL
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name)
                self._instruments[name] = inst
        CHECK(isinstance(inst, cls),
              f"telemetry instrument {name!r} already registered as "
              f"{type(inst).__name__}, requested {cls.__name__}")
        return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def max_gauge(self, name: str) -> MaxGauge:
        return self._get(name, MaxGauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def digest(self, name: str) -> Digest:
        return self._get(name, Digest)

    def digest_vectors(self) -> Dict[str, List[float]]:
        """{name: vector} for every registered Digest — the fleet
        rollup's raw material. Never collective."""
        with self._lock:
            items = [(n, i) for n, i in self._instruments.items()
                     if i.kind == "d"]
        return {name: inst._vector() for name, inst in sorted(items)}

    def gauge_values(self, prefixes=()) -> Dict[str, float]:
        """{name: value} of gauges/max-gauges, optionally filtered by
        name prefix — the fleet rollup's key-gauge read. Never
        collective."""
        pfx = tuple(prefixes)
        with self._lock:
            return {n: float(i.value)
                    for n, i in self._instruments.items()
                    if i.kind in ("g", "m")
                    and (not pfx or n.startswith(pfx))}

    def snapshot(self) -> Dict[str, dict]:
        """LOCAL snapshot: {name: typed dict}. Never collective — safe
        from any thread (the periodic reporter calls it on a timer)."""
        with self._lock:
            items = list(self._instruments.items())
        return {name: _SNAPSHOTTERS[inst.kind](inst._vector())
                for name, inst in sorted(items)}

    def merged_snapshot(self) -> Dict[str, dict]:
        """Job-wide snapshot summed over every host. COLLECTIVE in a
        multi-process world (every rank must call it at the same point,
        with the engine quiesced — like MV_Barrier); identity locally.

        Union-of-names: ranks may hold disjoint instrument sets
        (role-specific counters), so ``kind:name`` tags are exchanged
        first and one data exchange carries fixed-width vectors laid
        out from the sorted union — every rank agrees on shape. The
        reduce runs client-side per kind: counters/gauges/histograms
        sum elementwise, max-gauges take the rank maximum."""
        import numpy as np

        from multiverso_tpu.parallel import multihost

        with self._lock:
            local = {name: (inst.kind, inst._vector())
                     for name, inst in self._instruments.items()}
        tagged = {f"{kind}:{name}" for name, (kind, _) in local.items()}
        if multihost.process_count() > 1:
            blobs = multihost.host_allgather_bytes(
                "\x00".join(sorted(tagged)).encode())
            union = set()
            for blob in blobs:
                if blob:
                    union.update(blob.decode().split("\x00"))
        else:
            union = tagged
        tags = sorted(union)
        kinds = {}
        for tag in tags:
            kind, _, name = tag.partition(":")
            CHECK(name not in kinds,
                  f"telemetry instrument {name!r} has divergent kinds "
                  f"across hosts — every rank must register a name with "
                  f"one type")
            kinds[name] = kind
        names = sorted(kinds)
        if not names:
            return {}
        vec: List[float] = []
        for name in names:
            kind = kinds[name]
            have = local.get(name)
            if have is not None and have[0] == kind:
                vec.extend(have[1])
            elif kind == "d":
                # digest identity is NOT all-zeros: min/max sentinels
                vec.extend(Digest.empty_vector())
            else:
                vec.extend([0.0] * _WIDTHS[kind])
        arr = np.asarray(vec, np.float64)
        if multihost.process_count() > 1:
            # allgather (not allreduce-sum) so each kind picks its own
            # reduction: max-gauges must not sum across ranks
            blobs = multihost.host_allgather_bytes(arr.tobytes())
            ranks = np.stack([np.frombuffer(b, np.float64)
                              for b in blobs])
        else:
            ranks = arr.reshape(1, -1)
        out: Dict[str, dict] = {}
        pos = 0
        for name in names:
            kind = kinds[name]
            width = _WIDTHS[kind]
            cols = ranks[:, pos:pos + width]
            out[name] = _SNAPSHOTTERS[kind](_merge_cols(kind, cols))
            pos += width
        return out

    def _reset_for_tests(self) -> None:
        with self._lock:
            self._instruments.clear()


REGISTRY = MetricsRegistry()


def counter(name: str) -> Counter:
    return REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return REGISTRY.gauge(name)


def max_gauge(name: str) -> MaxGauge:
    return REGISTRY.max_gauge(name)


def histogram(name: str) -> Histogram:
    return REGISTRY.histogram(name)


def digest(name: str) -> Digest:
    return REGISTRY.digest(name)


def snapshot() -> Dict[str, dict]:
    return REGISTRY.snapshot()


def merged_snapshot() -> Dict[str, dict]:
    return REGISTRY.merged_snapshot()


def _reset_for_tests() -> None:
    REGISTRY._reset_for_tests()
