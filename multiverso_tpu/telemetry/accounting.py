"""Process memory/capacity ledger: where every byte of runtime state
lives, as typed ``mem.*`` gauges and the ``/memory`` ops endpoint.

The reference's Dashboard counts time; nothing in this build counted
BYTES — yet the ROADMAP's giant-table scenario (host-RAM authoritative
rows + a device hot-row cache) is unbuildable without knowing, per
table, how much state sits on the device, in host mirrors, and in host
control planes, and the PR 9 components that fail by *saturation*
(shm ring, snapshot retention, write-combine buffers) all fail by
byte growth first. This module is the measurement substrate:

* **pull, not push** — components are PROBED at sample time (the
  watchdog tick, an ops scrape, a Dashboard render); nothing on a verb
  path increments a byte gauge. Every probe is shape/size arithmetic
  under at most one short lock — never a device sync, a mirror
  creation, or a copy (``tables/base.py ledger_bytes`` contract).
* **typed gauge families, registered EAGERLY** — ``start_ledger()``
  registers every ``mem.*`` family at zero (the PR 6 rule), so the
  ``-stats_interval_s`` reporter and ``/metrics`` show the whole
  coverage map from the first scrape. Per-table / per-version detail
  lives in the ``/memory`` JSON body; the gauges carry family totals.
* **local only** — the ledger never issues collectives (the reporter/
  ops-handler rule); job-wide totals are Prometheus's aggregation job.

Coverage map (the ``/memory`` body mirrors this):

========================  =============================================
component                 what is counted
========================  =============================================
tables.device_bytes       per-table jax store leaves (LOGICAL array
                          bytes — a documented bound for sharded
                          multi-device processes, exact on one device)
tables.host_mirror_bytes  native f32 mirrors + numpy kv mirrors (exact)
tables.host_bytes         host-authoritative values, freshness bitmaps,
                          key indexes at ALLOCATED capacity — probing-
                          table load-factor headroom included (exact)
snapshots.bytes           every LIVE serving snapshot version
                          (serving/store.retained_bytes)
flight.bytes              flight-recorder ring estimate (events *
                          fixed tuple overhead + detail strings)
dedup.bytes               (src, msg_id) dedup window estimate
write_combine.bytes       worker-side combined-Add buffers (exact)
get_cache.bytes           staleness-bounded Get cache copies (exact)
shm.segment_bytes         owned shared-memory ring segments (+ peer
                          mappings reported separately in the body)
========================  =============================================
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from multiverso_tpu.telemetry import flight as tflight
from multiverso_tpu.telemetry import metrics as tmetrics
from multiverso_tpu.utils.log import Log

#: every family gauge the ledger maintains — registered eagerly at
#: :func:`start_ledger` so the whole coverage map scrapes at zero
#: before the first refresh (tests assert this)
MEM_FAMILIES = (
    "mem.total_bytes",
    "mem.tables.device_bytes",
    "mem.tables.host_mirror_bytes",
    "mem.tables.host_bytes",
    "mem.snapshots.bytes",
    "mem.flight.bytes",
    "mem.dedup.bytes",
    "mem.write_combine.bytes",
    "mem.get_cache.bytes",
    "mem.shm.segment_bytes",
    "mem.shm.frame_hw_bytes",
    "mem.replica.journal_bytes",
)

#: flight-ring estimate: one event is an 8-slot tuple (3 ints, 2
#: floats, 2 interned-ish strings, container overhead ~ this many
#: bytes) plus its detail string's characters. An ESTIMATE, and
#: documented as one in the /memory body — the ring holds python
#: objects, not flat buffers.
_FLIGHT_EVENT_OVERHEAD = 160

#: dedup-window estimate per entry: (src, msg_id) key tuple + ordered-
#: dict slot + outcome pointer
_DEDUP_ENTRY_OVERHEAD = 128

_started = False
_lock = threading.Lock()


def _tables_report() -> dict:
    """Per-table placement via the ``ledger_bytes`` probes (engine
    server tables) + the worker halves' buffered bytes."""
    per_table = []
    totals = {"device_bytes": 0, "host_mirror_bytes": 0, "host_bytes": 0}
    wc_bytes = 0
    gc_bytes = 0
    from multiverso_tpu.zoo import Zoo
    zoo = Zoo.Get()
    eng = zoo.server_engine
    if eng is not None:
        for tid, table in enumerate(getattr(eng, "store_", [])):
            try:
                rec = dict(table.ledger_bytes())
            except Exception as exc:    # one bad probe must not blind
                Log.Debug("ledger: table %d probe failed: %r", tid, exc)
                continue
            rec["table_id"] = tid
            rec["family"] = type(table).__name__
            per_table.append(rec)
            for k in totals:
                totals[k] += int(rec.get(k, 0))
    for wt in list(getattr(zoo, "worker_tables", [])):
        try:
            w = wt.worker_ledger_bytes()
        except Exception:
            continue
        wc_bytes += w.get("write_combine_bytes", 0)
        gc_bytes += w.get("get_cache_bytes", 0)
    return {"per_table": per_table, "totals": totals,
            "write_combine_bytes": wc_bytes, "get_cache_bytes": gc_bytes}


def _snapshots_report() -> dict:
    from multiverso_tpu.serving import peek_plane
    plane = peek_plane()
    if plane is None:
        return {"per_version": {}, "bytes": 0}
    per_version = {str(v): b
                   for v, b in plane.store.retained_bytes().items()}
    return {"per_version": per_version,
            "bytes": sum(per_version.values())}


def _flight_report() -> dict:
    # raw-tuple sum, NOT .events(): this runs every watchdog tick and
    # a full default ring is 4096 events — building a dict per event
    # per tick would dwarf the documented tick body
    count, est = tflight.RECORDER.approx_bytes(_FLIGHT_EVENT_OVERHEAD)
    recorded, dropped = tflight.stats()
    return {"events": count, "recorded": recorded,
            "dropped": dropped, "bytes_estimate": est,
            "note": ("estimate: events * ~%dB tuple overhead + detail "
                     "chars" % _FLIGHT_EVENT_OVERHEAD)}


def _dedup_report() -> dict:
    entries = 0
    from multiverso_tpu.zoo import Zoo
    eng = Zoo.Get().server_engine
    if eng is not None:
        for shard in _engine_shards(eng):
            dd = getattr(shard, "_dedup", None)
            if dd is not None:
                entries += len(dd)
    return {"entries": entries,
            "bytes_estimate": entries * _DEDUP_ENTRY_OVERHEAD}


def _engine_shards(eng) -> list:
    """The engine plus any live sub-shards (each a full engine)."""
    out = [eng]
    out.extend(getattr(eng, "_subs", {}).values())
    return out


def _shm_report() -> Optional[dict]:
    from multiverso_tpu.parallel import multihost
    wire = multihost.active_wire()
    if wire is None:
        return None
    return wire.mem_bytes()


def memory_report() -> dict:
    """The full ``/memory`` body: per-component byte placement with
    per-table / per-version detail, plus the reconciliation totals.
    LOCAL (never collective) and probe-only — safe from any thread;
    every component degrades to absence on teardown races. Also
    refreshes the ``mem.*`` family gauges so a scrape right after sees
    the same numbers."""
    comps: Dict[str, dict] = {}
    try:
        comps["tables"] = _tables_report()
    except Exception as exc:
        Log.Debug("ledger: tables probe failed: %r", exc)
        comps["tables"] = {"per_table": [], "totals": {
            "device_bytes": 0, "host_mirror_bytes": 0, "host_bytes": 0},
            "write_combine_bytes": 0, "get_cache_bytes": 0}
    try:
        comps["snapshots"] = _snapshots_report()
    except Exception:
        comps["snapshots"] = {"per_version": {}, "bytes": 0}
    try:
        comps["flight"] = _flight_report()
    except Exception:
        comps["flight"] = {"events": 0, "bytes_estimate": 0}
    try:
        comps["dedup"] = _dedup_report()
    except Exception:
        comps["dedup"] = {"entries": 0, "bytes_estimate": 0}
    try:
        comps["shm"] = _shm_report()
    except Exception:
        comps["shm"] = None
    # round 17 — replica fan-out plane: publish-journal bitmaps/write-
    # sets on the live tables + the retained per-version dirty
    # descriptors (the delta retention window). Exact shape arithmetic,
    # publisher-rank only; absent when the plane is off. (The replica
    # PROCESS accounts its own mirrors: mem.replica.mirror_bytes is set
    # at every apply over there and reported through its status op —
    # this ledger covers the trainer side of the split.)
    try:
        from multiverso_tpu import replica as treplica
        comps["replica"] = treplica.ledger_bytes()
    except Exception:
        comps["replica"] = None
    t = comps["tables"]["totals"]
    shm = comps["shm"] or {}
    rep = comps["replica"] or {}
    gauges = {
        "mem.tables.device_bytes": t["device_bytes"],
        "mem.tables.host_mirror_bytes": t["host_mirror_bytes"],
        "mem.tables.host_bytes": t["host_bytes"],
        "mem.snapshots.bytes": comps["snapshots"]["bytes"],
        "mem.flight.bytes": comps["flight"].get("bytes_estimate", 0),
        "mem.dedup.bytes": comps["dedup"].get("bytes_estimate", 0),
        "mem.write_combine.bytes": comps["tables"]["write_combine_bytes"],
        "mem.get_cache.bytes": comps["tables"]["get_cache_bytes"],
        "mem.shm.segment_bytes": shm.get("segment_bytes", 0),
        "mem.shm.frame_hw_bytes": shm.get("frame_hw_bytes", 0),
        "mem.replica.journal_bytes": (rep.get("journal_bytes", 0)
                                      + rep.get("dirty_set_bytes", 0)),
    }
    total = sum(gauges.values()) - gauges["mem.shm.frame_hw_bytes"]
    gauges["mem.total_bytes"] = total
    for name, v in gauges.items():
        tmetrics.gauge(name).set(float(v))
    return {
        "total_bytes": total,
        "components": comps,
        "note": ("local process ledger; device_bytes are LOGICAL jax "
                 "array bytes (documented bound on sharded multi-"
                 "device processes), host/mirror bytes exact, flight/"
                 "dedup are estimates; frame_hw_bytes is a high-"
                 "watermark, excluded from total_bytes"),
    }


def refresh() -> dict:
    """Alias used by the watchdog tick: probe + set gauges."""
    return memory_report()


def start_ledger() -> None:
    """Register every ``mem.*`` family gauge at zero (Zoo.Start).
    Idempotent per world; a no-op while ``-telemetry=false`` hands out
    NULL instruments (the registry stays empty, like everything
    else)."""
    global _started
    with _lock:
        for name in MEM_FAMILIES:
            tmetrics.gauge(name)
        _started = True


def stop_ledger() -> None:
    """Zoo.Stop teardown hook. The gauges stay registered (instrument
    registries live for the process); only the started mark resets so
    a later world re-arms cleanly."""
    global _started
    with _lock:
        _started = False


def started() -> bool:
    return _started
