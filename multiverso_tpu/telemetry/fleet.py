"""Fleet observability plane (round 22): heartbeat-shipped rollups,
the coordinator-side accumulator behind ``/fleet``, and the multi-dump
trace merge CLI.

Every observability surface before this round is rank-LOCAL by law —
flight rings, watchdog rules, the ledger, critpath all answer "what is
THIS process doing". The fleet plane answers "what is the JOB doing"
without breaking that law, by copying the reference system's control
plane shape (DMTK Multiverso: telemetry piggybacks on messages that
already flow) and the 1-bit-SGD lesson (ship the smallest faithful
representation):

* :func:`build_rollup` snapshots the process's mergeable digest
  vectors (telemetry/metrics.py ``Digest``) plus key gauges into one
  compact dict and :func:`encode_rollup` frames it with the sealed
  flat codec — a couple of KB per heartbeat at worst (the bench
  freezes ``fleet_rollup_bytes_per_hb`` as a ratcheted byte ceiling),
  never collective;
* the blob rides EXISTING lease traffic — ``replica_hb`` for reader
  processes, the elastic member heartbeat for trainer ranks, the
  fan-out owner's ``replica_roster`` tick for rank 0 — so the plane
  adds ZERO new connections and ZERO collectives (aggregation happens
  coordinator-side from pushed state);
* :class:`FleetAccumulator` (one module-global instance on whichever
  process hosts the coordinator) stamps each rollup's arrival, derives
  per-member QPS from request-count deltas, merges digests EXACTLY
  (the Digest merge law), and serves the ``/fleet`` ops document:
  per-member rows + fleet-merged p50/p95/p99/QPS + "slowest member by
  p99" attribution. Staleness is explicit: a member whose lease
  heartbeats still arrive but whose rollup stopped refreshing is
  marked stale rather than silently reporting frozen numbers.

Watchdog coupling is one-way: watchdog.collect_sample() merges
:func:`peek_sample` (this module NEVER imports watchdog — the fleet
rules live in telemetry/watchdog.py with the other typed rules) and
the three fleet rules (``fleet_p99_breach``, ``member_qps_outlier``,
``rollup_stale``) fire through the same alert/flight machinery,
giving the round-20 policy plane its first fleet-scoped inputs.

``python -m multiverso_tpu.telemetry.fleet --trace -o out.json
dump1.json dump2.json …`` stitches per-process ``MV_DumpTrace`` files
into ONE chrome trace: each dump's perf_counter timeline is anchored
onto a common wall timeline via the (wall, mono) clock pair stamped at
export, then refined with critpath's median-offset idiom over matched
client/server span pairs (the round-22 cross-wire trace contexts make
those pairs share a trace_id).

This module stays jax-free — the replica reader imports it on its
serve path (tests/test_packaging.py pins the property).
"""

from __future__ import annotations

import statistics
import threading
import time
from typing import Dict, List, Optional

from multiverso_tpu.telemetry import metrics as tmetrics
from multiverso_tpu.utils.configure import MV_DEFINE_double, cached_float_flag
from multiverso_tpu.utils.log import Log

MV_DEFINE_double(
    "mv_fleet_stale_s", 10.0,
    "age (s) past which a member's fleet rollup counts as STALE: the "
    "member row degrades to warn, the rollup_stale watchdog rule arms, "
    "and /healthz stops trusting its frozen replica-lag numbers")
MV_DEFINE_double(
    "mv_fleet_p99_s", 0.0,
    "fleet-merged request p99 (s) above which the fleet_p99_breach "
    "watchdog rule fires; 0 disables the rule (HOLD)")

stale_s = cached_float_flag("mv_fleet_stale_s", 10.0)

#: request-shaped digest families whose counts define a member's "ops"
#: total (QPS = arrival-stamped delta of this): one per serve surface.
#: Digests live under their own ``digest.`` prefix — several shadow a
#: same-named histogram and the registry CHECKs name/kind collisions.
#: The window-phase digest is deliberately NOT here — a window is not
#: a request.
QPS_FAMILIES = ("digest.serving.latency_s", "digest.replica.serve_s",
                "digest.worker.rtt_s")

#: rollup blob schema version (the seal guards bytes; this guards shape)
ROLLUP_V = 1

#: gauge-name prefixes that ride the rollup (replica lag/subscribers +
#: the memory ledger's totals — the "key gauges" of the fleet view)
_GAUGE_PREFIXES = ("replica.", "mem.")


def eager_register() -> None:
    """Register every always-on ``fleet.*`` family (plus the trainer
    digest families fed from the worker/engine hot paths) so the FIRST
    /metrics scrape shows them at zero — the PR 10 rule. Plane-scoped
    digests (serving.latency_s, replica.serve_s) register at their own
    plane starts."""
    tmetrics.counter("fleet.rollups")
    tmetrics.counter("fleet.rollup_errors")
    tmetrics.gauge("fleet.members")
    tmetrics.digest("digest.worker.rtt_s")
    tmetrics.digest("digest.engine.window_s")


# -- rollup build / codec ----------------------------------------------------

def build_rollup(member: str, role: str) -> dict:
    """Snapshot THIS process's digests + key gauges into one flat-
    encodable dict. Never collective — it reads the local registry
    under its lock and touches nothing else (mvlint pins this function
    as a never-collective root); safe from heartbeat daemon threads.

    ``member`` is the fleet-wide identity the coordinator keys on
    (``rank<N>`` for trainer ranks, ``replica:<rid>`` for readers) —
    callers supply it because this module must not import multihost
    (jax-free law)."""
    import numpy as np

    digests = tmetrics.REGISTRY.digest_vectors()
    ops = sum(vec[0] for name, vec in digests.items()
              if name in QPS_FAMILIES)
    gauges = tmetrics.REGISTRY.gauge_values(_GAUGE_PREFIXES)
    return {"v": ROLLUP_V, "member": member, "role": role,
            "ops": float(ops),
            "digests": {n: np.asarray(v, np.float64)
                        for n, v in digests.items()},
            "gauges": gauges}


def encode_rollup(rollup: dict) -> bytes:
    """Rollup dict -> sealed flat frame (the blob that rides a
    heartbeat). Lazy import: flat pulls compress which registers
    metrics counters — importing it at module top would cycle through
    the telemetry package during its own init."""
    from multiverso_tpu.parallel import flat
    return flat.encode_frame(rollup)


def decode_rollup(blob: bytes) -> dict:
    """Sealed flat frame -> rollup dict (digest vectors as plain float
    lists — the zero-copy views must not outlive the blob)."""
    from multiverso_tpu.parallel import flat
    rollup = flat.decode_frame(blob)
    if not isinstance(rollup, dict) or rollup.get("v") != ROLLUP_V:
        raise ValueError(f"not a v{ROLLUP_V} fleet rollup: "
                         f"{type(rollup).__name__}")
    rollup["digests"] = {n: [float(x) for x in vec]
                         for n, vec in rollup["digests"].items()}
    return rollup


# -- coordinator-side accumulation ------------------------------------------

class _Member:
    """One member's latest rollup + the derived rates/stamps."""

    __slots__ = ("member", "role", "t_arrival", "ops", "qps",
                 "digests", "gauges", "n_rollups")

    def __init__(self, member: str, role: str):
        self.member = member
        self.role = role
        self.t_arrival = 0.0
        self.ops = 0.0
        self.qps = 0.0
        self.digests: Dict[str, List[float]] = {}
        self.gauges: Dict[str, float] = {}
        self.n_rollups = 0


def _request_vec(digests: Dict[str, List[float]]) -> List[float]:
    """Fold a member's request-shaped digests into one vector."""
    vec = tmetrics.Digest.empty_vector()
    for name in QPS_FAMILIES:
        if name in digests:
            vec = tmetrics.Digest.merge_vec(vec, digests[name])
    return vec


class FleetAccumulator:
    """Coordinator-side fold of pushed member rollups.

    Aggregation is pull-free and collective-free BY CONSTRUCTION: the
    only inputs are blobs members already attached to their lease
    heartbeats; merging is the Digest vector merge (exact, order-
    independent) plus counter-delta QPS, all under one short lock.
    Everything it serves (/fleet, the dashboard line, the watchdog
    sample) is a read of this folded state — no rank is ever asked
    anything."""

    def __init__(self):
        self._lock = threading.Lock()
        self._members: Dict[str, _Member] = {}

    def ingest_rollup(self, rollup: dict,
                      now: Optional[float] = None) -> bool:
        member = rollup.get("member")
        if not member:
            tmetrics.counter("fleet.rollup_errors").inc()
            return False
        now = time.monotonic() if now is None else now
        ops = float(rollup.get("ops", 0.0))
        with self._lock:
            rec = self._members.get(member)
            if rec is None:
                rec = _Member(member, str(rollup.get("role", "?")))
                self._members[member] = rec
            dt = now - rec.t_arrival
            if rec.n_rollups > 0 and dt > 0 and ops >= rec.ops:
                rec.qps = (ops - rec.ops) / dt
            else:
                rec.qps = 0.0       # first rollup / counter reset
            rec.t_arrival = now
            rec.ops = ops
            rec.digests = rollup.get("digests", {})
            rec.gauges = rollup.get("gauges", {})
            rec.n_rollups += 1
            n = len(self._members)
        tmetrics.counter("fleet.rollups").inc()
        tmetrics.gauge("fleet.members").set(n)
        return True

    def ingest(self, blob: bytes, now: Optional[float] = None) -> bool:
        """Decode + fold one pushed blob. A torn/foreign blob must not
        take the heartbeat path down with it — it counts an error and
        the lease refresh proceeds."""
        try:
            rollup = decode_rollup(blob)
        except Exception as exc:
            tmetrics.counter("fleet.rollup_errors").inc()
            Log.Error("fleet: dropped undecodable rollup blob (%s)",
                      exc)
            return False
        return self.ingest_rollup(rollup, now=now)

    def rollup_age_s(self, member: str,
                     now: Optional[float] = None) -> Optional[float]:
        now = time.monotonic() if now is None else now
        with self._lock:
            rec = self._members.get(member)
            return None if rec is None else max(0.0, now - rec.t_arrival)

    def forget(self, member: str) -> None:
        """Drop a departed member (coordinator eviction path) so its
        last rollup stops aging into every staleness surface."""
        with self._lock:
            self._members.pop(member, None)
            n = len(self._members)
        tmetrics.gauge("fleet.members").set(n)

    def report(self, now: Optional[float] = None) -> dict:
        """The /fleet document. ALWAYS well-formed — before any rollup
        arrives it is the empty fleet, not an error."""
        now = time.monotonic() if now is None else now
        with self._lock:
            members = sorted(self._members.values(),
                             key=lambda m: m.member)
            rows = []
            fam_vecs: Dict[str, List[float]] = {}
            fleet_vec = tmetrics.Digest.empty_vector()
            binding = None
            stale = []
            limit = stale_s()
            for m in members:
                age = max(0.0, now - m.t_arrival)
                req = _request_vec(m.digests)
                p50 = tmetrics.Digest.quantile(req, 0.50)
                p99 = tmetrics.Digest.quantile(req, 0.99)
                is_stale = age > limit
                if is_stale:
                    stale.append(m.member)
                rows.append({
                    "member": m.member, "role": m.role,
                    "age_s": round(age, 3), "stale": is_stale,
                    "qps": round(m.qps, 3), "ops": m.ops,
                    "n_rollups": m.n_rollups,
                    "count": int(req[0]),
                    "p50_s": p50, "p99_s": p99,
                    "gauges": dict(m.gauges),
                })
                fleet_vec = tmetrics.Digest.merge_vec(fleet_vec, req)
                for name, vec in m.digests.items():
                    have = fam_vecs.get(name)
                    fam_vecs[name] = (list(vec) if have is None else
                                      tmetrics.Digest.merge_vec(have,
                                                                vec))
                if req[0] > 0 and (binding is None
                                   or p99 > binding["p99_s"]):
                    binding = {"member": m.member, "p99_s": p99}
        return {
            "n_members": len(rows),
            "members": rows,
            "fleet": {
                "qps": round(sum(r["qps"] for r in rows), 3),
                "count": int(fleet_vec[0]),
                "p50_s": tmetrics.Digest.quantile(fleet_vec, 0.50),
                "p95_s": tmetrics.Digest.quantile(fleet_vec, 0.95),
                "p99_s": tmetrics.Digest.quantile(fleet_vec, 0.99),
            },
            "binding_p99": binding,
            "digests": {n: tmetrics.Digest._snapshot(v)
                        for n, v in sorted(fam_vecs.items())},
            "stale_s": limit,
            "stale_members": stale,
        }

    def peek_sample(self, now: Optional[float] = None) -> dict:
        """Watchdog inputs — {} while the fleet is empty so every
        fleet rule HOLDs on non-coordinator ranks (same posture as the
        replica sample)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if not self._members:
                return {}
            members = list(self._members.values())
            fleet_vec = tmetrics.Digest.empty_vector()
            qps = {}
            ops = {}
            ages = {}
            for m in members:
                fleet_vec = tmetrics.Digest.merge_vec(
                    fleet_vec, _request_vec(m.digests))
                qps[m.member] = m.qps
                ops[m.member] = m.ops
                ages[m.member] = max(0.0, now - m.t_arrival)
        return {
            "fleet_members": len(qps),
            "fleet_qps": sum(qps.values()),
            "fleet_p99_s": tmetrics.Digest.quantile(fleet_vec, 0.99),
            "fleet_member_qps": qps,
            "fleet_member_ops": ops,
            "fleet_rollup_ages_s": ages,
            "fleet_rollup_age_max_s": max(ages.values()),
        }

    def clear(self) -> None:
        """Drop every folded member — the world-shutdown path. The
        fold aggregates members of ONE world's lease planes; letting it
        survive into the next world ages the old members into every
        staleness surface (rollup_stale would fire on a rank that is
        simply from a previous world)."""
        with self._lock:
            self._members.clear()
        tmetrics.gauge("fleet.members").set(0)

    def _reset_for_tests(self) -> None:
        self.clear()


#: THE accumulator — module-global so the coordinator op handlers (in
#: whatever thread/instance hosts them) and the /fleet route read one
#: fold, the Dashboard.Get idiom
_ACC = FleetAccumulator()


def shutdown_plane() -> None:
    """Clear the fold at world shutdown (Zoo.Stop) — the planes that
    fed it (replica heartbeats, elastic member heartbeats, the roster
    poll) are already down, and the next world starts from an empty
    fleet instead of inheriting stale members."""
    _ACC.clear()


def ingest(blob: bytes) -> bool:
    return _ACC.ingest(blob)


def ingest_rollup(rollup: dict) -> bool:
    return _ACC.ingest_rollup(rollup)


def rollup_age_s(member: str) -> Optional[float]:
    return _ACC.rollup_age_s(member)


def forget(member: str) -> None:
    _ACC.forget(member)


def fleet_report() -> dict:
    return _ACC.report()


def peek_sample() -> dict:
    return _ACC.peek_sample()


def status_lines() -> List[str]:
    """The ``[Fleet]`` dashboard line — empty while no rollup has
    arrived (non-coordinator ranks stay quiet)."""
    rep = _ACC.report()
    if not rep["n_members"]:
        return []
    fl = rep["fleet"]
    bind = rep["binding_p99"]
    line = (f"[Fleet] members={rep['n_members']} qps={fl['qps']:.0f} "
            f"p50={fl['p50_s'] * 1e3:.2f}ms p99={fl['p99_s'] * 1e3:.2f}ms")
    if bind is not None:
        line += (f" bind={bind['member']}"
                 f"@{bind['p99_s'] * 1e3:.2f}ms")
    if rep["stale_members"]:
        line += f" stale={','.join(rep['stale_members'])}"
    return [line]


def _reset_for_tests() -> None:
    _ACC._reset_for_tests()


# -- trace merge CLI ---------------------------------------------------------

def _dump_shift_us(dump: dict, ref_clock: Optional[dict]) -> float:
    """Anchor shift mapping this dump's perf_counter µs onto the ref
    dump's timeline via the (wall, mono) pair trace.dump() stamps."""
    clock = dump.get("clock")
    if not clock or not ref_clock:
        return 0.0
    return ((clock["wall_s"] * 1e6 - clock["mono_us"])
            - (ref_clock["wall_s"] * 1e6 - ref_clock["mono_us"]))


def merge_traces(dumps: List[dict]) -> dict:
    """Stitch per-process chrome-trace dumps into ONE trace.

    Two-stage alignment, critpath's recipe: (1) the coarse wall/mono
    anchor above (NTP-grade across hosts, exact same-host); (2) a
    median-offset refinement per dump from matched client/server span
    pairs — round-22 wire propagation gives a ``replica.call`` client
    span and its ``replica.serve`` dispatch span the same trace_id, and
    the server span's midpoint must sit at the client span's midpoint
    up to clock skew, so the median midpoint delta IS the residual
    skew (the same estimator critpath runs on exchange-done
    landmarks). ``align_err_us`` reports the worst post-fit residual."""
    ref_clock = next((d.get("clock") for d in dumps if d.get("clock")),
                     None)
    shifts = [_dump_shift_us(d, ref_clock) for d in dumps]

    # matched client/server span pairs by trace_id
    def _spans(d, cat):
        out = {}
        for ev in d.get("traceEvents", []):
            if ev.get("ph") == "X" and ev.get("cat") == cat:
                tid = ev.get("args", {}).get("trace_id")
                if tid is not None:
                    out[tid] = ev
        return out

    clients = [_spans(d, "client") for d in dumps]
    servers = [_spans(d, "server") for d in dumps]

    def _mid(ev, k):
        return ev["ts"] + ev.get("dur", 0.0) / 2.0 + shifts[k]

    residuals: Dict[int, List[float]] = {}
    for i, srv in enumerate(servers):
        for tid, sev in srv.items():
            for j, cli in enumerate(clients):
                if j == i or tid not in cli:
                    continue
                # positive delta = server timeline lags the client's
                delta = _mid(cli[tid], j) - _mid(sev, i)
                residuals.setdefault(i, []).append(delta)
                residuals.setdefault(j, []).append(-delta)
    corrections = [0.0] * len(dumps)
    align_err = 0.0
    for i, deltas in residuals.items():
        med = statistics.median(deltas)
        corrections[i] = med / 2.0      # split the pairwise skew
        align_err = max(align_err,
                        max(abs(d - med) for d in deltas))

    events: List[dict] = []
    process_names: Dict[int, str] = {}
    for k, d in enumerate(dumps):
        off = shifts[k] + corrections[k]
        for ev in d.get("traceEvents", []):
            if ev.get("ph") == "M":
                if (ev.get("name") == "process_name"
                        and "pid" in ev):
                    process_names[ev["pid"]] = ev["args"]["name"]
                continue
            ev = dict(ev)
            if "ts" in ev:
                ev["ts"] = ev["ts"] + off
            events.append(ev)
    events.sort(key=lambda e: e.get("ts", 0.0))
    from multiverso_tpu.telemetry import trace as ttrace
    out = ttrace.chrome_trace(events, process_names=process_names)
    out["merge"] = {
        "n_dumps": len(dumps),
        "shift_us": [round(s, 1) for s in shifts],
        "correction_us": [round(c, 1) for c in corrections],
        "align_err_us": round(align_err, 1),
        "n_span_pairs": sum(len(v) for v in residuals.values()) // 2,
    }
    return out


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import json
    import sys

    parser = argparse.ArgumentParser(
        prog="python -m multiverso_tpu.telemetry.fleet",
        description="fleet plane CLI: merge per-process trace dumps")
    parser.add_argument("--trace", action="store_true",
                        help="merge MV_DumpTrace chrome-trace files "
                             "into one aligned timeline")
    parser.add_argument("-o", "--out", default="fleet_trace.json",
                        help="merged trace output path")
    parser.add_argument("dumps", nargs="*",
                        help="per-process trace JSON files")
    args = parser.parse_args(argv)
    if not args.trace:
        parser.error("--trace is the only mode (so far)")
    if not args.dumps:
        parser.error("no trace dumps given")
    dumps = []
    for path in args.dumps:
        with open(path) as f:
            dumps.append(json.load(f))
    merged = merge_traces(dumps)
    with open(args.out, "w") as f:
        json.dump(merged, f)
    m = merged["merge"]
    sys.stdout.write(f"merged {m['n_dumps']} dumps, "
                     f"{len(merged['traceEvents'])} events, "
                     f"{m['n_span_pairs']} client/server span pairs, "
                     f"align_err={m['align_err_us']}us -> {args.out}\n")
    return 0


if __name__ == "__main__":      # pragma: no cover - CLI entry
    import sys
    sys.exit(main(sys.argv[1:]))
