"""Ops endpoint: a flag-gated stdlib-HTTP daemon serving /metrics,
/healthz, /flight, /perf, /alerts, /fleet and /memory.

``-mv_ops_port=N`` (default -1 = off; 0 = ephemeral, for tests and
multi-world processes) starts one daemon thread at MV_Init running a
``ThreadingHTTPServer`` bound to 127.0.0.1:

* ``GET /metrics`` — the LOCAL metrics snapshot rendered as Prometheus
  text exposition (``# TYPE`` lines + samples; histograms as cumulative
  ``_bucket{le=...}`` + ``_sum`` + ``_count``). Instrument names map
  ``server.window.latency_s`` -> ``mv_server_window_latency_s``.
* ``GET /healthz`` — JSON liveness: engine/actor poison state, exchange
  stage, mailbox depth, snapshot age, shed count, flight stats.
  200 while healthy, 503 once the engine is poisoned / its exchange
  stage died / the world stopped.
* ``GET /flight`` — the recent flight-recorder events as JSON.
* ``GET /perf`` — the LOCAL performance-forensics snapshot (round 11):
  engine.phase.* histograms, per-family apply seconds, the local
  binding-phase proxy and the ``-mv_row_sketch`` row-skew summaries.
  The cross-rank binding verdict needs every rank's dump through
  ``python -m multiverso_tpu.telemetry.critpath`` — the body says so.
* ``GET /alerts`` — the live watchdog plane's state (round 13,
  telemetry/watchdog.py): active typed alerts with durations + every
  rule's hysteresis counters; says "off" while ``-mv_watchdog_s`` is
  unarmed. Active alerts also degrade ``/healthz`` to a distinct
  ``warn`` status — still 200 (503 stays death-only).
* ``GET /actions`` — the policy plane's action report (round 20,
  multiverso_tpu/policy/): guard settings, install/revert/drain
  counts, actions under revert watch, and the bounded action history;
  says "off" while ``-mv_policy`` is unarmed.
* ``GET /fleet`` — the coordinator-side fleet rollup (round 22,
  telemetry/fleet.py): per-member rows (QPS, p50/p99, rollup age,
  staleness), the fleet-merged digest quantiles, and the "slowest
  member by p99" attribution. ALWAYS a well-formed document — before
  any rollup arrives (or on a rank that hosts no coordinator) it is
  the empty fleet, never a 500.
* ``GET /memory`` — the process byte ledger (round 13,
  telemetry/accounting.py): per-table device/mirror/host placement,
  per-version snapshot retention, flight/dedup/buffer estimates, shm
  ring footprint — refreshed at request time.

THE HANDLER NEVER ISSUES COLLECTIVES — same rule as the PR 2 periodic
reporter: a scrape thread running allgathers would interleave with the
engine's window exchanges and corrupt the SPMD stream. Everything
served here is a local-rank snapshot; job-wide totals remain the
explicitly collective ``MV_MetricsSnapshot()``'s business. Scrape every
rank and aggregate in Prometheus, which is how production PS
deployments surface per-node health anyway.

``Zoo.Stop`` shuts the server down and joins its thread bounded
(``failsafe.deadline.bounded``), so back-to-back worlds in one process
never leak the thread or find the port busy.
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from multiverso_tpu.telemetry import flight, metrics
from multiverso_tpu.telemetry.metrics import bucket_bounds
from multiverso_tpu.utils.configure import GetFlag, MV_DEFINE_int
from multiverso_tpu.utils.log import Log

MV_DEFINE_int("mv_ops_port", -1,
              "ops HTTP endpoint (/metrics Prometheus text, /healthz, "
              "/flight) on 127.0.0.1:<port>; -1 = off, 0 = pick an "
              "ephemeral port (tests / multi-world processes). The "
              "handler serves LOCAL snapshots only and never issues "
              "collectives")

_NAME_SAN = re.compile(r"[^a-zA-Z0-9_:]")


def _fmt(v) -> str:
    """Prometheus sample value: integers bare, floats via repr (both
    are valid exposition floats, incl. exponent forms like 1e-06)."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def prom_name(name: str) -> str:
    """Instrument name -> Prometheus metric name (mv_ prefix, dots and
    other illegal chars to underscores)."""
    return "mv_" + _NAME_SAN.sub("_", name)


def render_prometheus(snap: dict) -> str:
    """Render a LOCAL metrics snapshot ({name: typed dict}) as
    Prometheus text exposition format (version 0.0.4)."""
    lines = []
    for name in sorted(snap):
        rec = snap[name]
        pname = prom_name(name)
        kind = rec.get("type")
        if kind == "counter":
            lines.append(f"# TYPE {pname} counter")
            lines.append(f"{pname} {_fmt(rec['value'])}")
        elif kind == "gauge":
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname} {_fmt(rec['value'])}")
        elif kind == "histogram":
            lines.append(f"# TYPE {pname} histogram")
            cum = 0
            buckets = rec.get("buckets", {})
            for i in sorted(int(k) for k in buckets):
                cum += int(buckets[str(i)])
                le = bucket_bounds(i)[1]
                lines.append(f'{pname}_bucket{{le="{repr(le)}"}} {cum}')
            lines.append(f'{pname}_bucket{{le="+Inf"}} '
                         f'{int(rec["count"])}')
            lines.append(f"{pname}_sum {_fmt(rec['sum'])}")
            lines.append(f"{pname}_count {int(rec['count'])}")
        elif kind == "digest":
            # round 22 — mergeable digests scrape as Prometheus
            # summaries: clamped quantiles are point estimates, not
            # cumulative buckets (the full bucket vector rides the
            # fleet rollup, not the text exposition)
            lines.append(f"# TYPE {pname} summary")
            for q in ("0.5", "0.95", "0.99"):
                key = "p" + q[2:].ljust(2, "0")
                lines.append(f'{pname}{{quantile="{q}"}} '
                             f"{_fmt(rec.get(key, 0.0))}")
            lines.append(f"{pname}_sum {_fmt(rec['sum'])}")
            lines.append(f"{pname}_count {int(rec['count'])}")
    return "\n".join(lines) + "\n"


def health_report() -> dict:
    """LOCAL liveness snapshot (the /healthz body). Never collective —
    reads in-process state only."""
    out = {"healthy": True, "reasons": []}
    try:
        from multiverso_tpu.zoo import Zoo
        zoo = Zoo.Get()
        out["started"] = bool(zoo.started)
        if not zoo.started:
            out["healthy"] = False
            out["reasons"].append("zoo not started")
        eng = zoo.server_engine
        if eng is not None:
            poison = getattr(eng, "_poison", None)
            out["engine"] = {
                "poisoned": repr(poison) if poison is not None else None,
                "mailbox_depth": eng.mailbox.Size(),
                "window_epoch": getattr(eng, "window_epoch", 0),
                "window_exchanges": getattr(eng, "mh_window_exchanges",
                                            0),
            }
            if poison is not None:
                out["healthy"] = False
                out["reasons"].append(f"engine poisoned: {poison!r}")
            try:
                from multiverso_tpu import elastic
                el = elastic.state_report()
                if el is not None:
                    # current membership epoch + member count (round
                    # 10): the liveness answer changes meaning across
                    # epochs, so the scrape names the epoch it
                    # describes
                    out["elastic"] = el
            except Exception:   # elastic plane torn down mid-scrape
                pass
            stage = getattr(eng, "_ex_stage", None)
            if stage is not None:
                out["engine"]["exchange_stage"] = {
                    "depth": stage.depth(),
                    "pending_verbs": stage.pending_verbs(),
                    "mid_exchange": bool(stage.busy_since),
                    "dead": repr(stage.dead) if stage.dead is not None
                    else None,
                }
                if stage.dead is not None:
                    out["healthy"] = False
                    out["reasons"].append(
                        f"exchange stage dead: {stage.dead!r}")
            # round 12 — sharded engine: per-shard stream state, with a
            # dead SHARD (poisoned actor or dead exchange stage on any
            # stream) reported distinctly from the shard-0 probes above
            shards_fn = getattr(eng, "shard_states", None)
            if shards_fn is not None:
                try:
                    shards = shards_fn()
                except Exception:   # engine torn down mid-scrape
                    shards = []
                if len(shards) > 1:
                    out["engine"]["shards"] = shards
                    from multiverso_tpu.parallel import multihost
                    out["engine"]["transport"] = multihost.wire_name()
                    for s in shards:
                        st = s.get("stage") or {}
                        if s.get("poisoned") is not None:
                            out["healthy"] = False
                            out["reasons"].append(
                                f"engine shard {s['shard']} poisoned: "
                                f"{s['poisoned']}")
                        elif st.get("dead") is not None:
                            out["healthy"] = False
                            out["reasons"].append(
                                f"engine shard {s['shard']} exchange "
                                f"stage dead: {st['dead']}")
    except Exception as exc:    # health must never turn into a crash
        out["healthy"] = False
        out["reasons"].append(f"probe failed: {exc!r}")
    try:
        from multiverso_tpu.serving import peek_plane
        plane = peek_plane()
        if plane is not None:
            latest = plane.store.latest_version()
            age = (plane.store.get(None).age_s()
                   if latest is not None else None)
            snap = metrics.snapshot()
            out["serving"] = {
                "latest_version": latest,
                "snapshot_age_s": age,
                "shed": snap.get("serving.shed", {}).get("value", 0),
                "lookups": snap.get("serving.lookups",
                                    {}).get("value", 0),
            }
    except Exception:           # serving is optional
        pass
    # round 17 — replica plane: one line per known subscriber (rid,
    # mode, live/dead/evicted, acked version, lag). Served from the
    # fan-out thread's CACHED roster — the handler does no RPC and no
    # collective; departed replicas stay listed so operators see who
    # left and when the publisher evicted them.
    try:
        from multiverso_tpu import replica as treplica
        rrep = treplica.status_report()
        if rrep is not None:
            out["replica"] = rrep
    except Exception:           # replica plane is optional
        pass
    # round 20 — policy plane: one line naming whether the runtime is
    # self-driving (armed kill switch), how often it acted, and the
    # last action. Local engine state only.
    try:
        from multiverso_tpu import policy as tpolicy
        pline = tpolicy.status_line()
        if pline is not None:
            out["policy"] = pline
    except Exception:           # policy plane torn down mid-scrape
        pass
    # round 23 — coordinator HA: standby replication state (rank 0:
    # solo / replicated / degraded) + this process's client failover
    # posture (endpoint list, active endpoint, failover count). A
    # DEGRADED standby — the primary shed a dead standby and serves
    # solo, availability over replication — stays healthy but is a
    # NAMED warning: the operator must know redundancy is gone.
    try:
        from multiverso_tpu import elastic
        ha = elastic.ha_status()
        if ha is not None:
            out["coordinator_ha"] = ha
            if ha.get("standby") == "degraded":
                out.setdefault("warnings", []).append(
                    "coordinator standby lost — primary serving solo "
                    "(op log unreplicated)")
    except Exception:           # elastic plane torn down mid-scrape
        pass
    rec, drop = flight.stats()
    out["flight"] = {"recorded": rec, "dropped": drop,
                     "enabled": flight.enabled()}
    # round 13 — watchdog plane: active typed alerts degrade the
    # status to a DISTINCT "warn" (still 200 — 503 stays death-only;
    # an alert is a saturation symptom, not a corpse)
    try:
        from multiverso_tpu.telemetry import watchdog as twatchdog
        alerts = twatchdog.active_alerts()
        out["alerts"] = [a["rule"] for a in alerts]
        out["status"] = ("dead" if not out["healthy"]
                         else ("warn" if alerts or out.get("warnings")
                               else "ok"))
    except Exception:           # watchdog torn down mid-scrape
        out["status"] = ("dead" if not out["healthy"]
                         else ("warn" if out.get("warnings") else "ok"))
    return out


def perf_report() -> dict:
    """LOCAL performance-forensics snapshot (the /perf body): phase
    histograms, per-family apply seconds, the local binding-phase
    proxy, last fence cause and the row-skew sketches. Never
    collective — the cross-rank binding verdict needs every rank's
    flight dump through ``python -m multiverso_tpu.telemetry.critpath``
    (which this body says, so an operator scraping one rank is not
    misled)."""
    snap = metrics.snapshot()

    def _hist(rec):
        return {"count": rec.get("count", 0),
                "sum_s": rec.get("sum", 0.0),
                "p50_s": rec.get("p50", 0.0),
                "p99_s": rec.get("p99", 0.0)}

    out = {"phases": {}, "apply_tables": {}, "binding_phase": None,
           "last_fence_cause": None, "row_skew": [],
           "note": ("local rank only — cross-rank critical path: dump "
                    "flight rings on every rank and run python -m "
                    "multiverso_tpu.telemetry.critpath")}
    for name, rec in snap.items():
        if (name.startswith("engine.phase.")
                and rec.get("type") == "histogram"):
            out["phases"][name[len("engine.phase."):-2]] = _hist(rec)
        elif (name.startswith("engine.apply.table_s.")
                and rec.get("type") == "histogram"):
            out["apply_tables"][name.rsplit(".", 1)[-1]] = _hist(rec)
    try:
        from multiverso_tpu.zoo import Zoo
        eng = Zoo.Get().server_engine
        if eng is not None:
            out["binding_phase"] = (getattr(eng, "last_binding_phase",
                                            "") or None)
            out["last_fence_cause"] = (getattr(eng, "last_fence_cause",
                                               "") or None)
            for tid, table in enumerate(getattr(eng, "store_", [])):
                sk = getattr(table, "_row_sketch", None)
                if sk is not None:
                    out["row_skew"].append(dict(sk.summary(),
                                                table_id=tid))
    except Exception:           # engine torn down mid-scrape
        pass
    return out


class _OpsHandler(BaseHTTPRequestHandler):
    # one scrape per connection is the expected pattern; keep-alive off
    # so a dangling scraper can't pin handler threads across Zoo.Stop
    protocol_version = "HTTP/1.0"

    def log_message(self, fmt, *args):  # route through the leveled log
        Log.Debug("ops http: " + fmt, *args)

    def _send(self, code: int, body: str, ctype: str) -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    # mv-lint: ok(device-work-domain): the ledger probes this handler reaches walk jax.tree leaves and read .nbytes/process_count on the HOST — no device program launches; the probe-never-syncs-mirror regression test (test_watchdog) pins the matrix path
    def do_GET(self):           # noqa: N802 - stdlib handler API
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                # mirror the hot paths' plain tallies into their gauges
                # before rendering (local probes only, never
                # collective); a scrape must see current saturation AND
                # ledger numbers even when no watchdog ticks between
                # scrapes (the watchdog is OFF by default — without
                # this the mem.* family would scrape frozen at zero)
                try:
                    from multiverso_tpu.telemetry import \
                        watchdog as twatchdog
                    twatchdog.refresh_saturation_gauges()
                except Exception:
                    pass
                try:
                    from multiverso_tpu.telemetry import accounting
                    accounting.refresh()
                except Exception:
                    pass
                self._send(200, render_prometheus(metrics.snapshot()),
                           "text/plain; version=0.0.4")
            elif path == "/healthz":
                rep = health_report()
                self._send(200 if rep["healthy"] else 503,
                           json.dumps(rep, indent=1, sort_keys=True),
                           "application/json")
            elif path == "/flight":
                rec, drop = flight.stats()
                self._send(200, json.dumps(
                    {"recorded": rec, "dropped": drop,
                     "events": flight.events(512)}),
                    "application/json")
            elif path == "/perf":
                self._send(200, json.dumps(perf_report(), indent=1,
                                           sort_keys=True),
                           "application/json")
            elif path == "/alerts":
                from multiverso_tpu.telemetry import \
                    watchdog as twatchdog
                self._send(200, json.dumps(twatchdog.alerts_report(),
                                           indent=1, sort_keys=True),
                           "application/json")
            elif path == "/fleet":
                from multiverso_tpu.telemetry import fleet as tfleet
                rep = tfleet.fleet_report()
                # round 23 — coordinator HA posture rides the fleet
                # view: which endpoint of the failover list this
                # process talks to, failover count, standby state
                try:
                    from multiverso_tpu import elastic
                    ha = elastic.ha_status()
                    if ha is not None:
                        rep["coordinator_ha"] = ha
                except Exception:
                    pass
                self._send(200, json.dumps(rep, indent=1,
                                           sort_keys=True),
                           "application/json")
            elif path == "/memory":
                from multiverso_tpu.telemetry import accounting
                self._send(200, json.dumps(accounting.memory_report(),
                                           indent=1, sort_keys=True),
                           "application/json")
            elif path == "/actions":
                from multiverso_tpu import policy as tpolicy
                self._send(200, json.dumps(tpolicy.actions_report(),
                                           indent=1, sort_keys=True),
                           "application/json")
            else:
                self._send(404, "unknown path (know /metrics /healthz "
                                "/flight /perf /alerts /actions "
                                "/fleet /memory)\n",
                           "text/plain")
        except Exception as exc:    # never kill the handler thread
            try:
                self._send(500, f"ops handler failed: {exc!r}\n",
                           "text/plain")
            except Exception:
                pass


class OpsServer:
    """One HTTP daemon thread serving the ops endpoint."""

    def __init__(self, port: int):
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port),
                                          _OpsHandler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="mv-ops-http",
            daemon=True)

    def start(self) -> None:
        self._thread.start()
        Log.Info("ops endpoint serving on 127.0.0.1:%d "
                 "(/metrics /healthz /flight /perf /alerts /actions "
                 "/fleet /memory)", self.port)

    def stop(self, join_s: float = 5.0) -> None:
        """Shut down + join BOUNDED (Zoo.Stop must never hang on a
        wedged scrape; failsafe.deadline.bounded escalates typed when
        -mv_deadline_s is armed)."""
        from multiverso_tpu.failsafe import deadline as fdeadline
        from multiverso_tpu.failsafe.errors import DeadlineExceeded

        def _shutdown():
            self._httpd.shutdown()
            self._httpd.server_close()
            self._thread.join(join_s)

        try:
            fdeadline.bounded(_shutdown, "ops HTTP thread join",
                              fatal=False)
        except DeadlineExceeded as exc:
            Log.Error("ops endpoint stop timed out (%r) — abandoning "
                      "its daemon thread", exc)
        if self._thread.is_alive():
            Log.Error("ops HTTP thread still alive after bounded join "
                      "— daemon thread abandoned")


_server: Optional[OpsServer] = None
_server_lock = threading.Lock()


def start_ops() -> Optional[int]:
    """Start the ops endpoint when ``-mv_ops_port >= 0`` (Zoo.Start).
    Idempotent; returns the bound port or None when off."""
    global _server
    try:
        want = int(GetFlag("mv_ops_port"))
    except Exception:
        want = -1
    with _server_lock:
        if _server is not None:
            return _server.port
        if want < 0:
            return None
        # round 22 — the scrape surface is a plane start too: the
        # fleet.* families (and the trainer digest families) must show
        # at zero on the FIRST /metrics read even when the watchdog
        # (the other eager-registration site) stays unarmed
        try:
            from multiverso_tpu.telemetry import fleet as tfleet
            tfleet.eager_register()
        except Exception:
            pass
        try:
            _server = OpsServer(want)
        except OSError as exc:
            Log.Error("ops endpoint failed to bind port %d: %r — "
                      "continuing without it", want, exc)
            return None
        _server.start()
        return _server.port


def stop_ops() -> None:
    """Stop + join the ops endpoint (Zoo.Stop). Idempotent."""
    global _server
    with _server_lock:
        srv, _server = _server, None
    if srv is not None:
        srv.stop()


def port() -> Optional[int]:
    """The live endpoint's bound port (ephemeral ports included), or
    None when off — tests and the dashboard [Ops] line read this."""
    srv = _server
    return srv.port if srv is not None else None


def dump_diagnostics(dir_path: Optional[str] = None) -> Optional[str]:
    """Write the complete postmortem artifact set under ``dir_path``
    (default ``-mv_diag_dir``): the flight ring
    (``flight_rank<R>.jsonl``), the local telemetry snapshot sidecar
    (``telemetry_rank<R>.json``) and the span trace dump
    (``trace_rank<R>.json``) — one directory, one flag, everything a
    postmortem needs. Returns the directory or None when no directory
    is configured. Best-effort per artifact; LOCAL only."""
    import os

    d = dir_path or flight.diag_dir()
    if not d:
        return None
    os.makedirs(d, exist_ok=True)
    r = flight._rank()
    try:
        flight.dump(os.path.join(d, f"flight_rank{r}.jsonl"))
    except Exception as exc:
        Log.Error("diag dump: flight ring failed: %r", exc)
    try:
        from multiverso_tpu.telemetry.export import write_snapshot_sidecar
        write_snapshot_sidecar(os.path.join(d, f"telemetry_rank{r}.json"))
    except Exception as exc:
        Log.Error("diag dump: telemetry sidecar failed: %r", exc)
    try:
        from multiverso_tpu.telemetry import trace
        trace.dump(os.path.join(d, f"trace_rank{r}.json"))
    except Exception as exc:
        Log.Error("diag dump: span trace failed: %r", exc)
    return d
