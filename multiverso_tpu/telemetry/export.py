"""Telemetry export: the periodic stats reporter + snapshot sidecars.

``-stats_interval_s=N`` starts a daemon thread at MV_Init that logs a
compact JSON line of the LOCAL metrics snapshot every N seconds through
the leveled logger (so stats respect the configured log level and
sink). The reporter never issues collectives — a timer thread running
allgathers would interleave with the engine's window exchanges and
corrupt the SPMD stream; job-wide totals come from the explicitly
collective ``MV_MetricsSnapshot()`` instead.

``write_snapshot_sidecar`` serializes a snapshot next to a bench/run
artifact (bench.py writes docs/TELEMETRY_latest.json beside
BENCH_FULL_latest.json every run).
"""

from __future__ import annotations

import json
import threading
from typing import Optional

from multiverso_tpu.telemetry import metrics
from multiverso_tpu.utils.configure import GetFlag, MV_DEFINE_double
from multiverso_tpu.utils.log import Log

MV_DEFINE_double("stats_interval_s", 0.0,
                 "log a local telemetry snapshot every N seconds "
                 "(0 = off)")


def _compact(snap: dict) -> dict:
    """Snapshot with histogram bucket maps dropped — the periodic line
    is for humans tailing a log, not for re-aggregation."""
    out = {}
    for name, rec in snap.items():
        if rec.get("type") in ("histogram", "digest"):
            rec = {k: (round(v, 6) if isinstance(v, float) else v)
                   for k, v in rec.items() if k != "buckets"}
        out[name] = rec
    return out


class StatsReporter:
    """Daemon timer thread emitting ``[telemetry] {...}`` log lines."""

    def __init__(self, interval_s: float):
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="mv-stats-reporter",
                                        daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # join through failsafe.deadline.bounded (lazy import: this
        # module loads before the failsafe package on the zoo import
        # chain): with -mv_deadline_s armed a wedged reporter raises a
        # typed DeadlineExceeded we log instead of stalling Zoo.Stop;
        # the inner join timeout bounds the flag-unset path
        from multiverso_tpu.failsafe import deadline as fdeadline
        from multiverso_tpu.failsafe.errors import DeadlineExceeded
        try:
            fdeadline.bounded(lambda: self._thread.join(timeout=5),
                              "stats reporter join", fatal=False)
        except DeadlineExceeded as exc:
            Log.Error("stats reporter stop timed out (%r) — abandoning "
                      "its daemon thread", exc)
        if self._thread.is_alive():
            Log.Error("stats reporter thread still alive after bounded "
                      "join — daemon thread abandoned")

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.emit()
        self.emit()     # final flush so short runs still report once

    def emit(self) -> None:
        snap = metrics.snapshot()
        if not snap:
            return
        Log.Info("[telemetry] %s",
                 json.dumps(_compact(snap), sort_keys=True))


_reporter: Optional[StatsReporter] = None
_reporter_lock = threading.Lock()


def start_reporter() -> bool:
    """Start the periodic reporter when -stats_interval_s > 0 (called
    by Zoo.Start after flag parsing). Idempotent; False when off."""
    global _reporter
    try:
        interval = float(GetFlag("stats_interval_s"))
    except Exception:
        interval = 0.0
    with _reporter_lock:
        if interval <= 0 or _reporter is not None:
            return _reporter is not None
        _reporter = StatsReporter(interval)
        _reporter.start()
        return True


def stop_reporter() -> None:
    """Stop + flush the reporter (Zoo.Stop)."""
    global _reporter
    with _reporter_lock:
        rep, _reporter = _reporter, None
    if rep is not None:
        rep.stop()


def write_snapshot_sidecar(path: str) -> str:
    """Write the LOCAL metrics snapshot as pretty JSON to ``path``."""
    with open(path, "w") as f:
        json.dump(metrics.snapshot(), f, indent=1, sort_keys=True)
    return path
