"""Telemetry — typed metrics, span tracing, and export for the runtime.

The reference's only host-side instrument was the Dashboard monitor
(count + mean per named region, utils/dashboard.py); production traffic
needs latency *distributions*, byte accounting, and a way to follow one
verb across the actor mailboxes. This package provides the three layers
(docs/DESIGN.md §6):

* ``metrics`` — a thread-safe registry of typed instruments (Counter,
  Gauge, log-bucketed Histogram with p50/p90/p99) that merges across
  hosts over the same union-of-names allreduce the Dashboard uses,
  extended to fixed bucket vectors so every rank agrees on collective
  shape.
* ``trace`` — Dapper-style span trees carried on ``Message`` across the
  worker -> mailbox -> server-window hops, exported as Chrome
  trace-event JSON (Perfetto-loadable), with
  ``jax.profiler.TraceAnnotation`` bridging so host spans line up with
  the xplane device traces ``MV_StartProfiler`` produces.
* ``export`` — the ``-stats_interval_s`` periodic reporter plus the
  snapshot/dump helpers behind ``MV_MetricsSnapshot`` /
  ``MV_DumpTrace``.

Importing this package registers every telemetry flag (``-telemetry``,
``-trace``, ``-stats_interval_s``) so ``MV_Init`` argv parsing claims
them.
"""

from multiverso_tpu.telemetry import export, metrics, trace  # noqa: F401
