"""Telemetry — typed metrics, span tracing, and export for the runtime.

The reference's only host-side instrument was the Dashboard monitor
(count + mean per named region, utils/dashboard.py); production traffic
needs latency *distributions*, byte accounting, and a way to follow one
verb across the actor mailboxes. This package provides the three layers
(docs/DESIGN.md §6):

* ``metrics`` — a thread-safe registry of typed instruments (Counter,
  Gauge, log-bucketed Histogram with p50/p90/p99) that merges across
  hosts over the same union-of-names allreduce the Dashboard uses,
  extended to fixed bucket vectors so every rank agrees on collective
  shape.
* ``trace`` — Dapper-style span trees carried on ``Message`` across the
  worker -> mailbox -> server-window hops, exported as Chrome
  trace-event JSON (Perfetto-loadable), with
  ``jax.profiler.TraceAnnotation`` bridging so host spans line up with
  the xplane device traces ``MV_StartProfiler`` produces.
* ``export`` — the ``-stats_interval_s`` periodic reporter plus the
  snapshot/dump helpers behind ``MV_MetricsSnapshot`` /
  ``MV_DumpTrace``.

The ops plane (round 9) adds three more:

* ``flight`` — the always-on flight recorder: a bounded,
  allocation-cheap ring of structured events (windows with exchange
  SEQ, fence causes, barriers, CRC retries, dedup hits, snapshot
  publish/evict, serving dispatch/shed, actor poison), dumped as JSONL
  by ``MV_DumpFlightRecorder`` and automatically on failure paths
  under ``-mv_diag_dir``.
* ``forensics`` — aligns several ranks' flight dumps by exchange SEQ
  and pinpoints the first diverging stream position (``python -m
  multiverso_tpu.telemetry.forensics``). An offline tool with no
  flags, so it is NOT eagerly imported — import it when correlating.
* ``ops`` — the ``-mv_ops_port`` HTTP endpoint: ``/metrics``
  (Prometheus text), ``/healthz`` (poison-aware liveness),
  ``/flight`` (recent events). Local snapshots only — the handler
  never issues collectives.

The watchdog plane (round 13) adds two more:

* ``accounting`` — the process memory/capacity ledger: pull-probed
  ``mem.*`` byte gauges (per-table device/mirror/host placement,
  snapshot retention, flight/dedup/buffer footprints, shm rings) and
  the ``/memory`` ops endpoint.
* ``watchdog`` — ``-mv_watchdog_s`` typed online alert rules with
  fire/clear hysteresis over LOCAL instruments only (shard imbalance,
  shm backpressure, apply-pool saturation, mailbox/memory growth,
  snapshot staleness, the straggler proxy), surfaced at ``/alerts``,
  in ``alert.<rule>`` counters + flight events, and as the /healthz
  ``warn`` status.

The fleet plane (round 22) adds one more:

* ``fleet`` — mergeable-digest rollups piggybacked on the lease
  heartbeats that already flow (``replica_hb`` for readers, the
  elastic member heartbeat for trainer ranks), folded coordinator-side
  into the ``/fleet`` ops document (per-member QPS/p50/p99, staleness,
  "slowest member by p99"), three fleet watchdog rules, and the
  ``python -m multiverso_tpu.telemetry.fleet --trace`` multi-dump
  trace merge CLI. Zero new connections, zero collectives.

Importing this package registers every telemetry flag (``-telemetry``,
``-trace``, ``-stats_interval_s``, ``-mv_flight_events``,
``-mv_diag_dir``, ``-mv_ops_port``, ``-mv_watchdog_s``,
``-mv_fleet_stale_s``, ``-mv_fleet_p99_s``) so ``MV_Init`` argv
parsing claims them.
"""

from multiverso_tpu.telemetry import (export, flight,  # noqa: F401
                                      metrics, ops, trace)
from multiverso_tpu.telemetry import accounting, watchdog  # noqa: F401,E402
from multiverso_tpu.telemetry import fleet  # noqa: F401,E402
