"""Live watchdog plane: typed online alert rules over local instruments.

The reference ships a Dashboard people watch BY HAND; the PR 6/PR 8
planes are post-hoc (forensics and critpath explain a stall after rings
hit disk, /healthz flips only on actor death). Meanwhile the PR 9
components fail by *saturation*, not death: a shard stream falling
behind its siblings, the shm ring backpressuring, the native apply pool
degrading to inline slices, a mailbox growing without bound. This
module is the Borgmon-style answer — a ``-mv_watchdog_s`` daemon tick
(off by default, like ``-stats_interval_s``) evaluating TYPED rules
with fire/clear hysteresis over **local instruments only**:

* never collective — the tick thread reads in-process state (the
  metrics registry, engine probes, the accounting ledger, the shm
  wire's counters); a timer thread issuing allgathers would interleave
  with window exchanges and corrupt the SPMD stream (the PR 2 reporter
  rule). Cross-rank verdicts stay ``critpath``'s job; the watchdog
  names the LOCAL symptom on the rank that has it.
* hysteresis, not edge triggers — a rule FIRES only after
  ``fire_after`` consecutive breaching ticks and CLEARS only after
  ``clear_after`` consecutive healthy ones; ticks with insufficient
  evidence (idle engine, no new windows) HOLD the current state — an
  idle world is not evidence of health, and alerts must not flap.
* typed surfaces — a firing rule increments ``alert.<rule>``, records
  an ``alert.<rule>`` flight event (so postmortem rings carry the
  online verdicts), appears at the ``/alerts`` ops endpoint, and
  degrades ``/healthz`` to a distinct ``warn`` status (still 200 —
  503 stays death-only).

Rule set (DESIGN.md §15 carries the full table):

==================  ====================================================
rule                local symptom
==================  ====================================================
shard_imbalance     max/mean per-shard apply-seconds across live engine
                    streams exceeds a ratio (one stream lags siblings)
shm_backpressure    shm writer-stall seconds growing as a fraction of
                    the tick (readers lag this rank's ring)
apply_pool_sat      native host-store pool busy: most dispatches fell
                    back to inline slices (shards convoying)
mailbox_backlog     engine mailbox depth rising monotonically
snapshot_stale      newest serving snapshot older than the observed
                    publish cadence says it should be
memory_growth       accounting-ledger total rising monotonically
straggler           sustained local proxy: per-window apply seconds
                    over the floor and this rank barely waits in the
                    collective — ITS apply gates the stream (the
                    critpath drill's culprit); a live stamped binding
                    phase other than ``apply`` vetoes
replica_lag         a live replica subscriber sits >= N published
                    versions behind the newest snapshot (fan-out
                    stalled, ring backpressured, or the replica's
                    apply can't keep up) — or its fleet rollup went
                    stale, in which case the lag numbers are frozen
                    and the rule degrades to warn instead of trusting
                    them
fleet_p99_breach    the COORDINATOR-side fleet-merged request p99
                    (telemetry/fleet.py rollups) exceeds
                    ``-mv_fleet_p99_s`` (0 disables)
member_qps_outlier  one previously-serving fleet member's QPS fell far
                    below its peers' mean (a chaos-delayed or wedged
                    member drags the fleet tail)
rollup_stale        a member's lease heartbeats still arrive but its
                    fleet rollup stopped refreshing
                    (``-mv_fleet_stale_s``) — frozen telemetry, named
==================  ====================================================

The three ``fleet_*`` rules read the coordinator-side accumulator's
sample (fleet.peek_sample(), merged into every tick's evidence) and
HOLD everywhere else — they are the never-collective law applied to
fleet state: aggregation happened when members PUSHED rollups on their
lease heartbeats; the rules only read the fold.

Every ``alert.*`` counter is registered EAGERLY at
:func:`start_watchdog` (the PR 6 rule) so the whole rule family scrapes
at zero from the first ``/metrics`` read.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Deque, Dict, List, Optional

from multiverso_tpu.telemetry import accounting
from multiverso_tpu.telemetry import fleet as tfleet
from multiverso_tpu.telemetry import flight as tflight
from multiverso_tpu.telemetry import metrics as tmetrics
from multiverso_tpu.utils.configure import GetFlag, MV_DEFINE_double
from multiverso_tpu.utils.log import Log

MV_DEFINE_double("mv_watchdog_s", 0.0,
                 "watchdog tick interval: evaluate the typed online "
                 "alert rules (shard imbalance, shm backpressure, "
                 "apply-pool saturation, mailbox/memory growth, "
                 "snapshot staleness, straggler proxy, replica lag, "
                 "fleet p99 breach / QPS outlier / rollup staleness) "
                 "every N seconds "
                 "over LOCAL instruments only, with fire/clear "
                 "hysteresis; alerts surface at /alerts, in "
                 "alert.<rule> counters + flight events, and degrade "
                 "/healthz to 'warn' (0 = off)")

#: sentinel a rule returns when the tick carries insufficient evidence
#: (idle engine, counters unavailable): HOLD the current alert state —
#: neither a breach nor proof of health. The hysteresis counters do
#: not move, which is what keeps a finished burst's verdict readable
#: at /alerts instead of flapping clear the moment traffic stops.
HOLD = object()

#: bounded sample history every rule reads (slope rules look back a
#: few ticks; nothing needs more than this)
_HISTORY = 32


def stream_pos() -> tuple:
    """Best-effort ``(mepoch, head-stream exchange SEQ)`` stamp for
    alert/action flight events (round 20): forensics aligns a policy
    action with its triggering alert by exactly this pair, the same
    (mepoch, seq) keying the membership events ride. ``(0, -1)`` when
    no engine/world is live (synthetic-sample unit tests)."""
    mep, seq = 0, -1
    try:
        from multiverso_tpu.parallel import multihost
        mep = int(multihost.membership_epoch())
    except Exception:
        pass
    try:
        from multiverso_tpu.zoo import Zoo
        eng = Zoo.Get().server_engine
        if eng is not None:
            seq = int(eng._mh_seq)
    except Exception:
        pass
    return mep, seq


class Rule:
    """One typed online alert rule. Subclasses implement
    :meth:`check` over the watchdog's sample history (newest last) and
    return ``None`` (healthy), a breach-detail string, or :data:`HOLD`
    (insufficient evidence — keep the current state)."""

    name = "rule"
    fire_after = 2
    clear_after = 3

    def check(self, history: List[dict]) -> object:
        raise NotImplementedError

    @staticmethod
    def _delta(history: List[dict], key: str, default=0.0) -> float:
        if len(history) < 2:
            return 0.0
        return (history[-1].get(key, default)
                - history[-2].get(key, default))


class ShardImbalanceRule(Rule):
    """max/mean of per-shard apply-second DELTAS across live engine
    streams: one stream doing several times its siblings' work means
    the table->shard routing (or one table's updater) is the hot spot
    — the host_scaling wall coming back by the side door."""

    name = "shard_imbalance"

    def __init__(self, ratio: float = 1.5, min_busy_s: float = 0.05):
        self.ratio = ratio
        self.min_busy_s = min_busy_s

    def check(self, history):
        if len(history) < 2:
            return HOLD
        prev = {s["shard"]: s.get("apply_busy_s", 0.0)
                for s in history[-2].get("shards", [])}
        cur = history[-1].get("shards", [])
        if len(cur) < 2:
            return None      # one stream: nothing to imbalance
        deltas = [max(0.0, s.get("apply_busy_s", 0.0)
                      - prev.get(s["shard"], 0.0)) for s in cur]
        peak = max(deltas)
        if peak < self.min_busy_s:
            return HOLD      # idle tick: no evidence either way
        mean = sum(deltas) / len(deltas)
        if mean > 0 and peak / mean >= self.ratio:
            hot = cur[deltas.index(peak)]["shard"]
            return (f"shard {hot} applied {peak:.3f}s this tick vs "
                    f"{mean:.3f}s mean over {len(deltas)} streams "
                    f"(ratio {peak / mean:.2f} >= {self.ratio})")
        return None


class ShmBackpressureRule(Rule):
    """shm WRITER-stall seconds growing as a fraction of the tick:
    this rank publishes faster than its readers ack — the ring (or a
    slow reader) is the bottleneck. Reader-side waits deliberately
    don't count (they are the peer's fault, named by critpath)."""

    name = "shm_backpressure"

    def __init__(self, stall_frac: float = 0.25):
        self.stall_frac = stall_frac

    def check(self, history):
        if len(history) < 2:
            return HOLD
        d_rounds = self._delta(history, "shm_rounds")
        if d_rounds <= 0:
            return HOLD      # no exchanges: no evidence
        d_stall = self._delta(history, "shm_writer_stall_s")
        dt = max(1e-9, self._delta(history, "t"))
        if d_stall / dt >= self.stall_frac:
            return (f"shm writer stalled {d_stall:.3f}s of a "
                    f"{dt:.3f}s tick ({100 * d_stall / dt:.0f}% >= "
                    f"{100 * self.stall_frac:.0f}%) over "
                    f"{int(d_rounds)} rounds")
        return None


class ApplyPoolSaturationRule(Rule):
    """Native host-store pool saturation: the majority of parallel-
    eligible applies this tick found the pool owned by another shard
    and ran inline — N shards convoying where the config expected pool
    parallelism (PR 9 made the fallback safe; this makes it VISIBLE)."""

    name = "apply_pool_sat"

    def __init__(self, busy_frac: float = 0.5, min_dispatches: int = 8):
        self.busy_frac = busy_frac
        self.min_dispatches = min_dispatches

    def check(self, history):
        if len(history) < 2:
            return HOLD
        d_busy = self._delta(history, "pool_inline_busy")
        d_par = self._delta(history, "pool_parallel")
        eligible = d_busy + d_par
        if eligible < self.min_dispatches:
            return HOLD
        if d_busy / eligible >= self.busy_frac:
            return (f"native pool busy for {int(d_busy)}/"
                    f"{int(eligible)} parallel-eligible applies this "
                    f"tick ({100 * d_busy / eligible:.0f}% >= "
                    f"{100 * self.busy_frac:.0f}%)")
        return None


class MailboxBacklogRule(Rule):
    """Engine mailbox depth rising across consecutive ticks past a
    floor: admission outruns the apply stream — the typed early
    warning ahead of a deadline expiry."""

    name = "mailbox_backlog"

    def __init__(self, window: int = 3, min_depth: int = 64):
        self.window = window
        self.min_depth = min_depth

    def check(self, history):
        if len(history) < self.window:
            return HOLD
        depths = [h.get("mailbox_depth", 0)
                  for h in history[-self.window:]]
        if depths[-1] < self.min_depth:
            return None
        if all(b > a for a, b in zip(depths, depths[1:])):
            return (f"mailbox depth rising {depths} over "
                    f"{self.window} ticks (>= {self.min_depth})")
        return None


class SnapshotStaleRule(Rule):
    """Newest serving snapshot older than the publish cadence says it
    should be: the cadence is ESTIMATED from the ticks where the
    publish counter moved (local observation, no clock agreement), and
    the alert needs >= 2 publishes — a world that never publishes has
    no cadence to violate."""

    name = "snapshot_stale"

    def __init__(self, ratio: float = 3.0, min_age_s: float = 1.0):
        self.ratio = ratio
        self.min_age_s = min_age_s

    def check(self, history):
        cur = history[-1]
        age = cur.get("snapshot_age_s")
        if age is None or cur.get("publishes", 0) < 2:
            return HOLD
        # publish instants observed by THIS watchdog: ticks where the
        # counter moved
        times = []
        for prev, nxt in zip(history, history[1:]):
            if nxt.get("publishes", 0) > prev.get("publishes", 0):
                times.append(nxt.get("t", 0.0))
        if len(times) < 2:
            return HOLD      # cadence not yet observable
        gaps = sorted(b - a for a, b in zip(times, times[1:]))
        cadence = gaps[len(gaps) // 2]
        bound = max(self.ratio * cadence, self.min_age_s)
        if age > bound:
            return (f"newest snapshot is {age:.2f}s old vs an observed "
                    f"publish cadence of {cadence:.2f}s (bound "
                    f"{bound:.2f}s)")
        return None


class MemoryGrowthRule(Rule):
    """Accounting-ledger total rising monotonically across the window
    AND by more than ``grow_frac`` overall: the typed early warning
    for unbounded retention (snapshots pinned forever, a cache that
    never evicts) before the OOM killer writes the postmortem. The
    sampled ``mem_total`` EXCLUDES the capacity-bounded flight/dedup
    estimates (collect_sample) — a fresh world's ring filling to its
    cap is expected, not a leak."""

    name = "memory_growth"

    def __init__(self, window: int = 4, grow_frac: float = 0.10,
                 floor_bytes: int = 1 << 20):
        self.window = window
        self.grow_frac = grow_frac
        self.floor_bytes = floor_bytes

    def check(self, history):
        if len(history) < self.window:
            return HOLD
        totals = [h.get("mem_total", 0) for h in history[-self.window:]]
        if totals[0] < self.floor_bytes:
            return HOLD
        if (all(b > a for a, b in zip(totals, totals[1:]))
                and (totals[-1] - totals[0]) / totals[0]
                >= self.grow_frac):
            return (f"ledger total grew {totals[0]} -> {totals[-1]} "
                    f"bytes (+{100 * (totals[-1] - totals[0]) / totals[0]:.0f}%) "
                    f"over {self.window} ticks")
        return None


class ReplicaLagRule(Rule):
    """A LIVE replica subscriber sitting ``max_lag`` or more published
    versions behind the newest snapshot: the fan-out is stalled (slow
    ring drain, relay mailbox churn) or the replica's apply can't keep
    the publish cadence — either way its reads serve stale versions
    and its next resync will be a full base. Reads the publisher's
    plain local attrs (refreshed by the fan-out tick — local-only, the
    never-collective rule); a world with no subscribers, or with the
    plane off, HOLDs."""

    name = "replica_lag"

    def __init__(self, max_lag: int = 3):
        self.max_lag = max_lag

    def check(self, history):
        cur = history[-1]
        subs = cur.get("replica_subscribers")
        if not subs:
            return HOLD      # plane off / nobody subscribed
        # round 22 — the rollup staleness stamp outranks the lag
        # numbers: a subscriber whose lease heartbeats still arrive but
        # whose fleet rollup stopped refreshing is reporting FROZEN
        # telemetry, so the rule degrades to warn naming that instead
        # of trusting (or HOLDing on) numbers that cannot move
        age = cur.get("replica_rollup_age_max_s")
        if age is not None and age > tfleet.stale_s():
            return (f"a replica's telemetry rollup is {age:.1f}s stale "
                    f"(> {tfleet.stale_s():.1f}s) — its lag numbers "
                    f"are frozen, not trustworthy")
        lag = cur.get("replica_lag_versions", 0)
        if lag >= self.max_lag:
            return (f"a live replica is {int(lag)} published versions "
                    f"behind (>= {self.max_lag}) across "
                    f"{int(subs)} subscriber(s)")
        return None


class StragglerRule(Rule):
    """Sustained LOCAL straggler proxy (multi-process windows only):
    the binding phase reads ``apply``, per-window apply seconds sit
    over the floor, and this rank spends several times less time
    blocked in the collective than applying — i.e. peers wait for IT,
    it waits for nobody. The cross-rank verdict (which rank bound each
    window) stays critpath's; this is the live tripwire on the culprit
    rank. A uniformly apply-bound world fires on every rank — honest:
    the stream IS apply-gated everywhere (DESIGN.md §15). The
    per-window floor is deliberately generous (20ms — an apply that
    slow gates any realistic window cadence) so ordinary busy applies
    under scheduler load never read as stragglers.

    Inputs are the engine's PLAIN attrs (apply_busy_s / xw_busy_s),
    which accumulate unconditionally — the rule keeps watching with
    ``-mv_phase_stamps=0`` or the flight recorder off. The stamped
    binding-phase gauge, when live, acts as a VETO (a window bound by
    decode/form/pack is not an apply straggler however slow its
    applies); when stamps are off it is simply absent and the
    apply-vs-collective-wait ratio carries the verdict alone."""

    name = "straggler"

    def __init__(self, min_windows: int = 3,
                 min_apply_per_window_s: float = 0.02,
                 xw_ratio: float = 3.0):
        self.min_windows = min_windows
        self.min_apply_per_window_s = min_apply_per_window_s
        self.xw_ratio = xw_ratio

    def check(self, history):
        if len(history) < 2:
            return HOLD
        d_ex = self._delta(history, "exchanges")
        if d_ex < self.min_windows:
            return HOLD      # single-process / idle: no stream to gate
        d_apply = self._delta(history, "apply_s")
        d_xw = self._delta(history, "exchange_wait_s")
        per_window = d_apply / d_ex
        binding = history[-1].get("binding_phase")
        if binding and binding != "apply":
            return None         # stamped verdict: something else gates
        if (per_window >= self.min_apply_per_window_s
                and d_apply >= self.xw_ratio * d_xw):
            return (f"local apply gates the stream: "
                    f"{1e3 * per_window:.1f}ms apply/window over "
                    f"{int(d_ex)} windows, {d_apply:.3f}s applying vs "
                    f"{d_xw:.3f}s waiting in the collective "
                    f"(binding_phase={binding or 'unstamped'})")
        return None


class FleetP99BreachRule(Rule):
    """COORDINATOR-side: the fleet-merged request p99 (folded from the
    rollups members pushed on their lease heartbeats) exceeds the
    ``-mv_fleet_p99_s`` budget. HOLDs on every rank that accumulated
    no rollups and while the flag is 0 (no budget, no verdict)."""

    name = "fleet_p99_breach"

    def __init__(self, threshold_s: Optional[float] = None):
        self.threshold_s = threshold_s      # None: read the flag live

    def check(self, history):
        cur = history[-1]
        p99 = cur.get("fleet_p99_s")
        if p99 is None:
            return HOLD      # no accumulator here / no rollups yet
        thr = self.threshold_s
        if thr is None:
            try:
                thr = float(GetFlag("mv_fleet_p99_s"))
            except Exception:
                thr = 0.0
        if thr <= 0:
            return HOLD      # unbudgeted: the rule is disarmed
        if p99 >= thr:
            return (f"fleet-merged request p99 {1e3 * p99:.2f}ms >= "
                    f"{1e3 * thr:.2f}ms budget across "
                    f"{int(cur.get('fleet_members', 0))} member(s)")
        return None


class MemberQpsOutlierRule(Rule):
    """COORDINATOR-side: one PREVIOUSLY-SERVING member's QPS fell far
    below its peers' mean — the live tripwire for a chaos-delayed or
    wedged member dragging the fleet tail. Members that never served a
    request (ops == 0 — e.g. a trainer rank in a replica-serving
    fleet) are not candidates: a role that serves nothing is not an
    outlier among roles that do. HOLDs while fewer than two members
    serve or the fleet is near-idle (an idle fleet's QPS spread is
    noise, not evidence)."""

    name = "member_qps_outlier"

    def __init__(self, frac: float = 0.25, min_peer_qps: float = 5.0):
        self.frac = frac
        self.min_peer_qps = min_peer_qps

    def check(self, history):
        cur = history[-1]
        qps = cur.get("fleet_member_qps")
        ops = cur.get("fleet_member_ops", {})
        if not qps:
            return HOLD
        serving = {m: q for m, q in qps.items() if ops.get(m, 0) > 0}
        if len(serving) < 2:
            return HOLD
        total = sum(serving.values())
        worst = min(serving, key=serving.get)
        peers_mean = (total - serving[worst]) / (len(serving) - 1)
        if peers_mean < self.min_peer_qps:
            return HOLD      # near-idle fleet: spread is noise
        if serving[worst] < self.frac * peers_mean:
            return (f"member {worst} serves {serving[worst]:.1f} qps "
                    f"vs a {peers_mean:.1f} qps peer mean over "
                    f"{len(serving) - 1} peer(s) "
                    f"(< {100 * self.frac:.0f}%)")
        return None


class RollupStaleRule(Rule):
    """COORDINATOR-side: a member's lease heartbeats still arrive (it
    is in the fold) but its fleet rollup stopped refreshing past
    ``-mv_fleet_stale_s`` — every number it contributes to /fleet is
    frozen. Named per member so the operator knows WHOSE telemetry to
    distrust."""

    name = "rollup_stale"

    def __init__(self, stale_s: Optional[float] = None):
        self.stale_s = stale_s              # None: read the flag live

    def check(self, history):
        cur = history[-1]
        ages = cur.get("fleet_rollup_ages_s")
        if not ages:
            return HOLD
        limit = (self.stale_s if self.stale_s is not None
                 else tfleet.stale_s())
        worst = max(ages, key=ages.get)
        if ages[worst] > limit:
            return (f"member {worst} rollup is {ages[worst]:.1f}s "
                    f"stale (> {limit:.1f}s) — its fleet numbers are "
                    f"frozen")
        return None


class CoordinatorFailoverRule(Rule):
    """This process's coordinator clients failed over to a different
    endpoint of the ordered ``-mv_coordinator`` list since the last
    tick — the primary died (or vanished long enough for the dialer to
    land on a successor). Fires on the FIRST tick that sees the
    counter move (fire_after=1: one failover is already the event, not
    noise needing corroboration), clears once the counter stops moving
    — so one takeover alerts exactly once."""

    name = "coordinator_failover"
    fire_after = 1
    clear_after = 1

    def check(self, history):
        if len(history) < 2:
            return HOLD
        d = self._delta(history, "coordinator_failovers")
        if d > 0:
            return (f"{int(d)} coordinator client failover(s) this "
                    f"tick — active endpoint index "
                    f"{int(history[-1].get('coordinator_endpoint', 0))}")
        return None


def default_rules() -> List[Rule]:
    return [ShardImbalanceRule(), ShmBackpressureRule(),
            ApplyPoolSaturationRule(), MailboxBacklogRule(),
            SnapshotStaleRule(), MemoryGrowthRule(), StragglerRule(),
            ReplicaLagRule(), FleetP99BreachRule(),
            MemberQpsOutlierRule(), RollupStaleRule(),
            CoordinatorFailoverRule()]


def refresh_saturation_gauges() -> None:
    """Mirror the hot paths' plain-attribute tallies into typed gauges:
    per-shard stream load (``engine.shard<k>.*``), apply-pool and
    native-pool dispatch splits. Called by the watchdog tick and by
    the ops handler ahead of a /metrics render — NEVER from a verb
    path (the gauges' locks must not bill the blocking round)."""
    try:
        from multiverso_tpu.zoo import Zoo
        eng = Zoo.Get().server_engine
        if eng is not None:
            for s in eng.shard_states():
                k = s["shard"]
                tmetrics.gauge(f"engine.shard{k}.windows").set(
                    float(s.get("window_epoch", 0)))
                tmetrics.gauge(f"engine.shard{k}.apply_s").set(
                    float(s.get("apply_busy_s", 0.0)))
                tmetrics.gauge(f"engine.shard{k}.mailbox_depth").set(
                    float(s.get("mailbox_depth", 0)))
    except Exception:           # engine torn down mid-refresh
        pass
    try:
        from multiverso_tpu import native
        ps = native.pool_stats()
        if ps is not None:
            tmetrics.gauge("native.pool.parallel_runs").set(
                float(ps["parallel_runs"]))
            tmetrics.gauge("native.pool.inline_busy").set(
                float(ps["inline_busy"]))
            tmetrics.gauge("native.pool.inline_small").set(
                float(ps["inline_small"]))
            tmetrics.gauge("native.pool.threads").set(
                float(ps["pool_threads"]))
    except Exception:
        pass


def collect_sample() -> dict:
    """One watchdog tick's LOCAL evidence record. Pure probes: the
    metrics snapshot, engine plain attributes, the shm wire's tallies,
    the serving store's age, the ledger total. Every section is
    best-effort (teardown races read as absence, which rules HOLD
    on)."""
    sample: dict = {"t": time.perf_counter()}
    snap = tmetrics.snapshot()

    def _counter(name):
        rec = snap.get(name)
        return rec.get("value", 0.0) if rec else 0.0

    sample["exchanges"] = _counter("server.window.exchanges")
    sample["publishes"] = _counter("serving.publishes")
    sample["shm_writer_stall_s"] = _counter("shm_wire.writer_stall_s")
    sample["shm_rounds"] = _counter("shm_wire.exchanges")
    # coordinator HA: the shared dialer's failover counter + active
    # endpoint index (plain metric reads — the CoordinatorFailoverRule
    # watches the counter's delta)
    sample["coordinator_failovers"] = _counter("elastic.client_failovers")
    ep = snap.get("elastic.active_endpoint")
    if ep:
        sample["coordinator_endpoint"] = ep.get("value", 0.0)
    try:
        from multiverso_tpu.zoo import Zoo
        eng = Zoo.Get().server_engine
        if eng is not None:
            shards = eng.shard_states()
            sample["shards"] = shards
            sample["mailbox_depth"] = sum(
                s.get("mailbox_depth", 0) for s in shards)
            # plain engine attrs, NOT the engine.phase.* histograms:
            # those are gated on -mv_phase_stamps AND the flight
            # recorder, and the straggler rule must keep watching when
            # either is off (the attrs accumulate unconditionally)
            sample["apply_s"] = sum(
                s.get("apply_busy_s", 0.0) for s in shards)
            sample["exchange_wait_s"] = sum(
                s.get("xw_busy_s", 0.0) for s in shards)
            sample["binding_phase"] = (
                getattr(eng, "last_binding_phase", "") or None)
    except Exception:
        pass
    try:
        from multiverso_tpu import native
        ps = native.pool_stats()
        if ps is not None:
            sample["pool_inline_busy"] = ps["inline_busy"]
            sample["pool_parallel"] = ps["parallel_runs"]
    except Exception:
        pass
    try:
        from multiverso_tpu.serving import peek_plane
        plane = peek_plane()
        if plane is not None and plane.store.latest_version() is not None:
            sample["snapshot_age_s"] = plane.store.get(None).age_s()
    except Exception:
        pass
    try:
        from multiverso_tpu import replica as treplica
        rsample = treplica.peek_sample()
        if rsample is not None:
            sample.update(rsample)
    except Exception:
        pass
    # round 22 — the fleet accumulator's fold: non-empty only on the
    # coordinator-hosting process (everywhere else the fleet rules
    # HOLD). Reading the fold is local by construction — the pushes
    # happened on member heartbeats, not here.
    try:
        sample.update(tfleet.peek_sample())
    except Exception:
        pass
    try:
        rep = accounting.refresh()
        # the growth rule watches components that CAN grow without
        # bound (tables, snapshots, buffers) — the flight ring and
        # dedup window are capacity-bounded by flags, and their
        # expected fill-to-cap would read as 4 ticks of monotonic
        # growth on every fresh world
        comps = rep.get("components", {})
        bounded = (comps.get("flight", {}).get("bytes_estimate", 0)
                   + comps.get("dedup", {}).get("bytes_estimate", 0))
        sample["mem_total"] = rep["total_bytes"] - bounded
    except Exception:
        pass
    return sample


class Watchdog:
    """Rule evaluator + (optionally) the daemon tick thread driving
    it. Tests drive :meth:`evaluate` directly with synthetic samples;
    the live tick feeds it :func:`collect_sample`."""

    def __init__(self, interval_s: float,
                 rules: Optional[List[Rule]] = None):
        self.interval_s = float(interval_s)
        self.rules = rules if rules is not None else default_rules()
        self._history: Deque[dict] = collections.deque(maxlen=_HISTORY)
        self._lock = threading.Lock()
        #: rule name -> {"active", "bad", "good", "since", "detail"}
        self._state: Dict[str, dict] = {
            r.name: {"active": False, "bad": 0, "good": 0,
                     "since": None, "detail": None}
            for r in self.rules}
        self.ticks = 0
        #: round 20 — the alert->action hand-off: tick listeners called
        #: AFTER every evaluate (outside the lock) with one record
        #: ``{"ticks", "sample", "fired", "active"}``. The policy plane
        #: registers here; listeners must be cheap and never raise (a
        #: listener enqueues for its own thread — the watchdog tick
        #: thread does no policy work itself).
        self._tick_listeners: List = []
        self._t_ticks = tmetrics.counter("watchdog.ticks")
        # EAGER registration (the PR 6 rule): the whole alert family
        # scrapes at zero from the first /metrics read — the fleet
        # plane's always-on families ride the same moment
        for r in self.rules:
            tmetrics.counter(f"alert.{r.name}")
        tfleet.eager_register()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- evaluation ---------------------------------------------------------

    def evaluate(self, sample: dict) -> List[str]:
        """Feed one sample; run every rule with hysteresis; return the
        names of rules that FIRED on this tick (transitions only)."""
        fired = []
        with self._lock:
            self._history.append(sample)
            history = list(self._history)
            self.ticks += 1
            self._t_ticks.inc()
            for rule in self.rules:
                st = self._state[rule.name]
                try:
                    verdict = rule.check(history)
                except Exception as exc:    # a buggy rule must not
                    Log.Error("watchdog rule %s failed: %r",
                              rule.name, exc)
                    verdict = HOLD
                if verdict is HOLD:
                    continue
                if verdict is None:
                    st["bad"] = 0
                    st["good"] += 1
                    if st["active"] and st["good"] >= rule.clear_after:
                        st["active"] = False
                        st["since"] = None
                        tflight.record(f"alert.{rule.name}",
                                       detail="cleared")
                        Log.Info("[watchdog] alert %s cleared",
                                 rule.name)
                    continue
                st["good"] = 0
                st["bad"] += 1
                st["detail"] = verdict
                if not st["active"] and st["bad"] >= rule.fire_after:
                    st["active"] = True
                    st["since"] = sample.get("t", time.perf_counter())
                    tmetrics.counter(f"alert.{rule.name}").inc()
                    # (mepoch, seq) stamped so the policy plane's
                    # action events align with their triggering alert
                    # in forensics (round 20)
                    mep, seq = stream_pos()
                    tflight.record(f"alert.{rule.name}", seq=seq,
                                   mepoch=mep,
                                   detail=str(verdict)[:200])
                    Log.Info("[watchdog] ALERT %s: %s", rule.name,
                             verdict)
                    fired.append(rule.name)
            active = [name for name, st in self._state.items()
                      if st["active"]]
            ticks = self.ticks
            listeners = list(self._tick_listeners)
        for fn in listeners:        # outside the lock: a listener may
            try:                    # itself read active_alerts()
                fn({"ticks": ticks, "sample": sample, "fired": fired,
                    "active": active})
            except Exception as exc:    # a buggy listener must not
                Log.Error("watchdog tick listener failed: %r", exc)
        return fired

    def tick(self) -> List[str]:
        """One live tick: refresh the ledger + saturation gauges, then
        evaluate the rules over a fresh sample."""
        refresh_saturation_gauges()
        return self.evaluate(collect_sample())

    def add_tick_listener(self, fn) -> None:
        """Register an alert->action hand-off listener (round 20 —
        the policy plane's intake). Called after every evaluate with
        ``{"ticks", "sample", "fired", "active"}``; must be cheap and
        never raise."""
        with self._lock:
            self._tick_listeners.append(fn)

    # -- state surfaces -----------------------------------------------------

    def active_alerts(self) -> List[dict]:
        now = time.perf_counter()
        with self._lock:
            return [{"rule": name, "detail": st["detail"],
                     "for_s": (round(now - st["since"], 3)
                               if st["since"] is not None else None)}
                    for name, st in self._state.items() if st["active"]]

    def report(self) -> dict:
        with self._lock:
            rules = {name: {"active": st["active"], "bad": st["bad"],
                            "good": st["good"],
                            "last_detail": st["detail"]}
                     for name, st in self._state.items()}
            ticks = self.ticks
        return {"enabled": True, "interval_s": self.interval_s,
                "ticks": ticks, "alerts": self.active_alerts(),
                "rules": rules}

    # -- daemon lifecycle ---------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run,
                                        name="mv-watchdog",
                                        daemon=True)
        self._thread.start()

    # mv-lint: ok(device-work-domain): the tick's ledger refresh walks jax.tree leaves and reads .nbytes on the HOST — no device program launches; the probe-never-syncs-mirror regression test below pins the matrix path
    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as exc:    # the tick must never die
                Log.Error("watchdog tick failed: %r", exc)

    def stop(self) -> None:
        """Stop + join BOUNDED through failsafe.deadline.bounded (the
        Zoo.Stop contract: a wedged probe raises typed instead of
        hanging shutdown; the daemon thread is abandoned on expiry)."""
        self._stop.set()
        if self._thread is None:
            return
        from multiverso_tpu.failsafe import deadline as fdeadline
        from multiverso_tpu.failsafe.errors import DeadlineExceeded
        try:
            fdeadline.bounded(lambda: self._thread.join(timeout=5),
                              "watchdog thread join", fatal=False)
        except DeadlineExceeded as exc:
            Log.Error("watchdog stop timed out (%r) — abandoning its "
                      "daemon thread", exc)
        if self._thread.is_alive():
            Log.Error("watchdog thread still alive after bounded join "
                      "— daemon thread abandoned")


_watchdog: Optional[Watchdog] = None
_wd_lock = threading.Lock()


def start_watchdog() -> bool:
    """Arm the watchdog when ``-mv_watchdog_s > 0`` (Zoo.Start, after
    the engine is up). Idempotent; False when off."""
    global _watchdog
    try:
        interval = float(GetFlag("mv_watchdog_s"))
    except Exception:
        interval = 0.0
    with _wd_lock:
        if interval <= 0 or _watchdog is not None:
            return _watchdog is not None
        _watchdog = Watchdog(interval)
        _watchdog.start()
        Log.Info("watchdog armed: tick %.3fs, %d rules", interval,
                 len(_watchdog.rules))
        return True


def stop_watchdog() -> None:
    """Stop + join the watchdog (Zoo.Stop). Idempotent."""
    global _watchdog
    with _wd_lock:
        wd, _watchdog = _watchdog, None
    if wd is not None:
        wd.stop()


def peek() -> Optional[Watchdog]:
    return _watchdog


def active_alerts() -> List[dict]:
    """The live watchdog's active alerts ([] when off) — the /healthz
    warn probe."""
    wd = _watchdog
    return wd.active_alerts() if wd is not None else []


def alerts_report() -> dict:
    """The ``/alerts`` body. When the watchdog is off the body says so
    instead of claiming health."""
    wd = _watchdog
    if wd is None:
        return {"enabled": False, "ticks": 0, "alerts": [],
                "rules": {},
                "note": "watchdog off — arm with -mv_watchdog_s=N"}
    return wd.report()
