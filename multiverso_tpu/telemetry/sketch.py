"""Bounded frequency sketches for access-skew measurement.

The ROADMAP's giant-table hot-row cache needs its measurement first:
WHICH rows of a MatrixTable do Gets actually hit, and how skewed is
the distribution? A per-row counter array would cost O(num_rows);
this module provides the bounded classic instead — the Space-Saving
top-K sketch (Metwally et al., "Efficient computation of frequent and
top-k elements in data streams"): at most ``capacity`` tracked keys,
each with a count and an over-count bound (the count a key may have
inherited when it evicted the minimum). Guarantees: every true heavy
hitter with frequency > N/capacity IS tracked, and a tracked count
over-estimates the truth by at most its recorded error bound.

Off by default behind ``-mv_row_sketch`` (the capacity; 0 disables —
tables never construct a sketch, the per-Get cost is one cached int
read). Updates run on the engine actor thread; reads (dashboard,
/metrics gauge, /perf) take the same short lock.
"""

from __future__ import annotations

import heapq
import threading
from typing import List, Tuple

from multiverso_tpu.utils.configure import MV_DEFINE_int, cached_int_flag

MV_DEFINE_int("mv_row_sketch", 0,
              "per-row access-skew sketch on MatrixTable row Gets AND "
              "KVTable key Gets (round 13): track the top-N hottest "
              "rows/keys per table in a bounded Space-Saving sketch "
              "(0 = off, no per-Get cost beyond one cached flag "
              "read). Surfaced in /metrics "
              "(table.<family><id>.row_skew_top_share), the Dashboard "
              "[RowSkew] line and /perf — the measurement groundwork "
              "for the ROADMAP's hot-row cache, which needs skew on "
              "both families.")

#: the -mv_row_sketch gate, listener-cached (consulted per Get)
row_sketch_capacity = cached_int_flag("mv_row_sketch", 0)

#: how many top rows the share gauge/summary aggregates over
TOP_N = 8


class SpaceSaving:
    """Space-Saving top-K: bounded dict of key -> (count, err).

    Eviction finds the minimum through a LAZY-DELETION HEAP instead of
    an O(capacity) scan: entries are (count, key) pushed at insert
    time; a popped entry whose count no longer matches the live dict
    is stale (the key was incremented or already evicted) and is
    discarded. When the heap runs dry of valid entries it is rebuilt
    from the live counts — amortized O(log capacity) per eviction, so
    an armed sketch on a low-skew stream (nearly every id evicting)
    stays cheap on the engine actor thread instead of becoming the
    apply-stage stall it is meant to measure."""

    def __init__(self, capacity: int):
        self.capacity = max(2, int(capacity))
        self._lock = threading.Lock()
        self._counts: dict = {}
        self._errs: dict = {}
        self._heap: list = []       # lazy (count, key) min-candidates
        self._total = 0

    def update(self, key, n: int = 1) -> None:
        with self._lock:
            self._update_locked(key, n)

    def _evict_min_locked(self):
        """Pop the true minimum's (key, count), lazy-heap style."""
        counts = self._counts
        while self._heap:
            c, key = heapq.heappop(self._heap)
            if counts.get(key) == c:
                return key, c
        # every candidate went stale (hot keys grew past their pushed
        # counts): rebuild from the live dict — rare, O(capacity)
        self._heap = [(c, k) for k, c in counts.items()]
        heapq.heapify(self._heap)
        c, key = heapq.heappop(self._heap)
        return key, c

    def _update_locked(self, key, n: int) -> None:
        self._total += n
        counts = self._counts
        if key in counts:
            # no heap push: the key's old (smaller) entry goes stale
            # and is discarded by the validity check at eviction time
            counts[key] += n
            return
        if len(counts) < self.capacity:
            counts[key] = n
            self._errs[key] = 0
            heapq.heappush(self._heap, (n, key))
            return
        # evict the minimum; the newcomer inherits its count as the
        # over-estimate bound (the Space-Saving replacement rule)
        victim, floor = self._evict_min_locked()
        counts.pop(victim, None)
        self._errs.pop(victim, None)
        counts[key] = floor + n
        self._errs[key] = floor
        heapq.heappush(self._heap, (floor + n, key))
        if len(self._heap) > 8 * self.capacity:
            # stale-entry bound: churn-heavy streams rebuild instead
            # of letting discarded candidates accumulate
            self._heap = [(c, k) for k, c in counts.items()]
            heapq.heapify(self._heap)

    def update_ids(self, ids) -> None:
        """Count one Get's row-id array. Deduplicated first: per-Get
        cost is O(unique ids) dict ops under one short lock."""
        import numpy as np
        uniq, cnt = np.unique(np.asarray(ids).ravel(),
                              return_counts=True)
        with self._lock:
            for key, n in zip(uniq.tolist(), cnt.tolist()):
                self._update_locked(key, int(n))

    @property
    def total(self) -> int:
        return self._total

    def top(self, n: int = TOP_N) -> List[Tuple[int, int, int]]:
        """The ``n`` hottest tracked keys as (key, count,
        overcount_bound), hottest first."""
        with self._lock:
            items = sorted(self._counts.items(), key=lambda kv: -kv[1])
            return [(k, c, self._errs.get(k, 0)) for k, c in items[:n]]

    def top_share(self, n: int = TOP_N) -> float:
        """Fraction of ALL counted accesses landing on the current
        top-``n`` keys (0.0 when nothing counted) — the one-number
        skew signal the /metrics gauge carries. An over-estimate by at
        most the tracked error bounds, like every Space-Saving read."""
        with self._lock:
            if self._total <= 0:
                return 0.0
            counts = sorted(self._counts.values(), reverse=True)
            return min(1.0, sum(counts[:n]) / self._total)

    def summary(self, n: int = TOP_N) -> dict:
        """JSON-ready summary for /perf and the dashboard line."""
        return {"total": self.total, "capacity": self.capacity,
                "top_share": round(self.top_share(n), 4),
                "top": [{"key": int(k), "count": int(c),
                         "overcount_max": int(e)}
                        for k, c, e in self.top(n)]}


def note_table_access(table, ids, fam: str) -> None:
    """The ONE per-Get sketch hook both table families ride (round 13
    extended the matrix-only round-11 hook to KVTable key Gets): feed
    one Get's id/key array to ``table._row_sketch``, creating it
    lazily when ``-mv_row_sketch`` arms. The off path is one cached
    int read; the /metrics top-share gauge refreshes every 32 notes,
    not per Get. ``table`` must carry ``_row_sketch`` /
    ``_row_sketch_notes`` slots (both families initialize them)."""
    cap = row_sketch_capacity()
    if cap <= 0:
        return
    sk = table._row_sketch
    if sk is None:
        sk = table._row_sketch = SpaceSaving(cap)
    sk.update_ids(ids)
    table._row_sketch_notes += 1
    if table._row_sketch_notes & 31 == 1:
        from multiverso_tpu.telemetry import metrics as tmetrics
        tmetrics.gauge(
            f"table.{fam}{getattr(table, 'table_id', 0)}"
            f".row_skew_top_share").set(sk.top_share())
