"""Cross-rank window critical-path reconstruction (round 11).

PR 6's fence-cause profiling answered WHY the pipelined engine's
exchange stage stalls (the depth cap — apply lags the exchange); this
tool answers WHERE each window's wall time actually went and WHICH
rank bound it. Every rank's engine stamps its window lifecycle phases
— form, pack, encode, exchange (with the time blocked in the
collective split from local codec work), decode, apply — as compact
``window.phases`` flight events keyed by ``(mepoch, stream, SEQ)``
(sync/server.py; ``stream`` is the engine shard, round 12 — each
shard owns an independent window stream), plus per-(table, verb)
apply seconds as ``window.tables``. :func:`correlate` merges the
per-rank dumps into ONE cross-rank timeline and names the binding
rank and binding phase per window — per stream, with a cross-stream
summary in ``report["streams"]``.

Clock alignment
===============

Ranks' wall clocks disagree (NTP skew, steps). But the windowed
engine hands us a free sync pulse per window: every rank leaves the
SAME allgather at ~the same instant, and each ``window.phases`` event
carries its exchange-done wall stamp (re-anchored through the event's
dual wall/mono stamps, telemetry/flight.py). The per-rank offset vs
the reference rank is the MEDIAN over common windows of the
exchange-done deltas — median, so a straggler-free estimate survives
occasional outliers. The residual per-window spread after removing
the offsets is the ALIGNMENT ERROR BOUND the report carries
(``align_err_s``): it is bounded by the collective's exit skew (one
gloo/ICI hop, sub-millisecond on a healthy fabric) plus the ~us stamp
latency, and every cross-rank comparison this tool makes is only
trusted to that bound.

Binding attribution
===================

The binding rank of a window is the LAST rank to enter its collective
(everyone else sat blocked in the allgather waiting for it). What
delayed its entry is read off its own rank-local monotonic timeline —
no cross-rank clock math needed for the phase verdict: between its
previous exchange-done and this exchange-enter it ran decode (prev
window), apply (any window applying in the gap — the depth-fence
culprit), form/pack/encode (this window). The largest component — or
the collective itself when the gap is negligible — is the binding
phase. Per-window verdicts aggregate into the straggler report:
binding-rank histogram, per-rank exchange-wait asymmetry, top tables
by apply seconds.

CLI::

    python -m multiverso_tpu.telemetry.critpath diag/flight_rank*.jsonl
    python -m multiverso_tpu.telemetry.critpath diag/
    python -m multiverso_tpu.telemetry.critpath --trace merged.json ...

(a directory argument globs its own ``flight_rank*.jsonl`` — the
layout ``-mv_diag_dir`` writes).

``--trace`` writes the merged cross-rank timeline as Chrome trace
JSON (one track per rank x stage, the PR 2 writer's schema) for
Perfetto. Offline, local, never collective.
"""

from __future__ import annotations

import statistics
from typing import Dict, List, Optional

from multiverso_tpu.telemetry import align

#: phase taxonomy, mirroring sync/server.py ENGINE_PHASES (binding
#: verdicts draw from these plus the synthetic "exchange" = the
#: collective itself bound the window)
PHASES = ("form", "pack", "encode", "exchange", "exchange_wait",
          "decode", "apply")

_US = 1e-6


def _parse_detail(detail: str) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for part in str(detail).split(";"):
        key, sep, val = part.partition("=")
        if sep:
            try:
                out[key] = float(val)
            except ValueError:
                pass
    return out


def _window_record(ev: dict) -> dict:
    """One ``window.phases`` event -> phase durations (seconds) +
    rank-local monotonic landmarks + the exchange-done wall anchor."""
    d = _parse_detail(ev.get("detail", ""))
    rec = {"verbs": int(d.get("v", 0)),
           "form": d.get("f", 0.0) * _US, "pack": d.get("p", 0.0) * _US,
           "encode": d.get("e", 0.0) * _US,
           "exchange": d.get("x", 0.0) * _US,
           "exchange_wait": d.get("xw", 0.0) * _US,
           "decode": d.get("d", 0.0) * _US,
           "apply": d.get("a", 0.0) * _US,
           "x_done_m": None, "x_done_w": None, "x_enter_m": None,
           "a_start_m": None}
    tm = ev.get("tm")
    xd = d.get("xd")
    if tm is not None and xd is not None:
        # the event's dual stamps were sampled together, so the same
        # offset re-anchors the landmark on both clocks
        x_done_m = float(tm) - xd * _US
        rec["x_done_m"] = x_done_m
        rec["x_done_w"] = float(ev.get("t", 0.0)) - xd * _US
        rec["x_enter_m"] = x_done_m - rec["exchange"]
        ax = d.get("ax")
        if ax is not None:
            rec["a_start_m"] = x_done_m + ax * _US
    return rec


def _table_totals(events: List[dict]) -> Dict[tuple, float]:
    """Sum ``window.tables`` attribution events into
    {(table_label, verb): seconds}."""
    out: Dict[tuple, float] = {}
    for ev in events:
        if ev.get("kind") != "window.tables":
            continue
        for part in str(ev.get("detail", "")).split(";"):
            name, sep, val = part.partition("=")
            if not sep or ":" not in name:
                continue
            label, _, verb = name.rpartition(":")
            try:
                secs = float(val) * _US
            except ValueError:
                continue
            out[(label, verb)] = out.get((label, verb), 0.0) + secs
    return out


def correlate(paths: List[str]) -> dict:
    """Merge per-rank flight dumps into a cross-rank window timeline;
    return the critical-path / straggler report (see module
    docstring). Degrades gracefully: a single-rank dump yields local
    phase totals with a ``degraded`` note instead of binding verdicts;
    ragged/evicted tails shrink the covered overlap (the shared
    telemetry/align.py rules) and are summarized in ``coverage``."""
    dumps = [align.load(p) for p in paths]
    streams, dropped = align.by_rank(dumps, ("window.phases",))
    ranks = sorted(streams)
    # per-rank ALL phase events (single-process records carry seq -1 —
    # not stream positions, but their durations are real local data
    # and must land in the phase totals)
    all_phase: Dict[int, List[dict]] = {}
    #: rank -> host label from the dump header (round 24 — cross-host
    #: worlds need verdicts that name WHICH BOX binds, not just which
    #: rank; pre-round-24 dumps without the field fall back to "rankN")
    hosts: Dict[int, str] = {}
    for d in dumps:
        rank = d["rank"] if d["rank"] >= 0 else len(all_phase)
        all_phase[rank] = [_window_record(e) for e in d["events"]
                           if e.get("kind") == "window.phases"]
        hosts[rank] = str(d["header"].get("host") or "") or f"rank{rank}"
    # per-rank parsed stream windows + per-rank apply intervals (mono)
    win: Dict[int, Dict[tuple, dict]] = {}
    apply_iv: Dict[int, List[tuple]] = {}
    for r in ranks:
        win[r] = {}
        apply_iv[r] = []
        for pos, evs in streams[r].items():
            rec = _window_record(evs[0])
            win[r][pos] = rec
            if rec["a_start_m"] is not None and rec["apply"] > 0:
                apply_iv[r].append((rec["a_start_m"],
                                    rec["a_start_m"] + rec["apply"]))
        apply_iv[r].sort()
    phase_totals = {r: {p: sum(rec[p] for rec in all_phase.get(r, ()))
                        for p in PHASES} for r in ranks}
    tables = {}
    for d in dumps:
        for key, secs in _table_totals(d["events"]).items():
            tables[key] = tables.get(key, 0.0) + secs
    tables_top = [{"table": label, "verb": verb,
                   "seconds": round(secs, 6)}
                  for (label, verb), secs in
                  sorted(tables.items(), key=lambda kv: -kv[1])]
    report = {"ranks": ranks,
              "hosts": {r: hosts.get(r, f"rank{r}") for r in ranks},
              "n_windows": 0, "windows": [],
              "clock_offsets_s": {r: 0.0 for r in ranks},
              "align_err_s": 0.0,
              "binding_rank_hist": {}, "binding_phase_hist": {},
              "streams": {},
              "phase_totals_s": {r: {p: round(s, 6)
                                     for p, s in phase_totals[r].items()}
                                 for r in ranks},
              "exchange_wait_excess_s": {},
              "tables_top": tables_top,
              "coverage": align.coverage_note(streams, dropped),
              "degraded": None, "accounted_pct": None, "note": ""}
    if not ranks or all(not s for s in streams.values()):
        if any(all_phase.get(r) for r in ranks):
            # stamped, but only single-process (seq -1) records: real
            # local phase data, just nothing to align across ranks
            report["degraded"] = (
                "only single-process phase records (no exchange SEQ) "
                "— cross-rank alignment needs multi-process windows; "
                "reporting local phase totals")
        else:
            report["degraded"] = (
                "no window.phases events found — phase stamping off "
                "(-mv_phase_stamps=0 / -mv_flight_events=0) or a "
                "pre-round-11 dump")
        report["note"] = report["degraded"]
        return report
    common = [pos for pos in align.common_positions(streams)
              if all(win[r][pos]["x_done_w"] is not None for r in ranks)]
    report["n_windows"] = len(common)
    if len(ranks) < 2:
        report["degraded"] = ("single-rank dump: cross-rank critical "
                              "path needs every rank's ring — "
                              "reporting local phase totals only")
        report["note"] = report["degraded"]
        return report
    if not common:
        report["degraded"] = ("no common stamped window positions "
                              "across ranks — dumps do not overlap")
        report["note"] = report["degraded"]
        return report
    # -- clock offsets from the exchange-done rendezvous ------------------
    ref = ranks[0]
    offsets = {ref: 0.0}
    for r in ranks[1:]:
        offsets[r] = statistics.median(
            win[r][pos]["x_done_w"] - win[ref][pos]["x_done_w"]
            for pos in common)
    spreads = []
    for pos in common:
        aligned = [win[r][pos]["x_done_w"] - offsets[r] for r in ranks]
        spreads.append(max(aligned) - min(aligned))
    err = (statistics.quantiles(spreads, n=10)[-1]
           if len(spreads) >= 2 else (spreads[0] if spreads else 0.0))
    report["clock_offsets_s"] = {r: round(offsets[r], 6) for r in ranks}
    report["align_err_s"] = round(err, 6)
    # -- per-window binding verdicts --------------------------------------
    rank_hist: Dict[int, int] = {}
    phase_hist: Dict[str, int] = {}
    wait_excess = {r: 0.0 for r in ranks}
    accounted = []
    # the binding gap is between CONSECUTIVE windows of the SAME
    # (mepoch, stream) sub-stream: engine shards drain independently,
    # so "previous window" must never cross shard streams
    prev_common: Dict[tuple, tuple] = {}
    last_by_sub: Dict[tuple, tuple] = {}
    for pos in common:
        prev_common[pos] = last_by_sub.get(pos[:2])
        last_by_sub[pos[:2]] = pos
    #: per engine shard stream: binding verdicts (round 12 — the
    #: sharded engine's per-stream report + cross-stream summary)
    per_stream: Dict[int, dict] = {}
    windows_out = []
    for pos in common:
        enters = {r: win[r][pos]["x_done_w"] - offsets[r]
                  - win[r][pos]["exchange"] for r in ranks}
        binding = max(enters, key=enters.get)
        rank_hist[binding] = rank_hist.get(binding, 0) + 1
        # wait asymmetry from the BLOCKED-IN-COLLECTIVE slice (xw) —
        # the total exchange wall also carries per-rank local staging
        # (buffer copies scale with the rank's own blob size), which
        # must not be billed as "waited on a slower peer". Dumps from
        # engines that recorded no xw fall back to the total.
        waits = {r: (win[r][pos]["exchange_wait"]
                     or win[r][pos]["exchange"]) for r in ranks}
        min_w = min(waits.values())
        for r in ranks:
            wait_excess[r] += waits[r] - min_w
        # binding phase: what the binding rank did between its previous
        # exchange-done and this exchange-enter, on ITS OWN monotonic
        # clock (no cross-rank math -> not limited by align_err_s)
        rec = win[binding][pos]
        prev = prev_common[pos]
        comp = {"form": rec["form"], "pack": rec["pack"],
                "encode": rec["encode"], "exchange": rec["exchange"]}
        period = None
        unacc = None
        if prev is not None and win[binding][prev]["x_done_m"] is not None:
            prec = win[binding][prev]
            gap_lo = prec["x_done_m"]
            gap_hi = rec["x_enter_m"]
            comp["decode"] = prec["decode"]
            comp["apply"] = sum(
                max(0.0, min(hi, gap_hi) - max(lo, gap_lo))
                for lo, hi in apply_iv[binding]
                if hi > gap_lo and lo < gap_hi)
            # the engine's "form" stamp includes the depth-fence wait,
            # and while the fence holds, an APPLY is what is running —
            # the same wall time shows up in both. Attribute the
            # overlapped stretch to its cause (apply) and keep only the
            # apply-free remainder as genuine window formation, so a
            # straggling apply stage is named "apply", not "form".
            comp["form"] = max(0.0, comp["form"] - comp["apply"])
            period = rec["x_done_m"] - prec["x_done_m"]
            unacc = max(0.0, period - sum(comp.values()))
        phase = max(comp, key=comp.get) if any(comp.values()) else "exchange"
        phase_hist[phase] = phase_hist.get(phase, 0) + 1
        if period is not None and period > 0:
            accounted.append(100.0 * (period - unacc) / period)
        ps = per_stream.setdefault(pos[1], {
            "n_windows": 0, "binding_rank_hist": {},
            "binding_phase_hist": {}})
        ps["n_windows"] += 1
        ps["binding_rank_hist"][binding] = (
            ps["binding_rank_hist"].get(binding, 0) + 1)
        ps["binding_phase_hist"][phase] = (
            ps["binding_phase_hist"].get(phase, 0) + 1)
        windows_out.append({
            "pos": list(pos), "binding_rank": binding,
            "binding_host": hosts.get(binding, f"rank{binding}"),
            "binding_phase": phase,
            "period_s": round(period, 6) if period is not None else None,
            "unaccounted_s": (round(unacc, 6) if unacc is not None
                              else None),
            "per_rank": {r: {
                "x_enter": round(enters[r], 6),
                "x_done": round(win[r][pos]["x_done_w"] - offsets[r], 6),
                "exchange_s": round(win[r][pos]["exchange"], 6),
                "apply_s": round(win[r][pos]["apply"], 6),
            } for r in ranks}})
    report["windows"] = windows_out
    report["binding_rank_hist"] = rank_hist
    report["binding_phase_hist"] = phase_hist
    # cross-stream summary: the flat hists above AGGREGATE every shard
    # stream; per_stream carries each stream's own verdicts so a
    # straggling shard is visible as such
    for s in per_stream.values():
        bp = s["binding_phase_hist"]
        br = s["binding_rank_hist"]
        s["dominant_phase"] = max(bp, key=bp.get)
        s["dominant_rank"] = max(br, key=br.get)
        s["dominant_host"] = hosts.get(s["dominant_rank"],
                                       f"rank{s['dominant_rank']}")
    report["streams"] = per_stream
    report["exchange_wait_excess_s"] = {r: round(s, 6)
                                        for r, s in wait_excess.items()}
    if accounted:
        report["accounted_pct"] = round(
            sum(accounted) / len(accounted), 1)
    top_rank = max(rank_hist, key=rank_hist.get)
    top_phase = max(phase_hist, key=phase_hist.get)
    multi = (f" across {len(per_stream)} engine streams"
             if len(per_stream) > 1 else "")
    report["note"] = (
        f"{len(common)} windows{multi}: rank {top_rank} "
        f"(host {hosts.get(top_rank, f'rank{top_rank}')}) binds "
        f"{rank_hist[top_rank]}/{len(common)}, dominant binding phase "
        f"'{top_phase}' ({phase_hist[top_phase]}/{len(common)}); "
        f"alignment error <= {report['align_err_s'] * 1e3:.3f} ms")
    return report


def report_text(report: dict) -> str:
    """Human-readable straggler report."""
    lines = [f"== window critical path: ranks {report['ranks']} =="]
    if report.get("degraded"):
        lines.append(f"DEGRADED: {report['degraded']}")
    if report.get("coverage"):
        lines.append(f"coverage: {report['coverage']}")
    if report["note"] and report["note"] != report.get("degraded"):
        lines.append(report["note"])
    hosts = report.get("hosts", {})

    def _host(r):
        return hosts.get(r, f"rank{r}")

    if report["binding_rank_hist"]:
        lines.append("binding ranks: " + ", ".join(
            f"rank {r} ({_host(r)}): {n}" for r, n in
            sorted(report["binding_rank_hist"].items())))
        lines.append("binding phases: " + ", ".join(
            f"{p}: {n}" for p, n in
            sorted(report["binding_phase_hist"].items(),
                   key=lambda kv: -kv[1])))
        if len(report.get("streams", {})) > 1:
            for sid, s in sorted(report["streams"].items()):
                lines.append(
                    f"  stream {sid}: {s['n_windows']} windows, "
                    f"binding rank {s['dominant_rank']} on "
                    f"{s.get('dominant_host', _host(s['dominant_rank']))} "
                    f"({s['binding_rank_hist'][s['dominant_rank']]}"
                    f"/{s['n_windows']}), dominant phase "
                    f"'{s['dominant_phase']}'")
        lines.append("exchange-wait excess (blocked waiting on a "
                     "slower peer): " + ", ".join(
                         f"rank {r}: {s * 1e3:.1f}ms" for r, s in
                         sorted(report["exchange_wait_excess_s"].items())))
        if report.get("accounted_pct") is not None:
            lines.append(f"phase accounting covers "
                         f"{report['accounted_pct']:.1f}% of window "
                         f"wall on the binding ranks")
    for r in report["ranks"]:
        tot = report["phase_totals_s"].get(r, {})
        lines.append(f"rank {r} phase totals: " + ", ".join(
            f"{p}={tot.get(p, 0.0) * 1e3:.1f}ms" for p in PHASES))
    if report["tables_top"]:
        lines.append("top tables by apply seconds:")
        for rec in report["tables_top"][:5]:
            lines.append(f"  {rec['table']} {rec['verb']}: "
                         f"{rec['seconds'] * 1e3:.1f}ms")
    return "\n".join(lines)


#: stage -> Perfetto track id (one track per rank x stage; rank = pid)
_TRACKS = {"form": 1, "pack": 2, "encode": 3, "exchange": 4,
           "decode": 5, "apply": 6}


def to_chrome_trace(paths: List[str],
                    report: Optional[dict] = None) -> dict:
    """The merged cross-rank timeline as Chrome trace JSON (Perfetto):
    one process per rank, one track per stage. EVERY stamped window
    renders (ragged tails included — they carry real local phases);
    ranks sit on the reference rank's clock via the report's offsets.
    When the report is degraded (no common windows to estimate offsets
    from), multi-rank output is rendered on RAW wall clocks and each
    process label says so — a silently skewed timeline must not look
    aligned."""
    from multiverso_tpu.telemetry import trace as ttrace

    report = report if report is not None else correlate(paths)
    dumps = [align.load(p) for p in paths]
    streams, _ = align.by_rank(dumps, ("window.phases",))
    offsets = report.get("clock_offsets_s", {})
    unaligned = (report.get("degraded") is not None
                 and len(streams) > 1)
    events = []
    t0 = None
    slices = []
    for r, stream_r in sorted(streams.items()):
        off = offsets.get(r, 0.0)
        for pos, evs in sorted(stream_r.items()):
            rec = _window_record(evs[0])
            if rec["x_done_w"] is None:
                continue
            done = rec["x_done_w"] - off
            enter = done - rec["exchange"]
            marks = [("exchange", enter, rec["exchange"]),
                     ("decode", done, rec["decode"]),
                     ("encode", enter - rec["encode"], rec["encode"]),
                     ("pack", enter - rec["encode"] - rec["pack"],
                      rec["pack"]),
                     ("form", enter - rec["encode"] - rec["pack"]
                      - rec["form"], rec["form"])]
            if rec["a_start_m"] is not None:
                # apply landmarks are rank-local mono; re-anchor via
                # this window's exchange-done on both clocks
                marks.append(("apply",
                              done + (rec["a_start_m"]
                                      - rec["x_done_m"]),
                              rec["apply"]))
            for stage, start, dur in marks:
                if dur <= 0.0:
                    continue
                slices.append((r, stage, start, dur, pos))
                t0 = start if t0 is None else min(t0, start)
    for r, stage, start, dur, pos in slices:
        st = f" st{pos[1]}" if pos[1] else ""
        events.append({"name": f"{stage}{st} s{pos[2]}",
                       "cat": "critpath",
                       "ph": "X", "ts": (start - (t0 or 0.0)) * 1e6,
                       "dur": dur * 1e6, "pid": r,
                       "tid": _TRACKS[stage],
                       "args": {"mepoch": pos[0], "stream": pos[1],
                                "seq": pos[2]}})
    suffix = " (UNALIGNED CLOCK)" if unaligned else ""
    process_names = {r: f"rank {r}{suffix}" for r in streams}
    thread_names = {(r, tid): stage for r in streams
                    for stage, tid in _TRACKS.items()}
    return ttrace.chrome_trace(events, process_names=process_names,
                               thread_names=thread_names)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import json

    from multiverso_tpu.utils.log import Log
    parser = argparse.ArgumentParser(
        prog="python -m multiverso_tpu.telemetry.critpath",
        description="merge per-rank flight dumps by (mepoch, stream, "
                    "SEQ), align clocks on exchange-done rendezvous "
                    "points, and report each window's binding rank + "
                    "phase (per engine shard stream)")
    parser.add_argument("paths", nargs="+",
                        help="per-rank flight_rank<R>.jsonl dumps, or "
                             "a directory (e.g. the -mv_diag_dir) "
                             "whose flight_rank*.jsonl are globbed")
    parser.add_argument("--trace", default="",
                        help="also write the merged timeline as Chrome "
                             "trace JSON (Perfetto) to this path")
    parser.add_argument("--json", action="store_true",
                        help="emit the full report as JSON instead of "
                             "the text rendering")
    args = parser.parse_args(argv)
    paths = align.expand_paths(args.paths)
    report = correlate(paths)
    if args.json:
        Log.Info("%s", json.dumps(report, indent=1, sort_keys=True))
    else:
        Log.Info("%s", report_text(report))
    if args.trace:
        with open(args.trace, "w") as f:
            json.dump(to_chrome_trace(paths, report), f)
        Log.Info("critpath: wrote merged timeline to %s", args.trace)
    return 0 if report.get("degraded") is None else 2


if __name__ == "__main__":      # pragma: no cover - CLI shim
    raise SystemExit(main())
