"""Node/Role records.

Behavioral equivalent of reference include/multiverso/node.h:6-20: a node is
a (rank, role bitmask, worker_id, server_id) record; roles are a bitmask of
NONE/WORKER/SERVER (ALL = both, the default — reference zoo.cpp:23
``ps_role=default`` maps to ALL).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class Role(enum.IntFlag):
    NONE = 0
    WORKER = 1
    SERVER = 2
    ALL = 3


ROLE_NAMES = {
    "none": Role.NONE,
    "worker": Role.WORKER,
    "server": Role.SERVER,
    "default": Role.ALL,
    "all": Role.ALL,
}


@dataclass
class Node:
    rank: int = 0
    role: Role = Role.ALL
    worker_id: int = -1
    server_id: int = -1

    def is_worker(self) -> bool:
        return bool(self.role & Role.WORKER)

    def is_server(self) -> bool:
        return bool(self.role & Role.SERVER)
