"""LogisticRegression configuration.

Key=value config-file parser with the same keys and defaults as the
reference (Applications/LogisticRegression/src/configure.h:19-97,
configure.cpp) so reference config files (e.g. example/mnist.config) work
unchanged. Lines starting with '#' are comments; unknown keys warn.
"""

from __future__ import annotations

from dataclasses import dataclass, field, fields
from typing import Optional

from multiverso_tpu.utils.log import Log


@dataclass
class Configure:
    # dimensions (reference configure.h:20-22)
    input_size: int = 0
    output_size: int = 0
    # is input data sparse (configure.h:25)
    sparse: bool = False
    # training (configure.h:27-34)
    train_epoch: int = 1
    minibatch_size: int = 20
    read_buffer_size: int = 2048
    show_time_per_sample: int = 10000
    # objective/regular coefficients (configure.h:36-43)
    regular_coef: float = 0.0005
    learning_rate: float = 0.8
    learning_rate_coef: float = 1e6
    # FTRL parameters (configure.h:45-49)
    alpha: float = 0.005
    beta: float = 1.0
    lambda1: float = 5.0
    lambda2: float = 0.002
    # files (configure.h:51-77)
    init_model_file: str = ""
    train_file: str = "train.data"
    reader_type: str = "default"   # default / weight / bsparse
    test_file: str = ""
    output_model_file: str = "logreg.model"
    output_file: str = "logreg.output"
    # distributed mode (configure.h:79-87)
    use_ps: bool = False
    pipeline: bool = True
    sync_frequency: int = 1
    # algorithm selection (configure.h:89-97)
    updater_type: str = "default"    # default / sgd / ftrl
    objective_type: str = "default"  # default / sigmoid / softmax / ftrl
    regular_type: str = "default"    # default / L1 / L2
    # TPU-native extension (no reference counterpart): dtype the dense
    # objective's matmuls run in. "bfloat16" feeds the MXU at its native
    # width and halves data-side HBM traffic; weights, gradients, and the
    # loss stay float32 (mixed precision), so training trajectories track
    # the float32 ones to bf16 rounding.
    compute_type: str = "float32"    # float32 / bfloat16
    # TPU-native extension 2: wire compression of the sparse PS table's
    # row pushes ("sparse" = exact index/value pairs, "1bit" = sign bits
    # + error feedback; tables/base.py TableOption.compress). "" = off.
    compress: str = ""
    # TPU-native extension 3: train whole windows as one jit'd program
    # consuming the PS tables' HBM storage directly (the WE -device_pairs
    # pattern; models/logreg/device_plane.py). Requires use_ps; dense and
    # sparse objectives. Multi-process worlds train COLLECTIVELY:
    # lockstep windows with filler for ragged shard streams
    # (device_plane.py docstring).
    device_plane: bool = False
    # TPU-native extension 4: parse-once epoch cache (data.py WindowCache)
    # — epoch 2+ replay the identical window sequence from memory instead
    # of re-parsing the text files; capped at cache_data_mb (larger
    # datasets stream every epoch, reference-style).
    cache_data: bool = True
    cache_data_mb: int = 4096

    @classmethod
    def from_file(cls, config_file: str) -> "Configure":
        cfg = cls()
        cfg.load(config_file)
        return cfg

    def load(self, config_file: str) -> None:
        typed = {f.name: f.type for f in fields(self)}
        with open(config_file) as f:
            for raw in f:
                line = raw.strip()
                if not line or line.startswith("#"):
                    continue
                key, _, val = line.partition("=")
                key, val = key.strip(), val.strip()
                if key not in typed:
                    Log.Error("[logreg] unknown config key %r", key)
                    continue
                current = getattr(self, key)
                if isinstance(current, bool):
                    setattr(self, key, val.lower() in ("true", "1", "yes"))
                elif isinstance(current, int):
                    setattr(self, key, int(float(val)))
                elif isinstance(current, float):
                    setattr(self, key, float(val))
                else:
                    setattr(self, key, val)
        self.finalize()

    def finalize(self) -> None:
        """Normalize derived settings; idempotent. Called from_file and by
        LogReg for programmatically-built configs."""
        if self.objective_type == "ftrl":
            # ftrl objective implies ftrl updater + sparse model
            # (reference updater.cpp:106-108, ftrl uses sparse entries)
            self.updater_type = "ftrl"
            self.sparse = True
        if self.compute_type not in ("float32", "bfloat16"):
            raise ValueError(
                f"compute_type={self.compute_type!r}: must be 'float32' or "
                "'bfloat16'")
